"""Shape-bucketed microbatch serving: coalesce concurrent requests into
one vmapped executable per flush.

The r7 engine made a *single* request run as one compiled program; this
layer makes *N concurrent small requests* run as ``N / batch`` compiled
programs. Requests enter through a future-returning :meth:`submit` on
the hot endpoints — dense/CWT sketch-apply, Fastfood/RFT feature maps,
sketched least squares, KRR predict — and are grouped by **bucket**:
(endpoint statics, dtype, pow2 shape class, sharding) as defined in
:mod:`libskylark_tpu.engine.bucket`.

Sparse operands are first-class (docs/serving, "Sparse operands on
the serve path"): :meth:`~MicrobatchExecutor.submit_sparse` /
:meth:`~MicrobatchExecutor.submit_sparse_solve` pack a
:class:`~libskylark_tpu.base.sparse.SparseMatrix` (or scipy sparse)
operand as padded (data, indices, indptr) CSR lanes whose bucket keys
carry a pow2 **nnz class** next to the dims/dtype — ragged-nnz
cohorts coalesce into one flush executable, bit-equal to the dense
reference (``todense()`` → ``transform.apply``), with operands past
``SKYLARK_SPARSE_MIN_DENSITY`` auto-densified onto the dense path
(counted). Sparse CWT buckets participate in the flush-kernel ladder
via :mod:`libskylark_tpu.sketch.pallas_sparse`.

Flush kernels: the sketch-apply and fastfood buckets can flush through
the endpoint's **batched Pallas kernel** (one ``pallas_call`` over the
stacked cohort — ``sketch/pallas_hash.py`` scatter-free CountSketch,
``sketch/pallas_dense.py`` fused generate+matmul, ``sketch/
pallas_fastfood.py`` fused SHGΠHB chain) instead of the vmapped XLA
path. Which program serves a (bucket, capacity) flush is resolved by
:meth:`MicrobatchExecutor._resolve_flush_kernel` with the precedence
``kernel=`` argument > ``SKYLARK_SERVE_KERNEL`` env > tune plan cache >
default (xla); the resolved choice is a **static of the executable
cache key**, so selection can never retrace a warm bucket
(docs/performance, "Serve-bucket kernel selection").
A cohort flushes as ONE ``jax.vmap``-batched executable when it reaches
``max_batch`` or its oldest request has lingered ``linger_us``; past
``max_queue`` pending requests, ``submit`` blocks (backpressure) and
eventually raises :class:`ServeOverloadedError`.

Batched executables route through the same process-global executable
cache as the r7 solver pipelines (:mod:`libskylark_tpu.engine.compiled`)
— the bucket statics ride the ``key_fn`` extras and the padded batch
shape rides the avals, so steady-state traffic is zero-recompile after
one warmup per (bucket, capacity class). The stacked per-flush operand
buffers are **donated**: the executor owns them (freshly allocated each
flush, never re-read), so XLA may reuse their memory for the batch
output regardless of the global ``SKYLARK_ENGINE_DONATE`` opt-in, which
continues to govern only user-owned operands.

Exactness: padding is bit-exact, not approximate. The sketch operators
are positional virtual streams, so zero-padded coordinates contribute
exact zeros (``sketch.dense.serve_apply`` / ``sketch.hash
.cwt_serve_apply``); batch lanes are invariant to the capacity class
(a cohort of 3 padded to capacity 4 returns the same bits per lane as
capacity 8). Filler lanes replicate the last real request rather than
feeding zeros into factorizations.

Counters (``MicrobatchExecutor.stats()`` / ``engine.serve_stats()``):
submitted / completed / failed / rejected, queued gauge, coalesced
(requests that shared a flush), flushes, batch-capacity and cohort-size
histograms, padding-waste ratio, and p50/p99/mean request latency.

Stateful sessions (:mod:`libskylark_tpu.sessions`, docs/sessions): the
executor also hosts *bucket-lived* sketch sessions —
:meth:`~MicrobatchExecutor.open_sketch_session` /
:meth:`~MicrobatchExecutor.session_append` /
:meth:`~MicrobatchExecutor.session_finalize` — a registry keyed
alongside the bucket statics (session id → maintained sketch state +
append journal sequence number). Every accepted append is journaled
under ``SKYLARK_SESSION_DIR`` *before* its future resolves; a drain
checkpoints live session state (the r9 drain hook discipline), so a
peer executor resumes a drained — or ``kill -9``'d — replica's
sessions from checkpoint + journal tail, bit-equal, with idempotent
sequence numbers making duplicate replay a no-op. Under DEGRADED
health, session appends (the best-effort streaming class) shed
*before* interactive one-shot traffic; expired deadlines and TTL
evictions resolve append futures to :class:`ServeOverloadedError` /
:class:`~libskylark_tpu.base.errors.SessionEvictedError` instead of
hanging.

Multi-tenant QoS (:mod:`libskylark_tpu.qos`, docs/qos): every request
carries a **priority class** (interactive / standard / best_effort)
resolved from its ``tenant=`` argument by a
:class:`~libskylark_tpu.qos.TenantRegistry` — with token-bucket rate
limits refusing over-quota tenants at admission
(:class:`~libskylark_tpu.base.errors.TenantQuotaError`). The class
rides the bucket *key* (classes queue separately, share executables)
and the flusher drains the per-class queues with **weighted-fair
deficit round robin** (8:4:1); shedding — DEGRADED and queue-pressure
— is class-ordered: best_effort before standard before interactive,
session appends below interactive. An optional per-executor
**adaptive batching controller** (``adaptive=True``,
:mod:`libskylark_tpu.qos.controller`) retunes per-bucket
linger/batch targets against the class p99 SLOs, moving batch
targets only along already-warm capacity rungs — zero recompiles by
construction. Heterogeneous library endpoints ride the same
machinery: :meth:`~MicrobatchExecutor.submit_graph_ase` /
:meth:`~MicrobatchExecutor.submit_graph_ppr` (adjacency over the
sparse CSR lanes), :meth:`~MicrobatchExecutor.submit_condest`,
:meth:`~MicrobatchExecutor.submit_lowrank`,
:meth:`~MicrobatchExecutor.submit_rlsc_predict` — each a distinct
bucket family, each bit-equal to its capacity-1 dispatch and to its
eager twin.

Content-addressed caching (:mod:`libskylark_tpu.engine.resultcache`,
docs/caching; opt-in via ``cache=True`` / ``SKYLARK_CACHE``): every
endpoint is a pure function of (operand bytes, key data, statics), so
requests carry a blake2b **digest** — computed once, at the fleet
front door when one exists (``_digest=``) — behind three fast paths
at intake: results pinned by :meth:`~MicrobatchExecutor
.register_operand` (the sketch-once residency API), a byte-bounded
QoS-partitioned digest→result cache, and **single-flight** coalescing
of concurrent identical requests onto one flush (one leader, N
futures, bit-equal fan-out; a poisoned flush fails all coalesced
waiters with the leader's exception). All three are bypassed while
DEGRADED — a shedding executor never blocks intake on cache locks.

Resilience (r9, :mod:`libskylark_tpu.resilience`): a failed flush no
longer fans its exception to the whole cohort — the executor retries
**bisection-style**, splitting the cohort in half and re-executing each
half, so a single poison request converges to its own capacity-1 flush
in ≤ log2(max_batch) retries and receives the exception *alone* while
every cohort-mate re-coalesces and succeeds (lane invariance makes the
re-coalesced results bit-equal). The executor carries health states —
``SERVING`` → ``DEGRADED`` (recent-flush failure ratio past
``degraded_threshold``; submits load-shed at a reduced queue bound) →
``DRAINING`` (:meth:`drain`: intake refused, queue flushed, in-flight
futures resolved — what the preemption handler calls on SIGTERM) →
``STOPPED``. Requests accept a ``deadline``; one that expires while
queued resolves to :class:`ServeOverloadedError` and never consumes an
isolation retry. The flush worker hosts the ``serve.flush`` fault-
injection site (:mod:`libskylark_tpu.resilience.faults`), so all of the
above is deterministically chaos-testable (``benchmarks/
chaos_battery.py``, the CI chaos gate).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import itertools
import math
import sys
import threading
import time
import warnings
import weakref
from concurrent.futures import Future
from typing import Optional

import numpy as np

from libskylark_tpu import telemetry as _telemetry
from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors as _errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.qos import scheduler as _qsched
from libskylark_tpu.qos import tenants as _qtenants
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.engine import bucket as bucketing
from libskylark_tpu.engine import resultcache as _rcache
from libskylark_tpu.engine.compiled import compiled as engine_compile
from libskylark_tpu.engine.compiled import digest as engine_digest
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience import health as _health
from libskylark_tpu.resilience.policy import Deadline
from libskylark_tpu.telemetry import trace as _trace

ENDPOINTS = ("sketch_apply", "fastfood_features", "solve_l2_sketched",
             "krr_predict", "sparse_sketch_apply",
             "sparse_solve_l2_sketched", "graph_ase", "graph_ppr",
             "condest", "lowrank", "rlsc_predict",
             "compressed_matmul")

# endpoints with a batched Pallas flush kernel behind the selection
# seam (arg > env > plan cache > default); the others always flush
# through the vmapped XLA path
_KERNEL_ENDPOINTS = ("sketch_apply", "fastfood_features",
                     "sparse_sketch_apply")

# sparse-operand intake telemetry (docs/serving, "Sparse operands on
# the serve path") — registry metrics created HERE once (the
# metric-names rule's one-creation-site contract); the per-executor
# disaggregation lives in ``stats()["sparse"]`` and rides the serve
# collector.
_SPARSE_SUBMITS = _metrics.counter(
    "serve.sparse_submits",
    "Sparse (CSR) serve submissions accepted by submit_sparse / "
    "submit_sparse_solve, before the densify decision")
_SPARSE_DENSIFIED = _metrics.counter(
    "serve.sparse_densified",
    "Sparse submissions auto-densified onto the dense serve path "
    "(operand density >= SKYLARK_SPARSE_MIN_DENSITY)")
_SPARSE_KERNEL_FLUSHES = _metrics.counter(
    "serve.sparse_kernel_flushes",
    "Sparse-bucket flushes by resolved flush backend (pallas = the "
    "scatter-free sparse-CountSketch kernel, xla = the O(nnz) "
    "scatter)")
_SPARSE_NNZ_HIST = _metrics.histogram(
    "serve.sparse_nnz_class",
    "pow2 nnz class of accepted sparse submissions — the sparse "
    "bucket-population signal (one bucket per (shape class, nnz "
    "class, dtype))",
    buckets=tuple(float(1 << p) for p in range(6, 21)))

# panel-free FWHT tier telemetry (docs/performance, "In-kernel FWHT
# and compressed matmul") — created HERE once; the per-executor
# disaggregation lives in ``stats()["fwht"]`` and rides the serve
# collector.
_FWHT_FLUSHES = _metrics.counter(
    "serve.fwht_flushes",
    "SRHT-family sketch_apply flushes by resolved flush backend "
    "(pallas = the in-kernel FWHT butterfly, xla = the panel-free "
    "fwht_sketch lowering)")
_CM_SUBMITS = _metrics.counter(
    "serve.compressed_matmul_submits",
    "Compressed approximate-matmul submissions reaching the flush "
    "path (cache hits bypass prep and are not counted here)")

_KERNEL_BACKENDS = _env.SERVE_KERNEL_BACKENDS

# multi-tenant QoS instruments (docs/qos) — created HERE once (the
# metric-names one-creation-site contract); the always-on per-class
# accounting lives in ``stats()["qos"]`` and rides the ``qos``
# collector registered at the bottom of this module. The controller
# gauges (qos.linger_target / qos.batch_target) are created in
# ``qos/controller.py``.
_QOS_ADMITTED = _metrics.counter(
    "qos.admitted",
    "Requests admitted past QoS admission, by priority class and "
    "tenant")
_QOS_SHED = _metrics.counter(
    "qos.shed",
    "Requests shed by the class-ordered shed policy (DEGRADED or "
    "queue pressure), by priority class and tenant")
_QOS_RATE_LIMITED = _metrics.counter(
    "qos.rate_limited",
    "Requests refused at admission by a tenant token bucket "
    "(TenantQuotaError), by priority class and tenant")
_QOS_QUEUE_DEPTH = _metrics.gauge(
    "qos.queue_depth",
    "Queued (not yet dispatched) requests, by priority class and "
    "replica (per-executor series — N executors must not clobber one "
    "label key)")
_QOS_LATENCY = _metrics.histogram(
    "qos.request_latency",
    "Request latency (submit to resolve, seconds), by priority class")

# auto-assigned replica identity labels ("ex-0", "ex-1", ...) for
# executors constructed without an explicit ``name`` — every executor
# has an identity so per-replica telemetry disaggregation never falls
# back to "some anonymous executor"
_EX_SEQ = itertools.count()

# Executor health states (see the module docstring / docs/resilience).
SERVING = "SERVING"
DEGRADED = "DEGRADED"
DRAINING = "DRAINING"
STOPPED = "STOPPED"


class ServeOverloadedError(RuntimeError):
    """Backpressure bound hit (the queue stayed at ``max_queue`` past
    the submit timeout), load shed in a DEGRADED/DRAINING executor, or
    a request deadline that expired while queued."""


@dataclasses.dataclass
class _Request:
    endpoint: str
    arrays: dict            # per-request operands (host np, stack-padded)
    true_shapes: dict       # name -> original shape (for unpad/waste)
    meta: dict              # endpoint bits: squeeze flags, true extents
    future: Future = dataclasses.field(default_factory=Future)
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    deadline: Optional[Deadline] = None   # expires-while-queued bound
    tags: frozenset = frozenset()         # fault-injection tags (chaos)
    request_id: Optional[str] = None      # telemetry request identity
    tctx: Optional[object] = None         # telemetry SpanContext handoff
    qos_class: str = "standard"           # resolved priority class
    tenant: str = ""                      # resolved tenant name


@dataclasses.dataclass
class _Bucket:
    key: tuple              # full bucket identity (statics + model ids)
    statics: tuple          # engine key_fn extras (no object ids)
    ctx: dict               # closure objects: dist/kernel/model arrays
    reqs: list = dataclasses.field(default_factory=list)
    qos_class: str = "standard"   # the per-class queue this bucket
    #                               belongs to (class is part of the
    #                               bucket KEY, never of the statics:
    #                               classes share executables)

    @property
    def oldest(self) -> float:
        return self.reqs[0].t_submit if self.reqs else float("inf")


def dispatch_loop(workq) -> None:
    """Flush-worker loop over a dispatch queue of ``(executor,
    (bucket, cohort))`` items (``None`` poisons one worker). Run by
    each executor's own worker threads, and by a
    :class:`~libskylark_tpu.fleet.ReplicaPool`'s shared worker pool
    when replicas are constructed with ``dispatch_queue=`` — cohorts
    from many executors then drain through one host-sized pool."""
    while True:
        item = workq.get()
        if item is None:
            return
        ex, work = item
        ex._dispatch_cohort(*work)


#: sentinel "bucket" for a training slice in the dispatch plumbing
#: (docs/training): the flusher offers it to the deficit scheduler as
#: best-effort backlog only when no higher class has pending work, and
#: ``_dispatch_cohort`` routes it to the train manager instead of the
#: cohort runner. One sentinel per flusher pass — at most one slice
#: dispatches per scheduler decision, so training yields the moment
#: real traffic arrives (preemption at slice boundaries, structurally).
_TRAIN_KEY = object()


def _percentile(sorted_vals: list, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[i]


# ---------------------------------------------------------------------------
# bucket statics derivation — shared by the executor's per-endpoint prep
# and the fleet router's affinity key (libskylark_tpu/fleet/router.py):
# both MUST hash the same tuple or sticky routing would send a request
# to a replica whose executable cache is warm for a DIFFERENT class.
# ---------------------------------------------------------------------------


def _serve_kernel_env():
    """``SKYLARK_SERVE_KERNEL`` — the one-shot override between the
    executor argument and the tune plan cache in the flush-kernel
    precedence (``pallas`` | ``xla``; anything else is ignored so a
    typo degrades to cache consultation, the repo's env-parse
    convention — the registry parser encodes exactly that)."""
    return _env.SERVE_KERNEL.get()


def _pallas_native() -> bool:
    """Whether this backend compiles Mosaic kernels natively; off-TPU a
    pallas flush runs the interpreter (a correctness surface the tests
    and the CI bit-equality leg use — the tuner never *selects* it for
    throughput off-TPU, the cost model's interpret penalty sees to
    that)."""
    from libskylark_tpu.sketch.pallas_dense import available

    return available()


def _parse_plan_token(token: str):
    """Invert :meth:`libskylark_tpu.tune.Plan.plan_id` for warmup-pack
    kernel restoration (``pallas/mt128/pipe`` → a Plan). None when the
    token is not a plan id this build understands. The real decoder
    lives next to the encoder (``Plan.from_plan_id``) so the formats
    cannot drift apart; this wrapper only narrows the backends to the
    serve-kernel set."""
    from libskylark_tpu.tune import Plan

    return Plan.from_plan_id(token, known_backends=_KERNEL_BACKENDS)


def _decline_slug(msg: str) -> str:
    """Compact label-value form of a kernel decline reason (the
    ``by_reason`` Prometheus label set must not carry free prose)."""
    import re

    return re.sub(r"[^a-z0-9]+", "-", str(msg).lower()).strip("-")[:48]


def _sketch_family(transform):
    """(family tag, dist instance) for a serve-able transform."""
    from libskylark_tpu.sketch.dense import DenseTransform
    from libskylark_tpu.sketch.fjlt import FJLT
    from libskylark_tpu.sketch.hash import CWT

    if isinstance(transform, CWT):
        return "CWT", None
    if isinstance(transform, FJLT):
        # the serve family is the SRHT: the panel-free fwht_sketch
        # program (and the in-kernel FWHT butterfly behind it) is
        # closed-form only for the Sylvester-Hadamard mixer — the
        # same restriction operator_panel/fold_rows carry
        if transform._fut_name != "wht":
            raise _errors.UnsupportedError(
                "FJLT serves panel-free only with the 'wht' "
                f"(Sylvester-Hadamard) mixer, not "
                f"{transform._fut_name!r}")
        return "SRHT", None
    if isinstance(transform, DenseTransform):
        return transform.sketch_type, transform.dist
    raise TypeError(
        "serve endpoints batch dense (JLT/CT), CWT and FJLT/SRHT "
        "transforms (Fastfood/RFT feature maps go through "
        f"submit_fastfood); got {type(transform).__name__}")


def _sketch_statics(transform, A, dimension, pad_floor):
    """(statics, info) for a sketch_apply request. ``info`` carries the
    derivation intermediates the executor's prep reuses (reshaped
    operand, family, dist, rowwise flag, padded class shape)."""
    from libskylark_tpu.sketch import COLUMNWISE, Dimension

    dimension = dimension or COLUMNWISE
    rowwise = Dimension(dimension) == Dimension.ROWWISE
    A = np.asarray(A)
    if A.ndim == 1:
        A = A[None, :] if rowwise else A[:, None]
    n = A.shape[1] if rowwise else A.shape[0]
    if n != transform.input_dim:
        raise ValueError(
            f"operand dim {n} != transform input dim "
            f"{transform.input_dim}")
    family, dist = _sketch_family(transform)
    if family == "SRHT":
        # the FWHT length IS the operator: padding the transform axis
        # would change what the sketch computes, so only the free axis
        # buckets (the panel path padded both — the operator panel was
        # stream-exact at any extent; the panel-free program is not)
        if n & (n - 1):
            raise ValueError(
                f"SRHT serve requires a power-of-2 transform dim, "
                f"got {n}")
        pad_axes = (0,) if rowwise else (1,)
    else:
        pad_axes = (0, 1)  # both extents paddable: N is stream-exact,
        #                    the other axis is sliced off the output
    padded = bucketing.pad_shape(A.shape, pad_axes, pad_floor)
    statics = ("sketch_apply", family, repr(dist),
               transform.sketch_dim, rowwise, str(A.dtype), padded)
    return statics, {"A": A, "family": family, "dist": dist,
                     "rowwise": rowwise, "padded": padded}


def _is_sparse_operand(A) -> bool:
    from libskylark_tpu.base.sparse import SparseMatrix

    if isinstance(A, SparseMatrix):
        return True
    try:
        import scipy.sparse as sp

        return sp.issparse(A)
    except ImportError:  # pragma: no cover - scipy is a hard dep here
        return False


def default_cmm_transform(A, *, s_dim: Optional[int] = None,
                          seed: int = 0):
    """The transform ``submit_compressed_matmul`` builds when the
    caller holds none: SRHT (FJLT/``wht``) when A's contraction dim is
    a power of two, CWT otherwise, at ``s_dim`` (default
    ``SKYLARK_FWHT_CM_SDIM``) buckets seeded from ``seed``. Shared by
    the executor and fleet-router conveniences so the two front doors
    build bit-identical operators — a fleet submit and a local submit
    of the same (A, B, s_dim, seed) coalesce in the result cache."""
    from libskylark_tpu.base.context import Allocation

    n = int(A.shape[1] if hasattr(A, "shape")
            else np.asarray(A).shape[1])
    s = int(s_dim or _env.FWHT_CM_SDIM.get())
    alloc = Allocation(int(seed), 0)
    if n & (n - 1):
        from libskylark_tpu.sketch.hash import CWT

        return CWT(n, s, alloc)
    from libskylark_tpu.sketch.fjlt import FJLT

    return FJLT(n, s, alloc, fut="wht")


def _cmm_statics(transform, A, B, pad_floor):
    """(statics, info) for a compressed_matmul request: estimate A·B
    (A: (m, n) dense or CSR, B: (n, p) dense) from one shared sketch —
    ``(A Sᵀ)(S B)`` with the SAME operator S both sides, family CWT or
    SRHT. The contraction extent n is an exact bucket component (both
    family programs are stream-exact only at the true extent, and the
    error bound is a function of the true contraction); m and p bucket
    to their pow2 classes. The expected-error scale
    ``‖A‖_F·‖B‖_F·√(2/s)`` is computed host-side here and rides the
    request meta — the future resolves to ``(estimate, bound)``."""
    family, _dist = _sketch_family(transform)
    if family not in ("CWT", "SRHT"):
        raise TypeError(
            f"compressed_matmul serves CWT/SRHT sketches, got "
            f"{family} (a dense virtual panel would cost more than "
            "the product it estimates)")
    B = np.asarray(B)
    if B.ndim != 2:
        raise ValueError(f"compressed_matmul expects a (n, p) B, got "
                         f"{B.shape}")
    sparse = _is_sparse_operand(A)
    if sparse:
        A = _coerce_sparse(A)
        m, n = A.shape
        dtype = str(np.dtype(A.device_dtype))
        norm_a = float(np.linalg.norm(A.csr_parts(
            np.dtype(dtype))[0]))
    else:
        A = np.asarray(A)
        if A.ndim != 2:
            raise ValueError(
                f"compressed_matmul expects a (m, n) A, got {A.shape}")
        m, n = A.shape
        dtype = str(A.dtype)
        norm_a = float(np.linalg.norm(A))
    if B.shape[0] != n:
        raise ValueError(
            f"contraction mismatch: A is {(m, n)}, B is {B.shape}")
    if n != transform.input_dim:
        raise ValueError(
            f"contraction dim {n} != transform input dim "
            f"{transform.input_dim}")
    if family == "SRHT" and n & (n - 1):
        raise ValueError(
            f"SRHT compressed_matmul requires a power-of-2 "
            f"contraction dim, got {n}")
    s_dim = transform.sketch_dim
    bound = (norm_a * float(np.linalg.norm(B))
             * math.sqrt(2.0 / s_dim))
    m_pad = bucketing.pow2_pad(m, pad_floor)
    p_pad = bucketing.pow2_pad(B.shape[1], pad_floor)
    nnz_cls = (bucketing.nnz_class(A.nnz,
                                   _env.SPARSE_NNZ_FLOOR.get())
               if sparse else 0)
    statics = ("compressed_matmul", family, s_dim, sparse, n, dtype,
               m_pad, p_pad, nnz_cls)
    return statics, {"A": A, "B": B, "family": family,
                     "sparse": sparse, "s_dim": s_dim, "n": n,
                     "m": m, "p": B.shape[1], "bound": bound,
                     "padded_A": (m_pad, n), "padded_B": (n, p_pad),
                     "nnz_class": nnz_cls, "dtype": dtype}


def _fastfood_statics(transform, A, pad_floor):
    """(statics, info) for a fastfood_features request: the Fastfood /
    RFT feature-map serve endpoint. The row extent is the one paddable
    class dimension (rows are independent lanes of the chain); the
    column extent must equal the transform's input dim exactly — the
    chain's own NB-padding is part of the feature definition. The Sm
    spec (kind, param) is a bucket static: transforms differing only by
    seed share one executable (streams rebuild from the stacked raw
    keys), transforms differing by sigma/nu do not."""
    from libskylark_tpu.sketch.frft import FastRFT

    if not isinstance(transform, FastRFT):
        raise TypeError(
            "fastfood_features serves FastRFT-family transforms "
            f"(FastGaussianRFT/FastMaternRFT); got "
            f"{type(transform).__name__}")
    A = np.asarray(A)
    squeeze = A.ndim == 1
    if squeeze:
        A = A[None, :]
    if A.shape[1] != transform.input_dim:
        raise ValueError(
            f"operand dim {A.shape[1]} != transform input dim "
            f"{transform.input_dim}")
    sm_kind, sm_param = transform._sm_spec()
    m_pad = bucketing.pow2_pad(A.shape[0], pad_floor)
    statics = ("fastfood_features", transform._fut_name, sm_kind,
               repr(sm_param), transform.sketch_dim, A.shape[1],
               str(A.dtype), m_pad)
    return statics, {"A": A, "squeeze": squeeze, "m_pad": m_pad,
                     "fut": transform._fut_name, "sm_kind": sm_kind,
                     "sm_param": sm_param,
                     "family": type(transform).sketch_type}


def _coerce_sparse(A):
    """The framework's :class:`~libskylark_tpu.base.sparse
    .SparseMatrix` view of a sparse serve operand (scipy sparse
    accepted and attached zero-copy where possible). Dense operands
    are a type error — they belong on ``submit_sketch``."""
    from libskylark_tpu.base.sparse import SparseMatrix

    if isinstance(A, SparseMatrix):
        return A
    try:
        import scipy.sparse as sp

        if sp.issparse(A):
            return SparseMatrix.from_scipy(A)
    except ImportError:  # pragma: no cover - scipy is a hard dep here
        pass
    raise TypeError(
        "sparse serve endpoints take a SparseMatrix or scipy.sparse "
        f"operand; got {type(A).__name__} (dense operands go through "
        "submit_sketch)")


def _sparse_sketch_statics(transform, A, dimension, pad_floor):
    """(statics, info) for a sparse_sketch_apply request: the CSR twin
    of :func:`_sketch_statics`, with the pow2 **nnz class**
    (``engine.bucket.nnz_class`` at the ``SKYLARK_SPARSE_NNZ_FLOOR``
    granularity) riding the statics next to the padded dims/dtype —
    two ragged-nnz requests in one class share one flush executable,
    their (data, indices) lanes zero-padded to the class extent."""
    from libskylark_tpu.sketch import COLUMNWISE, Dimension

    dimension = dimension or COLUMNWISE
    rowwise = Dimension(dimension) == Dimension.ROWWISE
    A = _coerce_sparse(A)
    n = A.width if rowwise else A.height
    if n != transform.input_dim:
        raise ValueError(
            f"operand dim {n} != transform input dim "
            f"{transform.input_dim}")
    family, dist = _sketch_family(transform)
    padded = bucketing.pad_shape(A.shape, (0, 1), pad_floor)
    nnz_cls = bucketing.nnz_class(A.nnz, _env.SPARSE_NNZ_FLOOR.get())
    dtype = str(np.dtype(A.device_dtype))
    statics = ("sparse_sketch_apply", family, repr(dist),
               transform.sketch_dim, rowwise, dtype, padded, nnz_cls)
    return statics, {"A": A, "family": family, "dist": dist,
                     "rowwise": rowwise, "padded": padded,
                     "nnz_class": nnz_cls, "dtype": dtype}


def _sparse_solve_statics(transform, A, B, method, pad_floor):
    """(statics, info) for a sparse_solve_l2_sketched request: CSR
    design matrix, dense target block."""
    A = _coerce_sparse(A)
    B = np.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if B.shape[0] != A.height:
        raise ValueError(f"solve expects (n,d) A and (n,t) B, got "
                         f"{A.shape} / {B.shape}")
    if A.height != transform.input_dim:
        raise ValueError(
            f"operand rows {A.height} != transform input dim "
            f"{transform.input_dim}")
    family, dist = _sketch_family(transform)
    if family not in ("JLT", "CWT"):
        raise TypeError(f"sparse solve serve path supports JLT/CWT, "
                        f"got {family}")
    n_pad = bucketing.pow2_pad(A.height, pad_floor)
    nnz_cls = bucketing.nnz_class(A.nnz, _env.SPARSE_NNZ_FLOOR.get())
    dtype = str(np.dtype(A.device_dtype))
    # d and t are exact bucket components (zero feature/target columns
    # would make the compressed problem singular) — same rule as the
    # dense solve bucket
    statics = ("sparse_solve_l2_sketched", family,
               transform.sketch_dim, method, A.width, B.shape[1],
               dtype, n_pad, nnz_cls)
    return statics, {"A": A, "B": B, "squeeze": squeeze,
                     "family": family, "n_pad": n_pad,
                     "nnz_class": nnz_cls, "dtype": dtype}


@functools.lru_cache(maxsize=1024)
def _seed_key_data(seed: int) -> np.ndarray:
    """Raw PRNG key data of ``jax.random.key(seed)`` as a host array —
    the key material of the seed-addressed endpoints (graph_ase,
    condest). Cached: the key derivation is a host-synced jax op worth
    paying once per seed, not once per request."""
    import jax.random as jr

    return np.asarray(jr.key_data(jr.key(int(seed))), dtype=np.uint32)


def _coerce_adjacency(A):
    from libskylark_tpu.ml.graph import coerce_adjacency

    return coerce_adjacency(A)[0]


def _graph_ase_statics(A, k, iters, pad_floor):
    """(statics, info) for a graph_ase request: adjacency spectral
    embedding over the r18 sparse CSR lanes — adjacency matrices are
    exactly the sparse regime those lanes optimize. ``k`` (embedding
    dim) and ``iters`` (subspace iterations) are statics; the seed
    rides as key-data operand bits so seeds share one executable."""
    S = _coerce_adjacency(A)
    padded = bucketing.pad_shape(S.shape, (0, 1), pad_floor)
    nnz_cls = bucketing.nnz_class(S.nnz, _env.SPARSE_NNZ_FLOOR.get())
    dtype = str(np.dtype(S.device_dtype))
    k = int(k)
    iters = max(int(iters), 1)
    if not 0 < k <= S.height:
        raise ValueError(f"embedding dim k={k} must be in (0, "
                         f"{S.height}]")
    statics = ("graph_ase", k, iters, dtype, padded, nnz_cls)
    return statics, {"A": S, "padded": padded, "nnz_class": nnz_cls,
                     "dtype": dtype, "k": k, "iters": iters}


def _graph_ppr_statics(A, s, alpha, iters, pad_floor):
    """(statics, info) for a graph_ppr request: fixed-iteration
    personalized PageRank over the CSR adjacency. ``alpha``/``iters``
    are statics; the personalization vector is an operand."""
    S = _coerce_adjacency(A)
    padded = bucketing.pad_shape(S.shape, (0, 1), pad_floor)
    nnz_cls = bucketing.nnz_class(S.nnz, _env.SPARSE_NNZ_FLOOR.get())
    dtype = str(np.dtype(S.device_dtype))
    s = np.asarray(s, dtype=np.dtype(dtype))
    if s.shape != (S.height,):
        raise ValueError(f"personalization vector shape {s.shape} != "
                         f"({S.height},)")
    alpha = float(alpha)
    if not 0.0 < alpha < 1.0:
        raise ValueError(f"alpha must be in (0, 1), got {alpha}")
    statics = ("graph_ppr", alpha, max(int(iters), 1), dtype, padded,
               nnz_cls)
    return statics, {"A": S, "s": s, "padded": padded,
                     "nnz_class": nnz_cls, "dtype": dtype,
                     "alpha": alpha, "iters": max(int(iters), 1)}


def _condest_statics(A, steps, pad_floor):
    """(statics, info) for a condest request: fixed-step Golub-Kahan
    condition estimation (``nla.condest.condest_serve_apply``)."""
    A = np.asarray(A)
    if A.ndim != 2:
        raise ValueError(f"condest expects a matrix, got {A.shape}")
    steps = max(int(steps), 1)
    if steps >= min(A.shape):
        raise ValueError(
            f"steps={steps} must be < min(shape)={min(A.shape)} "
            "(the Krylov space is exhausted past that)")
    padded = bucketing.pad_shape(A.shape, (0, 1), pad_floor)
    statics = ("condest", steps, str(A.dtype), padded)
    return statics, {"A": A, "padded": padded, "steps": steps}


def _lowrank_statics(transform_s, transform_t, A, k, pad_floor):
    """(statics, info) for a lowrank request: two-level-sketch
    dominant-subspace basis (``nla.lowrank.lowrank_serve_apply``)
    from two caller-held dense-family transforms. The row extent is
    the paddable class dimension (rows sketch independently); the
    feature extent is exact."""
    fam_s, dist_s = _sketch_family(transform_s)
    fam_t, dist_t = _sketch_family(transform_t)
    if fam_s != fam_t or repr(dist_s) != repr(dist_t):
        raise TypeError(
            f"lowrank serves a matched dense transform pair, got "
            f"{fam_s}/{fam_t}")
    if dist_s is None:
        raise TypeError("lowrank serves dense families (JLT/CT); CWT "
                        "has no dense virtual panel here")
    A = np.asarray(A)
    if A.ndim != 2 or A.shape[1] != transform_s.input_dim \
            or A.shape[1] != transform_t.input_dim:
        raise ValueError(
            f"operand {A.shape} does not match transform input dims "
            f"{transform_s.input_dim}/{transform_t.input_dim}")
    k = int(k)
    if not 0 < k <= transform_s.sketch_dim:
        raise ValueError(f"k={k} must be in (0, "
                         f"{transform_s.sketch_dim}]")
    m_pad = bucketing.pow2_pad(A.shape[0], pad_floor)
    statics = ("lowrank", fam_s, repr(dist_s),
               transform_s.sketch_dim, transform_t.sketch_dim, k,
               A.shape[1], str(A.dtype), m_pad)
    return statics, {"A": A, "dist": dist_s,
                     "padded": (m_pad, A.shape[1]), "k": k}


def _lowrank_key_data(transform, dtype):
    """(key data, scale) operand pair of one lowrank transform —
    shared with the eager twin (``nla.lowrank.lowrank_serve``) so
    both sides feed the pure endpoint identical bits."""
    kd = MicrobatchExecutor._key_data(transform)
    scale = np.asarray(getattr(transform, "scale", 1.0),
                       dtype=np.dtype(dtype))
    return kd, scale


def _solve_statics(transform, A, B, method, pad_floor):
    """(statics, info) for a solve_l2_sketched request."""
    A = np.asarray(A)
    B = np.asarray(B)
    squeeze = B.ndim == 1
    if squeeze:
        B = B[:, None]
    if A.ndim != 2 or B.shape[0] != A.shape[0]:
        raise ValueError(f"solve expects (n,d) A and (n,t) B, got "
                         f"{A.shape} / {B.shape}")
    if A.shape[0] != transform.input_dim:
        raise ValueError(
            f"operand rows {A.shape[0]} != transform input dim "
            f"{transform.input_dim}")
    family, dist = _sketch_family(transform)
    if family not in ("JLT", "CWT"):
        raise TypeError(f"solve serve path supports JLT/CWT, "
                        f"got {family}")
    n_pad = bucketing.pow2_pad(A.shape[0], pad_floor)
    # d and t are exact bucket components: zero feature/target
    # columns would make the compressed problem singular
    statics = ("solve_l2_sketched", family, transform.sketch_dim,
               method, A.shape[1], B.shape[1], str(A.dtype), n_pad)
    return statics, {"A": A, "B": B, "squeeze": squeeze,
                     "family": family, "n_pad": n_pad}


def _krr_statics(kernel, X_new, X_train, coef, pad_floor,
                 endpoint: str = "krr_predict"):
    """(statics, info) for a krr_predict request — and, with
    ``endpoint="rlsc_predict"``, for its classification twin (same
    bucket anatomy, distinct bucket family: the endpoints trace
    different programs). Shape-only on the model operands — the
    router must not pay a device conversion to compute an affinity
    key, so this reads ``np.shape`` where the executor's prep later
    converts."""
    X_new = np.asarray(X_new)
    squeeze_q = X_new.ndim == 1
    if squeeze_q:
        X_new = X_new[None, :]
    train_shape = tuple(np.shape(X_train))
    coef_shape = tuple(np.shape(coef))
    if len(coef_shape) == 1:
        coef_shape = coef_shape + (1,)
    if X_new.shape[1] != train_shape[1]:
        raise ValueError(
            f"query dim {X_new.shape[1]} != train dim "
            f"{train_shape[1]}")
    q_pad = bucketing.pow2_pad(X_new.shape[0], pad_floor)
    statics = (endpoint, engine_digest(kernel),
               train_shape, coef_shape, str(X_new.dtype), q_pad)
    return statics, {"X_new": X_new, "squeeze_q": squeeze_q,
                     "q_pad": q_pad}


def request_statics(endpoint: str, *,
                    pad_floor: int = bucketing.PAD_FLOOR,
                    **kwargs) -> tuple:
    """The engine-level bucket statics a request of ``endpoint`` with
    these operands lands in: (endpoint, family/digest, dtype, shape
    class, ...) — exactly the tuple the executor keys its batched
    executables on. This is the fleet router's affinity key (one
    executable class == one consistent-hash bucket), exposed as a
    module function so routing never has to build a request to know
    where it belongs. Transport kwargs (``timeout`` / ``deadline`` /
    ``request_id``) are ignored; ``pad_floor`` must match the target
    executors' (a :class:`~libskylark_tpu.fleet.ReplicaPool` keeps it
    uniform)."""
    return derive_request(endpoint, pad_floor=pad_floor, **kwargs)[0]


def derive_request(endpoint: str, *,
                   pad_floor: int = bucketing.PAD_FLOOR,
                   **kwargs) -> tuple:
    """``(statics, info)`` — the full derivation behind
    :func:`request_statics`. The fleet router uses this form and hands
    the result back to the chosen replica's ``submit`` (internal
    ``_derived=`` kwarg) so the derivation runs once per routed
    request, not once in the router and again in the executor."""
    for transport in ("timeout", "deadline", "request_id", "tenant",
                      "qos_class", "_digest"):
        kwargs.pop(transport, None)
    if endpoint == "sketch_apply":
        kwargs.setdefault("dimension", None)
        return _sketch_statics(kwargs["transform"], kwargs["A"],
                               kwargs["dimension"], pad_floor)
    if endpoint == "fastfood_features":
        return _fastfood_statics(kwargs["transform"], kwargs["A"],
                                 pad_floor)
    if endpoint == "solve_l2_sketched":
        kwargs.setdefault("method", "qr")
        return _solve_statics(kwargs["transform"], kwargs["A"],
                              kwargs["B"], kwargs["method"], pad_floor)
    if endpoint == "krr_predict":
        return _krr_statics(kwargs["kernel"], kwargs["X_new"],
                            kwargs["X_train"], kwargs["coef"],
                            pad_floor)
    if endpoint == "sparse_sketch_apply":
        kwargs.setdefault("dimension", None)
        return _sparse_sketch_statics(kwargs["transform"], kwargs["A"],
                                      kwargs["dimension"], pad_floor)
    if endpoint == "sparse_solve_l2_sketched":
        kwargs.setdefault("method", "qr")
        return _sparse_solve_statics(kwargs["transform"], kwargs["A"],
                                     kwargs["B"], kwargs["method"],
                                     pad_floor)
    if endpoint == "graph_ase":
        return _graph_ase_statics(kwargs["A"], kwargs["k"],
                                  kwargs.get("iters", 2), pad_floor)
    if endpoint == "graph_ppr":
        return _graph_ppr_statics(kwargs["A"], kwargs["s"],
                                  kwargs.get("alpha", 0.85),
                                  kwargs.get("iters", 16), pad_floor)
    if endpoint == "condest":
        return _condest_statics(kwargs["A"], kwargs.get("steps", 8),
                                pad_floor)
    if endpoint == "lowrank":
        return _lowrank_statics(kwargs["transform_s"],
                                kwargs["transform_t"], kwargs["A"],
                                kwargs["k"], pad_floor)
    if endpoint == "rlsc_predict":
        return _krr_statics(kwargs["kernel"], kwargs["X_new"],
                            kwargs["X_train"], kwargs["coef"],
                            pad_floor, endpoint="rlsc_predict")
    if endpoint == "compressed_matmul":
        return _cmm_statics(kwargs["transform"], kwargs["A"],
                            kwargs["B"], pad_floor)
    raise ValueError(f"unknown serve endpoint {endpoint!r}; "
                     f"expected one of {ENDPOINTS}")


def request_digest(endpoint: str, derived: tuple, kwargs: dict) -> str:
    """The request's content address (docs/caching, "Digest anatomy"):
    blake2b-256 over the bucket statics plus everything else that
    reaches the executable — the transform's raw key data (the seed;
    same operand bytes under a different seed MUST digest differently,
    the miscoalesce regression), any scale, the operand bytes (CSR
    operands hash their (data, indices, indptr) parts — never
    densified), and model/seed material per endpoint family.

    ``derived`` is :func:`derive_request`'s ``(statics, info)`` and
    ``kwargs`` the endpoint kwargs it was derived from, so the fleet
    router — which has both in hand — computes the digest ONCE per
    request and forwards it (``_digest=``); a standalone executor
    derives it itself. The digest deliberately contains no object ids
    and no transport state: two replicas handed the same request
    bytes compute the same address, which is what makes the cache
    deterministic across a fleet."""
    statics, info = derived
    kd = MicrobatchExecutor._key_data

    def scale_of(t):
        return np.float64(getattr(t, "scale", 1.0))

    def csr(A, dtype):
        data, indices, indptr = A.csr_parts(np.dtype(dtype))
        return [("shape", repr(tuple(A.shape))), ("data", data),
                ("indices", indices), ("indptr", indptr)]

    if endpoint in ("sketch_apply", "fastfood_features"):
        t = kwargs["transform"]
        parts = [("kd", kd(t)), ("scale", scale_of(t)),
                 ("A", info["A"])]
    elif endpoint == "solve_l2_sketched":
        t = kwargs["transform"]
        parts = [("kd", kd(t)), ("scale", scale_of(t)),
                 ("A", info["A"]), ("B", info["B"])]
    elif endpoint in ("krr_predict", "rlsc_predict"):
        # the model CONTENT is part of the address (the bucket key's
        # id()-identity is a queueing concern — content addressing
        # must survive a model round-trip through a new object)
        parts = [("Xq", info["X_new"]),
                 ("X_train", np.asarray(kwargs["X_train"])),
                 ("coef", np.asarray(kwargs["coef"])),
                 ("coding", repr(kwargs.get("coding")))]
    elif endpoint in ("sparse_sketch_apply", "sparse_solve_l2_sketched"):
        t = kwargs["transform"]
        parts = [("kd", kd(t)), ("scale", scale_of(t))]
        parts += csr(info["A"], info["dtype"])
        if endpoint == "sparse_solve_l2_sketched":
            parts.append(("B", info["B"]))
    elif endpoint == "graph_ase":
        parts = [("seed", repr(int(kwargs.get("seed", 0))))]
        parts += csr(info["A"], info["dtype"])
    elif endpoint == "graph_ppr":
        parts = csr(info["A"], info["dtype"]) + [("s", info["s"])]
    elif endpoint == "condest":
        parts = [("seed", repr(int(kwargs.get("seed", 0)))),
                 ("A", info["A"])]
    elif endpoint == "lowrank":
        ts, tt = kwargs["transform_s"], kwargs["transform_t"]
        parts = [("kd_s", kd(ts)), ("scale_s", scale_of(ts)),
                 ("kd_t", kd(tt)), ("scale_t", scale_of(tt)),
                 ("A", info["A"])]
    elif endpoint == "compressed_matmul":
        t = kwargs["transform"]
        parts = [("kd", kd(t)), ("scale", scale_of(t))]
        if info["sparse"]:
            parts += csr(info["A"], info["dtype"])
        else:
            parts.append(("A", info["A"]))
        parts.append(("B", info["B"]))
    else:
        raise ValueError(f"unknown serve endpoint {endpoint!r}; "
                         f"expected one of {ENDPOINTS}")
    return _rcache.operand_digest(parts, statics=statics)


class MicrobatchExecutor:
    """Thread-safe microbatching executor over the serve endpoints.

    ::

        ex = engine.MicrobatchExecutor(max_batch=8, linger_us=2000)
        fut = ex.submit_sketch(transform, A, dimension=sk.ROWWISE)
        fut2 = ex.submit_solve(A, b, transform=T, method="qr")
        fut3 = ex.submit_krr_predict(kernel, Xq, X_train, coef)
        SA = fut.result()
        ex.shutdown()

    ``mesh`` (optional ``jax.sharding.Mesh``) shards every flush's batch
    dimension across the mesh — capacity classes round up to the device
    count so each device gets equal lanes; model operands (KRR's
    training set and coefficients) are replicated.

    ``workers`` flush cohorts concurrently; the executable cache is
    single-flight, so concurrent cold flushes of one bucket compile
    once. Submission itself is cheap (a host-side pack + queue append)
    and safe from any thread.

    ``dispatch_queue`` (advanced; a ``queue.Queue``) makes this
    executor enqueue its cohorts there instead of spawning its own
    workers — the seam a :class:`~libskylark_tpu.fleet.ReplicaPool`
    uses to size flush concurrency to the HOST rather than to N
    replicas (N replicas × own workers oversubscribes a small host;
    see docs/fleet "Tuning N"). The queue's owner runs the worker
    threads (:func:`dispatch_loop`) and must outlive the executor.
    """

    def __init__(self, max_batch: int = 8, linger_us: int = 2000,
                 max_queue: int = 1024, workers: int = 1,
                 mesh=None, pad_floor: int = bucketing.PAD_FLOOR,
                 degraded_threshold: float = 0.5,
                 failure_window: int = 32,
                 shed_fraction: float = 0.25,
                 name: Optional[str] = None,
                 dispatch_queue=None,
                 kernel: Optional[str] = None,
                 tenants=None,
                 adaptive: bool = False,
                 cache: Optional[bool] = None,
                 cache_bytes: Optional[int] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if kernel is not None and kernel not in _KERNEL_BACKENDS:
            raise ValueError(
                f"kernel must be one of {_KERNEL_BACKENDS} or None "
                f"(autotuned selection), got {kernel!r}")
        if not 0.0 < degraded_threshold <= 1.0:
            raise ValueError("degraded_threshold must be in (0, 1]")
        if not 0.0 < shed_fraction <= 1.0:
            raise ValueError("shed_fraction must be in (0, 1]")
        # replica identity: the label under which this executor's
        # counters disaggregate in telemetry.snapshot() / Prometheus,
        # and the name a ReplicaPool/Router address it by
        self.name = str(name) if name else f"ex-{next(_EX_SEQ)}"
        self.max_batch = int(max_batch)
        self.linger = float(linger_us) * 1e-6
        self.max_queue = int(max_queue)
        self.pad_floor = int(pad_floor)
        self.degraded_threshold = float(degraded_threshold)
        self.shed_fraction = float(shed_fraction)
        self._mesh = mesh
        self._batch_axis = None
        self._ndev = 1
        if mesh is not None:
            self._batch_axis = tuple(mesh.shape.keys())[0]
            self._ndev = int(mesh.shape[self._batch_axis])

        self._lock = _locks.make_lock("serve.state")
        self._work_cv = threading.Condition(self._lock)   # flusher wakeups
        self._space_cv = threading.Condition(self._lock)  # backpressure
        self._idle_cv = threading.Condition(self._lock)   # drain quiescence
        self._buckets: "dict[tuple, _Bucket]" = {}
        self._pending = 0
        # multi-tenant QoS (docs/qos): the tenant registry resolves
        # tenant= submits to priority classes and charges token
        # buckets; the deficit scheduler replaces the FIFO drain order
        # across the per-class queues; per-bucket linger/batch targets
        # start at the static config and move only when the adaptive
        # controller is on
        self._tenants = (tenants if tenants is not None
                         else _qtenants.get_registry())
        self._sched = _qsched.DeficitScheduler(quantum=self.max_batch)
        self._class_pending = collections.Counter()  # under _lock
        self._qos_targets: "dict[tuple, list]" = {}  # under _lock
        self._inflight = 0                # popped cohorts being executed
        self._stop = False
        self._draining = False

        self._compiled: dict = {}          # bucket key -> CompiledFn
        self._compiled_lock = _locks.make_lock("serve.compiled")
        # flush-kernel selection (docs/performance "Serve-bucket kernel
        # selection"): the explicit argument tops the precedence; the
        # memo makes key_fn's per-call re-resolution a dict hit, keyed
        # on (bucket statics, capacity, plan fingerprint) so a plan
        # edit re-resolves while steady-state traffic never recomputes
        self.kernel = kernel
        self._kernel_memo: dict = {}
        self._kernel_memo_fp: Optional[str] = None

        self._stats_lock = _locks.make_lock("serve.stats")
        self._counts = collections.Counter()
        # flush-kernel selection counters (per flush): backend ->
        # flushes served, decline-reason -> flushes that fell back
        self._kernel_sel: "collections.Counter" = collections.Counter()
        self._kernel_dec: "collections.Counter" = collections.Counter()
        self._batch_hist: "collections.Counter" = collections.Counter()
        self._cohort_hist: "collections.Counter" = collections.Counter()
        # sparse-operand intake/flush disaggregation (docs/serving,
        # "Sparse operands on the serve path")
        self._sparse_kernel_sel: "collections.Counter" = \
            collections.Counter()
        self._sparse_nnz_hist: "collections.Counter" = \
            collections.Counter()
        # SRHT/FWHT flush disaggregation (docs/performance, "In-kernel
        # FWHT and compressed matmul")
        self._fwht_sel: "collections.Counter" = collections.Counter()
        # QoS accounting (under _stats_lock): (kind, class, tenant)
        # counters, per-class latency windows, per-bucket adaptive-
        # controller observations (latency window, warm capacity set,
        # padding-waste raw counts, classes seen)
        self._qos_counts: "collections.Counter" = collections.Counter()
        self._latency_by_class: dict = {
            c: collections.deque(maxlen=4096) for c in _qtenants.CLASSES}
        self._bucket_obs: dict = {}
        self._pad_real = 0
        self._pad_total = 0
        self._latency = collections.deque(maxlen=8192)
        # sliding window of flush-attempt outcomes (1.0 = failed): the
        # DEGRADED detector's evidence
        self._health = collections.deque(maxlen=max(int(failure_window), 4))
        # push-side of the health states: the last state published to
        # the resilience hub (fleet routers subscribe); guarded by its
        # own lock so a flush worker and a drain can race a transition
        # without serializing on the executor lock
        self._pub_lock = _locks.make_lock("serve.pub")
        self._published_state = SERVING
        # stateful sketch sessions (docs/sessions): the registry is
        # built lazily on the first session verb — one-shot serving
        # never pays the directory setup
        self._session_registry = None
        # training jobs (docs/training): lazy like the registry — the
        # flusher consults it only once a job has been submitted
        self._train_mgr = None
        # content-addressed result cache + single-flight dedupe
        # (docs/caching): opt-in — the ctor argument wins, else the
        # SKYLARK_CACHE flag. The residency table exists regardless:
        # register_operand must pin on a cache-off replica too (the
        # fleet broadcasts registrations to every replica, and an
        # OperandRef must resolve wherever the request lands).
        if cache is None:
            cache = bool(_env.CACHE.get())
        self._cache = (_rcache.ResultCache(name=self.name,
                                           max_bytes=cache_bytes)
                       if cache else None)
        self._residency = _rcache.ResidencyTable(name=self.name)
        # pipelined dist-serve endpoints (docs/distributed): the local
        # no-fleet coordinator is built lazily; by-replica shard-task
        # counts feed the serve_stats() dist block
        self._dist_local_co = None
        self._dist_by_replica: "collections.Counter" = \
            collections.Counter()

        import queue as _queue

        if dispatch_queue is not None:
            self._workq = dispatch_queue
            self._workers = []        # the queue's owner runs them
        else:
            self._workq = _queue.Queue()
            self._workers = [
                threading.Thread(
                    target=dispatch_loop, args=(self._workq,),
                    name=f"skylark-serve-worker-{i}", daemon=True)
                for i in range(max(int(workers), 1))
            ]
            for t in self._workers:
                t.start()
        self._flusher = threading.Thread(
            target=self._flusher_loop, name="skylark-serve-flusher",
            daemon=True)
        self._flusher.start()
        # the adaptive batching controller (docs/qos): opt-in per
        # executor; SKYLARK_QOS_ADAPT=0 freezes even opted-in ones
        self._controller = None
        if adaptive:
            from libskylark_tpu.qos.controller import AdaptiveController

            self._controller = AdaptiveController(self)
        _EXECUTORS.add(self)

    # ------------------------------------------------------------------
    # submit: request intake
    # ------------------------------------------------------------------

    def submit(self, endpoint: str, /, **kwargs) -> Future:
        """Queue one request; returns a future resolving to exactly what
        the endpoint's sequential API returns. ``timeout`` (seconds,
        default 30) bounds the backpressure wait. ``deadline`` (seconds
        or a :class:`~libskylark_tpu.resilience.Deadline`) bounds the
        request's whole queued life: one that expires before its flush
        executes resolves to :class:`ServeOverloadedError` instead of
        occupying a batch lane (or an isolation retry). ``request_id``
        names the request in the telemetry trace (docs/observability;
        minted automatically when telemetry is on) — it survives the
        cross-thread hop into the flush worker and appears on the flush
        span and every bisection-isolation child span."""
        timeout = kwargs.pop("timeout", 30.0)
        deadline = Deadline.coerce(kwargs.pop("deadline", None))
        rid = kwargs.pop("request_id", None)
        # QoS admission (docs/qos): resolve the tenant to its priority
        # class and charge its token bucket. ``qos_class=`` marks a
        # request the front door (a fleet Router, whose registry holds
        # the token buckets) already admitted — re-charging here would
        # double-bill every routed request.
        tenant = kwargs.pop("tenant", None)
        qos_class = kwargs.pop("qos_class", None)
        if qos_class is None:
            try:
                tenant, qos_class = self._tenants.admit(tenant)
            except _errors.TenantQuotaError as e:
                _cls = self._tenants.resolve(tenant)[1]
                with self._stats_lock:
                    self._qos_counts[
                        ("rate_limited", _cls, e.tenant)] += 1
                _QOS_RATE_LIMITED.inc(
                    **{"class": _cls, "tenant": e.tenant})
                raise
            # cardinality bound: unregistered tenant names account
            # under the anonymous bucket — label sets and per-tenant
            # stats must not grow with arbitrary caller strings. Only
            # applied where the tenant is RESOLVED: a pre-resolved
            # request (qos_class= from a fleet front door) carries a
            # label its router already vetted against ITS registry —
            # a process replica's own registry doesn't know it
            tenant = self._tenants.accounting_name(tenant)
        else:
            qos_class = _qtenants.coerce_class(qos_class)
            tenant = str(tenant) if tenant else ""
        # chaos seam: a plan can deterministically fail admission
        faults.check("qos.admit", tags=faults.current_tags(),
                     detail=f"{endpoint} {tenant or '-'} {qos_class}")
        # internal fast path: the fleet router already derived the
        # bucket statics to pick this replica — reuse them instead of
        # re-deriving (the derivation is the submit hot path's single
        # biggest cost; doing it twice per routed request would tax
        # every fleet submit)
        derived = kwargs.pop("_derived", None)
        digest = kwargs.pop("_digest", None)
        # operand residency (docs/caching): an ``A=`` that is an
        # OperandRef resolves to the pinned bytes before derivation —
        # the ref IS the content hash, so resolution cannot change
        # what the request means, only skip re-shipping it
        if _rcache.is_ref(kwargs.get("A")):
            kwargs["A"] = self._residency.resolve(
                _rcache.as_ref(kwargs["A"]).digest)
        # content-addressed fast paths (docs/caching): pinned results
        # → digest→result cache → single-flight coalescing. All three
        # are skipped wholesale while DEGRADED: a shedding executor
        # must never block intake on cache locks, and a degraded
        # flush path must not populate the cache either (the settle
        # callback re-checks). A front-door digest (``_digest=`` from
        # a fleet router) is reused; otherwise it is derived here —
        # at most once per request, with the derivation shared with
        # the per-endpoint prep below.
        flight = None
        cache_key = None
        if self._cache is not None and not self._is_degraded():
            if digest is None:
                if derived is None:
                    derived = derive_request(
                        endpoint, pad_floor=self.pad_floor, **kwargs)
                digest = request_digest(endpoint, derived, kwargs)
            cache_key = digest
            pinned = self._residency.result(cache_key)
            if pinned is not None:
                self._cache.note_hit(qos_class, pinned)
                return self._bypass_future(qos_class, pinned)
            hit = self._cache.lookup(cache_key, qos_class)
            if hit is not _rcache.MISS:
                return self._bypass_future(qos_class, hit)
            follower = self._cache.join_flight(cache_key, qos_class)
            if follower is not None:
                with self._lock:
                    self._sched.note_bypass(qos_class)
                return follower
        if rid is None and _telemetry.enabled():
            rid = _trace.new_request_id()
        # the submit span covers pack + enqueue; its context (trace id,
        # span id, request id) rides the request into the flush thread
        with _trace.span("serve.submit", attrs={"endpoint": endpoint},
                         request_id=rid) as sp:
            if endpoint == "sketch_apply":
                key, statics, ctx, req = self._prep_sketch(
                    _derived=derived, **kwargs)
            elif endpoint == "fastfood_features":
                key, statics, ctx, req = self._prep_fastfood(
                    _derived=derived, **kwargs)
            elif endpoint == "solve_l2_sketched":
                key, statics, ctx, req = self._prep_solve(
                    _derived=derived, **kwargs)
            elif endpoint == "krr_predict":
                key, statics, ctx, req = self._prep_krr(
                    _derived=derived, **kwargs)
            elif endpoint == "sparse_sketch_apply":
                key, statics, ctx, req = self._prep_sparse_sketch(
                    _derived=derived, **kwargs)
            elif endpoint == "sparse_solve_l2_sketched":
                key, statics, ctx, req = self._prep_sparse_solve(
                    _derived=derived, **kwargs)
            elif endpoint == "graph_ase":
                key, statics, ctx, req = self._prep_graph_ase(
                    _derived=derived, **kwargs)
            elif endpoint == "graph_ppr":
                key, statics, ctx, req = self._prep_graph_ppr(
                    _derived=derived, **kwargs)
            elif endpoint == "condest":
                key, statics, ctx, req = self._prep_condest(
                    _derived=derived, **kwargs)
            elif endpoint == "lowrank":
                key, statics, ctx, req = self._prep_lowrank(
                    _derived=derived, **kwargs)
            elif endpoint == "rlsc_predict":
                key, statics, ctx, req = self._prep_rlsc(
                    _derived=derived, **kwargs)
            elif endpoint == "compressed_matmul":
                key, statics, ctx, req = self._prep_cmm(
                    _derived=derived, **kwargs)
            else:
                raise ValueError(f"unknown serve endpoint {endpoint!r}; "
                                 f"expected one of {ENDPOINTS}")
            req.deadline = deadline
            req.request_id = rid
            req.qos_class = qos_class
            req.tenant = tenant or ""
            if sp is not None:
                req.tctx = sp.context()
            # capture the submitting thread's fault tags so chaos plans
            # can pin a fault to THIS request wherever its cohort
            # executes
            req.tags = faults.current_tags()
            # single-flight leadership spans the whole enqueue: the
            # flight must be joinable BEFORE the request is queued
            # (identical concurrent submits coalesce while the leader
            # lingers), and a synchronous refusal — shed, drain,
            # backpressure timeout — must fan its exception to every
            # follower already attached (no orphaned futures)
            if cache_key is not None:
                flight = self._cache.lead_flight(
                    cache_key, qos_class, req.future)
            try:
                self._enqueue(key, statics, ctx, req, timeout)
            except BaseException as e:
                if flight is not None:
                    self._cache.abort_flight(flight, e)
                raise
            if flight is not None:
                req.future.add_done_callback(
                    lambda f, _fl=flight: self._cache.settle_flight(
                        _fl, f, insert=not self._is_degraded()))
        return req.future

    def submit_sketch(self, transform, A, dimension=None, **kw) -> Future:
        return self.submit("sketch_apply", transform=transform, A=A,
                           dimension=dimension, **kw)

    def submit_fastfood(self, transform, A, **kw) -> Future:
        """Fastfood/RFT feature-map endpoint: resolves to exactly what
        ``transform.apply(A, ROWWISE)`` returns (the (m, S) feature
        map; 1-D input returns (S,))."""
        return self.submit("fastfood_features", transform=transform,
                           A=A, **kw)

    def submit_solve(self, A, B, transform, method: str = "qr",
                     **kw) -> Future:
        return self.submit("solve_l2_sketched", A=A, B=B,
                           transform=transform, method=method, **kw)

    def _note_sparse_intake(self, A) -> bool:
        """Count one sparse submission and decide the densify fallback
        (docs/serving, "Sparse operands on the serve path"): an operand
        at or above ``SKYLARK_SPARSE_MIN_DENSITY`` routes to the dense
        endpoint — at high density the padded CSR lanes carry more
        bytes than the dense operand and the O(nnz) scatter loses to
        the dense contraction. Returns whether to densify."""
        nnz_cls = bucketing.nnz_class(A.nnz,
                                      _env.SPARSE_NNZ_FLOOR.get())
        _SPARSE_SUBMITS.inc_always()
        _SPARSE_NNZ_HIST.observe_always(float(nnz_cls))
        densify = A.density >= _env.SPARSE_MIN_DENSITY.get()
        if densify:
            _SPARSE_DENSIFIED.inc_always()
        with self._stats_lock:
            self._counts["sparse_submits"] += 1
            self._sparse_nnz_hist[nnz_cls] += 1
            if densify:
                self._counts["sparse_densified"] += 1
        return densify

    def submit_sparse(self, transform, A, dimension=None, **kw) -> Future:
        """Sparse (CSR-packed) sketch-apply endpoint: ``A`` is a
        :class:`~libskylark_tpu.base.sparse.SparseMatrix` or
        scipy.sparse operand; resolves to what
        ``transform.apply(A.todense(), dimension)`` returns, as a host
        array — bit-equal to the densified request through the serve
        layer (for CWT that extends to the eager dense apply at any
        shape: the CSR lanes accumulate in the dense scatter's
        row-major order; dense families carry the dense serve
        endpoint's own epsilon band off pow2 stream classes — docs/
        serving, "Sparse operands on the serve path"). Operands at or
        above the auto-densify threshold route through the dense
        serve path (counted as ``sparse_densified``)."""
        A = _coerce_sparse(A)
        if self._note_sparse_intake(A):
            Ad = np.asarray(A.to_scipy().toarray(),
                            dtype=np.dtype(A.device_dtype))
            return self.submit("sketch_apply", transform=transform,
                               A=Ad, dimension=dimension, **kw)
        return self.submit("sparse_sketch_apply", transform=transform,
                           A=A, dimension=dimension, **kw)

    def submit_sparse_solve(self, A, B, transform, method: str = "qr",
                            **kw) -> Future:
        """Sparse sketched least-squares: CSR design matrix ``A``,
        dense target block ``B``; resolves to what
        ``solve_l2_sketched(A.todense(), B, transform)`` returns. Same
        densify fallback rule as :meth:`submit_sparse`."""
        A = _coerce_sparse(A)
        if self._note_sparse_intake(A):
            Ad = np.asarray(A.to_scipy().toarray(),
                            dtype=np.dtype(A.device_dtype))
            return self.submit("solve_l2_sketched", A=Ad, B=B,
                               transform=transform, method=method,
                               **kw)
        return self.submit("sparse_solve_l2_sketched", A=A, B=B,
                           transform=transform, method=method, **kw)

    def submit_krr_predict(self, kernel, X_new, X_train, coef,
                           **kw) -> Future:
        return self.submit("krr_predict", kernel=kernel, X_new=X_new,
                           X_train=X_train, coef=coef, **kw)

    # -- heterogeneous library endpoints (docs/qos) --------------------

    def submit_graph_ase(self, A, k: int, *, seed: int = 0,
                         iters: int = 2, **kw) -> Future:
        """Adjacency spectral embedding endpoint: ``A`` is a
        :class:`~libskylark_tpu.ml.graph.Graph`, SparseMatrix, scipy
        sparse, or dense square adjacency (packed as r18 CSR lanes);
        resolves to the (n, k) embedding host array — bit-equal to
        :func:`~libskylark_tpu.ml.graph.graph_ase_serve` with the
        same seed."""
        return self.submit("graph_ase", A=A, k=k, seed=seed,
                           iters=iters, **kw)

    def submit_graph_ppr(self, A, s, *, alpha: float = 0.85,
                         iters: int = 16, **kw) -> Future:
        """Personalized-PageRank endpoint: ``s`` is the (n,)
        personalization vector in adjacency row order; resolves to
        the (n,) diffusion vector — bit-equal to
        :func:`~libskylark_tpu.ml.graph.graph_ppr_serve`."""
        return self.submit("graph_ppr", A=A, s=s, alpha=alpha,
                           iters=iters, **kw)

    def submit_condest(self, A, *, steps: int = 8, seed: int = 0,
                       **kw) -> Future:
        """Condition-estimation endpoint: fixed-step Golub-Kahan;
        resolves to the ``(cond, sigma_max, sigma_min)`` host (3,)
        array — bit-equal to
        :func:`~libskylark_tpu.nla.condest.condest_serve`."""
        return self.submit("condest", A=A, steps=steps, seed=seed,
                           **kw)

    def submit_lowrank(self, transform_s, transform_t, A, k: int,
                       **kw) -> Future:
        """Dominant-subspace endpoint: two-level sketch basis from a
        matched dense transform pair; resolves to the (n, k) basis —
        bit-equal to
        :func:`~libskylark_tpu.nla.lowrank.lowrank_serve` at pow2
        row classes."""
        return self.submit("lowrank", transform_s=transform_s,
                           transform_t=transform_t, A=A, k=k, **kw)

    def submit_compressed_matmul(self, A, B, transform=None, *,
                                 s_dim: Optional[int] = None,
                                 seed: int = 0, **kw) -> Future:
        """Compressed approximate matmul (docs/performance,
        "In-kernel FWHT and compressed matmul"): estimate ``A @ B``
        from one shared sketch — ``(A Sᵀ)(S B)`` with the SAME
        operator S on both sides, so the estimate is unbiased
        (``E[SᵀS] = I`` for both families). Resolves to
        ``(estimate, bound)``: the (m, p) host estimate and the
        expected-error scale ``‖A‖_F·‖B‖_F·√(2/s)`` (the standard
        sketched-AMM Frobenius bound — an expectation-level scale,
        not a tail guarantee). ``A`` may be dense or CSR (the sparse
        lane sketches straight off the r18 CSR packing for CWT, and
        densifies in-executable for SRHT). Pass a caller-held CWT or
        FJLT/``wht`` transform for seed control, or let ``s_dim``
        (default ``SKYLARK_FWHT_CM_SDIM``) and ``seed`` build one:
        SRHT when the contraction dim is a power of two, CWT
        otherwise."""
        if transform is None:
            transform = default_cmm_transform(A, s_dim=s_dim, seed=seed)
        return self.submit("compressed_matmul", transform=transform,
                           A=A, B=B, **kw)

    def submit_rlsc_predict(self, kernel, X_new, X_train, coef,
                            coding=None, **kw) -> Future:
        """RLSC classification endpoint: argmax over the one-vs-all
        KRR scores; resolves to int32 class indices (decoded to
        labels when ``coding`` is given) — bit-equal to
        :func:`~libskylark_tpu.ml.rlsc.rlsc_predict`."""
        return self.submit("rlsc_predict", kernel=kernel, X_new=X_new,
                           X_train=X_train, coef=coef, coding=coding,
                           **kw)

    # ------------------------------------------------------------------
    # pipelined distributed serve endpoints (docs/distributed)
    # ------------------------------------------------------------------

    def _submit_dist(self, endpoint: str, plan, source, *,
                     tenant=None, qos_class=None, min_coverage=None,
                     deadline=None, timeout=None, request_id=None,
                     pool=None, replicas=None, coordinator=None,
                     pipeline=None, _digest=None, solve=None,
                     digest_extra=()) -> Future:
        """Common path of the dist endpoints: QoS admission, the
        content-addressed fast paths (a dist result is a pure function
        of (source digest, plan fingerprint, seed) — same digest, same
        bits), then a :class:`~libskylark_tpu.dist.serve.DistServeJob`
        driven on a daemon thread under a ``serve.submit`` span whose
        request id parents every ``dist.shard_task`` span."""
        from libskylark_tpu.dist import serve as _dserve
        from libskylark_tpu.dist.coordinator import (
            DistSketchCoordinator)

        plan.validate()
        if source.n < plan.n:
            raise _errors.InvalidParametersError(
                f"source holds {source.n} rows < plan.n={plan.n}")
        rid = request_id
        # QoS admission: same double-billing discipline as submit() —
        # ``qos_class=`` marks a front-door-admitted request
        if qos_class is None:
            try:
                tenant, qos_class = self._tenants.admit(tenant)
            except _errors.TenantQuotaError as e:
                _cls = self._tenants.resolve(tenant)[1]
                with self._stats_lock:
                    self._qos_counts[
                        ("rate_limited", _cls, e.tenant)] += 1
                _QOS_RATE_LIMITED.inc(
                    **{"class": _cls, "tenant": e.tenant})
                raise
            tenant = self._tenants.accounting_name(tenant)
        else:
            qos_class = _qtenants.coerce_class(qos_class)
            tenant = str(tenant) if tenant else ""
        faults.check("qos.admit", tags=faults.current_tags(),
                     detail=f"{endpoint} {tenant or '-'} {qos_class}")
        with self._stats_lock:
            self._counts["dist_jobs"] += 1
        # the effective coverage gate is part of the request's identity:
        # an interactive caller gating at 0.9 and a batch caller gating
        # at 1.0 must never share a cache or single-flight key, or the
        # batch caller could be handed a degraded answer its SLO forbids
        gate = (_dserve.class_min_coverage(qos_class)
                if min_coverage is None else float(min_coverage))
        flight = None
        cache_key = None
        if self._cache is not None and not self._is_degraded():
            cache_key = _digest or _dserve.dist_request_digest(
                endpoint, plan, source,
                extra=(*tuple(digest_extra), ("gate", gate)))
            pinned = self._residency.result(cache_key)
            if pinned is not None:
                self._cache.note_hit(qos_class, pinned)
                return self._bypass_future(qos_class, pinned)
            hit = self._cache.lookup(cache_key, qos_class)
            if hit is not _rcache.MISS:
                return self._bypass_future(qos_class, hit)
            follower = self._cache.join_flight(cache_key, qos_class)
            if follower is not None:
                with self._lock:
                    self._sched.note_bypass(qos_class)
                return follower
        if rid is None and _telemetry.enabled():
            rid = _trace.new_request_id()
        fut: Future = Future()
        with _trace.span("serve.submit", attrs={"endpoint": endpoint},
                         request_id=rid) as sp:
            co = coordinator
            if co is None and (pool is not None
                               or replicas is not None):
                co = DistSketchCoordinator(pool=pool, replicas=replicas)
            if co is None:
                co = self._dist_local_co
                if co is None:
                    with self._lock:
                        if self._dist_local_co is None:
                            self._dist_local_co = \
                                DistSketchCoordinator()
                        co = self._dist_local_co
            job = _dserve.DistServeJob(
                plan, source, coordinator=co, qos_class=qos_class,
                tenant=tenant, registry=self._tenants,
                min_coverage=min_coverage,
                deadline=deadline if deadline is not None else timeout,
                pipeline=pipeline, request_id=rid,
                parent_ctx=sp.context() if sp is not None else None)

            def _settle(j, exc):
                with self._stats_lock:
                    self._counts["dist_completed" if exc is None
                                 else "dist_failed"] += 1
                    if j.stats.get("early_resolved"):
                        self._counts["dist_early_resolves"] += 1
                    for name, k in j.stats.get("by_replica",
                                               {}).items():
                        self._dist_by_replica[name] += k

            if cache_key is not None:
                flight = self._cache.lead_flight(cache_key, qos_class,
                                                 fut)
            try:
                _dserve.run_job_into(job, fut, solve=solve,
                                     on_done=_settle)
            except BaseException as e:
                if flight is not None:
                    self._cache.abort_flight(flight, e)
                raise
            if flight is not None:
                def _insert_ok(f) -> bool:
                    # a degraded result is circumstance (which replicas
                    # died this time), not content — never cache it;
                    # settle_flight still shares it with in-flight
                    # followers of the same gate+digest
                    if self._is_degraded() or f.exception() is not None:
                        return False
                    v = f.result()
                    if isinstance(v, dict):
                        return not v.get("degraded")
                    return not getattr(v, "degraded", False)

                fut.add_done_callback(
                    lambda f, _fl=flight: self._cache.settle_flight(
                        _fl, f, insert=_insert_ok(f)))
        return fut

    def submit_dist_sketch(self, plan, source, **kw) -> Future:
        """Pipelined distributed sketch: shard tasks of ``plan`` fan
        across the coordinator's fleet (``pool=`` / ``replicas=`` /
        ``coordinator=``; with none, a private thread pool pipelines
        shard compute locally) and partials merge incrementally as
        they land. Resolves to the
        :class:`~libskylark_tpu.dist.plan.DistSketchResult` —
        full-coverage bits equal to
        :func:`~libskylark_tpu.dist.plan.sketch_local`. Per-class
        ``min_coverage`` gates apply (docs/qos): interactive requests
        may resolve early with a quantified
        :class:`~libskylark_tpu.dist.plan.DegradedSketchResult`."""
        return self._submit_dist("dist_sketch", plan, source, **kw)

    def submit_dist_lstsq(self, source, *, s_dim: int, seed: int = 0,
                          kind: str = "cwt", shard_rows: int = 0,
                          **kw) -> Future:
        """Distributed sketch-and-solve least squares
        (:func:`~libskylark_tpu.dist.algorithms.sketched_lstsq` as a
        serve endpoint): the joint ``[X | Y]`` sketch streams through
        the fleet, only the local ``s_dim`` system solves here.
        Resolves to the same ``{"coef", "coverage", "missing",
        "degraded"}`` dict."""
        from libskylark_tpu.dist import serve as _dserve
        from libskylark_tpu.dist.algorithms import lstsq_plan

        plan = lstsq_plan(source, s_dim=s_dim, seed=seed, kind=kind,
                          shard_rows=shard_rows)
        return self._submit_dist("dist_lstsq", plan, source,
                                 solve=_dserve.solve_lstsq, **kw)

    def submit_dist_svd(self, source, rank: int, *, s_dim=None,
                        seed: int = 0, kind: str = "jlt",
                        shard_rows: int = 0, **kw) -> Future:
        """Distributed randomized SVD
        (:func:`~libskylark_tpu.dist.algorithms.randomized_svd` as a
        serve endpoint): resolves to the same ``{"singular_values",
        "Vt", "coverage", "missing", "degraded"}`` dict."""
        from libskylark_tpu.dist import serve as _dserve
        from libskylark_tpu.dist.algorithms import svd_plan

        plan = svd_plan(source, rank, s_dim=s_dim, seed=seed,
                        kind=kind, shard_rows=shard_rows)
        return self._submit_dist(
            "dist_svd", plan, source,
            solve=lambda r: _dserve.solve_svd(r, rank),
            digest_extra=(("rank", int(rank)),), **kw)

    # ------------------------------------------------------------------
    # stateful sketch sessions (docs/sessions)
    # ------------------------------------------------------------------

    @property
    def sessions(self):
        """This executor's :class:`~libskylark_tpu.sessions.registry
        .SessionRegistry` (built on first use; every executor in a
        host shares the ``SKYLARK_SESSION_DIR`` root, which is what
        makes drain handoff and crash replay possible)."""
        if self._session_registry is None:
            from libskylark_tpu.sessions import SessionRegistry

            with self._lock:
                if self._session_registry is None:
                    self._session_registry = SessionRegistry(
                        name=self.name)
        return self._session_registry

    def open_sketch_session(self, kind: str, *, n: int, s_dim: int,
                            d: int, seed: int = 0,
                            dtype: str = "float32", targets: int = 0,
                            k: int = 0, lam: float = 1e-3,
                            sigma: float = 1.0,
                            ttl_s: Optional[float] = None,
                            session_id: Optional[str] = None) -> str:
        """Open a stateful sketch session and return its id. ``kind``
        is one of :data:`libskylark_tpu.sessions.KINDS` (``cwt`` /
        ``jlt`` / ``srht`` row-batch appenders, ``isvd`` incremental
        randomized SVD, ``krr`` online KRR); the remaining arguments
        are the :class:`~libskylark_tpu.sessions.SessionSpec` fields.
        Refused (like any intake) on a draining/stopped executor."""
        from libskylark_tpu.sessions import SessionSpec

        with self._lock:
            self._refuse_if_unavailable_locked()
        spec = SessionSpec(kind=kind, n=int(n), s_dim=int(s_dim),
                           d=int(d), seed=int(seed), dtype=str(dtype),
                           targets=int(targets), k=int(k),
                           lam=float(lam), sigma=float(sigma),
                           ttl_s=ttl_s)
        return self.sessions.open(spec, session_id=session_id)

    def session_append(self, session_id: str, X, Y=None,
                       seq: Optional[int] = None,
                       deadline=None) -> Future:
        """Fold one row batch into a session; the returned future
        resolves to ``(seq, rows)`` only after the append is journaled
        (durable) AND folded. Duplicate sequence numbers resolve to
        the current position as a no-op (crash-retry idempotency).
        Shedding (all resolved on the future, never raised here):
        DRAINING refuses; DEGRADED sheds session appends *before*
        interactive traffic (streaming is the best-effort class — the
        client owns the journal replay story, an interactive caller
        does not); an expired ``deadline`` resolves to
        :class:`ServeOverloadedError` without journaling; an evicted
        or unknown session resolves to :class:`~libskylark_tpu.base
        .errors.SessionEvictedError`."""
        fut: Future = Future()
        try:
            with self._lock:
                self._refuse_if_unavailable_locked()
            if self._is_degraded():
                with self._stats_lock:
                    self._counts["session_shed"] += 1
                raise ServeOverloadedError(
                    "executor DEGRADED: session appends shed before "
                    "interactive traffic")
            dl = Deadline.coerce(deadline)
            if dl is not None and dl.expired:
                with self._stats_lock:
                    self._counts["expired"] += 1
                raise ServeOverloadedError(
                    "session append deadline expired before execution")
            out = self.sessions.append(
                session_id, X, Y=Y, seq=seq,
                tags=faults.current_tags())
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — resolve, don't leak
            fut.set_exception(e)
            return fut
        fut.set_result(out)
        return fut

    def session_finalize(self, session_id: str) -> Future:
        """Terminal result of a session (the maintained sketch / the
        iSVD factors / the KRR coefficients); the session's artifacts
        are removed and its id tombstoned. Resolves to
        :class:`~libskylark_tpu.base.errors.SessionEvictedError` for
        an evicted/unknown id — never hangs."""
        fut: Future = Future()
        try:
            with self._lock:
                if self._stop:
                    raise RuntimeError(
                        "MicrobatchExecutor is shut down")
            out = self.sessions.finalize(session_id)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)
            return fut
        fut.set_result(out)
        return fut

    def _checkpoint_sessions(self) -> None:
        """Drain-path hook: checkpoint every live session synchronously
        (journal fsync + accumulator snapshot) so a peer resumes from
        state instead of a full journal replay. No-op when this
        executor never opened a session. Training sessions are
        sessions — a drain checkpoints them here, and the flusher has
        already stopped offering their slices (the draining guard), so
        a resuming peer continues bit-equal from this snapshot."""
        reg = self._session_registry
        if reg is not None:
            reg.checkpoint_all()

    # -- training jobs (docs/training) ----------------------------------

    @property
    def train_jobs(self):
        """This executor's :class:`~libskylark_tpu.train.jobs
        .TrainManager` (built on first use, like :attr:`sessions`)."""
        if self._train_mgr is None:
            from libskylark_tpu.train.jobs import TrainManager

            with self._lock:
                if self._train_mgr is None:
                    self._train_mgr = TrainManager(self)
        return self._train_mgr

    def _wake_flusher(self) -> None:
        """Nudge the flusher: training work became runnable (submit,
        resume, or a requeued slice) and the fast-path submit routes
        never signal ``_work_cv`` for it."""
        with self._lock:
            self._work_cv.notify_all()

    def submit_train_job(self, spec, operands: Optional[dict] = None,
                         *, session_id: Optional[str] = None):
        """Submit a training job (docs/training): the job's operands
        and session open durably here, then its slices run as
        best-effort work in idle scheduler slots. Returns a
        :class:`~libskylark_tpu.train.jobs.TrainJobHandle` whose
        future resolves to the trained model — or raises
        :class:`~libskylark_tpu.base.errors.TrainBudgetExhaustedError`
        with exact progress when the iteration/deadline budget runs
        out first. Refused on a draining/stopped executor; shed (like
        session appends) on a DEGRADED one — training is the
        definitionally-preemptible class."""
        with self._lock:
            self._refuse_if_unavailable_locked()
        if self._is_degraded():
            with self._stats_lock:
                self._counts["train_shed"] += 1
            raise ServeOverloadedError(
                "executor DEGRADED: train submits shed before "
                "interactive traffic")
        return self.train_jobs.submit(spec, operands=operands,
                                      session_id=session_id)

    def resume_train_job(self, session_id: str):
        """Adopt a training job from its on-disk session (drain
        handoff / crash replay) and continue running its slices here.
        Same availability gates as submit."""
        with self._lock:
            self._refuse_if_unavailable_locked()
        return self.train_jobs.resume(session_id)

    def train_job_status(self, session_id: str) -> dict:
        """Progress snapshot of a job live on this executor (raises
        :class:`~libskylark_tpu.base.errors.SessionEvictedError` when
        it is not)."""
        mgr = self._train_mgr
        if mgr is None:
            raise _errors.SessionEvictedError(
                f"train job {session_id!r} is not live on this "
                "replica (no jobs were ever submitted here)")
        return mgr.status(session_id)

    # -- result cache + operand residency (docs/caching) ---------------

    def _bypass_future(self, cls: str, value) -> Future:
        """A request satisfied without a dispatch (pinned result or
        cache hit): an already-resolved future holding the shared
        read-only value, noted in the scheduler's fairness ledger so
        a hot cached class never LOOKS starved next to its goodput."""
        with self._lock:
            self._sched.note_bypass(cls)
        f: Future = Future()
        f.set_result(value)
        return f

    def register_operand(self, A, transform=None, dimension=None,
                         **kw) -> "_rcache.OperandRef":
        """Content-hash ``A``, pin it resident, and return its
        :class:`~libskylark_tpu.engine.resultcache.OperandRef`. Later
        submits may pass the ref as the ``A=`` operand of any dense
        endpoint and the executor substitutes the pinned bytes — no
        re-shipping, and (with the cache on) the request digest is
        identical to submitting the raw bytes, so ref and raw callers
        share one cache line. A fleet Router broadcasts registrations
        so every replica resolves the ref locally (docs/fleet).

        With ``transform=`` the operand is sketched ONCE — an
        ordinary submit: admission, QoS and chaos all apply — and the
        result pinned under the request digest, outside the byte
        quotas: every later ``submit_sketch(transform, ref)`` (or the
        same raw bytes) skips the sketch stage entirely, cache
        evictions notwithstanding. Pins live until
        :meth:`unregister_operand`; re-registering identical bytes is
        a no-op (the digest IS the bytes)."""
        A = np.asarray(A)
        d = _rcache.operand_digest([("A", A)])
        self._residency.pin(d, A)
        ref = _rcache.OperandRef(d)
        if transform is not None:
            value = self.submit(
                "sketch_apply", transform=transform, A=A,
                dimension=dimension, **kw).result()
            derived = derive_request(
                "sketch_apply", pad_floor=self.pad_floor,
                transform=transform, A=A, dimension=dimension)
            rd = request_digest(
                "sketch_apply", derived,
                {"transform": transform, "A": A,
                 "dimension": dimension})
            self._residency.pin_result(rd, value, owner=d)
        return ref

    def unregister_operand(self, ref) -> bool:
        """Unpin a registered operand — and every result pinned with
        it. Returns whether it was resident. In-cache entries for the
        operand's requests survive (they are ordinary quota-bounded
        entries); only the pins go."""
        return self._residency.unpin(_rcache.as_ref(ref).digest)

    def resident_operands(self) -> list:
        """Digests of the operands currently pinned here, sorted."""
        return self._residency.digests()

    def _cache_stats_block(self) -> Optional[dict]:
        """The ``stats()["cache"]`` block: the cache's own counters
        plus the residency sub-block; ``None`` on a cache-off executor
        with nothing pinned (the common case must not grow every
        stats dump)."""
        res = self._residency.stats()
        if self._cache is None:
            if not res["resident_operands"] and not res["pinned_results"]:
                return None
            return {"residency": res}
        blk = self._cache.stats()
        blk["residency"] = res
        return blk

    # -- per-endpoint packing -----------------------------------------

    @staticmethod
    def _key_data(transform) -> np.ndarray:
        """Raw key data of the transform's allocation, cached on the
        transform — submit is on the request hot path and the key
        derivation is a (host-synced) jax op worth paying once per
        transform, not once per request."""
        kd = getattr(transform, "_serve_key_data", None)
        if kd is None:
            import jax.random as jr

            kd = np.asarray(jr.key_data(transform.allocation.key),
                            dtype=np.uint32)
            try:
                transform._serve_key_data = kd
            except Exception:
                pass
        return kd

    def _prep_sketch(self, transform, A, dimension=None, _derived=None):
        statics, info = _derived or _sketch_statics(
            transform, A, dimension, self.pad_floor)
        A = info["A"]
        ctx = {"dist": info["dist"], "family": info["family"],
               "s_dim": transform.sketch_dim, "rowwise": info["rowwise"],
               "padded": info["padded"], "dtype": str(A.dtype)}
        req = _Request(
            endpoint="sketch_apply",
            arrays={"kd": self._key_data(transform),
                    "scale": np.asarray(getattr(transform, "scale", 1.0),
                                        dtype=A.dtype),
                    "A": A},
            true_shapes={"A": A.shape},
            meta={"padded": info["padded"], "rowwise": info["rowwise"],
                  "s_dim": transform.sketch_dim},
        )
        return statics, statics, ctx, req

    def _prep_cmm(self, transform, A, B, _derived=None):
        statics, info = _derived or _cmm_statics(
            transform, A, B, self.pad_floor)
        A, B = info["A"], info["B"]
        dtype = np.dtype(info["dtype"])
        ctx = {"family": info["family"], "s_dim": info["s_dim"],
               "sparse": info["sparse"], "padded_A": info["padded_A"],
               "padded_B": info["padded_B"],
               "nnz_class": info["nnz_class"], "dtype": info["dtype"]}
        arrays = {"kd": self._key_data(transform),
                  "B": B.astype(dtype, copy=False)}
        if info["sparse"]:
            data, idx, ptr = self._pack_csr(
                A, info["padded_A"][0], info["nnz_class"], dtype)
            arrays.update(data=data, indices=idx, indptr=ptr)
            true_shapes = {"data": (A.nnz,), "B": B.shape}
        else:
            arrays["A"] = A.astype(dtype, copy=False)
            true_shapes = {"A": A.shape, "B": B.shape}
        _CM_SUBMITS.inc_always()
        with self._stats_lock:
            self._counts["cm_submits"] += 1
        req = _Request(
            endpoint="compressed_matmul",
            arrays=arrays,
            true_shapes=true_shapes,
            meta={"m": info["m"], "p": info["p"],
                  "bound": info["bound"],
                  "padded_A": info["padded_A"]},
        )
        return statics, statics, ctx, req

    def _prep_fastfood(self, transform, A, _derived=None):
        statics, info = _derived or _fastfood_statics(
            transform, A, self.pad_floor)
        A = info["A"]
        ctx = {"family": info["family"], "fut": info["fut"],
               "sm_kind": info["sm_kind"], "sm_param": info["sm_param"],
               "n_dim": A.shape[1], "s_dim": transform.sketch_dim,
               "padded": (info["m_pad"], A.shape[1]),
               "dtype": str(A.dtype)}
        req = _Request(
            endpoint="fastfood_features",
            arrays={"kd": self._key_data(transform), "A": A},
            true_shapes={"A": A.shape},
            meta={"padded": (info["m_pad"], A.shape[1]),
                  "m": A.shape[0], "squeeze": info["squeeze"]},
        )
        return statics, statics, ctx, req

    def _prep_solve(self, A, B, transform, method: str = "qr",
                    _derived=None):
        statics, info = _derived or _solve_statics(
            transform, A, B, method, self.pad_floor)
        A, B, n_pad = info["A"], info["B"], info["n_pad"]
        ctx = {"family": info["family"], "s_dim": transform.sketch_dim,
               "method": method}
        req = _Request(
            endpoint="solve_l2_sketched",
            arrays={"kd": self._key_data(transform),
                    "scale": np.asarray(getattr(transform, "scale", 1.0),
                                        dtype=A.dtype),
                    "A": A, "B": B.astype(A.dtype, copy=False)},
            true_shapes={"A": A.shape, "B": B.shape},
            meta={"padded_A": (n_pad, A.shape[1]),
                  "padded_B": (n_pad, B.shape[1]),
                  "squeeze": info["squeeze"]},
        )
        return statics, statics, ctx, req

    @staticmethod
    def _pack_csr(A, rows_pad: int, nnz_class: int, dtype):
        """One request's padded (data, indices, indptr) CSR lanes:
        data/indices zero-padded to the nnz class (value 0.0 at a
        clamped coordinate — exact no-ops through every sparse
        endpoint), indptr monotone-padded with the true nnz to the
        padded row extent (so the in-executable row-id expansion stays
        a valid binary search; docs/serving)."""
        data, indices, indptr = A.csr_parts(dtype)
        nnz = len(data)
        d = np.zeros(int(nnz_class), dtype=dtype)
        d[:nnz] = data
        idx = np.zeros(int(nnz_class), dtype=np.int32)
        idx[:nnz] = indices
        ptr = np.full(int(rows_pad) + 1, nnz, dtype=np.int32)
        ptr[: len(indptr)] = indptr
        return d, idx, ptr

    def _prep_sparse_sketch(self, transform, A, dimension=None,
                            _derived=None):
        statics, info = _derived or _sparse_sketch_statics(
            transform, A, dimension, self.pad_floor)
        A = info["A"]
        dtype = np.dtype(info["dtype"])
        data, idx, ptr = self._pack_csr(
            A, info["padded"][0], info["nnz_class"], dtype)
        ctx = {"dist": info["dist"], "family": info["family"],
               "s_dim": transform.sketch_dim,
               "rowwise": info["rowwise"], "padded": info["padded"],
               "nnz_class": info["nnz_class"], "dtype": info["dtype"]}
        req = _Request(
            endpoint="sparse_sketch_apply",
            arrays={"kd": self._key_data(transform),
                    "scale": np.asarray(
                        getattr(transform, "scale", 1.0), dtype=dtype),
                    "data": data, "indices": idx, "indptr": ptr},
            true_shapes={"data": (A.nnz,)},
            meta={"padded": info["padded"],
                  "rowwise": info["rowwise"],
                  "s_dim": transform.sketch_dim,
                  "shape": A.shape, "nnz": A.nnz},
        )
        return statics, statics, ctx, req

    def _prep_sparse_solve(self, A, B, transform, method: str = "qr",
                           _derived=None):
        statics, info = _derived or _sparse_solve_statics(
            transform, A, B, method, self.pad_floor)
        A, B, n_pad = info["A"], info["B"], info["n_pad"]
        dtype = np.dtype(info["dtype"])
        data, idx, ptr = self._pack_csr(A, n_pad, info["nnz_class"],
                                        dtype)
        ctx = {"family": info["family"],
               "s_dim": transform.sketch_dim, "method": method,
               "padded_A": (n_pad, A.width),
               "nnz_class": info["nnz_class"], "dtype": info["dtype"]}
        req = _Request(
            endpoint="sparse_solve_l2_sketched",
            arrays={"kd": self._key_data(transform),
                    "scale": np.asarray(
                        getattr(transform, "scale", 1.0), dtype=dtype),
                    "data": data, "indices": idx, "indptr": ptr,
                    "B": B.astype(dtype, copy=False)},
            true_shapes={"data": (A.nnz,), "B": B.shape},
            meta={"padded_B": (n_pad, B.shape[1]),
                  "nnz": A.nnz, "squeeze": info["squeeze"]},
        )
        return statics, statics, ctx, req

    def _prep_krr(self, kernel, X_new, X_train, coef, _derived=None):
        import jax.numpy as jnp

        statics, info = _derived or _krr_statics(
            kernel, X_new, X_train, coef, self.pad_floor)
        X_new, squeeze_q, q_pad = (info["X_new"], info["squeeze_q"],
                                   info["q_pad"])
        # model identity is taken from the objects the CALLER holds,
        # before any conversion: a server submitting the same numpy
        # model on every request must keep coalescing into one bucket
        # (the converted arrays would have a fresh id per submit)
        model_ids = (id(X_train), id(coef))
        model_refs = (X_train, coef)
        X_train = jnp.asarray(X_train)
        coef = jnp.asarray(coef)
        squeeze_t = coef.ndim == 1
        if squeeze_t:
            coef = coef[:, None]
        # model identity separates buckets (cohorts must not mix
        # models) but stays OUT of the engine key: two models with the
        # same shapes share one executable. The bucket ctx pins the
        # caller's original objects so their ids stay valid for the
        # bucket's lifetime.
        key = statics + model_ids
        ctx = {"kernel": kernel, "X_train": X_train, "coef": coef,
               "model_refs": model_refs}
        req = _Request(
            endpoint="krr_predict",
            arrays={"Xq": X_new},
            true_shapes={"Xq": X_new.shape},
            meta={"padded": (q_pad, X_new.shape[1]),
                  "q": X_new.shape[0],
                  "squeeze_q": squeeze_q, "squeeze_t": squeeze_t},
        )
        return key, statics, ctx, req

    def _prep_graph_ase(self, A, k, seed=0, iters=2, _derived=None):
        statics, info = _derived or _graph_ase_statics(
            A, k, iters, self.pad_floor)
        S = info["A"]
        dtype = np.dtype(info["dtype"])
        data, idx, ptr = self._pack_csr(
            S, info["padded"][0], info["nnz_class"], dtype)
        ctx = {"k": info["k"], "iters": info["iters"],
               "padded": info["padded"],
               "nnz_class": info["nnz_class"], "dtype": info["dtype"]}
        req = _Request(
            endpoint="graph_ase",
            arrays={"kd": _seed_key_data(int(seed)),
                    "data": data, "indices": idx, "indptr": ptr},
            true_shapes={"data": (S.nnz,)},
            meta={"n": S.height, "k": info["k"]},
        )
        return statics, statics, ctx, req

    def _prep_graph_ppr(self, A, s, alpha=0.85, iters=16,
                        _derived=None):
        statics, info = _derived or _graph_ppr_statics(
            A, s, alpha, iters, self.pad_floor)
        S, s = info["A"], info["s"]
        dtype = np.dtype(info["dtype"])
        data, idx, ptr = self._pack_csr(
            S, info["padded"][0], info["nnz_class"], dtype)
        ctx = {"alpha": info["alpha"], "iters": info["iters"],
               "padded": info["padded"],
               "nnz_class": info["nnz_class"], "dtype": info["dtype"]}
        req = _Request(
            endpoint="graph_ppr",
            arrays={"data": data, "indices": idx, "indptr": ptr,
                    "s": s},
            true_shapes={"data": (S.nnz,)},
            meta={"n": S.height},
        )
        return statics, statics, ctx, req

    def _prep_condest(self, A, steps=8, seed=0, _derived=None):
        statics, info = _derived or _condest_statics(
            A, steps, self.pad_floor)
        A = info["A"]
        ctx = {"steps": info["steps"], "padded": info["padded"],
               "dtype": str(A.dtype)}
        req = _Request(
            endpoint="condest",
            arrays={"kd": _seed_key_data(int(seed)), "A": A},
            true_shapes={"A": A.shape},
            meta={"padded": info["padded"]},
        )
        return statics, statics, ctx, req

    def _prep_lowrank(self, transform_s, transform_t, A, k,
                      _derived=None):
        statics, info = _derived or _lowrank_statics(
            transform_s, transform_t, A, k, self.pad_floor)
        A = info["A"]
        kd_s, sc_s = _lowrank_key_data(transform_s, A.dtype)
        kd_t, sc_t = _lowrank_key_data(transform_t, A.dtype)
        ctx = {"dist": info["dist"], "k": info["k"],
               "s_dim": transform_s.sketch_dim,
               "t_dim": transform_t.sketch_dim,
               "padded": info["padded"]}
        req = _Request(
            endpoint="lowrank",
            arrays={"kd_s": kd_s, "scale_s": sc_s,
                    "kd_t": kd_t, "scale_t": sc_t, "A": A},
            true_shapes={"A": A.shape},
            meta={"padded": info["padded"], "m": A.shape[0],
                  "k": info["k"]},
        )
        return statics, statics, ctx, req

    def _prep_rlsc(self, kernel, X_new, X_train, coef, coding=None,
                   _derived=None):
        import jax.numpy as jnp

        statics, info = _derived or _krr_statics(
            kernel, X_new, X_train, coef, self.pad_floor,
            endpoint="rlsc_predict")
        X_new, squeeze_q, q_pad = (info["X_new"], info["squeeze_q"],
                                   info["q_pad"])
        # same model-identity rule as krr_predict: ids of the CALLER's
        # objects separate buckets, converted arrays live in the ctx
        model_ids = (id(X_train), id(coef))
        model_refs = (X_train, coef)
        X_train = jnp.asarray(X_train)
        coef = jnp.asarray(coef)
        if coef.ndim == 1:
            coef = coef[:, None]
        key = statics + model_ids
        ctx = {"kernel": kernel, "X_train": X_train, "coef": coef,
               "model_refs": model_refs}
        req = _Request(
            endpoint="rlsc_predict",
            arrays={"Xq": X_new},
            true_shapes={"Xq": X_new.shape},
            meta={"padded": (q_pad, X_new.shape[1]),
                  "q": X_new.shape[0], "squeeze_q": squeeze_q,
                  "coding": (list(coding)
                             if coding is not None else None)},
        )
        return key, statics, ctx, req

    # ------------------------------------------------------------------
    # queueing + flushing
    # ------------------------------------------------------------------

    def _refuse_if_unavailable_locked(self) -> None:
        """Reject intake into a draining/stopped executor (caller holds
        ``_lock``). Draining is a load-shed (the caller should
        re-resolve to a healthy replica); a plain shutdown is a
        programming error."""
        if self._draining:
            with self._stats_lock:
                self._counts["shed"] += 1
            raise ServeOverloadedError(
                "executor is draining (preemption) — request refused")
        if self._stop:
            raise RuntimeError("MicrobatchExecutor is shut down")

    def _class_shed_bound(self, cls: str) -> int:
        """DEGRADED shed bound (queued + in-flight requests) of one
        priority class: ``max_queue x the class's shed fraction``,
        scaled by the executor's ``shed_fraction`` argument relative
        to the standard class's *declared default* (0.25) — so the
        pre-QoS ctor knob still moves all three bounds together while
        each ``SKYLARK_QOS_SHED_*`` env knob moves exactly its own
        class (scaling by the LIVE standard value would make the
        standard knob a no-op and inversely rescale the others)."""
        scale = self.shed_fraction / float(
            _env.QOS_SHED_STANDARD.default)
        return max(1, int(self.max_queue
                          * _qtenants.shed_fraction(cls) * scale))

    def _note_shed(self, req: _Request) -> None:
        with self._stats_lock:
            self._counts["shed"] += 1
            self._qos_counts[("shed", req.qos_class, req.tenant)] += 1
        _QOS_SHED.inc(**{"class": req.qos_class,
                         "tenant": req.tenant})

    def _enqueue(self, key, statics, ctx, req, timeout) -> None:
        deadline = time.monotonic() + (timeout if timeout else 0)
        degraded = self._is_degraded()
        cls = req.qos_class
        # the per-class queue is the bucket itself: class rides the
        # bucket KEY (same statics = same executable, the class only
        # separates queues so the deficit scheduler can order them)
        key = tuple(key) + (cls,)
        shed_bound = self._class_shed_bound(cls)
        pressure = _qtenants.PRESSURE_FRACTIONS.get(cls, 1.0)
        with self._lock:
            self._refuse_if_unavailable_locked()
            exposure = self._pending + self._inflight
            if degraded and exposure >= shed_bound:
                # DEGRADED load shed, class-ordered (docs/qos): reject
                # immediately at the class's reduced bound instead of
                # letting callers linger behind a failing flush path —
                # best_effort's bound is the smallest, so it sheds
                # FIRST; interactive's is the largest, so it sheds
                # LAST. The bound counts queued AND in-flight requests
                # — the full-cohort fast path moves work straight to
                # the workers, so a queued-only count would let a
                # max_batch-sized burst bypass the shed
                self._note_shed(req)
                raise ServeOverloadedError(
                    f"load shed: executor DEGRADED and exposure at "
                    f"{exposure} >= {cls} shed bound {shed_bound}")
            if pressure < 1.0 and exposure >= max(
                    1, int(self.max_queue * pressure)):
                # queue-pressure shed: a best_effort storm stops
                # admitting at its fractional bound even on a HEALTHY
                # executor, so it can never fill the queue against
                # standard/interactive traffic (the global-shed
                # unfairness fix — the regression test pins that one
                # best_effort storm never sheds a concurrent
                # interactive request)
                self._note_shed(req)
                raise ServeOverloadedError(
                    f"load shed: {cls} exposure at {exposure} >= "
                    f"pressure bound {int(self.max_queue * pressure)}")
            while self._pending >= self.max_queue:
                wait = deadline - time.monotonic() if timeout else None
                if timeout and wait <= 0:
                    with self._stats_lock:
                        self._counts["rejected"] += 1
                    raise ServeOverloadedError(
                        f"serve queue at bound ({self.max_queue}) for "
                        f"{timeout}s")
                if not self._space_cv.wait(timeout=wait):
                    with self._stats_lock:
                        self._counts["rejected"] += 1
                    raise ServeOverloadedError(
                        f"serve queue at bound ({self.max_queue}) for "
                        f"{timeout}s")
                self._refuse_if_unavailable_locked()
            # a waiter woken by the queue draining may reacquire the
            # lock only AFTER a drain/shutdown completed — appending
            # then would strand the future in a bucket no flusher will
            # ever pop, so the availability check repeats at loop exit
            self._refuse_if_unavailable_locked()
            b = self._buckets.get(key)
            if b is None:
                b = self._buckets[key] = _Bucket(key=key, statics=statics,
                                                ctx=ctx, qos_class=cls)
            b.reqs.append(req)
            self._pending += 1
            self._class_pending[cls] += 1
            _QOS_QUEUE_DEPTH.set(float(self._class_pending[cls]),
                                 **{"class": cls,
                                    "replica": self.name})
            with self._stats_lock:
                self._counts["submitted"] += 1
                self._counts["queued_peak"] = max(
                    self._counts["queued_peak"], self._pending)
                self._qos_counts[("admitted", cls, req.tenant)] += 1
            _QOS_ADMITTED.inc(**{"class": cls, "tenant": req.tenant})
            # full-cohort fast path: hand the cohort straight to the
            # worker queue instead of waking the flusher thread to
            # rediscover it — one less wakeup/context switch on the
            # max_batch steady state (the flusher still owns linger
            # expiry, drain, and partial flushes). The put must stay
            # under the lock: popped outside it, a racing shutdown()
            # could post the worker-poisoning sentinels between our
            # pop and put (the cohort is no longer in _buckets, so
            # the flusher sees nothing left), stranding every future
            # in the cohort behind workers that already exited —
            # under the lock, FIFO orders the work ahead of the
            # sentinels. The queue is unbounded, so put cannot block.
            # ... and it is QoS-gated: a full best_effort cohort must
            # not jump the workers ahead of queued interactive work —
            # the fast path only fires when no strictly-higher class
            # has pending requests (then the scheduler's order is
            # trivially respected); otherwise the flusher's deficit
            # round-robin decides
            ci = _qtenants.CLASSES.index(cls)
            higher_pending = any(
                self._class_pending.get(c, 0) > 0
                for c in _qtenants.CLASSES[:ci])
            work = (self._pop_cohort_locked(key)
                    if (len(b.reqs) >= self._bucket_cap_locked(statics)
                        and not higher_pending)
                    else None)
            if work is None:
                self._work_cv.notify_all()
            else:
                self._sched.charge(cls, len(work[1]))
                self._workq.put((self, work))

    def _bucket_targets_locked(self, statics: tuple) -> tuple:
        """(linger seconds, cohort cap) of one bucket — the static
        config unless the adaptive controller retuned it (caller
        holds ``_lock``)."""
        t = self._qos_targets.get(statics)
        if t is None:
            return self.linger, self.max_batch
        return float(t[0]), int(t[1])

    def _bucket_cap_locked(self, statics: tuple) -> int:
        t = self._qos_targets.get(statics)
        return self.max_batch if t is None else int(t[1])

    def bucket_targets(self, statics) -> tuple:
        """Public (linger_s, batch_cap) view of one bucket's live
        targets (the adaptive controller's read side)."""
        with self._lock:
            return self._bucket_targets_locked(tuple(statics))

    def set_bucket_targets(self, statics, *, linger_s=None,
                           batch_cap=None) -> None:
        """Retune one bucket (the adaptive controller's write side).
        ``batch_cap`` clamps to [1, max_batch] — the compiled
        capacity ladder's roof — and the flusher re-evaluates
        immediately (a shortened linger must fire now, not at the old
        expiry)."""
        statics = tuple(statics)
        with self._lock:
            cur = list(self._bucket_targets_locked(statics))
            if linger_s is not None:
                cur[0] = max(float(linger_s), 0.0)
            if batch_cap is not None:
                cur[1] = max(1, min(int(batch_cap), self.max_batch))
            self._qos_targets[statics] = cur
            self._work_cv.notify_all()

    def _pop_cohort_locked(self, key) -> Optional[tuple]:
        b = self._buckets.get(key)
        if b is None or not b.reqs:
            return None
        cap = self._bucket_cap_locked(b.statics)
        cohort = b.reqs[:cap]
        b.reqs = b.reqs[cap:]
        if not b.reqs:
            del self._buckets[key]
        self._pending -= len(cohort)
        self._class_pending[b.qos_class] -= len(cohort)
        _QOS_QUEUE_DEPTH.set(
            float(max(self._class_pending[b.qos_class], 0)),
            **{"class": b.qos_class, "replica": self.name})
        self._inflight += 1
        self._space_cv.notify_all()
        return (b, cohort)

    def _cohort_done_locked(self) -> None:
        self._inflight -= 1
        if self._pending == 0 and self._inflight == 0:
            self._idle_cv.notify_all()

    def _flusher_loop(self) -> None:
        """Linger expiry + weighted-fair dispatch (docs/qos): ready
        cohorts (full, lingered out, or flushed by drain/stop) are
        grouped by priority class and the deficit scheduler picks
        which class dispatches next — the replacement for the pre-QoS
        dict-order drain. Within a class, the oldest bucket goes
        first (FIFO per class). Linger and cohort caps are
        per-bucket: the adaptive controller's targets, falling back
        to the static config."""
        while True:
            work = None
            with self._lock:
                if self._stop and not self._buckets:
                    break
                now = time.monotonic()
                wait = None
                ready: dict = {}          # class -> oldest ready key
                for key in list(self._buckets):
                    b = self._buckets[key]
                    linger, cap = self._bucket_targets_locked(b.statics)
                    full = len(b.reqs) >= cap
                    expired = now - b.oldest >= linger
                    if full or expired or self._stop or self._draining:
                        prev = ready.get(b.qos_class)
                        if (prev is None or b.oldest
                                < self._buckets[prev].oldest):
                            ready[b.qos_class] = key
                    else:
                        w = b.oldest + linger - now
                        wait = w if wait is None else min(wait, w)
                # training slices ride the same scheduler pass as
                # best-effort backlog (docs/training) — but only when
                # no higher class has pending work: idle slots feed
                # training, a single interactive request displaces it
                # at the next slice boundary
                train_mgr = self._train_mgr
                if (train_mgr is not None and not self._stop
                        and not self._draining
                        and train_mgr.has_runnable()):
                    higher = any(
                        self._class_pending.get(c, 0) > 0
                        for c in _qtenants.CLASSES
                        if c != _qtenants.BEST_EFFORT)
                    if higher:
                        train_mgr.note_deferred()
                    elif _qtenants.BEST_EFFORT not in ready:
                        ready[_qtenants.BEST_EFFORT] = _TRAIN_KEY
                    if _TRAIN_KEY not in ready.values():
                        # displaced (or a real best-effort bucket won
                        # the slot): the fast-path submit does not
                        # signal _work_cv, so poll for the idle window
                        # instead of lingering indefinitely
                        w = 0.05
                        wait = w if wait is None else min(wait, w)
                if ready:
                    backlog = {
                        c: (self._class_pending.get(c, 0)
                            + (1 if ready[c] is _TRAIN_KEY else 0))
                        for c in ready}

                    def cost(c):
                        if ready[c] is _TRAIN_KEY:
                            return 1
                        b0 = self._buckets[ready[c]]
                        return min(len(b0.reqs),
                                   self._bucket_cap_locked(b0.statics))

                    cls = self._sched.next_class(backlog, cost)
                    if cls is not None and ready[cls] is _TRAIN_KEY:
                        job = train_mgr.claim_next()
                        if job is not None:
                            self._inflight += 1
                            self._sched.charge(cls, 1)
                            work = (_TRAIN_KEY, job)
                    elif cls is not None:
                        work = self._pop_cohort_locked(ready[cls])
                        if work is not None:
                            self._sched.charge(cls, len(work[1]))
                if work is None:
                    if self._stop:
                        continue
                    self._work_cv.wait(timeout=wait)
                    continue
            self._workq.put((self, work))
        for _ in self._workers:
            self._workq.put(None)

    def _dispatch_cohort(self, bucket_obj, cohort) -> None:
        """Run one popped cohort through the isolation-retrying
        executor, with the last-resort exception fan and the in-flight
        bookkeeping — the single dispatch path shared by the worker
        threads and the synchronous :meth:`flush`."""
        if bucket_obj is _TRAIN_KEY:
            # a training slice: ``cohort`` is the claimed job. The
            # manager resolves every outcome on the job future or
            # requeues — no client futures to fan an exception to.
            try:
                mgr = self._train_mgr
                if mgr is not None:
                    mgr.run_slice(cohort)
            finally:
                with self._lock:
                    self._cohort_done_locked()
            return
        try:
            self._run_cohort(bucket_obj, cohort)
        except (KeyboardInterrupt, SystemExit):
            raise       # a synchronous flush() on the main thread must
            #             let Ctrl-C stop the process
        except BaseException as e:  # noqa: BLE001 — last-resort fan
            for r in cohort:
                if not r.future.done():
                    r.future.set_exception(e)
            with self._stats_lock:
                self._counts["failed"] += len(cohort)
        finally:
            with self._lock:
                self._cohort_done_locked()


    def flush(self) -> None:
        """Synchronously flush every pending cohort from the calling
        thread (tests/bench warmup; normal traffic never needs it).
        Returns only after every in-flight cohort has resolved too —
        the full-cohort fast path hands work to the worker threads at
        submit time, and "synchronous" must cover those (a chaos test
        activates a fault plan around submit+flush and the flush
        attempts must execute inside the plan's extent)."""
        while True:
            with self._lock:
                work = None
                for key in list(self._buckets):
                    work = self._pop_cohort_locked(key)
                    if work:
                        break
            if not work:
                break
            self._dispatch_cohort(*work)
        with self._lock:
            while self._inflight:
                self._idle_cv.wait(timeout=0.1)

    # ------------------------------------------------------------------
    # failure isolation: bisection converges on the poison request
    # ------------------------------------------------------------------

    def _drop_expired(self, cohort: list) -> list:
        """Resolve deadline-expired requests to ServeOverloadedError and
        return the survivors. Runs before EVERY execution attempt, so an
        expired request never occupies a lane or an isolation retry."""
        live = []
        expired = 0
        for r in cohort:
            if r.deadline is not None and r.deadline.expired:
                expired += 1
                if not r.future.done():
                    r.future.set_exception(ServeOverloadedError(
                        f"request deadline expired after "
                        f"{time.monotonic() - r.t_submit:.3f}s in queue"))
            else:
                live.append(r)
        if expired:
            with self._stats_lock:
                self._counts["expired"] += expired
        return live

    def _run_cohort(self, b: _Bucket, cohort: list, depth: int = 0) -> None:
        """Execute a cohort; on failure, bisect to isolate the poison.

        A failed flush splits the cohort in half and re-executes each
        half (lane invariance keeps the re-coalesced results bit-equal
        to what the full flush would have produced), recursing until the
        failure pins to a single request — only THAT future gets the
        exception; every cohort-mate resolves successfully. Worst case
        per request: ``ceil(log2(cohort))`` ≤ ``log2(max_batch)`` retry
        levels, ~2× the flush work of the clean path for the one
        afflicted cohort. Transient faults (that pass on re-execution)
        cost one split and poison nobody.
        """
        cohort = self._drop_expired(cohort)
        if not cohort:
            return
        # Telemetry (docs/observability): the root attempt is the
        # "serve.flush" span, parented — across the thread hop — under
        # the first request's submit span, so the request id minted at
        # submit() is on this span; bisection halves recurse INSIDE the
        # span's extent, so every "serve.isolation" retry nests under
        # it (and inherits the request id) with its own half's ids in
        # ``request_ids``. Disabled telemetry: one no-op branch.
        span_cm = _trace.span(
            "serve.flush" if depth == 0 else "serve.isolation",
            parent=cohort[0].tctx if depth == 0 else None)
        with span_cm as sp:
            if sp is not None:
                sp.set_attr("endpoint", b.statics[0])
                sp.set_attr("cohort", len(cohort))
                sp.set_attr("depth", depth)
                sp.set_attr("request_ids",
                            [r.request_id for r in cohort
                             if r.request_id is not None])
            try:
                self._execute(b, cohort)
            except (KeyboardInterrupt, SystemExit):
                raise   # cancellation stops the process — it must not
                #         be "isolated" into some request's future
            except BaseException as e:  # noqa: BLE001 — taxonomy-agnostic
                if sp is not None:
                    sp.status = "error"
                    sp.error = repr(e)
                with self._stats_lock:
                    self._counts["flush_failures"] += 1
                    if depth == 0:
                        # health evidence is per INCIDENT (root attempts
                        # only): a bisection records log2(B)+1 correlated
                        # failures, which would let ONE poison request in
                        # a quiet executor flip the state to DEGRADED and
                        # shed healthy traffic — contradicting "fails
                        # alone"
                        self._health.append(1.0)
                if depth == 0:
                    self._maybe_publish_state()
                if len(cohort) == 1:
                    r = cohort[0]
                    if not r.future.done():
                        r.future.set_exception(e)
                    with self._stats_lock:
                        self._counts["failed"] += 1
                        self._counts["poisoned"] += 1
                    return
                mid = len(cohort) // 2
                with self._stats_lock:
                    self._counts["isolation_retries"] += 2
                    self._counts["isolation_depth_peak"] = max(
                        self._counts["isolation_depth_peak"], depth + 1)
                self._run_cohort(b, cohort[:mid], depth + 1)
                self._run_cohort(b, cohort[mid:], depth + 1)
            else:
                if depth == 0:
                    with self._stats_lock:
                        self._health.append(0.0)
                    self._maybe_publish_state()

    def _is_degraded(self) -> bool:
        with self._stats_lock:
            n = len(self._health)
            if n < 4:
                return False
            return sum(self._health) / n >= self.degraded_threshold

    # ------------------------------------------------------------------
    # cohort execution: pad → stack → one vmapped executable → unpad
    # ------------------------------------------------------------------

    def _compiled_for(self, b: _Bucket):
        # keyed on the engine statics, NOT the full bucket key: model
        # ids separate buckets only to keep cohorts unmixed, but every
        # same-shaped model shares one wrapper (and one executable) —
        # and the dict stays bounded by the shape-class space instead of
        # growing with model churn
        with self._compiled_lock:
            cf = self._compiled.get(b.statics)
            if cf is None:
                cf = self._build_batched(b)
                self._compiled[b.statics] = cf
            return cf

    # ------------------------------------------------------------------
    # flush-kernel selection (docs/performance, "Serve-bucket kernel
    # selection"): which program serves a (bucket, capacity) flush —
    # the endpoint's batched Pallas kernel or the vmapped XLA path.
    # Precedence: executor ``kernel=`` argument > SKYLARK_SERVE_KERNEL
    # env > tune plan cache > default (xla). A pallas intent that fails
    # host-side qualification declines (reason counted) back to xla.
    # ------------------------------------------------------------------

    def _kernel_workload(self, b: _Bucket, capacity: int):
        """The tune serve-bucket workload of a flush — (endpoint /
        orientation, family, dtype, padded lane class, capacity class)
        — or None when the endpoint has no kernel decision."""
        from libskylark_tpu import tune

        endpoint = b.statics[0]
        ctx = b.ctx
        if endpoint == "sketch_apply":
            return tune.serve_workload(
                "sketch_apply", ctx["family"], ctx["dtype"],
                ctx["padded"], ctx["s_dim"], capacity,
                rowwise=ctx["rowwise"])
        if endpoint == "fastfood_features":
            return tune.serve_workload(
                "fastfood_features", ctx["family"], ctx["dtype"],
                ctx["padded"], ctx["s_dim"], capacity)
        if endpoint == "sparse_sketch_apply":
            return tune.serve_workload(
                "sparse_sketch_apply", ctx["family"], ctx["dtype"],
                ctx["padded"], ctx["s_dim"], capacity,
                rowwise=ctx["rowwise"], nnz=ctx["nnz_class"])
        return None

    def _qualify_serve_kernel(self, b: _Bucket,
                              m_tile: Optional[int] = None):
        """Host-side (ok, why) qualification of the bucket's batched
        kernel at the padded lane class — run BEFORE a pallas choice is
        committed to the executable key, so an unqualified bucket keys
        (and compiles) the XLA program it will actually run."""
        ctx = b.ctx
        endpoint = b.statics[0]
        interpret = not _pallas_native()
        if endpoint == "sparse_sketch_apply":
            if ctx["family"] != "CWT":
                return False, ("dense-family sparse flush has no "
                               "kernel (in-executable densify serves)")
            from libskylark_tpu.sketch import pallas_sparse

            padded, rowwise = ctx["padded"], ctx["rowwise"]
            n = padded[1] if rowwise else padded[0]
            m = padded[0] if rowwise else padded[1]
            return pallas_sparse.qualify(
                ctx["s_dim"], n, m, ctx["nnz_class"], ctx["dtype"],
                interpret=interpret)
        if endpoint == "fastfood_features":
            from libskylark_tpu.sketch import pallas_fastfood

            return pallas_fastfood.serve_qualify(
                ctx["n_dim"], ctx["s_dim"], ctx["padded"][0],
                ctx["dtype"], ctx["fut"], interpret=interpret)
        padded, rowwise = ctx["padded"], ctx["rowwise"]
        n = padded[1] if rowwise else padded[0]
        m = padded[0] if rowwise else padded[1]
        if ctx["family"] == "SRHT":
            # n is the exact transform extent for this family
            # (_sketch_statics pads the free axis only)
            min_n = _env.FWHT_MIN_N.get()
            if n < min_n:
                return False, (f"n={n} below SKYLARK_FWHT_MIN_N="
                               f"{min_n} (short transforms beat the "
                               "in-kernel generation overhead)")
            from libskylark_tpu.sketch import pallas_fwht

            return pallas_fwht.qualify(ctx["s_dim"], n, m,
                                       ctx["dtype"],
                                       interpret=interpret)
        if ctx["family"] == "CWT":
            from libskylark_tpu.sketch import pallas_hash

            return pallas_hash.qualify(ctx["s_dim"], n, m,
                                       ctx["dtype"],
                                       interpret=interpret)
        from libskylark_tpu.sketch import pallas_dense

        return pallas_dense.serve_qualify(
            ctx["dist"], ctx["s_dim"], n, m, ctx["dtype"],
            interpret=interpret, m_tile=m_tile)

    def _resolve_flush_kernel(self, b: _Bucket, capacity: int) -> tuple:
        """``(backend, plan, source, declined)`` for one (bucket,
        capacity) flush. Memoized per plan-cache fingerprint: the
        engine key_fn re-resolves on every call (the kernel choice is
        a STATIC of the executable key — the r7 jit-leak gate's
        zero-recompile contract holds because this is a dict hit with
        a stable answer), and a plan edit changes the fingerprint,
        which both re-resolves the choice and re-keys the executable.
        ``declined`` is the reason slug when a pallas intent fell back
        to xla (the ``by_reason`` counter), else None."""
        if b.statics[0] not in _KERNEL_ENDPOINTS:
            return ("xla", None, "endpoint", None)
        from libskylark_tpu.engine.compiled import plan_fingerprint

        fp = plan_fingerprint()
        if fp != self._kernel_memo_fp:
            # new fingerprint era: every memoized choice (including
            # mosaic-reject poisonings — they hold "for the fingerprint
            # era") is stale; drop them so the memo stays bounded by
            # the live (bucket, capacity) population
            self._kernel_memo.clear()
            self._kernel_memo_fp = fp
        memo_key = (b.statics, int(capacity), fp)
        got = self._kernel_memo.get(memo_key)
        if got is not None:
            return got
        plan = None
        sparse_pin = (_env.SPARSE_KERNEL.get()
                      if b.statics[0] == "sparse_sketch_apply" else None)
        # the FWHT-family pin (SKYLARK_FWHT_KERNEL) plays the same
        # role for the SRHT buckets SKYLARK_SPARSE_KERNEL plays for
        # the sparse ones: route just this family without disturbing
        # the rest of the ladder
        fwht_pin = (_env.FWHT_KERNEL.get()
                    if (b.statics[0] == "sketch_apply"
                        and b.statics[1] == "SRHT") else None)
        if self.kernel is not None:
            choice, source = self.kernel, "arg"
        elif fwht_pin is not None:
            choice, source = fwht_pin, "env"
        elif sparse_pin is not None:
            # the sparse-family pin (SKYLARK_SPARSE_KERNEL) sits
            # between the executor argument and the general
            # SKYLARK_SERVE_KERNEL: an operator can route just the
            # sparse buckets without disturbing the dense ladder
            choice, source = sparse_pin, "env"
        elif _serve_kernel_env() is not None:
            choice, source = _serve_kernel_env(), "env"
        else:
            from libskylark_tpu.sketch import params as sketch_params

            if sketch_params.get_use_plan_cache():
                try:
                    from libskylark_tpu import tune

                    w = self._kernel_workload(b, capacity)
                    plan = tune.plan_for(w) if w is not None else None
                except Exception:
                    plan = None
            if plan is not None and plan.backend in _KERNEL_BACKENDS:
                choice, source = plan.backend, "plan"
            else:
                plan = None
                choice, source = "xla", "default"
        out = (choice, plan, source, None)
        if choice == "pallas":
            ok, why = self._qualify_serve_kernel(
                b, m_tile=plan.m_tile if plan else None)
            if not ok:
                out = ("xla", None, source, _decline_slug(why))
        self._kernel_memo[memo_key] = out
        return out

    def _kernel_key_token(self, b: _Bucket, capacity: int) -> str:
        """The kernel-choice static the flush executable is keyed on
        (plan_id carries the m-tile for the dense family — two plans
        trace different programs and must key differently)."""
        backend, plan, _src, _why = self._resolve_flush_kernel(
            b, capacity)
        return plan.plan_id() if (backend == "pallas"
                                  and plan is not None) else backend

    def _poison_kernel(self, b: _Bucket, capacity: int,
                       reason: str) -> None:
        """Force (bucket, capacity) onto the XLA path for the rest of
        this fingerprint era — the compile-time Mosaic-rejection
        fallback (a rejection is a decline, not an outage)."""
        from libskylark_tpu.engine.compiled import plan_fingerprint

        memo_key = (b.statics, int(capacity), plan_fingerprint())
        self._kernel_memo[memo_key] = ("xla", None, "fallback", reason)

    def restore_kernel_choice(self, statics, capacity: int,
                              token: str) -> bool:
        """Seed the flush-kernel memo for one (bucket statics,
        capacity) with a warmup-pack-recorded decision — the r12
        kernel choice ships *with* the compiled artifact instead of
        being re-resolved (plan-cache consult + host qualification)
        per process (docs/performance, "Persistent AOT artifacts &
        warmup packs"). The seed is keyed under the CURRENT plan
        fingerprint; the pack loader only calls this after verifying
        the fingerprints match, so the memoized choice is exactly what
        live resolution would certify. Returns whether the decision
        was restored (an unparseable token falls back to live
        resolution — a decline, not an error). An explicit pin —
        executor ``kernel=`` argument or ``SKYLARK_SERVE_KERNEL`` —
        outranks the pack: the memo is consulted before either, so
        seeding it would silently override the operator's pin; decline
        instead and let live resolution honor the precedence. The same
        goes for a disabled plan cache (``SKYLARK_USE_PLAN_CACHE=0``)
        — the pack's decisions ARE plan-cache decisions, and restoring
        them would re-enable the selection the operator turned off."""
        from libskylark_tpu.engine.compiled import plan_fingerprint
        from libskylark_tpu.sketch import params as sketch_params

        if self.kernel is not None or _serve_kernel_env() is not None:
            return False
        statics = tuple(statics)
        if (statics and statics[0] == "sparse_sketch_apply"
                and _env.SPARSE_KERNEL.get() is not None):
            # the sparse-family pin outranks a pack decision exactly
            # like the general pin does: the memo is consulted before
            # the pin in _resolve_flush_kernel, so seeding it would
            # silently override the operator's sparse routing
            return False
        if (len(statics) > 1 and statics[0] == "sketch_apply"
                and statics[1] == "SRHT"
                and _env.FWHT_KERNEL.get() is not None):
            # same rule for the FWHT-family pin
            return False
        if not sketch_params.get_use_plan_cache():
            return False
        value = None
        if token == "xla":
            value = ("xla", None, "pack", None)
        else:
            plan = _parse_plan_token(token)
            if plan is not None:
                value = (plan.backend, plan, "pack", None)
        if value is None:
            return False
        fp = plan_fingerprint()
        if fp != self._kernel_memo_fp:
            self._kernel_memo.clear()
            self._kernel_memo_fp = fp
        self._kernel_memo[(statics, int(capacity), fp)] = value
        return True

    def load_warmup_pack(self, pack_dir: str, *,
                         strict: bool = False) -> dict:
        """Boot this executor from a warmup pack: load every packed
        executable into the process executable cache and restore the
        packed per-bucket kernel decisions into this executor's memo
        (:func:`libskylark_tpu.engine.warmup.load_pack`). Call before
        accepting traffic; returns the loader's report."""
        from libskylark_tpu.engine import warmup as _warmup

        return _warmup.load_pack(pack_dir, executors=(self,),
                                 strict=strict)

    def _build_batched(self, b: _Bucket):
        import jax

        statics = b.statics
        ctx = b.ctx
        endpoint = statics[0]
        # kernel-selecting endpoints key their executables on the
        # resolved kernel-choice token too: the choice is derived from
        # the SAME (bucket, capacity, plan-fingerprint) triple at key
        # time and at trace time, so the key can never disagree with
        # the program it names
        def serve_key(*a):
            return statics + (
                "kernel", self._kernel_key_token(b, int(a[0].shape[0])))

        if endpoint == "sketch_apply":
            s_dim, rowwise = ctx["s_dim"], ctx["rowwise"]
            if ctx["family"] == "CWT":
                from libskylark_tpu.sketch.hash import cwt_serve_apply

                def one(kd, scale, A):
                    return cwt_serve_apply(kd, A, s_dim=s_dim,
                                           rowwise=rowwise)
            elif ctx["family"] == "SRHT":
                from libskylark_tpu.sketch.fjlt import srht_serve_apply

                # the SRHT's scaling is fully determined by (n, s_dim)
                # inside the program; the scale lane rides unread for
                # arity uniformity with the other sketch families
                def one(kd, scale, A):
                    return srht_serve_apply(kd, A, s_dim=s_dim,
                                            rowwise=rowwise)
            else:
                from libskylark_tpu.sketch.dense import serve_apply

                dist = ctx["dist"]

                def one(kd, scale, A):
                    return serve_apply(kd, scale, A, dist=dist,
                                       s_dim=s_dim, rowwise=rowwise)

            inner = jax.vmap(one)

            def batched_sketch(kd, scale, A):
                backend, plan, _src, _why = self._resolve_flush_kernel(
                    b, int(A.shape[0]))
                if backend == "pallas":
                    interpret = not _pallas_native()
                    if ctx["family"] == "CWT":
                        from libskylark_tpu.sketch import pallas_hash

                        # exact accumulation under the interpreter:
                        # bit-equal to the scatter (the CI bit-equality
                        # leg); the MXU mode serves on real silicon
                        return pallas_hash.cwt_apply_batched(
                            kd, A, s_dim=s_dim, rowwise=rowwise,
                            accum="exact" if interpret else "mxu",
                            interpret=interpret)
                    if ctx["family"] == "SRHT":
                        from libskylark_tpu.sketch import pallas_fwht

                        return pallas_fwht.srht_apply_batched(
                            kd, A, s_dim=s_dim, rowwise=rowwise,
                            m_tile=plan.m_tile if plan else None,
                            interpret=interpret)
                    from libskylark_tpu.sketch import pallas_dense

                    return pallas_dense.serve_batched_apply(
                        kd, scale, A, dist=ctx["dist"], s_dim=s_dim,
                        rowwise=rowwise,
                        m_tile=plan.m_tile if plan else None,
                        interpret=interpret)
                return inner(kd, scale, A)

            return engine_compile(
                batched_sketch, name="serve.sketch_apply",
                donate_argnums=(0, 1, 2),
                key_fn=serve_key)
        if endpoint == "fastfood_features":
            from libskylark_tpu.sketch.frft import fastfood_serve_apply

            n_dim, s_dim = ctx["n_dim"], ctx["s_dim"]
            fut, sm_kind, sm_param = (ctx["fut"], ctx["sm_kind"],
                                      ctx["sm_param"])

            def one_ff(kd, A):
                return fastfood_serve_apply(
                    kd, A, n_dim=n_dim, s_dim=s_dim, fut=fut,
                    sm_kind=sm_kind, sm_param=sm_param)

            inner_ff = jax.vmap(one_ff)

            def batched_fastfood(kd, A):
                backend, _plan, _src, _why = self._resolve_flush_kernel(
                    b, int(A.shape[0]))
                if backend == "pallas":
                    from libskylark_tpu.sketch import pallas_fastfood

                    return pallas_fastfood.serve_features_batched(
                        kd, A, n_dim=n_dim, s_dim=s_dim, fut=fut,
                        sm_kind=sm_kind, sm_param=sm_param,
                        interpret=not _pallas_native())
                return inner_ff(kd, A)

            return engine_compile(
                batched_fastfood, name="serve.fastfood_features",
                donate_argnums=(0, 1),
                key_fn=serve_key)
        if endpoint == "sparse_sketch_apply":
            from libskylark_tpu.sketch import sparse_serve as _ssrv

            s_dim, rowwise = ctx["s_dim"], ctx["rowwise"]
            padded = ctx["padded"]
            if ctx["family"] == "CWT":
                def one_sp(kd, scale, data, indices, indptr):
                    return _ssrv.cwt_sparse_serve_apply(
                        kd, data, indices, indptr, s_dim=s_dim,
                        rowwise=rowwise, shape=padded)
            else:
                dist = ctx["dist"]

                def one_sp(kd, scale, data, indices, indptr):
                    return _ssrv.dense_sparse_serve_apply(
                        kd, scale, data, indices, indptr, dist=dist,
                        s_dim=s_dim, rowwise=rowwise, shape=padded)

            inner_sp = jax.vmap(one_sp)

            def batched_sparse(kd, scale, data, indices, indptr):
                backend, _plan, _src, _why = self._resolve_flush_kernel(
                    b, int(data.shape[0]))
                if backend == "pallas":
                    from libskylark_tpu.sketch import pallas_sparse

                    interpret = not _pallas_native()
                    nnz_pad = int(data.shape[1])
                    rows = jax.vmap(
                        lambda p: _ssrv.csr_row_ids(p, nnz_pad))(indptr)
                    return pallas_sparse.cwt_sparse_apply_batched(
                        kd, data, rows, indices, s_dim=s_dim,
                        rowwise=rowwise, shape=padded,
                        accum="exact" if interpret else "mxu",
                        interpret=interpret)
                return inner_sp(kd, scale, data, indices, indptr)

            return engine_compile(
                batched_sparse, name="serve.sparse_sketch_apply",
                donate_argnums=(0, 1, 2, 3, 4),
                key_fn=serve_key)
        if endpoint == "compressed_matmul":
            # always-xla flush (like the solve endpoints): the two
            # family sketch programs each run panel-free already, and
            # the closing (m, s)x(s, p) gemm is XLA's bread and butter
            # — tune covers it as the xla-only "serve_cmm" op for
            # roofline/certification, not as a kernel decision
            family, s_dim = ctx["family"], ctx["s_dim"]
            padded_a = ctx["padded_A"]
            if family == "SRHT":
                from libskylark_tpu.sketch.fjlt import srht_serve_apply

                def skA_dense(kd, A):
                    return srht_serve_apply(kd, A, s_dim=s_dim,
                                            rowwise=True)

                def skB(kd, B):
                    return srht_serve_apply(kd, B, s_dim=s_dim,
                                            rowwise=False)
            else:
                from libskylark_tpu.sketch.hash import cwt_serve_apply

                def skA_dense(kd, A):
                    return cwt_serve_apply(kd, A, s_dim=s_dim,
                                           rowwise=True)

                def skB(kd, B):
                    return cwt_serve_apply(kd, B, s_dim=s_dim,
                                           rowwise=False)

            if ctx["sparse"]:
                from libskylark_tpu.sketch import sparse_serve as _ssrv

                if family == "CWT":
                    # sketch straight off the padded CSR lanes (the
                    # r18 packing) — no densify
                    def one_cm(kd, data, indices, indptr, B):
                        SA = _ssrv.cwt_sparse_serve_apply(
                            kd, data, indices, indptr, s_dim=s_dim,
                            rowwise=True, shape=padded_a)
                        return SA @ skB(kd, B)
                else:
                    # the SRHT has no CSR program (the FWHT mixes
                    # every coordinate); densify in-executable, the
                    # same policy the dense-family sparse flush uses
                    def one_cm(kd, data, indices, indptr, B):
                        Ad = _ssrv.scatter_dense(
                            data, indices, indptr, shape=padded_a)
                        return skA_dense(kd, Ad) @ skB(kd, B)

                inner_cm = jax.vmap(one_cm)

                def batched_cmm(kd, data, indices, indptr, B):
                    return inner_cm(kd, data, indices, indptr, B)

                return engine_compile(
                    batched_cmm, name="serve.compressed_matmul",
                    donate_argnums=(0, 1, 2, 3, 4),
                    key_fn=lambda *a: statics)

            def one_cm(kd, A, B):
                return skA_dense(kd, A) @ skB(kd, B)

            inner_cm = jax.vmap(one_cm)

            def batched_cmm(kd, A, B):
                return inner_cm(kd, A, B)

            return engine_compile(
                batched_cmm, name="serve.compressed_matmul",
                donate_argnums=(0, 1, 2),
                key_fn=lambda *a: statics)
        if endpoint == "sparse_solve_l2_sketched":
            from libskylark_tpu.sketch import sparse_serve as _ssrv

            family, s_dim, method = (ctx["family"], ctx["s_dim"],
                                     ctx["method"])
            padded_a = ctx["padded_A"]

            def one_sps(kd, scale, data, indices, indptr, B):
                return _ssrv.sparse_solve_serve(
                    kd, scale, data, indices, indptr, B,
                    sketch_type=family, s_dim=s_dim, method=method,
                    shape=padded_a)

            inner_sps = jax.vmap(one_sps)

            def batched_sparse_solve(kd, scale, data, indices, indptr,
                                     B):
                return inner_sps(kd, scale, data, indices, indptr, B)

            return engine_compile(
                batched_sparse_solve,
                name="serve.sparse_solve_l2_sketched",
                donate_argnums=(0, 1, 2, 3, 4, 5),
                key_fn=lambda *a: statics)
        if endpoint == "solve_l2_sketched":
            from libskylark_tpu.algorithms.regression import (
                sketched_solve_serve,
            )

            family, s_dim, method = (ctx["family"], ctx["s_dim"],
                                     ctx["method"])

            def one(kd, scale, A, B):
                return sketched_solve_serve(
                    kd, scale, A, B, sketch_type=family, s_dim=s_dim,
                    method=method)

            inner = jax.vmap(one)

            def batched_solve(kd, scale, A, B):
                return inner(kd, scale, A, B)

            return engine_compile(
                batched_solve, name="serve.solve_l2_sketched",
                donate_argnums=(0, 1, 2, 3),
                key_fn=lambda *a: statics)
        if endpoint == "graph_ase":
            from libskylark_tpu.ml.graph import ase_serve_apply

            k_dim, g_iters = ctx["k"], ctx["iters"]
            g_padded = ctx["padded"]

            def one_ga(kd, data, indices, indptr):
                return ase_serve_apply(kd, data, indices, indptr,
                                       k=k_dim, iters=g_iters,
                                       shape=g_padded)

            inner_ga = jax.vmap(one_ga)

            # capacity-1 flushes run the PLAIN single-lane program
            # (shape is static at trace time): the vmapped batch-1
            # lowering of a deep linalg chain can differ from the
            # unbatched program by an f32 ulp, and the capacity-1
            # dispatch is the bit-equality reference the other
            # capacities (whose lanes XLA lowers like the plain
            # program) are pinned against
            def batched_graph_ase(kd, data, indices, indptr):
                if kd.shape[0] == 1:
                    return one_ga(kd[0], data[0], indices[0],
                                  indptr[0])[None]
                return inner_ga(kd, data, indices, indptr)

            return engine_compile(
                batched_graph_ase, name="serve.graph_ase",
                donate_argnums=(0, 1, 2, 3),
                key_fn=lambda *a: statics)
        if endpoint == "graph_ppr":
            from libskylark_tpu.ml.graph import ppr_serve_apply

            p_alpha, p_iters = ctx["alpha"], ctx["iters"]
            p_padded = ctx["padded"]

            def one_pp(data, indices, indptr, s):
                return ppr_serve_apply(data, indices, indptr, s,
                                       alpha=p_alpha, iters=p_iters,
                                       shape=p_padded)

            inner_pp = jax.vmap(one_pp)

            def batched_graph_ppr(data, indices, indptr, s):
                if data.shape[0] == 1:   # see batched_graph_ase
                    return one_pp(data[0], indices[0], indptr[0],
                                  s[0])[None]
                return inner_pp(data, indices, indptr, s)

            return engine_compile(
                batched_graph_ppr, name="serve.graph_ppr",
                donate_argnums=(0, 1, 2, 3),
                key_fn=lambda *a: statics)
        if endpoint == "condest":
            from libskylark_tpu.nla.condest import condest_serve_apply

            c_steps = ctx["steps"]

            def one_ce(kd, A):
                return condest_serve_apply(kd, A, steps=c_steps)

            # statically unrolled lanes, NOT vmap: the deep Golub-
            # Kahan recurrence (dot-reorthogonalization chain) is not
            # lane-bitwise under XLA's batched lowering, and the
            # capacity-1 bit-equality contract outranks trace size
            # for this tiny program (k+1 short vectors per lane)
            def batched_condest(kd, A):
                return jax.numpy.stack(
                    [one_ce(kd[i], A[i]) for i in range(A.shape[0])])

            return engine_compile(
                batched_condest, name="serve.condest",
                donate_argnums=(0, 1),
                key_fn=lambda *a: statics)
        if endpoint == "lowrank":
            from libskylark_tpu.nla.lowrank import lowrank_serve_apply

            lr_dist, lr_k = ctx["dist"], ctx["k"]
            lr_s, lr_t = ctx["s_dim"], ctx["t_dim"]

            def one_lr(kd_s, sc_s, kd_t, sc_t, A):
                return lowrank_serve_apply(kd_s, sc_s, kd_t, sc_t, A,
                                           dist=lr_dist, s=lr_s,
                                           t=lr_t, k=lr_k)

            inner_lr = jax.vmap(one_lr)

            def batched_lowrank(kd_s, sc_s, kd_t, sc_t, A):
                if A.shape[0] == 1:      # see batched_graph_ase
                    return one_lr(kd_s[0], sc_s[0], kd_t[0],
                                  sc_t[0], A[0])[None]
                return inner_lr(kd_s, sc_s, kd_t, sc_t, A)

            return engine_compile(
                batched_lowrank, name="serve.lowrank",
                donate_argnums=(0, 1, 2, 3, 4),
                key_fn=lambda *a: statics)
        if endpoint == "rlsc_predict":
            # classification twin of krr_predict: model operands
            # broadcast, never donated
            from libskylark_tpu.ml.rlsc import rlsc_predict_kernel

            r_kernel = ctx["kernel"]

            def one_rl(Xq, X_train, coef):
                return rlsc_predict_kernel(r_kernel, Xq, X_train, coef)

            inner_rl = jax.vmap(one_rl, in_axes=(0, None, None))

            def batched_rlsc(Xq, X_train, coef):
                return inner_rl(Xq, X_train, coef)

            return engine_compile(
                batched_rlsc, name="serve.rlsc_predict",
                donate_argnums=(0,),
                key_fn=lambda *a: statics)
        # krr_predict: model operands broadcast, never donated (they
        # are bucket-lived and re-read by every flush)
        from libskylark_tpu.ml.krr import krr_predict_kernel

        kernel = ctx["kernel"]

        def one(Xq, X_train, coef):
            return krr_predict_kernel(kernel, Xq, X_train, coef)

        inner = jax.vmap(one, in_axes=(0, None, None))

        def batched_krr(Xq, X_train, coef):
            return inner(Xq, X_train, coef)

        return engine_compile(
            batched_krr, name="serve.krr_predict", donate_argnums=(0,),
            key_fn=lambda *a: statics)

    def _device_put_batch(self, arr):
        """Shard a stacked (capacity, ...) host buffer's batch dimension
        across the executor mesh (no-op without one)."""
        if self._mesh is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(self._batch_axis,
                             *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self._mesh, spec))

    def _device_put_replicated(self, arr):
        if self._mesh is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(
            arr, NamedSharding(self._mesh, PartitionSpec()))

    def _execute(self, b: _Bucket, cohort: list) -> None:
        k = len(cohort)
        capacity = bucketing.capacity_class(k, self.max_batch,
                                            multiple=self._ndev)
        endpoint = b.statics[0]
        # chaos seam: fires per execution ATTEMPT with the cohort's tag
        # union, so a tag-pinned plan fails exactly the attempts that
        # contain the poison request — which is what bisection needs
        faults.check("serve.flush",
                     tags=frozenset().union(*(r.tags for r in cohort)),
                     detail=f"{endpoint} k={k} cap={capacity}")
        # kernel selection: resolved once per flush (memo hit after the
        # first), counted per flush so operators see live which buckets
        # are on the fast path and WHY the others are not
        kernel_backend, kdeclined = "xla", None
        if endpoint in _KERNEL_ENDPOINTS:
            kernel_backend, _kp, _ks, kdeclined = \
                self._resolve_flush_kernel(b, capacity)
        if endpoint == "sketch_apply":
            padded = cohort[0].meta["padded"]
            args = self._stack_common(cohort, padded, capacity,
                                      with_b=False)
            primary = "A"
        elif endpoint == "fastfood_features":
            padded = cohort[0].meta["padded"]
            dtype = cohort[0].arrays["A"].dtype
            kd = bucketing.stack_pad([r.arrays["kd"] for r in cohort],
                                     (2,), capacity, np.uint32)
            Astk = bucketing.stack_pad([r.arrays["A"] for r in cohort],
                                       padded, capacity, dtype)
            args = (self._device_put_batch(kd),
                    self._device_put_batch(Astk))
            primary = "A"
        elif endpoint == "solve_l2_sketched":
            padded = cohort[0].meta["padded_A"]
            args = self._stack_common(
                cohort, padded, capacity, with_b=True,
                padded_b=cohort[0].meta["padded_B"])
            primary = "A"
        elif endpoint in ("sparse_sketch_apply",
                          "sparse_solve_l2_sketched"):
            # CSR lanes: every request in the bucket shares the nnz
            # class (a bucket static), so the (data, indices, indptr)
            # arrays are uniform; the nnz lane extent is the waste
            # accounting's "padded shape"
            nnz_pad = cohort[0].arrays["data"].shape[0]
            padded = (nnz_pad,)
            dtype = cohort[0].arrays["data"].dtype
            ptr_len = cohort[0].arrays["indptr"].shape[0]
            args = [
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["kd"] for r in cohort], (2,), capacity,
                    np.uint32)),
                self._device_put_batch(bucketing.stack_pad(
                    [np.asarray(r.arrays["scale"]).reshape(())
                     for r in cohort], (), capacity, dtype)),
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["data"] for r in cohort], (nnz_pad,),
                    capacity, dtype)),
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["indices"] for r in cohort], (nnz_pad,),
                    capacity, np.int32)),
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["indptr"] for r in cohort], (ptr_len,),
                    capacity, np.int32)),
            ]
            if endpoint == "sparse_solve_l2_sketched":
                args.append(self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["B"] for r in cohort],
                    cohort[0].meta["padded_B"], capacity, dtype)))
            args = tuple(args)
            primary = "data"
        elif endpoint == "compressed_matmul":
            dtype = cohort[0].arrays["B"].dtype
            kd = bucketing.stack_pad([r.arrays["kd"] for r in cohort],
                                     (2,), capacity, np.uint32)
            args = [self._device_put_batch(kd)]
            if b.ctx["sparse"]:
                nnz_pad = cohort[0].arrays["data"].shape[0]
                padded = (nnz_pad,)
                ptr_len = cohort[0].arrays["indptr"].shape[0]
                args += [
                    self._device_put_batch(bucketing.stack_pad(
                        [r.arrays["data"] for r in cohort],
                        (nnz_pad,), capacity, dtype)),
                    self._device_put_batch(bucketing.stack_pad(
                        [r.arrays["indices"] for r in cohort],
                        (nnz_pad,), capacity, np.int32)),
                    self._device_put_batch(bucketing.stack_pad(
                        [r.arrays["indptr"] for r in cohort],
                        (ptr_len,), capacity, np.int32)),
                ]
                primary = "data"
            else:
                padded = b.ctx["padded_A"]
                args.append(self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["A"] for r in cohort], padded, capacity,
                    dtype)))
                primary = "A"
            args.append(self._device_put_batch(bucketing.stack_pad(
                [r.arrays["B"] for r in cohort], b.ctx["padded_B"],
                capacity, dtype)))
            args = tuple(args)
        elif endpoint in ("graph_ase", "graph_ppr"):
            # CSR adjacency lanes (the r18 packing): uniform within
            # the bucket (nnz class is a static); graph_ase leads
            # with the key lanes, graph_ppr trails with the
            # personalization vectors
            nnz_pad = cohort[0].arrays["data"].shape[0]
            padded = (nnz_pad,)
            dtype = cohort[0].arrays["data"].dtype
            ptr_len = cohort[0].arrays["indptr"].shape[0]
            args = []
            if endpoint == "graph_ase":
                args.append(self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["kd"] for r in cohort], (2,), capacity,
                    np.uint32)))
            args += [
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["data"] for r in cohort], (nnz_pad,),
                    capacity, dtype)),
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["indices"] for r in cohort], (nnz_pad,),
                    capacity, np.int32)),
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["indptr"] for r in cohort], (ptr_len,),
                    capacity, np.int32)),
            ]
            if endpoint == "graph_ppr":
                args.append(self._device_put_batch(bucketing.stack_pad(
                    [r.arrays["s"] for r in cohort],
                    (b.ctx["padded"][0],), capacity, dtype)))
            args = tuple(args)
            primary = "data"
        elif endpoint == "condest":
            padded = cohort[0].meta["padded"]
            dtype = cohort[0].arrays["A"].dtype
            kd = bucketing.stack_pad([r.arrays["kd"] for r in cohort],
                                     (2,), capacity, np.uint32)
            Astk = bucketing.stack_pad([r.arrays["A"] for r in cohort],
                                       padded, capacity, dtype)
            args = (self._device_put_batch(kd),
                    self._device_put_batch(Astk))
            primary = "A"
        elif endpoint == "lowrank":
            padded = cohort[0].meta["padded"]
            dtype = cohort[0].arrays["A"].dtype
            args = tuple(
                self._device_put_batch(bucketing.stack_pad(
                    [r.arrays[nm] for r in cohort], shp, capacity, dt))
                for nm, shp, dt in (("kd_s", (2,), np.uint32),
                                    ("scale_s", (), dtype),
                                    ("kd_t", (2,), np.uint32),
                                    ("scale_t", (), dtype),
                                    ("A", padded, dtype)))
            primary = "A"
        else:
            padded = cohort[0].meta["padded"]
            Xq = bucketing.stack_pad(
                [r.arrays["Xq"] for r in cohort], padded, capacity,
                cohort[0].arrays["Xq"].dtype)
            args = (self._device_put_batch(Xq),
                    self._device_put_replicated(b.ctx["X_train"]),
                    self._device_put_replicated(b.ctx["coef"]))
            primary = "Xq"

        cf = self._compiled_for(b)
        from libskylark_tpu.base.precision import solver_precision

        # the sequential solve/KRR endpoints trace under
        # solver_precision() (full-f32 matmuls on TPU); the batched
        # program must bake in the SAME regime or a served result would
        # silently diverge from its sequential twin on MXU backends.
        # Sketch-apply stays at the fast ambient default, also matching
        # its sequential path (base/precision.py policy).
        def dispatch():
            prec = (contextlib.nullcontext()
                    if endpoint in _KERNEL_ENDPOINTS
                    else solver_precision())
            with prec, warnings.catch_warnings():
                # the donated stacked buffers rarely alias the batch
                # output — jax's unusable-donation warning is this
                # layer's expected steady state, silenced ONLY around
                # the serve dispatch so user donation sites keep their
                # diagnostic
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                return cf(*args)

        if kernel_backend == "pallas" and _pallas_native():
            # compile-time Mosaic rejection is a DECLINE, not an
            # outage: poison this (bucket, capacity) onto the XLA path
            # and re-dispatch — the key_fn re-resolves to the xla
            # token, so the retry compiles (and caches) the fallback
            # program. Rejections surface as JaxRuntimeError from
            # Mosaic proper but as trace-time NotImplementedError /
            # LoweringError from the Pallas lowering rules, so the net
            # is Exception-wide; the serve.flush fault seam fires
            # BEFORE this block, so an injected chaos fault can never
            # be misread as a rejection. A rejected attempt never
            # EXECUTED, so the donated buffers are intact and the
            # re-dispatch is safe; a post-compile runtime failure may
            # have consumed them — detected below — in which case the
            # original error propagates into bisection isolation
            # (future flushes of this bucket still take the XLA path).
            try:
                out = dispatch()
            except Exception:  # noqa: BLE001 — decline seam, see above
                self._poison_kernel(b, capacity, "mosaic-reject")
                kernel_backend, kdeclined = "xla", "mosaic-reject"
                if any(getattr(a, "is_deleted", lambda: False)()
                       for a in args):
                    raise
                out = dispatch()
        else:
            out = dispatch()
        # resolve futures from ONE host view of the batch output: a
        # per-request eager device slice would cost a dispatched XLA op
        # per lane — at microbatch request sizes that's comparable to
        # the whole flush. Serving results terminate at the client, so
        # they come back as host arrays (near zero-copy on CPU), and
        # each future resolves to a VIEW into this one buffer (_unpad
        # slices, never copies) — the handoff a process replica's
        # shared-memory transport writes straight out of (fleet/shm:
        # np.copyto from the strided view into the slot, no
        # ascontiguousarray staging copy in between).
        out = np.asarray(out)

        now = time.monotonic()
        for i, r in enumerate(cohort):
            try:
                r.future.set_result(self._unpad(endpoint, out, i, r))
            except BaseException as e:  # noqa: BLE001
                if not r.future.done():
                    r.future.set_exception(e)
        with self._stats_lock:
            self._counts["flushes"] += 1
            self._counts["completed"] += k
            if k > 1:
                self._counts["coalesced"] += k
            if endpoint in _KERNEL_ENDPOINTS:
                self._kernel_sel[kernel_backend] += 1
                if kdeclined:
                    self._kernel_dec[kdeclined] += 1
                if endpoint == "sparse_sketch_apply":
                    self._sparse_kernel_sel[kernel_backend] += 1
                    _SPARSE_KERNEL_FLUSHES.inc_always(
                        backend=kernel_backend)
                if (endpoint == "sketch_apply"
                        and b.statics[1] == "SRHT"):
                    self._fwht_sel[kernel_backend] += 1
                    _FWHT_FLUSHES.inc_always(backend=kernel_backend)
            self._batch_hist[capacity] += 1
            self._cohort_hist[k] += 1
            pad_total = bucketing.padded_elements(padded, capacity)
            pad_real = bucketing.real_elements(
                [r.true_shapes[primary] for r in cohort])
            self._pad_total += pad_total
            self._pad_real += pad_real
            # per-bucket adaptive-controller observations (docs/qos):
            # the latency window, the warm capacity set (the rungs the
            # controller may move the batch target along — already
            # compiled, so moving there can never compile), padding
            # waste and the classes whose traffic this bucket carried
            obs = self._bucket_obs.get(b.statics)
            if obs is None:
                obs = self._bucket_obs[b.statics] = {
                    "lat": collections.deque(maxlen=512),
                    "caps": set(), "classes": set(),
                    "pad_real": 0, "pad_total": 0, "n": 0}
            obs["caps"].add(int(capacity))
            obs["classes"].add(b.qos_class)
            obs["pad_total"] += pad_total
            obs["pad_real"] += pad_real
            obs["n"] += k
            for r in cohort:
                lat = now - r.t_submit
                self._latency.append(lat)
                self._latency_by_class[r.qos_class].append(lat)
                obs["lat"].append(lat)
        for r in cohort:
            _QOS_LATENCY.observe(now - r.t_submit,
                                 **{"class": r.qos_class})

    def _stack_common(self, cohort, padded, capacity, *, with_b,
                      padded_b=None) -> tuple:
        dtype = cohort[0].arrays["A"].dtype
        kd = bucketing.stack_pad([r.arrays["kd"] for r in cohort], (2,),
                                 capacity, np.uint32)
        scale = bucketing.stack_pad(
            [np.asarray(r.arrays["scale"]).reshape(()) for r in cohort],
            (), capacity, dtype)
        A = bucketing.stack_pad([r.arrays["A"] for r in cohort], padded,
                                capacity, dtype)
        args = [self._device_put_batch(kd), self._device_put_batch(scale),
                self._device_put_batch(A)]
        if with_b:
            B = bucketing.stack_pad([r.arrays["B"] for r in cohort],
                                    padded_b, capacity, dtype)
            args.append(self._device_put_batch(B))
        return tuple(args)

    @staticmethod
    def _unpad(endpoint: str, out, lane: int, r: _Request):
        if endpoint == "sketch_apply":
            if r.meta["rowwise"]:
                return out[lane, : r.true_shapes["A"][0], :]
            return out[lane, :, : r.true_shapes["A"][1]]
        if endpoint == "fastfood_features":
            p = out[lane, : r.meta["m"], :]
            return p[0] if r.meta["squeeze"] else p
        if endpoint == "solve_l2_sketched":
            x = out[lane]
            return x[:, 0] if r.meta["squeeze"] else x
        if endpoint == "sparse_sketch_apply":
            h, w = r.meta["shape"]
            if r.meta["rowwise"]:
                return out[lane, :h, :]
            return out[lane, :, :w]
        if endpoint == "sparse_solve_l2_sketched":
            x = out[lane]
            return x[:, 0] if r.meta["squeeze"] else x
        if endpoint == "compressed_matmul":
            # (estimate, bound): the view discipline holds for the
            # estimate; the bound is a host float computed at submit
            return (out[lane, : r.meta["m"], : r.meta["p"]],
                    r.meta["bound"])
        if endpoint == "graph_ase":
            return out[lane, : r.meta["n"], :]
        if endpoint == "graph_ppr":
            return out[lane, : r.meta["n"]]
        if endpoint == "condest":
            return out[lane]
        if endpoint == "lowrank":
            return out[lane, : r.meta["m"], :]
        if endpoint == "rlsc_predict":
            p = out[lane, : r.meta["q"]]
            coding = r.meta.get("coding")
            if coding is not None:
                p = np.asarray([coding[int(i)] for i in p])
            return p[0] if r.meta["squeeze_q"] else p
        p = out[lane, : r.meta["q"], :]
        if r.meta["squeeze_t"]:
            p = p[:, 0]
        if r.meta["squeeze_q"]:
            p = p[0]
        return p

    # ------------------------------------------------------------------
    # health + drain
    # ------------------------------------------------------------------

    @property
    def state(self) -> str:
        """``SERVING`` | ``DEGRADED`` | ``DRAINING`` | ``STOPPED``.

        DEGRADED = the recent flush-attempt failure ratio (over the
        ``failure_window`` sliding window) is at or past
        ``degraded_threshold``; submits load-shed at ``max_queue *
        shed_fraction`` instead of queueing behind a failing flush
        path. The state self-heals: successful flushes push the ratio
        back down."""
        with self._lock:
            if self._stop:
                return STOPPED
            if self._draining:
                return DRAINING
        return DEGRADED if self._is_degraded() else SERVING

    def queue_depth(self) -> int:
        """Pending + in-flight request count — the live load signal the
        fleet router's spill heuristic reads. Note this is a superset
        of the telemetry ``queued`` gauge, which reports only the
        pending (not-yet-dispatched) count: under high in-flight load
        the router sees a larger number than a scraped dashboard, so
        tune ``Router.spill_threshold`` against this method, not the
        gauge."""
        with self._lock:
            return self._pending + self._inflight

    def latency_quantile(self, q: float = 0.99) -> Optional[float]:
        """One quantile of the r10 request-latency histogram (seconds;
        ``None`` before any completion). Cheaper than :meth:`stats`
        (no counter snapshot) — the fleet router derives its hedge
        delay from this, and the autoscaler reads it at tick cadence,
        so it must not contend with the flush path for more than the
        stats lock."""
        with self._stats_lock:
            lat = sorted(self._latency)
        return _percentile(lat, q)

    def qos_bucket_obs(self) -> dict:
        """Per-bucket adaptive-controller observations: ``statics ->
        {p99, padding_waste, caps, classes, n}`` (docs/qos). The
        controller's read side — cheap (one stats-lock snapshot), no
        contention with the flush path beyond that lock."""
        with self._stats_lock:
            snap = {
                statics: {
                    "lat": sorted(o["lat"]),
                    "caps": frozenset(o["caps"]),
                    "classes": frozenset(o["classes"]),
                    "pad_real": o["pad_real"],
                    "pad_total": o["pad_total"],
                    "n": o["n"],
                }
                for statics, o in self._bucket_obs.items()
            }
        return {
            statics: {
                "p99": _percentile(o["lat"], 0.99),
                "padding_waste": (
                    round(1.0 - o["pad_real"] / o["pad_total"], 4)
                    if o["pad_total"] else None),
                "caps": o["caps"],
                "classes": o["classes"],
                "n": o["n"],
            }
            for statics, o in snap.items()
        }

    def qos_reset_bucket_obs(self, statics) -> None:
        """Drop one bucket's latency window and padding-waste counts
        (the warm capacity set and class set persist — the
        zero-recompile rungs must survive a reset). The adaptive
        controller calls this after acting on a bucket so the next
        decision scores post-change evidence: without it, the burst
        that triggered a step keeps dominating the rolling window and
        drives repeated same-direction steps long after the live
        latency recovered."""
        with self._stats_lock:
            o = self._bucket_obs.get(tuple(statics))
            if o is not None:
                o["lat"].clear()
                o["pad_real"] = 0
                o["pad_total"] = 0

    def _qos_stats_block(self) -> dict:
        """The ``stats()["qos"]`` block: per-class admission/shed/
        rate-limit counters, queue depths, latency percentiles, the
        scheduler's deficit state, the live adaptive targets and the
        controller rollup — rendered on the Prometheus surface by
        the ``qos`` collector (``skylark_qos_*``)."""
        with self._stats_lock:
            qc = dict(self._qos_counts)
            lat_cls = {c: sorted(d)
                       for c, d in self._latency_by_class.items()}
        with self._lock:
            depth = {c: int(self._class_pending.get(c, 0))
                     for c in _qtenants.CLASSES}
            targets = {
                str(statics[0]): {"linger_s": round(float(t[0]), 6),
                                  "batch": int(t[1])}
                for statics, t in self._qos_targets.items()}
        by_class: dict = {
            c: {"admitted": 0, "shed": 0, "rate_limited": 0,
                "queue_depth": depth[c]}
            for c in _qtenants.CLASSES}
        by_tenant: dict = {}
        for (kind, cls, tenant), n in qc.items():
            by_class[cls][kind] += n
            if tenant:
                t = by_tenant.setdefault(
                    tenant, {"admitted": 0, "shed": 0,
                             "rate_limited": 0})
                t[kind] += n
        for c, lat in lat_cls.items():
            by_class[c]["latency_s"] = {
                "p50": _percentile(lat, 0.50),
                "p99": _percentile(lat, 0.99),
                "n": len(lat),
            }
        return {
            "by_class": by_class,
            "by_tenant": dict(sorted(by_tenant.items())),
            "scheduler": self._sched.stats(),
            "targets": targets,
            "controller": (self._controller.stats()
                           if self._controller is not None else None),
        }

    def _maybe_publish_state(self) -> None:
        """Publish a health-state transition to the resilience hub
        (:mod:`libskylark_tpu.resilience.health`) if one happened —
        the push-side a fleet router subscribes to. Called from every
        root flush outcome (DEGRADED flips, both directions), from
        :meth:`drain` (DRAINING) and :meth:`shutdown` (STOPPED).
        Callbacks run outside the executor lock; the publish lock only
        serializes the compare-and-set so two racing workers can't
        both announce the same transition. The state read must happen
        INSIDE the publish lock: read outside, a worker descheduled
        between read and acquire would publish its stale snapshot
        after a peer already announced a newer one."""
        with self._pub_lock:
            new = self.state
            old = self._published_state
            if new == old:
                return
            self._published_state = new
            # publish under the (executor-independent) publish lock so
            # racing transitions reach subscribers in order — a
            # DEGRADED announcement landing after the recovery to
            # SERVING would wedge a router's view of a healthy replica
            _health.publish(self, old, new)

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Preemption-safe drain: stop intake (new submits raise
        :class:`ServeOverloadedError`), flush every queued cohort, and
        wait until every in-flight future has resolved, then stop the
        threads. Returns whether quiescence was reached inside
        ``timeout`` (the executor is stopped either way — a SIGTERM
        handler cannot wait forever). Idempotent; called by
        :func:`libskylark_tpu.resilience.install_preemption_handler`
        on SIGTERM for every live executor."""
        dl = Deadline.after(timeout)
        with self._lock:
            if self._stop:
                return True
            self._draining = True
            self._work_cv.notify_all()
            self._space_cv.notify_all()
        # announce DRAINING before waiting for quiescence: a subscribed
        # router must shed new traffic to peers WHILE the drain flushes
        # the queue, not after
        self._maybe_publish_state()
        with self._lock:
            drained = True
            while self._pending or self._inflight or self._buckets:
                rem = dl.remaining()
                if rem <= 0:
                    drained = False
                    break
                self._idle_cv.wait(
                    timeout=0.1 if rem == float("inf") else min(rem, 0.1))
        # live session state is checkpointed HERE — the r9 drain hook
        # discipline (docs/sessions "Graceful handoff"): journal
        # fsync'd + accumulator snapshot durable before the executor
        # stops, so a peer resumes the stream from state. Runs even on
        # a drain timeout (the journal already holds every accepted
        # append; the checkpoint just bounds the peer's replay).
        try:
            self._checkpoint_sessions()
        except Exception as e:  # noqa: BLE001 — the drain must finish
            warnings.warn(f"session checkpoint during drain failed: "
                          f"{e}", RuntimeWarning, stacklevel=2)
        # on timeout a cohort is wedged in execution — joining the
        # threads would block past the deadline the caller (a SIGTERM
        # grace window) budgeted, starving the checkpoint hooks that
        # run after the drain; stop without waiting instead
        self.shutdown(wait=drained)
        return drained

    # ------------------------------------------------------------------
    # stats + lifecycle
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        """Snapshot of the serving counters (see module docstring)."""
        with self._stats_lock:
            lat = sorted(self._latency)
            c = dict(self._counts)
            batch_hist = dict(sorted(self._batch_hist.items()))
            cohort_hist = dict(sorted(self._cohort_hist.items()))
            pad_real, pad_total = self._pad_real, self._pad_total
            ksel = dict(sorted(self._kernel_sel.items()))
            kdec = dict(sorted(self._kernel_dec.items()))
            sp_sel = dict(sorted(self._sparse_kernel_sel.items()))
            sp_nnz = dict(sorted(self._sparse_nnz_hist.items()))
            fw_sel = dict(sorted(self._fwht_sel.items()))
            dist_by = dict(self._dist_by_replica)
        with self._lock:
            queued = self._pending
        return {
            "state": self.state,
            "submitted": c.get("submitted", 0),
            "completed": c.get("completed", 0),
            "failed": c.get("failed", 0),
            "rejected": c.get("rejected", 0),
            "shed": c.get("shed", 0),
            "session_shed": c.get("session_shed", 0),
            "expired": c.get("expired", 0),
            "poisoned": c.get("poisoned", 0),
            "flush_failures": c.get("flush_failures", 0),
            "isolation_retries": c.get("isolation_retries", 0),
            "isolation_depth_peak": c.get("isolation_depth_peak", 0),
            "queued": queued,
            "queued_peak": c.get("queued_peak", 0),
            "coalesced": c.get("coalesced", 0),
            "flushes": c.get("flushes", 0),
            # by_<label> convention (docs/observability): renders on
            # the Prometheus surface as skylark_serve_kernel_flushes
            # {backend="pallas"} / ..._declined_flushes{reason="..."}
            "kernel": {
                "by_backend": {k: {"flushes": int(v)}
                               for k, v in ksel.items()},
                "by_reason": {k: {"declined_flushes": int(v)}
                              for k, v in kdec.items()},
            },
            # sparse-operand intake/flush disaggregation (docs/serving,
            # "Sparse operands on the serve path"); by_backend renders
            # as skylark_serve_sparse_kernel_flushes{backend="..."}
            "sparse": {
                "submits": c.get("sparse_submits", 0),
                "densified": c.get("sparse_densified", 0),
                "by_backend": {k: {"kernel_flushes": int(v)}
                               for k, v in sp_sel.items()},
                "nnz_class_hist": sp_nnz,
            },
            # panel-free FWHT tier (docs/performance, "In-kernel FWHT
            # and compressed matmul"); by_backend renders as
            # skylark_serve_fwht_flushes{backend="..."}
            "fwht": {
                "by_backend": {k: {"flushes": int(v)}
                               for k, v in fw_sel.items()},
                "cm_submits": c.get("cm_submits", 0),
            },
            # pipelined dist-serve jobs (docs/distributed): by_replica
            # renders as skylark_dist_shard_tasks{replica="..."} — the
            # shard placement skew surface
            "dist": {
                "jobs": c.get("dist_jobs", 0),
                "completed": c.get("dist_completed", 0),
                "failed": c.get("dist_failed", 0),
                "early_resolves": c.get("dist_early_resolves", 0),
                "by_replica": {k: {"shard_tasks": int(v)}
                               for k, v in sorted(dist_by.items())},
            },
            "batch_capacity_hist": batch_hist,
            "cohort_size_hist": cohort_hist,
            "padding_waste_ratio": (
                round(1.0 - pad_real / pad_total, 4) if pad_total else None
            ),
            "latency_s": {
                "p50": _percentile(lat, 0.50),
                "p99": _percentile(lat, 0.99),
                "mean": (sum(lat) / len(lat)) if lat else None,
                "n": len(lat),
            },
            # the multi-tenant QoS block (docs/qos): per-class
            # admission/shed/latency, scheduler deficits, adaptive
            # targets — the "qos" telemetry collector aggregates it
            # across executors
            "qos": self._qos_stats_block(),
            # the stateful-session block (None until the first session
            # verb; the cross-registry rollup is the "sessions"
            # telemetry collector)
            "sessions": (self._session_registry.stats()
                         if self._session_registry is not None
                         else None),
            # the training-job block (docs/training; None until the
            # first submit — the cross-executor rollup is the "train"
            # telemetry collector) plus the shed counter, which lives
            # on the executor because shedding happens before the
            # manager is consulted
            "train": (dict(self._train_mgr.stats(),
                           shed=c.get("train_shed", 0))
                      if self._train_mgr is not None
                      else None),
            # the result-cache block (docs/caching): None until the
            # cache is enabled or an operand is pinned; the "cache"
            # telemetry collector aggregates it across executors
            "cache": self._cache_stats_block(),
        }

    def shutdown(self, wait: bool = True) -> None:
        """Stop intake, flush everything pending, join the threads."""
        with self._lock:
            if self._stop:
                return
            self._stop = True
            self._work_cv.notify_all()
            self._space_cv.notify_all()
        self._maybe_publish_state()
        if self._controller is not None:
            self._controller.close()
        if wait:
            self._flusher.join()
            for t in self._workers:
                t.join()
        # live training jobs are released, not failed: their sessions
        # stay on disk (the drain hook checkpointed them) and each
        # unresolved job future breaks retryably so a router's resume
        # chain re-homes the job on a surviving replica
        mgr = self._train_mgr
        if mgr is not None:
            try:
                mgr.release_jobs(
                    f"executor {self.name!r} stopped mid-job; the "
                    "session remains on disk for a peer to resume")
            except Exception:  # noqa: BLE001 — shutdown must finish
                pass
        # sync the session journals WITHOUT deleting artifacts — a
        # peer (or a restarted process) resumes them from disk
        reg = self._session_registry
        if reg is not None:
            try:
                reg.close()
            except Exception:  # noqa: BLE001 — shutdown must finish
                pass

    def __enter__(self) -> "MicrobatchExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


_EXECUTORS: "weakref.WeakSet[MicrobatchExecutor]" = weakref.WeakSet()


def _merge_qos_blocks(blocks) -> dict:
    """Cross-executor merge of per-executor ``stats()["qos"]`` blocks
    — shared by :func:`serve_stats` and the ``qos`` collector so the
    aggregation semantics (counters sum, queue depths sum, served
    counts sum, tenants union) cannot drift apart."""
    qos_class: dict = {
        c: collections.Counter() for c in _qtenants.CLASSES}
    qos_tenant: dict = {}
    qos_served: "collections.Counter" = collections.Counter()
    for q in blocks:
        for cc, blk in q["by_class"].items():
            for kk in ("admitted", "shed", "rate_limited",
                       "queue_depth"):
                qos_class[cc][kk] += blk.get(kk, 0)
        for tname, blk in q["by_tenant"].items():
            t = qos_tenant.setdefault(tname, collections.Counter())
            t.update(blk)
        qos_served.update(q["scheduler"]["served"])
    return {
        "by_class": {c: dict(qos_class[c]) for c in _qtenants.CLASSES},
        "by_tenant": {t: dict(v)
                      for t, v in sorted(qos_tenant.items())},
        "served": dict(qos_served),
    }


def serve_stats() -> dict:
    """Aggregate counters across every live executor in the process
    (the serve analog of ``engine.stats()``; folded into
    ``engine.dump_stats`` under ``"serve"``), disaggregated per
    replica under ``by_replica``.

    Aggregation semantics over N executors (the r11 fix — the
    single-executor-era version summed what it knew and silently
    dropped the rest): monotone counters SUM; the peak diagnostics
    (``queued_peak``, ``isolation_depth_peak``) take the MAX — summing
    a per-replica high-water mark across replicas would report a queue
    depth no single executor ever saw; the capacity/cohort histograms
    merge bin-wise; padding waste re-derives from the pooled raw
    element counts (a mean of per-replica ratios would weight an idle
    replica equally with a loaded one); latency percentiles come from
    the pooled samples; ``states`` counts executors per health state.
    ``by_replica`` keys each executor's own :meth:`stats()` block by
    its ``name`` — the replica label telemetry and the Prometheus
    renderer use (``docs/observability``)."""
    agg: dict = {"executors": 0}
    _SUM_KEYS = ("submitted", "completed", "failed", "rejected", "shed",
                 "session_shed", "expired", "poisoned",
                 "flush_failures", "isolation_retries", "queued",
                 "coalesced", "flushes")
    _MAX_KEYS = ("queued_peak", "isolation_depth_peak")
    sums = collections.Counter({k: 0 for k in _SUM_KEYS})
    maxes = {k: 0 for k in _MAX_KEYS}
    batch_hist: "collections.Counter" = collections.Counter()
    cohort_hist: "collections.Counter" = collections.Counter()
    states: "collections.Counter" = collections.Counter()
    ksel: "collections.Counter" = collections.Counter()
    kdec: "collections.Counter" = collections.Counter()
    sparse_sums: "collections.Counter" = collections.Counter(
        {"submits": 0, "densified": 0})
    sparse_sel: "collections.Counter" = collections.Counter()
    sparse_nnz: "collections.Counter" = collections.Counter()
    fwht_sel: "collections.Counter" = collections.Counter()
    cm_submits = 0
    dist_sums: "collections.Counter" = collections.Counter(
        {"jobs": 0, "completed": 0, "failed": 0, "early_resolves": 0})
    dist_by: "collections.Counter" = collections.Counter()
    _TRAIN_SUM = ("jobs_submitted", "slices_run", "preemptions",
                  "resumes", "budget_exhausted", "completed", "failed",
                  "retries", "active", "queued", "shed")
    train_sums: "collections.Counter" = collections.Counter(
        {k: 0 for k in _TRAIN_SUM})
    train_seen = False
    qos_blocks: list = []
    cache_blocks: list = []
    by_replica: dict = {}
    lat_all: list = []
    waste_real = waste_total = 0
    for ex in list(_EXECUTORS):
        s = ex.stats()
        agg["executors"] += 1
        for k in _SUM_KEYS:
            sums[k] += s[k]
        for k in _MAX_KEYS:
            maxes[k] = max(maxes[k], s.get(k, 0))
        batch_hist.update(s["batch_capacity_hist"])
        cohort_hist.update(s["cohort_size_hist"])
        for kk, vv in s["kernel"]["by_backend"].items():
            ksel[kk] += vv["flushes"]
        for kk, vv in s["kernel"]["by_reason"].items():
            kdec[kk] += vv["declined_flushes"]
        sparse_sums["submits"] += s["sparse"]["submits"]
        sparse_sums["densified"] += s["sparse"]["densified"]
        for kk, vv in s["sparse"]["by_backend"].items():
            sparse_sel[kk] += vv["kernel_flushes"]
        sparse_nnz.update(s["sparse"]["nnz_class_hist"])
        for kk, vv in s["fwht"]["by_backend"].items():
            fwht_sel[kk] += vv["flushes"]
        cm_submits += s["fwht"]["cm_submits"]
        for kk in ("jobs", "completed", "failed", "early_resolves"):
            dist_sums[kk] += s["dist"][kk]
        for kk, vv in s["dist"]["by_replica"].items():
            dist_by[kk] += vv["shard_tasks"]
        if s.get("train") is not None:
            train_seen = True
            for kk in _TRAIN_SUM:
                train_sums[kk] += int(s["train"].get(kk, 0))
        qos_blocks.append(s["qos"])
        cache_blocks.append(s.get("cache"))
        states[s["state"]] += 1
        if s["padding_waste_ratio"] is not None:
            with ex._stats_lock:
                waste_real += ex._pad_real
                waste_total += ex._pad_total
        with ex._stats_lock:
            lat_all.extend(ex._latency)
        name = ex.name
        while name in by_replica:     # defensive: caller reused a name
            name += "+"
        by_replica[name] = s
    agg.update(sums)
    agg.update(maxes)
    agg["batch_capacity_hist"] = dict(sorted(batch_hist.items()))
    agg["cohort_size_hist"] = dict(sorted(cohort_hist.items()))
    agg["kernel"] = {
        "by_backend": {k: {"flushes": int(v)}
                       for k, v in sorted(ksel.items())},
        "by_reason": {k: {"declined_flushes": int(v)}
                      for k, v in sorted(kdec.items())},
    }
    agg["sparse"] = {
        "submits": sparse_sums["submits"],
        "densified": sparse_sums["densified"],
        "by_backend": {k: {"kernel_flushes": int(v)}
                       for k, v in sorted(sparse_sel.items())},
        "nnz_class_hist": dict(sorted(sparse_nnz.items())),
    }
    agg["fwht"] = {
        "by_backend": {k: {"flushes": int(v)}
                       for k, v in sorted(fwht_sel.items())},
        "cm_submits": int(cm_submits),
    }
    # dist-serve rollup (docs/distributed): executor job counters,
    # fleet-wide shard placement, plus the process-lifetime rollups of
    # the coordinator and the dist-serve driver (imported lazily —
    # dist pulls the engine package, not the other way around)
    agg["dist"] = {
        **{k: int(dist_sums[k]) for k in
           ("jobs", "completed", "failed", "early_resolves")},
        "by_replica": {k: {"shard_tasks": int(v)}
                       for k, v in sorted(dist_by.items())},
    }
    try:
        from libskylark_tpu.dist.coordinator import dist_stats
        from libskylark_tpu.dist.serve import dist_serve_stats
        agg["dist"]["lifetime"] = {"coordinator": dist_stats(),
                                   "serve": dist_serve_stats()}
    except Exception:  # noqa: BLE001 — stats must never fail serving
        pass
    # training-job rollup (docs/training): monotone counters and live
    # occupancy SUM across replicas; None when no replica ever ran one
    agg["train"] = ({k: int(train_sums[k]) for k in _TRAIN_SUM}
                    if train_seen else None)
    agg["qos"] = _merge_qos_blocks(qos_blocks)
    agg["cache"] = _rcache.merge_cache_blocks(cache_blocks)
    agg["states"] = dict(sorted(states.items()))
    agg["padding_waste_ratio"] = (
        round(1.0 - waste_real / waste_total, 4) if waste_total else None)
    lat_all.sort()
    agg["latency_s"] = {"p50": _percentile(lat_all, 0.50),
                        "p99": _percentile(lat_all, 0.99),
                        "n": len(lat_all)}
    agg["by_replica"] = dict(sorted(by_replica.items()))
    # network front-door rollup (docs/networking) — only when the net
    # tier is actually loaded (the sys.modules guard keeps a pure
    # in-process deployment from importing the socket layer just to
    # report stats about it)
    if "libskylark_tpu.net.server" in sys.modules:
        try:
            from libskylark_tpu.net.server import net_stats
            agg["net"] = net_stats()
        except Exception:  # noqa: BLE001 — stats must never fail serving
            pass
    return agg


# telemetry re-homing (docs/observability): the executor's counters are
# authoritative — the collector snapshots the cross-executor aggregate
# (including the live ``queued`` queue-depth gauge) instead of double-
# counting on the submit/flush hot paths.
_telemetry.register_collector("serve", serve_stats)


def qos_stats() -> dict:
    """Cross-executor multi-tenant QoS aggregate (the ``qos``
    collector block in ``telemetry.snapshot()``; renders as
    ``skylark_qos_*`` on the Prometheus surface — the ``by_class`` /
    ``by_tenant`` sub-blocks become label sets). Aggregates the
    per-executor qos blocks DIRECTLY (not via :func:`serve_stats` —
    a snapshot already runs the ``serve`` collector, and re-running
    the full cross-executor aggregation would double every scrape's
    latency-sort cost). Folds in the process-global tenant registry
    so a scrape shows the registered tenants and their live token
    balances."""
    agg = _merge_qos_blocks(
        [ex._qos_stats_block() for ex in list(_EXECUTORS)])
    agg["registry"] = _qtenants.get_registry().stats()
    return agg


_telemetry.register_collector("qos", qos_stats)


def cache_stats() -> dict:
    """Cross-executor result-cache aggregate (the ``cache`` collector
    block in ``telemetry.snapshot()``; renders as ``skylark_cache_*``
    on the Prometheus surface — ``by_class`` becomes the class label
    set). Aggregates the per-executor cache blocks DIRECTLY, not via
    :func:`serve_stats` — same double-scrape rationale as
    :func:`qos_stats`; cache-off executors contribute nothing."""
    return _rcache.merge_cache_blocks(
        [ex._cache_stats_block() for ex in list(_EXECUTORS)])


_telemetry.register_collector("cache", cache_stats)
