"""``engine.warmup`` — warmup packs: precompiled serve-bucket bundles
for zero-recompile fleet boot.

A **warmup pack** is a directory holding (a) one serialized AOT
artifact per hot ``(serve bucket, capacity)`` executable — the exact
programs a :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor`
flushes — and (b) a ``pack.json`` manifest recording, per entry, the
artifact digest, the endpoint/bucket statics, the capacity class, and
the **kernel decision** the tuner certified for that bucket (the r12
``plan_id`` static), plus the pack-wide compat stamp and the plan-cache
fingerprint everything was keyed under.

Boot flow (docs/performance, "Persistent AOT artifacts & warmup
packs"): a fresh process — a cold autoscaled replica, a
:class:`~libskylark_tpu.fleet.ProcessReplica` child — calls
:func:`load_pack` (or ``MicrobatchExecutor.load_warmup_pack``) before
accepting traffic. Every packed executable deserializes straight into
the process executable cache under its original key, and the packed
kernel decisions seed the executor's flush-kernel memo, so the first
request of every packed bucket is a cache **hit**: zero tracing, zero
backend compiles, bit-equal results (the executable is byte-identical
to the builder's).

Invalidation is inherited from the key, not re-implemented: a plan
edit changes the plan fingerprint (pack skipped, buckets recompile), a
code change re-keys (artifacts never hit), a jax upgrade / backend /
device change fails the compat probe (pack skipped). A skipped or
partial pack is never an error unless ``strict=True`` — boot degrades
to the ordinary compile path.

Pack **selection** (:func:`select_top_buckets`) reads the tune plan
cache's serve-bucket entries (``serve_sketch_rw`` / ``serve_sketch_cw``
/ ``serve_fastfood`` workloads, measured entries first) and optionally
a serve-stats block (``batch_capacity_hist`` from telemetry or a
``dump_stats`` artifact) to order capacities by live traffic — the
top-N (bucket, capacity) keys a fleet actually serves.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional, Sequence

from libskylark_tpu.engine import aot as _aot


def _compiled_module():
    """The :mod:`libskylark_tpu.engine.compiled` module — fetched by
    full name because the package re-exports the same-named decorator,
    shadowing the submodule attribute."""
    import importlib

    return importlib.import_module("libskylark_tpu.engine.compiled")


PACK_SCHEMA = 1
MANIFEST = "pack.json"
_ARTIFACTS = "artifacts"

#: serve-tune op -> (endpoint, rowwise) for plan-cache selection
_SERVE_OPS = {
    "serve_sketch_rw": ("sketch_apply", True),
    "serve_sketch_cw": ("sketch_apply", False),
    "serve_fastfood": ("fastfood_features", True),
}


@dataclasses.dataclass
class BucketSpec:
    """One serve bucket to precompile: the transform class and a
    representative operand shape (padding classes derive exactly as
    they do on the serve path, so a pow2-padded representative *is*
    the class)."""

    endpoint: str             # "sketch_apply" | "fastfood_features"
    family: str               # "JLT" | "CWT" | "FastGaussianRFT" | ...
    n: int                    # transform input dim (contracted extent)
    m: int                    # free extent (rows rowwise / cols columnwise)
    s_dim: int
    dtype: str = "float32"
    rowwise: bool = False
    capacities: tuple = (1,)
    sigma: float = 1.0        # fastfood kernel bandwidth (bucket static)
    seed: int = 0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["capacities"] = list(self.capacities)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "BucketSpec":
        d = dict(d)
        d["capacities"] = tuple(int(c) for c in d.get("capacities", (1,)))
        return cls(**d)


def _make_transform(spec: BucketSpec):
    from libskylark_tpu import Context
    from libskylark_tpu import sketch as sk

    ctx = Context(seed=int(spec.seed))
    if spec.family == "CWT":
        return sk.CWT(spec.n, spec.s_dim, ctx)
    if spec.family == "JLT":
        return sk.JLT(spec.n, spec.s_dim, ctx)
    if spec.family == "CT":
        return sk.CT(spec.n, spec.s_dim, ctx)
    if spec.family == "FastGaussianRFT":
        return sk.FastGaussianRFT(spec.n, spec.s_dim, ctx,
                                  sigma=spec.sigma)
    if spec.family == "FastMaternRFT":
        # the spec's sigma rides as the length scale l
        return sk.FastMaternRFT(spec.n, spec.s_dim, ctx, nu=1.5,
                                l=spec.sigma)
    raise ValueError(f"warmup pack cannot build family {spec.family!r}")


def _spec_requests(spec: BucketSpec, capacity: int):
    """``capacity`` distinct (transform, operand) pairs for one flush
    of the spec's bucket — ragged free extents inside one padding
    class, like real traffic."""
    import numpy as np

    rng = np.random.default_rng(spec.seed + capacity)
    out = []
    for i in range(capacity):
        T = _make_transform(dataclasses.replace(spec, seed=spec.seed + i))
        m = max(1, spec.m - (i % min(4, spec.m)))
        if spec.endpoint == "fastfood_features":
            shape = (m, spec.n)
        else:
            shape = (m, spec.n) if spec.rowwise else (spec.n, m)
        A = rng.standard_normal(shape).astype(spec.dtype)
        out.append((T, A))
    return out


def _submit(ex, spec: BucketSpec, T, A):
    from libskylark_tpu.sketch import COLUMNWISE, ROWWISE

    if spec.endpoint == "fastfood_features":
        return ex.submit_fastfood(T, A)
    return ex.submit_sketch(T, A,
                            dimension=ROWWISE if spec.rowwise
                            else COLUMNWISE)


def result_digest(arrays) -> str:
    """Content hash of a cohort's results (shape + dtype + bytes per
    lane) — the bit-equality witness the boot probe compares against
    the builder's recorded value."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for a in arrays:
        a = np.asarray(a)
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:32]


def _entry_from_key(key: tuple) -> dict:
    """Manifest entry metadata recovered from one executable-cache key
    (see engine/compiled docstring for the tuple anatomy)."""
    statics, kernel = _statics_and_kernel(key)
    capacity = None
    if key[4]:
        lead = key[4][0][0]
        capacity = int(lead[0]) if lead else None
    return {
        "digest": _aot.key_digest(key),
        "name": key[0],
        "endpoint": statics[0] if statics else None,
        "kernel": kernel,
        "capacity": capacity,
        "statics": repr(statics),
    }


def build_pack(pack_dir: str, specs: Sequence, *,
               pad_floor: Optional[int] = None, workers: int = 1,
               reset_engine: bool = True) -> dict:
    """Precompile every (spec, capacity) serve executable and serialize
    it into ``pack_dir`` (artifacts under ``artifacts/``, manifest at
    ``pack.json``). Returns the manifest.

    The builder drives a real :class:`MicrobatchExecutor` — the packed
    executables are the genuine serve programs (same statics, same
    avals, same kernel resolution), not reconstructions. By default the
    process executable cache is reset first so every packed key
    demonstrably produces an artifact (an offline builder has no warm
    cache worth keeping); pass ``reset_engine=False`` to ride an
    existing warm cache when you know the artifacts already exist.
    """
    from libskylark_tpu.engine import bucket as bucketing

    _compiled = _compiled_module()

    specs = [s if isinstance(s, BucketSpec) else BucketSpec.from_dict(s)
             for s in specs]
    if not specs:
        raise ValueError("a warmup pack needs at least one bucket spec")
    max_cap = max(max(s.capacities) for s in specs)
    artifacts = os.path.join(pack_dir, _ARTIFACTS)
    os.makedirs(artifacts, exist_ok=True)
    if reset_engine:
        _compiled.reset()

    from libskylark_tpu.engine.serve import MicrobatchExecutor

    entries: list[dict] = []
    with _aot.override_dir(artifacts):
        ex = MicrobatchExecutor(
            max_batch=max_cap, linger_us=50_000, workers=workers,
            pad_floor=pad_floor if pad_floor is not None
            else bucketing.PAD_FLOOR)
        try:
            for spec in specs:
                for cap in sorted(set(int(c) for c in spec.capacities)):
                    before = set(_compiled.cache().keys())
                    futs = [_submit(ex, spec, T, A)
                            for (T, A) in _spec_requests(spec, cap)]
                    ex.flush()
                    outs = [f.result(timeout=600) for f in futs]
                    # the canonical cohort is deterministic (seeded
                    # from the spec), so this digest is the value ANY
                    # process serving the packed executable must
                    # reproduce, bit for bit
                    rdigest = result_digest(outs)
                    for k in _compiled.cache().keys():
                        if k not in before:
                            ent = _entry_from_key(k)
                            ent["spec"] = spec.to_dict()
                            ent["results_digest"] = rdigest
                            if not os.path.exists(_aot.artifact_path(
                                    ent["digest"], artifacts)):
                                ent["artifact_missing"] = True
                            entries.append(ent)
        finally:
            ex.shutdown()

    manifest = {
        "schema": PACK_SCHEMA,
        "created": time.time(),
        "compat": _aot.compat_stamp(),
        "plan_fingerprint": _compiled.plan_fingerprint(),
        "pad_floor": int(pad_floor) if pad_floor is not None
        else bucketing.PAD_FLOOR,
        "max_batch": max_cap,
        "entries": entries,
    }
    path = os.path.join(pack_dir, MANIFEST)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return manifest


def read_manifest(pack_dir: str) -> dict:
    path = (pack_dir if pack_dir.endswith(".json")
            else os.path.join(pack_dir, MANIFEST))
    with open(path) as fh:
        return json.load(fh)


def _statics_and_kernel(key: tuple) -> tuple[tuple, Optional[str]]:
    extra = key[3]
    if len(extra) >= 2 and extra[-2] == "kernel":
        return extra[:-2], extra[-1]
    return extra, None


def load_pack(pack_dir: str, executors: Sequence = (), *,
              strict: bool = False) -> dict:
    """Load a pack's executables into the process executable cache and
    seed each executor's flush-kernel memo from the manifest's
    recorded decisions. Returns a report::

        {"entries": N, "loaded": n, "resident": n, "failed": n,
         "kernel_restored": n, "skipped": why-or-None,
         "plan_fingerprint_match": bool}

    Skips (compat mismatch, plan-fingerprint drift) are reported, not
    raised — boot falls back to the compile path — unless ``strict``.
    Loads count as engine ``aot_loads`` (``load_seconds`` split), never
    as misses or compiles: a packed bucket's first request is a HIT.
    An entry whose key is already in the process executable cache (a
    second thread replica booting from the same pack) is counted
    ``resident`` and not deserialized again — only its kernel
    decisions are (re)seeded into the given executors.
    """
    _compiled = _compiled_module()

    report = {"entries": 0, "loaded": 0, "resident": 0, "failed": 0,
              "kernel_restored": 0, "skipped": None,
              "plan_fingerprint_match": None}

    def _bail(why: str) -> dict:
        if strict:
            raise RuntimeError(f"warmup pack {pack_dir!r}: {why}")
        report["skipped"] = why
        return report

    try:
        manifest = read_manifest(pack_dir)
    except Exception as e:  # noqa: BLE001 — a missing pack degrades
        return _bail(f"unreadable manifest ({e!r})")
    if manifest.get("schema") != PACK_SCHEMA:
        return _bail(f"schema {manifest.get('schema')!r} != {PACK_SCHEMA}")
    report["entries"] = len(manifest.get("entries", ()))
    ok, why = _aot.compat_probe(manifest.get("compat"))
    if not ok:
        return _bail(f"compat: {why}")
    fp = _compiled.plan_fingerprint()
    fp_match = fp == manifest.get("plan_fingerprint")
    report["plan_fingerprint_match"] = fp_match
    if not fp_match:
        # every packed key embeds the builder's fingerprint — none
        # could ever be hit; the tuner's plans changed, so the buckets
        # must legitimately recompile under the new decisions
        return _bail("plan-fingerprint drift (plan cache edited since "
                     "the pack was built)")

    root = (os.path.dirname(pack_dir) if pack_dir.endswith(".json")
            else pack_dir)
    from libskylark_tpu.engine.cache import CacheEntry

    resident = {repr(k): k for k in _compiled.cache().keys()}
    for ent in manifest.get("entries", ()):
        path = _aot.artifact_path(ent["digest"],
                                  os.path.join(root, _ARTIFACTS))
        t0 = time.perf_counter()
        key = None
        if resident:
            # header-only probe: a key already in the process cache
            # (another thread replica loaded this pack) needs no
            # second deserialize, just its kernel seeding below. Only
            # worth the extra read when anything IS resident — the
            # common fresh-process boot goes straight to load_file
            try:
                key = resident.get(
                    _aot.read_header(path).get("key_repr"))
            except Exception:  # noqa: BLE001 — load_file reports it
                key = None
        if key is not None:
            report["resident"] += 1
        else:
            try:
                key, executable, header = _aot.load_file(path)
            except Exception as e:  # noqa: BLE001 — per-entry containment
                report["failed"] += 1
                _compiled.cache().note_aot_load_failure()
                if strict:
                    raise RuntimeError(
                        f"warmup pack entry {ent.get('digest')}: {e!r}"
                    ) from e
                continue
            dt = time.perf_counter() - t0
            _compiled.cache().insert(key, CacheEntry(
                executable=executable, name=header.get("name", "packed"),
                compile_seconds=0.0, loaded=True))
            _compiled.cache().note_aot_load(dt)
            report["loaded"] += 1
        token = ent.get("kernel")
        if token:
            statics, _tok = _statics_and_kernel(key)
            capacity = ent.get("capacity")
            for ex in executors:
                if capacity and ex.restore_kernel_choice(
                        statics, capacity, token):
                    report["kernel_restored"] += 1
    return report


def serve_probe(pack_dir: str, *, load: bool = True,
                strict: bool = False) -> dict:
    """Boot-and-serve probe: regenerate every manifest entry's
    canonical cohort from its recorded spec, serve it through a fresh
    executor — after loading the pack when ``load`` (the warm side of
    the boot A/B) or straight onto the compile path when not (the cold
    side) — and compare result digests against the builder's. The
    ``bench.py --boot`` children and the CI boot gate
    (``benchmarks/boot_smoke.py``) both run exactly this, so the
    "zero backend compiles + bit-equal" claim has one implementation.

    Returns ``{"entries", "served", "bit_equal", "mismatches",
    "warmup": load-report-or-None, "engine": stats dict,
    "t_first_result_s", "t_total_s"}``. Engine counters are read as a
    delta from function entry, so an in-process caller (tests) sees
    only the probe's own traffic."""
    import time as _time

    _compiled = _compiled_module()
    manifest = read_manifest(pack_dir)
    from libskylark_tpu.engine.serve import MicrobatchExecutor

    s0 = dataclasses.replace(_compiled.stats())
    t_start = _time.perf_counter()
    ex = MicrobatchExecutor(max_batch=int(manifest.get("max_batch", 8)),
                            linger_us=50_000, workers=1,
                            pad_floor=int(manifest.get("pad_floor", 8)))
    report: dict = {"entries": len(manifest.get("entries", ())),
                    "served": 0, "bit_equal": True, "mismatches": [],
                    "warmup": None}
    try:
        if load:
            report["warmup"] = load_pack(pack_dir, executors=(ex,),
                                         strict=strict)
        t_first = None
        for ent in manifest.get("entries", ()):
            spec = BucketSpec.from_dict(ent["spec"])
            cap = int(ent.get("capacity") or 1)
            futs = [_submit(ex, spec, T, A)
                    for (T, A) in _spec_requests(spec, cap)]
            ex.flush()
            outs = [f.result(timeout=600) for f in futs]
            if t_first is None:
                t_first = _time.perf_counter() - t_start
            report["served"] += cap
            got = result_digest(outs)
            want = ent.get("results_digest")
            if want is not None and got != want:
                report["bit_equal"] = False
                report["mismatches"].append(
                    {"digest": ent["digest"], "got": got, "want": want})
        report["t_first_result_s"] = round(t_first, 4) if t_first else None
        report["t_total_s"] = round(_time.perf_counter() - t_start, 4)
    finally:
        ex.shutdown()
    s1 = _compiled.stats()
    delta = {f.name: getattr(s1, f.name) - getattr(s0, f.name)
             for f in dataclasses.fields(s0)}
    delta["compile_seconds"] = round(delta["compile_seconds"], 4)
    delta["load_seconds"] = round(delta["load_seconds"], 4)
    delta["execute_seconds"] = round(delta["execute_seconds"], 4)
    report["engine"] = delta
    return report


def spawn_boot_probe(pack_dir: str, *, load: bool = True,
                     timeout: float = 600.0) -> dict:
    """Run :func:`serve_probe` in a FRESH python process (the
    ``skylark_warmup boot-probe`` CLI) and return its parsed record —
    the one implementation behind ``bench.py --boot``'s children and
    the CI boot gate (``benchmarks/boot_smoke.py``), so the two always
    measure the same thing. The child gets ``SKYLARK_BOOT_T0`` (the
    spawn instant) and reports honest wall-from-spawn
    time-to-first-result.

    The child environment is scrubbed hermetic: an ambient
    ``SKYLARK_AOT_DIR``/``SKYLARK_EXEC_CACHE_DIR`` would let the
    *cold* control load artifacts persisted by an earlier run (zero
    compiles, gate fails spuriously), and an ambient
    ``SKYLARK_SERVE_KERNEL`` pin would make the executor decline every
    packed kernel decision (``kernel_restored == 0``)."""
    import re
    import subprocess
    import sys

    env = dict(os.environ)
    for k in ("SKYLARK_AOT_DIR", "SKYLARK_EXEC_CACHE_DIR",
              "SKYLARK_SERVE_KERNEL"):
        env.pop(k, None)
    env["SKYLARK_BOOT_T0"] = repr(time.time())
    cmd = [sys.executable, "-m",
           "libskylark_tpu.cli.skylark_warmup", "boot-probe",
           "--pack", pack_dir]
    if not load:
        cmd.append("--no-load")
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=repo_root, env=env)
    m = re.search(r"BOOT_PROBE (\{.*\})", proc.stdout + proc.stderr)
    if not m:
        raise RuntimeError(
            f"boot probe (load={load}) produced no record "
            f"rc={proc.returncode}: "
            f"{(proc.stdout + proc.stderr)[-800:]}")
    return json.loads(m.group(1))


# ---------------------------------------------------------------------------
# pack selection: plan cache + serve telemetry
# ---------------------------------------------------------------------------


def _parse_workload_key(key: str) -> Optional[dict]:
    """Recover a serve-bucket spec from one plan-cache key string
    (``device|op|transform|dtype|MxNxS[|bC]``)."""
    parts = key.split("|")
    if len(parts) not in (5, 6):
        return None
    device, op, transform, dtype, shape = parts[:5]
    if op not in _SERVE_OPS:
        return None
    try:
        m, n, s = (int(x) for x in shape.split("x"))
        cap = int(parts[5][1:]) if len(parts) == 6 else 1
    except ValueError:
        return None
    endpoint, rowwise = _SERVE_OPS[op]
    return {"device_kind": device, "endpoint": endpoint,
            "family": transform, "dtype": dtype, "rowwise": rowwise,
            "m": m, "n": n, "s_dim": s, "capacity": cap}


def select_top_buckets(top_n: int = 8, *, stats: Optional[dict] = None,
                       device_kind: Optional[str] = None
                       ) -> list[BucketSpec]:
    """The top-N (bucket, capacity) keys worth packing, from the tune
    plan cache's serve entries — measured certifications first, then
    ranked ones — optionally ordered by a serve-stats block's
    ``batch_capacity_hist`` (hot capacity classes first). ``stats``
    accepts an ``engine.serve_stats()`` dict, a telemetry ``serve``
    collector block, or a ``dump_stats`` artifact's ``serve`` entry.

    Fastfood buckets select at the default bandwidth (``sigma=1.0``) —
    the plan cache's workload key does not carry the bandwidth, which
    is a bucket static; pass explicit :class:`BucketSpec`\\ s to
    :func:`build_pack` for non-default kernels."""
    from libskylark_tpu import tune

    device_kind = device_kind or tune.current_device_kind()
    cap_weight: dict[int, int] = {}
    if stats:
        hist = stats.get("batch_capacity_hist") or {}
        for k, v in hist.items():
            try:
                cap_weight[int(k)] = int(v)
            except (TypeError, ValueError):
                continue
    rows = []
    try:
        entries = dict(tune.get_cache().entries)
    except Exception:  # noqa: BLE001 — no cache, no selection
        entries = {}
    for key, ent in entries.items():
        w = _parse_workload_key(key)
        if w is None:
            continue
        if tune.normalize_device_kind(w["device_kind"]) != \
                tune.normalize_device_kind(device_kind):
            continue
        measured = 1 if ent.get("source") == "measured" else 0
        weight = cap_weight.get(w["capacity"], 0)
        rows.append(((measured, weight, ent.get("recorded", "")), w))
    rows.sort(key=lambda r: r[0], reverse=True)
    specs: list[BucketSpec] = []
    seen: set = set()
    for _rank, w in rows:
        ident = (w["endpoint"], w["family"], w["dtype"], w["rowwise"],
                 w["m"], w["n"], w["s_dim"], w["capacity"])
        if ident in seen:
            continue
        seen.add(ident)
        specs.append(BucketSpec(
            endpoint=w["endpoint"], family=w["family"], n=w["n"],
            m=w["m"], s_dim=w["s_dim"], dtype=w["dtype"],
            rowwise=w["rowwise"], capacities=(w["capacity"],)))
        if len(specs) >= top_n:
            break
    return specs


__all__ = [
    "BucketSpec", "MANIFEST", "PACK_SCHEMA", "build_pack", "load_pack",
    "read_manifest", "result_digest", "select_top_buckets",
    "serve_probe", "spawn_boot_probe",
]
