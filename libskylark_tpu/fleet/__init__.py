"""Fleet subsystem: a replicated serving tier over N microbatch
executors.

One :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor` per
process was the r8–r10 ceiling; this package is the "millions of
users" layer above it (ROADMAP item 1):

- :mod:`~libskylark_tpu.fleet.replica` — the unit of capacity:
  :class:`ThreadReplica` (in-process executor) and
  :class:`ProcessReplica` (spawned child with its own executor,
  preemption handler, and — via ``coordinator=`` — a seat in the
  :mod:`libskylark_tpu.parallel.multihost` distributed pool).
- :mod:`~libskylark_tpu.fleet.pool` — :class:`ReplicaPool`: N uniform
  named replicas, per-replica drain hooks (final checkpoints), and
  single-replica preemption composed with the process-wide r9 SIGTERM
  handler.
- :mod:`~libskylark_tpu.fleet.ring` — the consistent-hash
  :class:`HashRing` that makes routing *sticky*: one bucket class, one
  warm replica, one compile fleet-wide.
- :mod:`~libskylark_tpu.fleet.router` — :class:`Router`: the front
  door whose ``submit`` mirrors the executor API and routes on
  affinity + live queue depth + subscribed health states, failing over
  past refusing/draining replicas with zero client-visible failures —
  and, when enabled, *hedging* stragglers to a second replica after a
  p99-derived delay.
- :mod:`~libskylark_tpu.fleet.shm` — :class:`ShmTransport`: the
  shared-memory operand/result rings that keep a process replica's
  ndarrays off the pickle pipe (zero-copy receive, pickle fallback,
  leak-proof unlink-at-boot lifecycle).
- :mod:`~libskylark_tpu.fleet.autoscale` — :class:`Autoscaler`: the
  queue-depth controller growing the pool via the r13 pack boot and
  shrinking it via the r11 SIGTERM drain, with hysteresis.

Measured by ``bench.py --fleet`` (N-replica vs single-executor A/B,
affinity hit-rate, drain failover), chaos-replayed by
``benchmarks/chaos_battery.py`` (the ``fleet.route`` fault site), and
gated in CI by ``benchmarks/fleet_smoke.py``. See ``docs/fleet``.
"""

from libskylark_tpu.fleet.autoscale import Autoscaler, autoscale_stats
from libskylark_tpu.fleet.pool import ReplicaPool, resolve_backend
from libskylark_tpu.fleet.replica import (PROPAGATED_ENV, ProcessReplica,
                                          Replica, ThreadReplica,
                                          propagated_env)
from libskylark_tpu.fleet.ring import HashRing
from libskylark_tpu.fleet.router import (NoHealthyReplicaError, Router,
                                         fleet_stats)
from libskylark_tpu.fleet.shm import ShmTransport, shm_entries

__all__ = [
    "Autoscaler", "HashRing", "NoHealthyReplicaError", "PROPAGATED_ENV",
    "ProcessReplica", "Replica", "ReplicaPool", "Router",
    "ShmTransport", "ThreadReplica", "autoscale_stats", "fleet_stats",
    "propagated_env", "resolve_backend", "shm_entries",
]
