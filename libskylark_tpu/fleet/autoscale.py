"""Queue-depth autoscaler: elastic replica count under live load.

A fixed-N :class:`~libskylark_tpu.fleet.pool.ReplicaPool` forces the
operator to size for the peak — idle replicas burn memory (a process
replica is a whole interpreter plus an executable cache) and an
under-sized fleet sheds. The :class:`Autoscaler` closes that loop with
the two mechanisms the fleet already has:

- **scale-up is the r13 pack boot**:
  :meth:`~libskylark_tpu.fleet.pool.ReplicaPool.add_replica` builds the
  new replica from the pool's warmup pack, so added capacity serves
  its packed buckets with zero compiles from its first request, and
  the pool's per-replica ``coordinator``/``replica_env`` seats pin it
  to its own device subset;
- **scale-down is the r11 SIGTERM drain**:
  :meth:`~libskylark_tpu.fleet.pool.ReplicaPool.remove_replica`
  preempts the victim (a real SIGTERM for process replicas), the
  health hub announces DRAINING before the queue empties, the router
  sheds its traffic to peers, in-flight futures resolve, and its final
  drain hooks fire — zero client-visible failures by the same
  contract the fleet gate replays.

The control signal is the **queue-depth gauge** (each replica's
``queued + in-flight`` count — the same number the router's spill
heuristic and the telemetry ``queued`` gauge read) plus the **shed
evidence** a subscribed router accumulates (a replica refusing at its
shed bound surfaces as a router failover). The loop is deliberately
dumb and hysteretic:

- scale **up** when the mean depth per replica holds at or above
  ``up_depth`` (or sheds appear) for ``up_ticks`` consecutive ticks;
- scale **down** when it holds at or below ``down_depth`` with no
  sheds for ``down_ticks`` consecutive ticks;
- never outside ``[min_replicas, max_replicas]``, and never within
  ``cooldown_s`` of the previous scale event — a storm's trailing
  edge must not flap the fleet.

Ticks run on one daemon controller thread; a scale event blocks that
thread (a process-replica boot takes seconds) which is itself a
hysteresis — the controller cannot react faster than capacity can
actually change.
"""

from __future__ import annotations

import itertools
import threading
import time
import warnings
import weakref
from typing import Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.fleet.pool import ReplicaPool
from libskylark_tpu.telemetry import metrics as _metrics

_UP = _metrics.counter(
    "fleet.autoscale_up", "Replicas added by the autoscaler")
_DOWN = _metrics.counter(
    "fleet.autoscale_down", "Replicas drained away by the autoscaler")
_REPLICAS = _metrics.gauge(
    "fleet.replicas", "Live replica count of an autoscaled pool, by "
    "scaler (one process can autoscale several pools)")

_SCALERS: "weakref.WeakSet[Autoscaler]" = weakref.WeakSet()
_SCALER_SEQ = itertools.count()

# process-lifetime rollup: scale events survive their Autoscaler (a
# telemetry snapshot taken after an episode's scaler is gone must
# still carry the counts — collectors report live objects only)
_LIFETIME = _metrics.LifetimeCounter(
    "fleet.autoscale_life", kinds=("scale_ups", "scale_downs"))


class Autoscaler:
    """Controller thread scaling a :class:`ReplicaPool` between
    ``min_replicas`` and ``max_replicas`` (see module doc).

    ::

        pool = fleet.ReplicaPool(2, backend="process",
                                 warmup_pack=pack_dir, max_batch=16)
        router = fleet.Router(pool)
        scaler = fleet.Autoscaler(pool, router,
                                  min_replicas=2, max_replicas=8)
        ...
        scaler.close(); router.close(); pool.shutdown()

    ``router`` is optional but recommended: its failover counter is
    the shed evidence that lets the controller react to refusals even
    when queue depths look tame. Every unset knob defaults from the
    ``SKYLARK_FLEET_AUTOSCALE_*`` registry entries (:doc:`env_vars`).
    """

    def __init__(self, pool: ReplicaPool, router=None, *,
                 name: Optional[str] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 up_depth: Optional[int] = None,
                 down_depth: Optional[int] = None,
                 up_ticks: int = 2, down_ticks: int = 8,
                 cooldown_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 drain_timeout: float = 30.0,
                 start: bool = True):
        self.pool = pool
        self.router = router
        # gauge label: two autoscaled pools in one process must not
        # clobber each other's replica count
        self.name = str(name) if name else f"as{next(_SCALER_SEQ)}"
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else _env.FLEET_AUTOSCALE_MIN.get())
        self.max_replicas = int(
            max_replicas if max_replicas is not None
            else _env.FLEET_AUTOSCALE_MAX.get())
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{self.min_replicas}, {self.max_replicas}]")
        self.up_depth = int(up_depth if up_depth is not None
                            else _env.FLEET_AUTOSCALE_UP_DEPTH.get())
        self.down_depth = int(
            down_depth if down_depth is not None
            else _env.FLEET_AUTOSCALE_DOWN_DEPTH.get())
        self.up_ticks = max(int(up_ticks), 1)
        self.down_ticks = max(int(down_ticks), 1)
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else _env.FLEET_AUTOSCALE_COOLDOWN.get())
        self.interval_s = float(
            interval_s if interval_s is not None
            else _env.FLEET_AUTOSCALE_INTERVAL.get())
        self.drain_timeout = float(drain_timeout)
        self._cond = threading.Condition(
            _locks.make_lock("fleet.autoscale"))
        self._stats_lock = _locks.make_lock("fleet.autoscale_stats")
        self._stop = False
        self._up_run = 0
        self._down_run = 0
        self._last_event = 0.0          # monotonic stamp of last scale
        self._failover_seen = 0
        self._counts = {"scale_ups": 0, "scale_downs": 0, "ticks": 0}
        self._events: list = []
        self._added: list = []          # LIFO scale-down preference
        self._thread: Optional[threading.Thread] = None
        _SCALERS.add(self)
        _REPLICAS.set(len(pool.names()), scaler=self.name)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="skylark-fleet-autoscaler",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Stop the controller (the pool keeps its current size)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=self.drain_timeout + 10.0)
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- control loop --------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                self._cond.wait(timeout=self.interval_s)
                if self._stop:
                    return
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — controller lives
                warnings.warn(f"autoscaler tick failed: {e}",
                              RuntimeWarning, stacklevel=1)

    def _shed_delta(self) -> int:
        """New router failovers since the last tick — the shed
        evidence (a replica refusing at its shed bound is a failover
        from the router's point of view)."""
        if self.router is None:
            return 0
        seen = int(self.router.stats().get("failover", 0))
        delta = seen - self._failover_seen
        self._failover_seen = seen
        return max(delta, 0)

    def _tick(self) -> None:
        pool = self.pool
        names = pool.names()
        n = len(names)
        if n == 0:
            return
        depth = 0
        for name in names:
            try:
                depth += pool.get(name).queue_depth()
            except KeyError:
                continue               # removed mid-walk
        mean = depth / n
        shed = self._shed_delta()
        with self._stats_lock:
            self._counts["ticks"] += 1
        up_sig = mean >= self.up_depth or shed > 0
        down_sig = mean <= self.down_depth and shed == 0
        self._up_run = self._up_run + 1 if up_sig else 0
        self._down_run = self._down_run + 1 if down_sig else 0
        now = time.monotonic()
        if now - self._last_event < self.cooldown_s:
            return
        if n < self.min_replicas:
            # below the floor: a crashed member was reaped (the pool's
            # unexpected-exit handler) or an external removal shrank
            # the pool. Replace it NOW via the pack boot — no depth
            # run-up required; the floor is a capacity promise, not a
            # load signal. Cooldown still applies (the stamp in
            # _scale_up), so a persistently failing boot retries at
            # cooldown cadence, not every tick.
            self._scale_up(mean, shed)
        elif (self._up_run >= self.up_ticks
                and n < self.max_replicas):
            self._scale_up(mean, shed)
        elif (self._down_run >= self.down_ticks
              and n > self.min_replicas):
            self._scale_down(mean)

    def _record(self, kind: str, name: str, mean: float,
                shed: int) -> None:
        with self._stats_lock:
            self._counts["scale_ups" if kind == "up"
                         else "scale_downs"] += 1
            self._events.append({
                "kind": kind, "replica": name,
                "mean_depth": round(mean, 2), "shed": shed,
                "replicas": len(self.pool.names()),
            })
            del self._events[:-32]
        _REPLICAS.set(len(self.pool.names()), scaler=self.name)

    def _scale_up(self, mean: float, shed: int) -> None:
        # stamp BEFORE the boot attempt: a persistently failing
        # add_replica (spawn EAGAIN under the very pressure that
        # triggered the scale-up) must get the same cooldown as a
        # success, not a full boot retry every tick
        self._last_event = time.monotonic()
        self._up_run = self._down_run = 0
        name = self.pool.add_replica()   # pack boot (pool.warmup_pack)
        self._added.append(name)
        _UP.inc()
        _LIFETIME.inc("scale_ups")
        self._record("up", name, mean, shed)

    def _scale_down(self, mean: float) -> None:
        # prefer un-growing what we grew (LIFO), else the highest name
        # under NATURAL order — plain lexicographic max would pick
        # "r9" over "r10" and drain an operator-founded replica while
        # a later-grown one survives. Deterministic either way.
        names = set(self.pool.names())
        victim = None
        while self._added:
            cand = self._added.pop()
            if cand in names:
                victim = cand
                break
        if victim is None:
            victim = max(names, key=lambda n: (len(n), n))
        self._last_event = time.monotonic()
        self._up_run = self._down_run = 0
        self.pool.remove_replica(victim, timeout=self.drain_timeout)
        _DOWN.inc()
        _LIFETIME.inc("scale_downs")
        self._record("down", victim, mean, 0)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            c = dict(self._counts)
            events = list(self._events)
        return {
            "replicas": len(self.pool.names()),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "scale_ups": c["scale_ups"],
            "scale_downs": c["scale_downs"],
            "ticks": c["ticks"],
            "events": events,
        }


def autoscale_stats() -> dict:
    """Rollup over every live autoscaler plus the process-lifetime
    scale-event totals (folded into the ``fleet`` telemetry collector
    by :func:`libskylark_tpu.fleet.router.fleet_stats`)."""
    agg = {"scalers": 0, "scale_ups": 0, "scale_downs": 0,
           "replicas": 0}
    for scaler in list(_SCALERS):
        s = scaler.stats()
        agg["scalers"] += 1
        agg["scale_ups"] += s["scale_ups"]
        agg["scale_downs"] += s["scale_downs"]
        agg["replicas"] += s["replicas"]
    agg.update(_LIFETIME.snapshot())
    return agg


__all__ = ["Autoscaler", "autoscale_stats"]
