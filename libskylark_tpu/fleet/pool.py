"""ReplicaPool: owns N named replicas and their shared lifecycle.

The pool is the fleet's capacity layer: it constructs N replicas with
*uniform* executor configuration (one ``pad_floor``, one ``max_batch``
— the router derives affinity keys and spill bounds from the pool, so
a heterogeneous fleet would break sticky routing), names them
``r0..r{N-1}``, and gives the router one place to resolve health-hub
event sources back to replica names.

Preemption composition (the tentpole contract): the pool registers one
:func:`~libskylark_tpu.resilience.on_preemption` hook, so a
process-wide SIGTERM — which drains every in-process executor via the
r9 handler — also runs every replica's registered drain hooks (final
per-replica checkpoints) exactly once. A *single* replica can be
preempted without touching the rest via :meth:`preempt_replica`:
thread replicas drain in place (there is no thread-scoped SIGTERM);
process replicas get a real SIGTERM. Either way the replica's drain
hooks fire, its in-flight futures resolve, and the health hub
announces DRAINING → STOPPED so a subscribed router sheds its traffic
to peers mid-drain.
"""

from __future__ import annotations

import threading
import warnings
from typing import Callable, Dict, List, Optional

from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine import bucket as bucketing
from libskylark_tpu.fleet.replica import (ProcessReplica, Replica,
                                          ThreadReplica)
from libskylark_tpu.resilience import preemption as _preemption


class ReplicaPool:
    """N uniform replicas behind names (``r0``..``r{N-1}``).

    ::

        pool = fleet.ReplicaPool(4, max_batch=16, linger_us=2000)
        router = fleet.Router(pool)
        ...
        pool.shutdown()

    ``backend`` is ``"thread"`` (default) or ``"process"``; remaining
    keyword arguments are passed to every replica's
    ``MicrobatchExecutor`` (process replicas additionally accept
    ``coordinator=`` — multi-host kwargs forwarded to
    ``parallel.multihost.initialize_distributed`` in the child).

    ``warmup_pack`` boots every replica from a warmup pack
    (docs/performance, "Persistent AOT artifacts & warmup packs"):
    thread replicas load it into the shared process executable cache
    (deserialized once — later replicas find the keys resident and
    only seed their own flush-kernel memos);
    process replicas each load it in their own interpreter BEFORE the
    liveness probe resolves, and inherit the parent's AOT store / plan
    cache / telemetry environment explicitly
    (:data:`~libskylark_tpu.fleet.replica.PROPAGATED_ENV`), so a
    process fleet of N cold children boots serving every packed bucket
    with zero backend compiles.

    ``shared_workers`` (thread backend only) sizes flush concurrency
    to the HOST instead of to N: the pool owns one dispatch queue and
    that many flush worker threads, and every replica enqueues its
    cohorts there (``MicrobatchExecutor(dispatch_queue=...)``). N
    replicas each running their own workers oversubscribe a small
    host — N concurrent flushes thrash more cores than exist — while
    a host-sized shared pool keeps the fleet's flush concurrency
    equal to a well-tuned single executor's (docs/fleet, "Tuning N").
    """

    def __init__(self, n: int = 2, *, backend: str = "thread",
                 names: Optional[List[str]] = None, coordinator=None,
                 shared_workers: Optional[int] = None,
                 warmup_pack: Optional[str] = None,
                 **executor_kwargs):
        if n < 1:
            raise ValueError("a fleet needs at least one replica")
        if backend not in ("thread", "process"):
            raise ValueError(
                f"backend must be 'thread' or 'process', got {backend!r}")
        names = list(names) if names else [f"r{i}" for i in range(n)]
        if len(names) != n or len(set(names)) != n:
            raise ValueError(f"need {n} distinct replica names, "
                             f"got {names!r}")
        self.backend = backend
        self.executor_kwargs = dict(executor_kwargs)
        self.pad_floor = int(executor_kwargs.get(
            "pad_floor", bucketing.PAD_FLOOR))
        self.max_batch = int(executor_kwargs.get("max_batch", 8))
        self._lock = _locks.make_lock("fleet.pool")
        self._drain_hooks: Dict[str, list] = {name: [] for name in names}
        self._drained: set = set()
        self._replicas: Dict[str, Replica] = {}
        self._dispatchq = None
        self._dispatchers: list = []
        if shared_workers is not None:
            if backend != "thread":
                raise ValueError(
                    "shared_workers applies to thread replicas only "
                    "(process replicas have their own interpreters)")
            import queue as _queue

            from libskylark_tpu.engine.serve import dispatch_loop

            self._dispatchq = _queue.Queue()
            self._dispatchers = [
                threading.Thread(target=dispatch_loop,
                                 args=(self._dispatchq,),
                                 name=f"skylark-fleet-dispatch-{i}",
                                 daemon=True)
                for i in range(max(int(shared_workers), 1))
            ]
            for t in self._dispatchers:
                t.start()
            executor_kwargs = dict(executor_kwargs,
                                   dispatch_queue=self._dispatchq)
        try:
            for name in names:
                if backend == "thread":
                    self._replicas[name] = ThreadReplica(
                        name, warmup_pack=warmup_pack, **executor_kwargs)
                else:
                    self._replicas[name] = ProcessReplica(
                        name, coordinator=coordinator,
                        warmup_pack=warmup_pack, **executor_kwargs)
        except Exception:
            for r in self._replicas.values():
                r.shutdown()
            self._stop_dispatchers()
            raise
        # process-wide preemption (SIGTERM to THIS process): the r9
        # handler drains the executors; this hook runs after the drain
        # (hook order: drain_serving first) so the per-replica final
        # checkpoints see quiesced replicas
        self._unhook = _preemption.on_preemption(self._run_all_drain_hooks)

    # -- addressing ----------------------------------------------------

    def names(self) -> List[str]:
        return sorted(self._replicas)

    def replicas(self) -> List[Replica]:
        return [self._replicas[n] for n in self.names()]

    def get(self, name: str) -> Replica:
        return self._replicas[name]

    def resolve_source(self, source: object) -> Optional[str]:
        """Map a health-hub event source (an executor for thread
        replicas, the replica object for process replicas) to its
        replica name; ``None`` for sources outside this pool."""
        for name, r in self._replicas.items():
            if r.owns_source(source):
                return name
        return None

    # -- traffic helpers -----------------------------------------------

    def flush(self) -> None:
        """Synchronously flush every replica, in name order (tests and
        deterministic chaos storms; normal traffic never needs it)."""
        for name in self.names():
            self._replicas[name].flush()

    def stats(self) -> dict:
        return {name: self._replicas[name].stats()
                for name in self.names()}

    # -- per-replica preemption ----------------------------------------

    def on_replica_drain(self, name: str,
                         hook: Callable[[], None]) -> Callable[[], None]:
        """Register a final-drain hook for one replica (its "final
        checkpoint"); runs exactly once, whether the replica is
        preempted alone (:meth:`preempt_replica`) or the whole process
        is SIGTERM'd. Returns the unregister callable."""
        with self._lock:
            self._drain_hooks[name].append(hook)

        def unregister() -> None:
            with self._lock:
                try:
                    self._drain_hooks[name].remove(hook)
                except (KeyError, ValueError):
                    pass

        return unregister

    def _run_drain_hooks(self, name: str) -> None:
        with self._lock:
            if name in self._drained:
                return
            self._drained.add(name)
            hooks = list(self._drain_hooks.get(name, ()))
        for hook in hooks:
            try:
                hook()
            except Exception as e:  # noqa: BLE001 — contain, like r9
                warnings.warn(
                    f"replica {name!r} drain hook {hook!r} failed: {e}",
                    RuntimeWarning, stacklevel=2)

    def _run_all_drain_hooks(self) -> None:
        for name in self.names():
            self._run_drain_hooks(name)

    def preempt_replica(self, name: str,
                        timeout: Optional[float] = 30.0) -> bool:
        """Preempt ONE replica: drain it (intake refused — the health
        hub announces DRAINING, a subscribed router sheds its traffic
        to peers — queued cohorts flush, in-flight futures resolve),
        then fire its drain hooks. Process replicas get a real SIGTERM
        (the child's own preemption handler does the draining);
        thread replicas drain in place. Returns whether quiescence was
        reached inside ``timeout``."""
        replica = self._replicas[name]
        if isinstance(replica, ProcessReplica):
            replica.preempt()
            # the child's handler drains asynchronously; wait for its
            # STOPPED announcement by polling the cached state
            import time as _time

            deadline = _time.monotonic() + (timeout or 30.0)
            while (replica.state() != "STOPPED"
                   and _time.monotonic() < deadline):
                _time.sleep(0.05)
            drained = replica.state() == "STOPPED"
        else:
            drained = replica.drain(timeout=timeout)
        self._run_drain_hooks(name)
        return drained

    def drain_replica(self, name: str,
                      timeout: Optional[float] = 30.0) -> bool:
        """Drain one replica without the preemption framing (no drain
        hooks) — administrative removal, e.g. before a resize."""
        return self._replicas[name].drain(timeout=timeout)

    # -- lifecycle -----------------------------------------------------

    def _stop_dispatchers(self) -> None:
        for _ in self._dispatchers:
            self._dispatchq.put(None)     # FIFO: queued cohorts first
        for t in self._dispatchers:
            t.join(timeout=30.0)
        self._dispatchers = []

    def shutdown(self) -> None:
        self._unhook()
        for r in self.replicas():
            try:
                r.shutdown()
            except Exception as e:  # noqa: BLE001 — stop the rest too
                warnings.warn(f"replica {r.name!r} shutdown failed: {e}",
                              RuntimeWarning, stacklevel=2)
        if self._dispatchers:
            self._stop_dispatchers()

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


__all__ = ["ReplicaPool"]
