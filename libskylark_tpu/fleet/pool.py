"""ReplicaPool: owns N named replicas and their shared lifecycle.

The pool is the fleet's capacity layer: it constructs N replicas with
*uniform* executor configuration (one ``pad_floor``, one ``max_batch``
— the router derives affinity keys and spill bounds from the pool, so
a heterogeneous fleet would break sticky routing), names them
``r0..r{N-1}``, and gives the router one place to resolve health-hub
event sources back to replica names. Membership is elastic:
:meth:`~ReplicaPool.add_replica` grows the pool by one pack-booted
replica (announced to subscribed routers via the health hub's SERVING
publish) and :meth:`~ReplicaPool.remove_replica` shrinks it through
the r11 preemption drain — the two verbs the autoscaler
(:mod:`libskylark_tpu.fleet.autoscale`) drives.

Preemption composition (the tentpole contract): the pool registers one
:func:`~libskylark_tpu.resilience.on_preemption` hook, so a
process-wide SIGTERM — which drains every in-process executor via the
r9 handler — also runs every replica's registered drain hooks (final
per-replica checkpoints) exactly once. A *single* replica can be
preempted without touching the rest via :meth:`preempt_replica`:
thread replicas drain in place (there is no thread-scoped SIGTERM);
process replicas get a real SIGTERM. Either way the replica's drain
hooks fire, its in-flight futures resolve, and the health hub
announces DRAINING → STOPPED so a subscribed router sheds its traffic
to peers mid-drain.

Crash reap: a ``ProcessReplica`` child that exits *unexpectedly* (a
``kill -9``, an OOM, the chaos ``crash`` fault) publishes STOPPED from
the parent-side reader thread — the pool subscribes to the hub and
**reaps** the dead member: it leaves the membership immediately (no
drain hooks — there was no grace window), its name lands in
:meth:`crashed_names`, and an attached autoscaler's next tick sees the
pool below its floor and replaces the member via the r13 pack boot.
Without the reap the dead replica stayed a member forever: routers
dropped it from the ring (STOPPED) but the pool's count never shrank,
so the autoscaler never replaced it — the crash-then-shrink hole the
session replay chaos leg exercises (docs/sessions).
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Callable, Dict, List, Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine import bucket as bucketing
from libskylark_tpu.engine import serve as _serve
from libskylark_tpu.fleet.replica import (ProcessReplica, Replica,
                                          ThreadReplica)
from libskylark_tpu.resilience import health as _health
from libskylark_tpu.resilience import preemption as _preemption

_UNSET = object()


def resolve_backend(backend: Optional[str]) -> str:
    """The effective replica backend: an explicit argument wins, else
    ``SKYLARK_FLEET_BACKEND``; ``auto`` resolves to process replicas
    on hosts with >= 4 cores (where per-replica cores exist for them
    to use) and thread replicas below (where a spawned interpreter
    per replica buys nothing but boot time)."""
    if backend is None:
        backend = str(_env.FLEET_BACKEND.get())
    backend = str(backend)
    if backend == "auto":
        return "process" if (os.cpu_count() or 1) >= 4 else "thread"
    return backend


class ReplicaPool:
    """N uniform replicas behind names (``r0``..``r{N-1}``).

    ::

        pool = fleet.ReplicaPool(4, max_batch=16, linger_us=2000)
        router = fleet.Router(pool)
        ...
        pool.shutdown()

    ``backend`` is ``"thread"`` (default) or ``"process"``; remaining
    keyword arguments are passed to every replica's
    ``MicrobatchExecutor`` (process replicas additionally accept
    ``coordinator=`` — multi-host kwargs forwarded to
    ``parallel.multihost.initialize_distributed`` in the child).

    ``warmup_pack`` boots every replica from a warmup pack
    (docs/performance, "Persistent AOT artifacts & warmup packs"):
    thread replicas load it into the shared process executable cache
    (deserialized once — later replicas find the keys resident and
    only seed their own flush-kernel memos);
    process replicas each load it in their own interpreter BEFORE the
    liveness probe resolves, and inherit the parent's AOT store / plan
    cache / telemetry environment explicitly
    (:data:`~libskylark_tpu.fleet.replica.PROPAGATED_ENV`), so a
    process fleet of N cold children boots serving every packed bucket
    with zero backend compiles.

    ``shared_workers`` (thread backend only) sizes flush concurrency
    to the HOST instead of to N: the pool owns one dispatch queue and
    that many flush worker threads, and every replica enqueues its
    cohorts there (``MicrobatchExecutor(dispatch_queue=...)``). N
    replicas each running their own workers oversubscribe a small
    host — N concurrent flushes thrash more cores than exist — while
    a host-sized shared pool keeps the fleet's flush concurrency
    equal to a well-tuned single executor's (docs/fleet, "Tuning N").
    """

    def __init__(self, n: int = 2, *, backend: Optional[str] = None,
                 names: Optional[List[str]] = None, coordinator=None,
                 shared_workers: Optional[int] = None,
                 warmup_pack: Optional[str] = None,
                 replica_env=None,
                 **executor_kwargs):
        if n < 1:
            raise ValueError("a fleet needs at least one replica")
        backend = resolve_backend(backend)
        if backend not in ("thread", "process"):
            raise ValueError(
                f"backend must be 'thread', 'process' or 'auto', "
                f"got {backend!r}")
        names = list(names) if names else [f"r{i}" for i in range(n)]
        if len(names) != n or len(set(names)) != n:
            raise ValueError(f"need {n} distinct replica names, "
                             f"got {names!r}")
        self.backend = backend
        self.executor_kwargs = dict(executor_kwargs)
        self.pad_floor = int(executor_kwargs.get(
            "pad_floor", bucketing.PAD_FLOOR))
        self.max_batch = int(executor_kwargs.get("max_batch", 8))
        # per-replica seats: ``coordinator`` / ``replica_env`` may be a
        # dict applied to every process replica, or a callable
        # ``name -> dict`` pinning each replica to its own seat in the
        # multihost pool / its own device subset (env overrides like
        # CUDA_VISIBLE_DEVICES applied at child entry) — the
        # "one replica, one device subset" knob
        self._coordinator = coordinator
        self._replica_env = replica_env
        self.warmup_pack = warmup_pack
        self._lock = _locks.make_lock("fleet.pool")
        self._drain_hooks: Dict[str, list] = {name: [] for name in names}
        self._drained: set = set()
        self._replicas: Dict[str, Replica] = {}
        self._booting: set = set()
        # names under a pool-initiated preemption/drain: their STOPPED
        # events are expected and must not be misread as crashes
        self._removing: set = set()
        self._crashed: List[str] = []
        self._shutdown = False
        self._next_idx = n
        self._dispatchq = None
        self._dispatchers: list = []
        if shared_workers is not None:
            if backend != "thread":
                raise ValueError(
                    "shared_workers applies to thread replicas only "
                    "(process replicas have their own interpreters)")
            import queue as _queue

            from libskylark_tpu.engine.serve import dispatch_loop

            self._dispatchq = _queue.Queue()
            self._dispatchers = [
                threading.Thread(target=dispatch_loop,
                                 args=(self._dispatchq,),
                                 name=f"skylark-fleet-dispatch-{i}",
                                 daemon=True)
                for i in range(max(int(shared_workers), 1))
            ]
            for t in self._dispatchers:
                t.start()
            executor_kwargs = dict(executor_kwargs,
                                   dispatch_queue=self._dispatchq)
        # the FULL construction kwargs (including the shared dispatch
        # queue) — add_replica must build future replicas exactly like
        # the initial ones
        self._replica_kwargs = dict(executor_kwargs)
        try:
            for name in names:
                self._replicas[name] = self._build_replica(name)
        except Exception:
            for r in self._replicas.values():
                r.shutdown()
            self._stop_dispatchers()
            raise
        # process-wide preemption (SIGTERM to THIS process): the r9
        # handler drains the executors; this hook runs after the drain
        # (hook order: drain_serving first) so the per-replica final
        # checkpoints see quiesced replicas
        self._unhook = _preemption.on_preemption(self._run_all_drain_hooks)
        # crash reap (module doc): react to STOPPED events the pool
        # did not initiate
        self._health_unsub = _health.subscribe(self._on_health_event)

    def _per_replica(self, seat, name: str):
        return seat(name) if callable(seat) else seat

    def _build_replica(self, name: str,
                       warmup_pack=_UNSET) -> Replica:
        pack = (self.warmup_pack if warmup_pack is _UNSET
                else warmup_pack)
        if self.backend == "thread":
            return ThreadReplica(name, warmup_pack=pack,
                                 **self._replica_kwargs)
        return ProcessReplica(
            name, coordinator=self._per_replica(self._coordinator, name),
            env_overrides=self._per_replica(self._replica_env, name),
            warmup_pack=pack, **self._replica_kwargs)

    # -- addressing ----------------------------------------------------

    def names(self) -> List[str]:
        return sorted(self._replicas)

    def replicas(self) -> List[Replica]:
        return [self._replicas[n] for n in self.names()]

    def get(self, name: str) -> Replica:
        return self._replicas[name]

    def resolve_source(self, source: object) -> Optional[str]:
        """Map a health-hub event source (an executor for thread
        replicas, the replica object for process replicas) to its
        replica name; ``None`` for sources outside this pool."""
        for name, r in list(self._replicas.items()):
            if r.owns_source(source):
                return name
        return None

    # -- crash reap (module doc) ---------------------------------------

    def _on_health_event(self, source: object, old: str,
                         new: str) -> None:
        if new != _serve.STOPPED:
            return
        with self._lock:
            if self._shutdown:
                return
        name = self.resolve_source(source)
        if name is None:
            return
        dead = None
        with self._lock:
            if (name in self._removing or name in self._drained
                    or self._shutdown):
                return                 # pool-initiated: not a crash
            replica = self._replicas.get(name)
            if not isinstance(replica, ProcessReplica):
                return                 # threads have no crash mode
            if not replica.unexpected_exit:
                return                 # drain-flow STOPPED, not a crash
            # unexpected child exit: reap the membership NOW so the
            # autoscaler's next tick replaces the dead member (the
            # pack boot) instead of counting a corpse as capacity
            dead = self._replicas.pop(name)
            self._drain_hooks.pop(name, None)
            self._crashed.append(name)
        if dead is not None:
            warnings.warn(
                f"replica {name!r} exited unexpectedly — reaped from "
                "the pool (an attached autoscaler will replace it)",
                RuntimeWarning, stacklevel=2)
            try:
                dead.shutdown()        # reap pipe/threads; idempotent
            except Exception:  # noqa: BLE001 — the corpse is gone
                pass

    def crashed_names(self) -> List[str]:
        """Names of replicas reaped after an unexpected exit (crash
        forensics; the session chaos leg asserts on this)."""
        with self._lock:
            return list(self._crashed)

    # -- elastic membership (the autoscaler's seam) --------------------

    def add_replica(self, name: Optional[str] = None, *,
                    warmup_pack=_UNSET) -> str:
        """Grow the pool by one replica (same backend, same uniform
        executor configuration; process replicas get their own
        ``coordinator``/``replica_env`` seat from the per-replica
        callables). Boots from the pool's warmup pack by default — the
        scale-up path is the r13 pack boot, so a grown fleet serves
        its packed buckets with zero compiles. Publishes ``SERVING``
        to the health hub once the replica is live, which is how a
        subscribed router learns to add it to the ring. Returns the
        new replica's name."""
        with self._lock:
            if self._shutdown:
                raise RuntimeError("ReplicaPool is shut down")
            if name is None:
                while (f"r{self._next_idx}" in self._replicas
                       or f"r{self._next_idx}" in self._booting):
                    self._next_idx += 1
                name = f"r{self._next_idx}"
                self._next_idx += 1
            name = str(name)
            if name in self._replicas or name in self._booting:
                raise ValueError(f"replica {name!r} already exists")
            # reserve the name so two concurrent add_replica calls
            # cannot race one name (construction happens unlocked —
            # a process replica boot takes seconds)
            self._booting.add(name)
        try:
            replica = self._build_replica(name, warmup_pack=warmup_pack)
        except Exception:
            with self._lock:
                self._booting.discard(name)
            raise
        with self._lock:
            self._booting.discard(name)
            if self._shutdown:
                # the pool died while we were booting (a slow spawn
                # outliving an autoscaler close + pool.shutdown):
                # registering now would hand a subscribed router a
                # replica nothing will ever stop
                late = replica
            else:
                late = None
                self._replicas[name] = replica
                self._drain_hooks.setdefault(name, [])
                self._drained.discard(name)
        if late is not None:
            late.shutdown()
            raise RuntimeError(
                "ReplicaPool was shut down during the replica boot")
        _health.publish(replica, "NEW", _serve.SERVING)
        return name

    def remove_replica(self, name: str,
                       timeout: Optional[float] = 30.0) -> bool:
        """Shrink the pool by one replica: preempt it (the r11 SIGTERM
        drain for process replicas — DRAINING/STOPPED reach the hub,
        a subscribed router sheds its traffic, in-flight futures
        resolve, its final drain hooks fire), then forget it. Returns
        whether the drain reached quiescence inside ``timeout``."""
        with self._lock:
            if name not in self._replicas:
                raise KeyError(f"no replica named {name!r}")
        drained = self.preempt_replica(name, timeout=timeout)
        replica = None
        with self._lock:
            replica = self._replicas.pop(name, None)
            self._drain_hooks.pop(name, None)
            self._drained.discard(name)
        if replica is not None:
            replica.shutdown()
        return drained

    # -- traffic helpers -----------------------------------------------

    def flush(self) -> None:
        """Synchronously flush every replica, in name order (tests and
        deterministic chaos storms; normal traffic never needs it)."""
        for name in self.names():
            self._replicas[name].flush()

    def stats(self) -> dict:
        return {name: self._replicas[name].stats()
                for name in self.names()}

    # -- per-replica preemption ----------------------------------------

    def on_replica_drain(self, name: str,
                         hook: Callable[[], None]) -> Callable[[], None]:
        """Register a final-drain hook for one replica (its "final
        checkpoint"); runs exactly once, whether the replica is
        preempted alone (:meth:`preempt_replica`) or the whole process
        is SIGTERM'd. Returns the unregister callable."""
        with self._lock:
            self._drain_hooks[name].append(hook)

        def unregister() -> None:
            with self._lock:
                try:
                    self._drain_hooks[name].remove(hook)
                except (KeyError, ValueError):
                    pass

        return unregister

    def _run_drain_hooks(self, name: str) -> None:
        with self._lock:
            if name in self._drained:
                return
            self._drained.add(name)
            hooks = list(self._drain_hooks.get(name, ()))
        for hook in hooks:
            try:
                hook()
            except Exception as e:  # noqa: BLE001 — contain, like r9
                warnings.warn(
                    f"replica {name!r} drain hook {hook!r} failed: {e}",
                    RuntimeWarning, stacklevel=2)

    def _run_all_drain_hooks(self) -> None:
        for name in self.names():
            self._run_drain_hooks(name)

    def preempt_replica(self, name: str,
                        timeout: Optional[float] = 30.0) -> bool:
        """Preempt ONE replica: drain it (intake refused — the health
        hub announces DRAINING, a subscribed router sheds its traffic
        to peers — queued cohorts flush, in-flight futures resolve),
        then fire its drain hooks. Process replicas get a real SIGTERM
        (the child's own preemption handler does the draining);
        thread replicas drain in place. Returns whether quiescence was
        reached inside ``timeout``."""
        replica = self._replicas[name]
        # expected STOPPED ahead: the crash reap must not misread a
        # pool-initiated preemption as an unexpected exit
        with self._lock:
            self._removing.add(name)
        try:
            if isinstance(replica, ProcessReplica):
                replica.preempt()
                # the child's handler drains asynchronously; wait for
                # its STOPPED announcement by polling the cached state
                import time as _time

                deadline = _time.monotonic() + (timeout or 30.0)
                while (replica.state() != "STOPPED"
                       and _time.monotonic() < deadline):
                    _time.sleep(0.05)
                drained = replica.state() == "STOPPED"
            else:
                drained = replica.drain(timeout=timeout)
            self._run_drain_hooks(name)
        finally:
            with self._lock:
                self._removing.discard(name)
        return drained

    def drain_replica(self, name: str,
                      timeout: Optional[float] = 30.0) -> bool:
        """Drain one replica without the preemption framing (no drain
        hooks) — administrative removal, e.g. before a resize."""
        with self._lock:
            self._removing.add(name)
        try:
            return self._replicas[name].drain(timeout=timeout)
        finally:
            with self._lock:
                self._removing.discard(name)

    # -- lifecycle -----------------------------------------------------

    def _stop_dispatchers(self) -> None:
        for _ in self._dispatchers:
            self._dispatchq.put(None)     # FIFO: queued cohorts first
        for t in self._dispatchers:
            t.join(timeout=30.0)
        self._dispatchers = []

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
        self._unhook()
        self._health_unsub()
        for r in self.replicas():
            try:
                r.shutdown()
            except Exception as e:  # noqa: BLE001 — stop the rest too
                warnings.warn(f"replica {r.name!r} shutdown failed: {e}",
                              RuntimeWarning, stacklevel=2)
        if self._dispatchers:
            self._stop_dispatchers()

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


__all__ = ["ReplicaPool"]
