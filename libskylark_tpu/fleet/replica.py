"""Fleet replicas: one submit surface over thread- or process-backed
microbatch executors.

A *replica* is one unit of serving capacity the router can address:
it has a name (its ring identity and telemetry label), a
future-returning ``submit`` mirroring
:meth:`~libskylark_tpu.engine.serve.MicrobatchExecutor.submit`, a live
queue-depth signal, the r9 health states, and the drain lifecycle.

Two backings:

- :class:`ThreadReplica` — an in-process
  :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor` (the
  default). Cheapest possible hop (the router calls straight into the
  executor), shares the process executable cache, and health
  transitions reach the resilience hub directly.
- :class:`ProcessReplica` — a spawned child process hosting its own
  executor behind a pickle pipe. The child is a real OS-level
  preemption domain: it installs
  :func:`~libskylark_tpu.resilience.install_preemption_handler`, so a
  SIGTERM *to the child alone* drains its executor (in-flight futures
  resolve and their results still flow back over the pipe) while the
  parent-side router sheds new traffic to peers — the per-replica
  preemption story a thread cannot give. Multi-host placement rides
  the existing :mod:`libskylark_tpu.parallel.multihost` plumbing: pass
  ``coordinator`` kwargs and the child joins the distributed pool via
  ``initialize_distributed`` before serving. Spawn (not fork): a
  forked child would inherit jax's initialized backend and the parent's
  locked thread state.

Process-replica protocol (one duplex pipe, length-tagged tuples):
parent → child: ``("submit", rid, endpoint, kwargs)`` /
``("stats"|"env"|"depth"|"flush", rid)`` / ``("drain", rid, timeout)``
/ ``("shutdown", rid)``; child → parent: ``("result", rid, value)`` /
``("error", rid, exception)`` / ``("rpc", rid, value)`` /
``("state", None, new_state)`` — the last forwarded from the child's
health hub so the parent's hub (and any subscribed router) sees the
child's transitions with the :class:`ProcessReplica` as the source.
Both directions additionally carry ``("shmfree", None, [slots])``
acks for the shared-memory transport below.

**QoS propagation** (docs/qos): ``submit`` kwargs carry the router-
resolved ``tenant=``/``qos_class=`` pair verbatim — over the pickle
pipe for process replicas — so every replica's executor schedules a
request under the same priority class the front door admitted it in;
a replica never re-charges the tenant's token bucket (the buckets
live with the router's registry).

**Shared-memory transport** (:mod:`libskylark_tpu.fleet.shm`, default
on — ``SKYLARK_FLEET_SHM=0`` disables): large ndarrays inside
``submit`` kwargs and results do NOT ride the pickle pipe. The sender
copies them into a slot of the replica pair's shared-memory ring and
the pipe carries a tiny header; the receiver gets a zero-copy view
over the slot, released back to the writer when the view is
garbage-collected. Small values, oversize arrays and ring exhaustion
fall back to pickle — transport choice never changes a result.
"""

from __future__ import annotations

import itertools
import os
import signal
import threading
import warnings
from concurrent.futures import Future
from typing import Optional

import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine.serve import ServeOverloadedError

# Environment a replica child must agree with its parent on — the AOT
# artifact store, the tune plan cache (its fingerprint is in every
# executable key: a child on a different cache file would never hit
# the parent's warmup pack), and the telemetry switches. Propagated
# EXPLICITLY through the spawn args and applied at child entry, not
# left to the accident of what ``os.environ`` held when
# ``Process.start()`` happened to run (a parent that configures its
# store after constructing the pool — or a test that monkeypatches
# around replica construction — must still produce children that
# agree with it). The tuple is DERIVED from the typed registry
# (``base/env.py``: every declaration with ``propagate=True``), so a
# newly declared variable can never again silently miss propagation —
# the registry declaration is the single place that decides.
PROPAGATED_ENV = _env.propagated_names()


def propagated_env() -> dict:
    """Snapshot of :data:`PROPAGATED_ENV` in this process (``None``
    marks a variable to *unset* in the child)."""
    return _env.snapshot_propagated()


def _apply_env(env: Optional[dict]) -> None:
    """Apply a parent's snapshot in the child — set present values,
    delete absent ones — then re-arm the lazy readers that already ran
    at import time (telemetry's enable gate and JSONL exporter)."""
    if env is None:
        return
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        from libskylark_tpu import telemetry

        telemetry.set_enabled(bool(_env.TELEMETRY.get())
                              or bool(_env.TELEMETRY_DIR.get()))
        if _env.TELEMETRY_DIR.get():
            telemetry.install_exporter()
    except Exception:  # noqa: BLE001 — telemetry must not block boot
        pass


class Replica:
    """The surface the router programs against (see module doc)."""

    name: str

    def submit(self, endpoint: str, /, **kwargs) -> Future:
        raise NotImplementedError

    def session(self, op: str, /, **kwargs) -> Future:
        """Stateful-session verb (docs/sessions): ``op`` is ``open`` /
        ``append`` / ``finalize`` with the corresponding
        ``MicrobatchExecutor`` session method's kwargs. Returns a
        future (``open`` resolves to the session id, ``append`` to
        ``(seq, rows)``, ``finalize`` to the result dict)."""
        raise NotImplementedError

    def train(self, op: str, /, **kwargs) -> Future:
        """Training-job verb (docs/training): ``op`` is ``submit`` /
        ``resume`` / ``status`` with the corresponding
        ``MicrobatchExecutor`` train method's kwargs. ``submit`` and
        ``resume`` resolve to the job's TERMINAL result (the trained
        model dict, or the terminal error — slices run in the
        replica's idle slots in between); ``status`` resolves to a
        progress snapshot."""
        raise NotImplementedError

    def shard(self, task: dict) -> Future:
        """Distributed-sketch shard-task verb (docs/distributed): the
        payload is :func:`libskylark_tpu.dist.plan.execute_task`'s —
        a serialized :class:`~libskylark_tpu.dist.plan.ShardPlan`, the
        shard index, and a range-readable source. Resolves to the
        task's ``{"index", "rows", "partial"}`` dict. Idempotent by
        construction (the partial is a pure function of the plan), so
        the coordinator retries a failed/crashed future by simply
        re-invoking this on the next ring-preference replica."""
        raise NotImplementedError

    def register_operand(self, A, transform=None, dimension=None,
                         **kwargs) -> Future:
        """Operand-residency verb (docs/caching): content-hash ``A``
        and pin it resident on this replica — precomputing and
        pinning its sketch when ``transform`` is given — so later
        submits can reference the operand by digest instead of
        re-shipping (and re-sketching) it. Resolves to the operand's
        ref string (``ref:<digest>``)."""
        raise NotImplementedError

    def unregister_operand(self, ref) -> Future:
        """Drop a resident operand (and any sketches pinned with it);
        resolves to whether this replica held it."""
        raise NotImplementedError

    def queue_depth(self) -> int:
        raise NotImplementedError

    def state(self) -> str:
        raise NotImplementedError

    def stats(self) -> dict:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        raise NotImplementedError

    def shutdown(self) -> None:
        raise NotImplementedError

    def latency_quantile(self, q: float = 0.99) -> Optional[float]:
        """One quantile of the replica's r10 request-latency histogram
        (seconds; ``None`` when unknown). The router's hedge-delay
        seed — cheap for thread replicas; a process replica returns
        ``None`` rather than pay a pipe RPC on the submit path."""
        return None


class ThreadReplica(Replica):
    """In-process replica: a named ``MicrobatchExecutor`` plus the
    thin identity layer the router needs."""

    backend = "thread"

    def __init__(self, name: str, warmup_pack: Optional[str] = None,
                 **executor_kwargs):
        from libskylark_tpu import engine

        self.name = str(name)
        self.executor = engine.MicrobatchExecutor(name=self.name,
                                                  **executor_kwargs)
        self.warmup_report: Optional[dict] = None
        if warmup_pack:
            # pack loading precedes any traffic by construction (the
            # pool builds replicas before the router exists); a
            # degraded/partial load serves via the compile path
            self.warmup_report = self.executor.load_warmup_pack(
                warmup_pack)

    def submit(self, endpoint: str, /, **kwargs) -> Future:
        return self.executor.submit(endpoint, **kwargs)

    def session(self, op: str, /, **kwargs) -> Future:
        if op == "append":
            return self.executor.session_append(**kwargs)
        if op == "finalize":
            return self.executor.session_finalize(**kwargs)
        fut: Future = Future()
        try:
            if op != "open":
                raise ValueError(f"unknown session op {op!r}")
            fut.set_result(self.executor.open_sketch_session(**kwargs))
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — resolve, don't leak
            fut.set_exception(e)
        return fut

    def train(self, op: str, /, **kwargs) -> Future:
        if op in ("submit", "resume"):
            try:
                if op == "submit":
                    handle = self.executor.submit_train_job(
                        kwargs.pop("spec"),
                        operands=kwargs.pop("operands", None),
                        **kwargs)
                else:
                    handle = self.executor.resume_train_job(**kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — resolve
                fut: Future = Future()
                fut.set_exception(e)
                return fut
            return handle.future
        fut = Future()
        try:
            if op != "status":
                raise ValueError(f"unknown train op {op!r}")
            fut.set_result(self.executor.train_job_status(**kwargs))
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — resolve
            fut.set_exception(e)
        return fut

    def shard(self, task: dict) -> Future:
        # a one-shot thread, not the executor queue: shard compute is
        # host-side ingest + eager folds — queueing it behind flush
        # cohorts would stall serve traffic, and a thread per task
        # keeps the coordinator's dispatch loop non-blocking
        from libskylark_tpu.dist.plan import execute_task

        fut: Future = Future()

        def _run():
            try:
                fut.set_result(execute_task(task))
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — resolve
                fut.set_exception(e)

        threading.Thread(target=_run, name=f"{self.name}-shard",
                         daemon=True).start()
        return fut

    def register_operand(self, A, transform=None, dimension=None,
                         **kwargs) -> Future:
        # a one-shot thread, not inline: with a transform the pin
        # waits for the precompute flush, and the router broadcasts a
        # registration to every replica — serial waits would make the
        # broadcast O(replicas × flush) instead of one flush deep
        fut: Future = Future()

        def _run():
            try:
                fut.set_result(str(self.executor.register_operand(
                    A, transform=transform, dimension=dimension,
                    **kwargs)))
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — resolve
                fut.set_exception(e)

        threading.Thread(target=_run, name=f"{self.name}-register",
                         daemon=True).start()
        return fut

    def unregister_operand(self, ref) -> Future:
        fut: Future = Future()
        try:
            fut.set_result(self.executor.unregister_operand(ref))
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — resolve
            fut.set_exception(e)
        return fut

    def queue_depth(self) -> int:
        return self.executor.queue_depth()

    def state(self) -> str:
        return self.executor.state

    def stats(self) -> dict:
        return self.executor.stats()

    def flush(self) -> None:
        self.executor.flush()

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        return self.executor.drain(timeout=timeout)

    def shutdown(self) -> None:
        self.executor.shutdown()

    def latency_quantile(self, q: float = 0.99) -> Optional[float]:
        return self.executor.latency_quantile(q)

    def owns_source(self, source: object) -> bool:
        """Whether a health-hub event source is this replica (the
        executor publishes for thread replicas)."""
        return source is self.executor or source is self


# ---------------------------------------------------------------------------
# process-backed replica
# ---------------------------------------------------------------------------


def _send_exception(send, rid, e: BaseException) -> None:
    try:
        send(("error", rid, e))
    except Exception:  # unpicklable exception: degrade to its repr
        send(("error", rid, RuntimeError(repr(e))))


def _resolve(fut: Future, result=None, exception=None) -> None:
    """Resolve a parent-side future, tolerating one already resolved —
    a hedge winner cancels the loser, and the loser's pipe result may
    still arrive afterwards (InvalidStateError is the race's benign
    face, not an error)."""
    try:
        if exception is not None:
            fut.set_exception(exception)
        else:
            fut.set_result(result)
    except Exception:  # noqa: BLE001 — already done/cancelled
        pass


def _worker_main(conn, name: str, executor_kwargs: dict,
                 coordinator: Optional[dict],
                 env: Optional[dict] = None,
                 warmup_pack: Optional[str] = None,
                 shm_spec: Optional[dict] = None) -> None:
    """Child entry point (module-level: spawn pickles it by name)."""
    # the parent's engine/telemetry environment first — everything
    # below (jax config, engine import, executor construction, pack
    # load) must see the parent's explicit snapshot, not whatever
    # os.environ happened to hold at Process.start()
    _apply_env(env)
    # attach the shared-memory rings BEFORE the heavy imports: the
    # parent unlinks the names the moment our liveness RPC resolves,
    # and the attach is what keeps the mapping alive past that
    transport = None
    if shm_spec is not None:
        from libskylark_tpu.fleet.shm import ShmTransport

        transport = ShmTransport.attach(shm_spec)
    # the child honors the parent's platform pin the same way the
    # benchmarks do (env rides across spawn; sitecustomize may have
    # pre-imported jax with another platform)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from libskylark_tpu import engine, resilience
    from libskylark_tpu.resilience import health as _health

    if coordinator:
        # multi-host placement: the replica process joins the jax
        # distributed pool through the same multihost plumbing every
        # sharded code path uses (docs/distributed)
        from libskylark_tpu.parallel import multihost

        multihost.initialize_distributed(**coordinator)

    # SIGTERM → drain this executor + final checkpoint hooks, exactly
    # the in-process preemption contract, scoped to this replica
    resilience.install_preemption_handler()
    ex = engine.MicrobatchExecutor(name=name, **executor_kwargs)
    warmup_report = None
    if warmup_pack:
        # BEFORE the message loop: the parent's liveness RPC (its
        # first "stats") only resolves after this, so a packed child
        # is warm before it can ever accept traffic
        try:
            warmup_report = ex.load_warmup_pack(warmup_pack)
        except Exception as e:  # noqa: BLE001 — boot must not die on
            #                     a bad pack; the compile path serves
            warmup_report = {"skipped": f"load failed: {e!r}"}

    send_lock = _locks.make_lock("fleet.replica_send")

    def send(msg) -> None:
        with send_lock:
            conn.send(msg)

    def flush_acks() -> None:
        """Ship slots whose operand views have been collected back to
        the parent (the p2c ring's writer). Best-effort: a dead pipe
        means the whole pair is going down anyway."""
        if transport is None:
            return
        acks = transport.drain_acks()
        if acks:
            try:
                send(("shmfree", None, acks))
            except Exception:  # noqa: BLE001 — parent gone
                pass

    def forward_state(source, old, new) -> None:
        if source is ex:
            try:
                send(("state", None, new))
            except Exception:  # parent gone mid-teardown
                pass

    _health.subscribe(forward_state)

    def reply(rid, fut: Future) -> None:
        try:
            value = fut.result()
        except BaseException as e:  # noqa: BLE001 — future's exception
            _send_exception(send, rid, e)
            return
        try:
            if transport is None:
                send(("result", rid, value))
            else:
                # result handoff without a serialization copy: the
                # future's value is a view into the flush's one host
                # batch (engine/serve._execute); encode copies those
                # bytes straight into a ring slot and the parent maps
                # them zero-copy
                payload, claimed = transport.encode(value)
                try:
                    send(("result", rid, payload))
                except BaseException:
                    transport.unclaim(claimed)
                    raise
        except BaseException as e:  # noqa: BLE001 — containment
            _send_exception(send, rid, e)

    import functools

    while True:
        flush_acks()
        try:
            if not conn.poll(0.1):
                if (resilience.preemption_requested()
                        and resilience.wait_for_preemption_teardown(0.0)):
                    break            # drained by SIGTERM; parent's
                #                      reader sees our STOPPED event
                continue
            msg = conn.recv()
        except (EOFError, OSError):
            break
        kind, rid = msg[0], msg[1]
        if kind == "shmfree":
            if transport is not None:
                transport.release(msg[2])
            continue
        try:
            if kind == "submit":
                endpoint, kwargs = msg[2], msg[3]
                if transport is not None:
                    try:
                        kwargs = transport.decode(kwargs)
                    except Exception:
                        # request lost, ring capacity recovered; the
                        # outer handler errors the parent's future
                        transport.recover(kwargs)
                        flush_acks()
                        raise
                fut = ex.submit(endpoint, **kwargs)
                fut.add_done_callback(functools.partial(reply, rid))
            elif kind == "session":
                # stateful-session verbs (docs/sessions). Append
                # operands arrive over the pickle pipe (not the shm
                # rings — the batch is about to be journaled to disk
                # anyway, so a zero-copy view buys nothing); results
                # go back through the standard reply path
                op, kwargs = msg[2], msg[3]
                if op == "open":
                    # NOT inline: an open builds the O(n) positional
                    # streams and runs three fsyncs — serviced on the
                    # loop thread it would stall every queued submit
                    # and stats/depth probe on this replica for the
                    # duration. ``send`` is lock-protected (replies
                    # already cross threads), so a one-shot thread
                    # keeps the loop responsive.
                    def _open_reply(rid=rid, kwargs=kwargs):
                        try:
                            send(("rpc", rid,
                                  ex.open_sketch_session(**kwargs)))
                        except Exception as e:  # noqa: BLE001
                            _send_exception(send, rid, e)

                    threading.Thread(target=_open_reply,
                                     name=f"{name}-session-open",
                                     daemon=True).start()
                elif op == "append":
                    fut = ex.session_append(**kwargs)
                    fut.add_done_callback(functools.partial(reply, rid))
                elif op == "finalize":
                    fut = ex.session_finalize(**kwargs)
                    fut.add_done_callback(functools.partial(reply, rid))
                else:
                    raise ValueError(f"unknown session op {op!r}")
            elif kind == "train":
                # training-job verbs (docs/training). submit/resume
                # start on a one-shot thread — the operand persist +
                # session open run fsyncs that must not stall the
                # message loop (same reasoning as session opens) —
                # and the reply fires only at the job's TERMINAL
                # future (slices run in idle scheduler slots in
                # between; a SIGKILL before then leaves the session
                # on disk for a peer to resume)
                op, kwargs = msg[2], msg[3]
                if op == "status":
                    send(("rpc", rid, ex.train_job_status(**kwargs)))
                elif op in ("submit", "resume"):
                    def _train_start(rid=rid, op=op, kwargs=kwargs):
                        try:
                            if op == "submit":
                                h = ex.submit_train_job(
                                    kwargs.pop("spec"),
                                    operands=kwargs.pop(
                                        "operands", None),
                                    **kwargs)
                            else:
                                h = ex.resume_train_job(**kwargs)
                            h.future.add_done_callback(
                                functools.partial(reply, rid))
                        except Exception as e:  # noqa: BLE001
                            _send_exception(send, rid, e)

                    threading.Thread(target=_train_start,
                                     name=f"{name}-train",
                                     daemon=True).start()
                else:
                    raise ValueError(f"unknown train op {op!r}")
            elif kind == "shard":
                # distributed-sketch shard task (docs/distributed):
                # computed on a one-shot thread — ingest + eager folds
                # must not stall the message loop (the same reasoning
                # as session opens above). In-memory shard rows ride
                # the shm rings like submit operands (wire-flattened by
                # dist.plan.source_to_wire); descriptor sources pickle.
                # The ``dist.shard`` fault site fires INSIDE
                # execute_task, in this process — which is how a
                # ``crash`` spec in a victim child's SKYLARK_FAULT_PLAN
                # delivers the deterministic kill -9 mid-storm.
                task = msg[2]
                if transport is not None:
                    try:
                        task = transport.decode(task)
                    except Exception:
                        transport.recover(task)
                        flush_acks()
                        raise

                def _shard_reply(rid=rid, task=task):
                    from libskylark_tpu.dist.plan import execute_task

                    try:
                        send(("rpc", rid, execute_task(task)))
                    except Exception as e:  # noqa: BLE001
                        _send_exception(send, rid, e)

                threading.Thread(target=_shard_reply,
                                 name=f"{name}-shard",
                                 daemon=True).start()
            elif kind == "register":
                # operand-residency verb (docs/caching): the operand
                # rides the shm rings exactly like submit kwargs
                # (pickle-pipe fallback when the transport is off).
                # The executor's pin freezes a private COPY, so the
                # ring slot releases as soon as the decoded view
                # drops — a resident operand never holds shm capacity
                kwargs = msg[2]
                if transport is not None:
                    try:
                        kwargs = transport.decode(kwargs)
                    except Exception:
                        transport.recover(kwargs)
                        flush_acks()
                        raise

                # one-shot thread (the session-open reasoning): with
                # a transform the pin waits for its precompute flush,
                # which must not stall the message loop
                def _register_reply(rid=rid, kwargs=kwargs):
                    try:
                        send(("rpc", rid,
                              str(ex.register_operand(**kwargs))))
                    except Exception as e:  # noqa: BLE001
                        _send_exception(send, rid, e)

                threading.Thread(target=_register_reply,
                                 name=f"{name}-register",
                                 daemon=True).start()
            elif kind == "unregister":
                send(("rpc", rid, ex.unregister_operand(msg[2])))
            elif kind == "stats":
                send(("rpc", rid, ex.stats()))
            elif kind == "env":
                # boot introspection: the applied engine environment +
                # the pack-load report (the env-propagation regression
                # test and fleet debugging read this)
                send(("rpc", rid, {
                    "env": _env.snapshot_propagated(),
                    "warmup": warmup_report,
                    "engine": engine.stats().to_dict(),
                    "shm": (transport.stats()
                            if transport is not None else None),
                }))
            elif kind == "depth":
                send(("rpc", rid, ex.queue_depth()))
            elif kind == "flush":
                ex.flush()
                send(("rpc", rid, True))
            elif kind == "drain":
                send(("rpc", rid, ex.drain(timeout=msg[2])))
            elif kind == "shutdown":
                ex.shutdown()
                send(("rpc", rid, True))
                break
        except Exception as e:  # noqa: BLE001 — per-message containment
            _send_exception(send, rid, e)
    try:
        ex.shutdown()
    except Exception:
        pass
    conn.close()


class ProcessReplica(Replica):
    """A replica in its own spawned process (see module doc). Slow to
    boot (a fresh jax import per child) but a true preemption domain:
    :meth:`preempt` delivers a real SIGTERM."""

    backend = "process"

    def __init__(self, name: str, coordinator: Optional[dict] = None,
                 start_timeout: float = 120.0,
                 warmup_pack: Optional[str] = None,
                 env: Optional[dict] = None,
                 env_overrides: Optional[dict] = None,
                 shm: Optional[bool] = None, **executor_kwargs):
        import multiprocessing as mp

        self.name = str(name)
        ctx = mp.get_context("spawn")
        self._conn, child_conn = ctx.Pipe(duplex=True)
        # the engine environment rides the spawn args, not os.environ
        # timing (PROPAGATED_ENV): snapshot now, apply at child entry.
        # ``env_overrides`` layers on top — the device-pinning seat (a
        # pool can give each replica its own accelerator subset via
        # e.g. CUDA_VISIBLE_DEVICES/TPU flags without mutating the
        # parent's environment)
        self._env = dict(env) if env is not None else propagated_env()
        if env_overrides:
            self._env.update({str(k): (None if v is None else str(v))
                              for k, v in env_overrides.items()})
        # shared-memory operand/result transport (fleet/shm): created
        # before spawn so the names ride the args; unlinked the moment
        # the liveness probe proves the child attached
        if shm is None:
            shm = bool(_env.FLEET_SHM.get())
        self._transport = None
        shm_spec = None
        if shm:
            from libskylark_tpu.fleet.shm import ShmTransport

            self._transport = ShmTransport.create(self.name)
            shm_spec = self._transport.child_spec()
        self._proc = ctx.Process(
            target=_worker_main,
            args=(child_conn, self.name, dict(executor_kwargs),
                  coordinator, self._env, warmup_pack, shm_spec),
            name=f"skylark-replica-{self.name}", daemon=True)
        self._proc.start()
        child_conn.close()
        self._lock = _locks.make_lock("fleet.replica")  # send + bookkeeping
        self._rids = itertools.count()
        self._futures: "dict[int, Future]" = {}
        self._state = "SERVING"
        self._closed = False
        # set by the reader tail when the child died WITHOUT ever
        # announcing STOPPED through the drain flow — the pool's crash
        # reap keys off this, not off is_alive() (which can still read
        # True in the microseconds between pipe EOF and process reap)
        self.unexpected_exit = False
        self._reader = threading.Thread(
            target=self._reader_loop,
            name=f"skylark-replica-{self.name}-reader", daemon=True)
        self._reader.start()
        # prove liveness before the router ever trusts this replica: a
        # stats roundtrip forces the child through import + executor
        # construction (or surfaces its crash now, not mid-traffic)
        if self._rpc("stats", timeout=start_timeout) is None:
            self.shutdown()
            raise ServeOverloadedError(
                f"process replica {self.name!r} failed to come up "
                f"within {start_timeout}s")
        if self._transport is not None:
            # the child is alive, so it holds its own mapping: drop
            # the /dev/shm names NOW — from here on there is nothing a
            # SIGKILL on either side could leak
            self._transport.unlink()

    # -- child → parent ------------------------------------------------

    def _reader_loop(self) -> None:
        from libskylark_tpu.resilience import health as _health

        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError):
                break
            kind, rid, payload = msg[0], msg[1], msg[2]
            if kind == "state":
                old, self._state = self._state, payload
                _health.publish(self, old, payload)
                continue
            if kind == "shmfree":
                if self._transport is not None:
                    self._transport.release(payload)
                continue
            with self._lock:
                fut = self._futures.pop(rid, None)
            if fut is None:
                continue
            if kind == "error":
                _resolve(fut, exception=payload)
            else:                      # "result" / "rpc"
                if kind == "result" and self._transport is not None:
                    try:
                        payload = self._transport.decode(payload)
                    except Exception as e:  # noqa: BLE001 — torn slot
                        # the request is lost; the slots must not be —
                        # ack whatever the payload referenced
                        self._transport.recover(payload)
                        _resolve(fut, exception=ServeOverloadedError(
                            f"replica {self.name!r} shm decode failed: "
                            f"{e!r}"))
                        self._flush_shm_acks()
                        continue
                _resolve(fut, result=payload)
            # result views released since the last turnaround free
            # their slots on the child (the c2p ring's writer)
            self._flush_shm_acks()
        # child gone: nothing pending can ever resolve — and nothing
        # can arrive over the rings either, so tear the transport down
        # (unlink is long done; this drops the parent-side mapping)
        with self._lock:
            dead = list(self._futures.values())
            self._futures.clear()
        for fut in dead:
            if not fut.done():
                fut.set_exception(ServeOverloadedError(
                    f"replica process {self.name!r} exited with "
                    f"requests in flight"))
        if self._state not in ("STOPPED",):
            # the child never announced STOPPED itself: a graceful
            # drain forwards DRAINING -> STOPPED over the pipe BEFORE
            # the EOF, so landing here with a live state means the
            # process died out from under us (kill -9, OOM, the chaos
            # ``crash`` fault) — unless the parent itself tore the
            # pipe down (shutdown of a wedged child)
            old, self._state = self._state, "STOPPED"
            self.unexpected_exit = not self._closed
            _health.publish(self, old, "STOPPED")
        if self._transport is not None:
            self._transport.destroy()

    def _flush_shm_acks(self) -> None:
        """Best-effort ``shmfree`` turnaround for released result
        views (parent side). A dead pipe is fine — the pair is going
        down and the mappings die with the processes."""
        if self._transport is None:
            return
        acks = self._transport.drain_acks()
        if not acks:
            return
        try:
            with self._lock:
                self._conn.send(("shmfree", None, acks))
        except Exception:  # noqa: BLE001 — child gone
            pass

    # -- parent → child ------------------------------------------------

    def _send(self, kind: str, *payload) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._closed or not self._proc.is_alive():
                raise ServeOverloadedError(
                    f"replica process {self.name!r} is not serving")
            rid = next(self._rids)
            self._futures[rid] = fut
            try:
                self._conn.send((kind, rid) + payload)
            except (OSError, ValueError) as e:
                self._futures.pop(rid, None)
                raise ServeOverloadedError(
                    f"replica process {self.name!r} pipe closed") from e
            except BaseException:
                # e.g. an unpicklable payload (PicklingError /
                # AttributeError from a local callable): the message
                # never left, so the rid must not sit in _futures
                # waiting for a reply that cannot come
                self._futures.pop(rid, None)
                raise
        return fut

    def _rpc(self, kind: str, *payload, timeout: float = 30.0):
        try:
            return self._send(kind, *payload).result(timeout=timeout)
        except Exception:  # noqa: BLE001 — callers treat None as down
            return None

    def submit(self, endpoint: str, /, **kwargs) -> Future:
        # the router's predigested derivation is an in-process
        # optimization; over the pipe it would pickle the operands
        # twice — the child re-derives instead
        kwargs.pop("_derived", None)
        if self._transport is None:
            return self._send("submit", endpoint, kwargs)
        self._flush_shm_acks()
        payload, claimed = self._transport.encode(kwargs)
        try:
            return self._send("submit", endpoint, payload)
        except BaseException:
            # the header never left: the child will never ack these
            self._transport.unclaim(claimed)
            raise

    def session(self, op: str, /, **kwargs) -> Future:
        # session operands ride the pickle pipe (see _worker_main's
        # "session" branch); the child re-validates against its spec
        return self._send("session", op, kwargs)

    def train(self, op: str, /, **kwargs) -> Future:
        # train operands ride the pickle pipe like session appends —
        # the child persists them to disk at submit anyway, so a
        # zero-copy shm view buys nothing (docs/training)
        return self._send("train", op, kwargs)

    def register_operand(self, A, transform=None, dimension=None,
                         **kwargs) -> Future:
        # the operand crosses like submit kwargs: shm rings when the
        # transport is up, pickle pipe otherwise (docs/caching)
        kwargs = dict(kwargs, A=np.asarray(A), transform=transform,
                      dimension=dimension)
        if self._transport is None:
            return self._send("register", kwargs)
        self._flush_shm_acks()
        payload, claimed = self._transport.encode(kwargs)
        try:
            return self._send("register", payload)
        except BaseException:
            # the header never left: the child will never ack these
            self._transport.unclaim(claimed)
            raise

    def unregister_operand(self, ref) -> Future:
        return self._send("unregister", str(ref))

    def shard(self, task: dict) -> Future:
        # a task is a plan + source descriptor (or one shard's rows)
        # and the reply an s_dim × d partial — both sketch-sized, not
        # data-sized. In-memory rows (wire-flattened ArraySources)
        # ride the shm rings like submit operands; descriptors and the
        # reply take the pickle pipe
        if self._transport is None:
            return self._send("shard", task)
        self._flush_shm_acks()
        payload, claimed = self._transport.encode(task)
        try:
            return self._send("shard", payload)
        except BaseException:
            # the header never left: the child will never ack these
            self._transport.unclaim(claimed)
            raise

    def queue_depth(self) -> int:
        # outstanding submits the parent knows about — no pipe
        # roundtrip on the routing hot path
        with self._lock:
            return len(self._futures)

    def state(self) -> str:
        return self._state

    def stats(self) -> dict:
        return self._rpc("stats") or {}

    def boot_info(self) -> dict:
        """The child's applied engine environment, warmup-pack report,
        engine counters and shm-transport stats — proof of what the
        replica booted with (and of what its payloads rode on)."""
        return self._rpc("env") or {}

    def transport_stats(self) -> Optional[dict]:
        """Parent-side shared-memory transport counters (``None`` when
        the transport is off)."""
        if self._transport is None:
            return None
        return self._transport.stats()

    def flush(self) -> None:
        self._rpc("flush")

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        ok = self._rpc("drain", timeout,
                       timeout=(timeout or 30.0) + 10.0)
        return bool(ok)

    def preempt(self) -> None:
        """Deliver a real SIGTERM to the replica process — the child's
        preemption handler drains its executor (in-flight results
        still come back) and runs its checkpoint hooks."""
        if self._proc.is_alive():
            os.kill(self._proc.pid, signal.SIGTERM)

    def shutdown(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            if self._proc.is_alive():
                try:
                    self._conn.send(("shutdown", next(self._rids)))
                except (OSError, ValueError):
                    pass
            self._proc.join(timeout=30.0)
            if self._proc.is_alive():  # wedged child: don't leak it
                warnings.warn(
                    f"replica process {self.name!r} did not exit; "
                    "terminating", RuntimeWarning, stacklevel=2)
                self._proc.terminate()
                self._proc.join(timeout=5.0)
        finally:
            try:
                self._conn.close()
            except OSError:
                pass
            if self._transport is not None:
                self._transport.destroy()

    def owns_source(self, source: object) -> bool:
        return source is self


__all__ = ["PROPAGATED_ENV", "ProcessReplica", "Replica",
           "ThreadReplica", "propagated_env"]
