"""Consistent-hash ring: the fleet router's sticky-affinity structure.

Affinity routing exists because the microbatch executor's performance
model is *per-replica* warm executable caches: after one warmup flush,
a (bucket, capacity) class serves zero-compile forever — but only on
the replica that compiled it. A round-robin router would spray one
bucket class across every replica and pay N warmup compiles per class
(plus N× the executable-cache pressure); a consistent-hash ring pins
each class to one owner, so the fleet's aggregate compile count equals
a single executor's.

The ring is the classic construction: every member contributes
``vnodes`` virtual points at ``blake2b(f"{member}#{i}")``; a key hashes
to a point and is owned by the first member clockwise. Properties the
router relies on:

- **determinism across processes**: blake2b of the key's ``repr`` —
  bucket statics are tuples of primitives with stable reprs — so two
  router instances (or a router restarted after preemption) agree on
  ownership without coordination, and the chaos battery can replay
  routing decisions bit-identically;
- **minimal disruption**: removing a member (a DRAINING replica) only
  re-owns the keys it held — every other bucket class keeps its warm
  replica;
- **preference order**: :meth:`preference` yields *all* members in
  ring order from the key's point — the router's failover sequence,
  so retries after an injected route fault or a mid-submit drain land
  on a deterministic next candidate.

Members are plain strings (replica names); the ring never touches the
replicas themselves.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Iterator, List, Tuple

from libskylark_tpu.base import locks as _locks


def ring_hash(data: str) -> int:
    """64-bit stable hash (NOT Python's randomized ``hash``): ring
    positions must agree across processes and runs."""
    return int.from_bytes(
        hashlib.blake2b(data.encode("utf-8"), digest_size=8).digest(),
        "big")


def key_point(key: object) -> int:
    """Ring position of a routing key (bucket statics tuples have
    stable reprs; see ``engine.request_statics``)."""
    return ring_hash(repr(key))


class HashRing:
    """Thread-safe consistent-hash ring over named members."""

    def __init__(self, members: Iterable[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self._vnodes = int(vnodes)
        self._lock = _locks.make_lock("fleet.ring")
        self._members: set = set()
        self._points: List[Tuple[int, str]] = []   # sorted (point, member)
        for m in members:
            self.add(m)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def __contains__(self, member: str) -> bool:
        with self._lock:
            return member in self._members

    def members(self) -> set:
        with self._lock:
            return set(self._members)

    def add(self, member: str) -> None:
        """Idempotent; a re-added member lands on its original points
        (vnode hashes depend only on the name)."""
        member = str(member)
        with self._lock:
            if member in self._members:
                return
            self._members.add(member)
            for i in range(self._vnodes):
                bisect.insort(self._points,
                              (ring_hash(f"{member}#{i}"), member))

    def remove(self, member: str) -> None:
        """Idempotent removal (a DRAINING replica leaves the ring)."""
        with self._lock:
            if member not in self._members:
                return
            self._members.discard(member)
            self._points = [p for p in self._points if p[1] != member]

    def owner(self, key: object) -> str:
        """The member owning ``key`` (first point clockwise).
        Raises :class:`LookupError` on an empty ring."""
        for m in self.preference(key):
            return m
        raise LookupError("hash ring is empty")

    def preference(self, key: object) -> Iterator[str]:
        """Every member once, in ring order starting at ``key``'s
        point — the owner first, then the deterministic failover
        sequence."""
        with self._lock:
            points = list(self._points)
            n_members = len(self._members)
        if not points:
            return
        start = bisect.bisect_left(points, (key_point(key), ""))
        seen: set = set()
        for i in range(len(points)):
            member = points[(start + i) % len(points)][1]
            if member not in seen:
                seen.add(member)
                yield member
                if len(seen) == n_members:
                    return


__all__ = ["HashRing", "key_point", "ring_hash"]
