"""Warm-cache-aware router: the fleet's front door.

``Router.submit`` mirrors the single-executor API (`submit_sketch` /
`submit_fastfood` / `submit_solve` / `submit_krr_predict` return the
same futures) but
picks a replica per request from three live signals:

1. **Sticky bucket affinity.** The request's engine-level bucket
   statics (:func:`libskylark_tpu.engine.request_statics` — the exact
   tuple the executor keys its batched executables on) consistent-hash
   onto the replica ring (:mod:`libskylark_tpu.fleet.ring`) under a
   *bounded-load* ownership rule (first preference-order replica
   owning fewer than ``ceil(classes/replicas)`` classes — plain
   consistent hashing strands replicas when the live class population
   is small), so every request of a class lands on the one replica
   whose executable cache is already warm for it. The fleet compiles
   each (bucket, capacity) class once *total*, not once per replica —
   and affinity also keeps cohorts dense: requests that can coalesce
   meet in one queue instead of fragmenting into N half-empty flushes.
2. **Live load.** The affinity owner is checked against its queue
   depth (the per-replica ``queued`` signal telemetry exports); past
   ``spill_threshold`` the router spills to the least-loaded healthy
   peer — a deliberate affinity miss (counted) that trades one warmup
   compile for not queueing behind a hot spot.
3. **Health.** The router *subscribes* to the resilience health hub
   (:mod:`libskylark_tpu.resilience.health`): a DEGRADED replica is
   deprioritized (routed to only when every healthy peer is gone), a
   DRAINING/STOPPED one leaves the ring immediately — its in-flight
   futures still resolve (the drain flushes them) while new traffic
   sheds to peers. No polling: the DRAINING announcement arrives from
   the draining thread before the queue empties.

Failover: each candidate dispatch is wrapped — a replica that refuses
(load shed, drain race, pipe loss) or an injected ``fleet.route``
fault (:mod:`libskylark_tpu.resilience.faults`) moves the request to
the next replica in deterministic ring preference order. A SIGTERM'd
replica therefore costs zero client-visible failures: queued work
drains, new work fails over (``bench.py --fleet`` records it; the
chaos battery replays it under a fixed seed).

Telemetry: ``fleet.routed`` / ``fleet.affinity_hit`` /
``fleet.failover`` / ``fleet.spilled`` counters (labeled per replica),
a ``fleet.route`` span parented over the executor's ``serve.submit``
span (same request id), and a ``fleet`` collector block in
``telemetry.snapshot()`` aggregating every live router.
"""

from __future__ import annotations

import collections
import weakref
from concurrent.futures import Future
from typing import Iterable, Optional

from libskylark_tpu import telemetry as _telemetry
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine import serve as _serve
from libskylark_tpu.fleet.pool import ReplicaPool
from libskylark_tpu.fleet.ring import HashRing
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience import health as _health
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

# live (enablement-gated) instruments for scrape-time visibility; the
# always-on rollup is the "fleet" collector below (docs/observability)
_ROUTED = _metrics.counter(
    "fleet.routed", "Requests routed, by replica and affinity outcome")
_AFFINITY_HIT = _metrics.counter(
    "fleet.affinity_hit", "Requests landing on their ring owner")
_FAILOVER = _metrics.counter(
    "fleet.failover", "Route failovers, by refusing replica")
_SPILLED = _metrics.counter(
    "fleet.spilled", "Load spills away from a saturated ring owner")


class NoHealthyReplicaError(_serve.ServeOverloadedError):
    """Every replica refused the request (all draining/stopped, or the
    whole preference order failed over). A ``ServeOverloadedError``
    subclass so single-executor retry handling keeps working against a
    fleet."""


class Router:
    """Front-door router over a :class:`ReplicaPool` (see module doc).

    ::

        pool = fleet.ReplicaPool(4, max_batch=16)
        router = fleet.Router(pool)
        fut = router.submit_sketch(transform, A)
        ...
        router.close(); pool.shutdown()

    ``spill_threshold`` (requests queued on the affinity owner before
    the router spills to the least-loaded peer) defaults to
    ``4 * max_batch`` — a full cohort plus headroom, so microbatches
    still fill before load-balancing fragments them.
    """

    def __init__(self, pool: ReplicaPool, *, vnodes: int = 64,
                 spill_threshold: Optional[int] = None):
        self._pool = pool
        self._ring = HashRing(pool.names(), vnodes=vnodes)
        self.spill_threshold = int(
            spill_threshold if spill_threshold is not None
            else 4 * pool.max_batch)
        self._lock = _locks.make_lock("fleet.router")
        self._degraded: set = set()
        self._removed: set = set()
        self._counts = collections.Counter()
        self._by_replica = collections.Counter()
        # bounded-load ownership (consistent hashing with bounded
        # loads): a key's owner is the FIRST replica in its ring
        # preference order owning fewer than ceil(keys/replicas)
        # distinct keys. Plain ownership strands whole replicas when
        # the key population is small (four bucket classes over four
        # replicas leave one idle with high probability); the bound
        # spreads classes evenly while keeping assignment sticky and
        # deterministic for a fixed arrival order. The map doubles as
        # the routing fast path (one dict hit instead of a ring walk);
        # it clears when membership changes (epoch bump) — keys then
        # reassign, mostly back onto their surviving owners.
        self._epoch = 0
        self._assign: dict = {}        # statics -> (epoch, owner name)
        self._owned = collections.Counter()
        # seed the view from the replicas' CURRENT states: a router
        # built after a replica started draining must not route to it
        for name in pool.names():
            state = pool.get(name).state()
            if state in (_serve.DRAINING, _serve.STOPPED):
                self._ring.remove(name)
                self._removed.add(name)
            elif state == _serve.DEGRADED:
                self._degraded.add(name)
        # subscribe via a weak method: a router dropped without
        # close() must not be pinned alive by the hub (which would
        # also keep its _ROUTERS entry — and so its counters in every
        # fleet_stats() snapshot — forever); the shim unsubscribes
        # itself on the first publish after collection
        wm = weakref.WeakMethod(self._on_state)
        unsub_cell: list = []

        def _dispatch(source, old, new):
            fn = wm()
            if fn is None:
                if unsub_cell:
                    unsub_cell[0]()
                return
            fn(source, old, new)

        self._unsub = _health.subscribe(_dispatch)
        unsub_cell.append(self._unsub)
        _ROUTERS.add(self)

    # -- health subscription -------------------------------------------

    def _on_state(self, source, old: str, new: str) -> None:
        name = self._pool.resolve_source(source)
        if name is None:
            return                     # some other pool's executor
        with self._lock:
            if new in (_serve.DRAINING, _serve.STOPPED):
                if name in self._ring:
                    self._ring.remove(name)
                    # membership changed: every sticky assignment is
                    # re-derived against the surviving ring
                    self._epoch += 1
                    self._assign.clear()
                    self._owned.clear()
                self._removed.add(name)
                self._degraded.discard(name)
            elif new == _serve.DEGRADED:
                self._degraded.add(name)
            elif new == _serve.SERVING:
                self._degraded.discard(name)

    def _affinity_owner(self, statics: tuple,
                        record: bool = True) -> Optional[str]:
        """Sticky bounded-load owner of a bucket class (see
        ``__init__``); ``None`` on an empty ring. Assignment is lazy
        and cached per statics tuple — the routing fast path. With
        ``record=False`` the derivation is read-only: no sticky
        assignment is stored and no ownership is charged, so
        introspection (``owner_of``) can never perturb where real
        traffic lands."""
        with self._lock:
            hit = self._assign.get(statics)
            if hit is not None and hit[0] == self._epoch:
                return hit[1]
            n_members = len(self._ring)
            if n_members == 0:
                return None
            cap = -(-(len(self._assign) + 1) // n_members)  # ceil
            owner = None
            for name in self._ring.preference(statics):
                if owner is None:
                    owner = name           # unbounded fallback
                if self._owned[name] < cap:
                    owner = name
                    break
            if record:
                self._assign[statics] = (self._epoch, owner)
                self._owned[owner] += 1
            return owner

    # -- routing -------------------------------------------------------

    def _candidates(self, statics: tuple) -> tuple:
        """(ordered candidate names, affinity owner, spilled?). The
        bounded-load owner leads; the rest follow in ring preference
        order with DEGRADED members demoted to the tail (still
        candidates — a degraded replica beats a refused request);
        under owner saturation the least-loaded healthy peer is
        promoted to the front (a counted spill)."""
        owner = self._affinity_owner(statics)
        if owner is None:
            return (), None, False
        pref = [owner] + [n for n in self._ring.preference(statics)
                          if n != owner]
        with self._lock:
            degraded = set(self._degraded)
        healthy = [n for n in pref if n not in degraded]
        order = healthy + [n for n in pref if n in degraded]
        spilled = False
        if len(healthy) > 1 and order and order[0] == owner:
            depth = self._pool.get(owner).queue_depth()
            if depth >= self.spill_threshold:
                peers = [(self._pool.get(n).queue_depth(), n)
                         for n in healthy[1:]]
                best_depth, best = min(peers)
                if best_depth < depth:
                    order.remove(best)
                    order.insert(0, best)
                    spilled = True
        return tuple(order), owner, spilled

    def submit(self, endpoint: str, /, **kwargs) -> Future:
        """Route one request; returns the chosen replica's future.
        Accepts exactly the executor ``submit`` kwargs (operands plus
        ``timeout`` / ``deadline`` / ``request_id``)."""
        derived = _serve.derive_request(
            endpoint, pad_floor=self._pool.pad_floor,
            **{k: v for k, v in kwargs.items()
               if k not in ("timeout",)})
        statics = derived[0]
        # the chosen replica reuses this derivation (one prep per
        # routed request); replicas with a different pad_floor would
        # re-derive, but the pool keeps the fleet uniform
        kwargs["_derived"] = derived
        rid = kwargs.get("request_id")
        if rid is None and _telemetry.enabled():
            rid = kwargs["request_id"] = _trace.new_request_id()
        # the route span is the request's ROOT: the executor's
        # serve.submit span opens inside it (same thread) and parents
        # under it with the same request id — docs/observability
        with _trace.span("fleet.route", attrs={"endpoint": endpoint},
                         request_id=rid) as sp:
            tags = faults.current_tags()
            # fast path: a healthy, unsaturated owner takes the
            # request without materializing the failover order (the
            # submit hot path — the full candidate walk only runs on
            # refusal, saturation, or a degraded owner)
            owner = self._affinity_owner(statics)
            if owner is not None and owner not in self._degraded:
                if (self._pool.get(owner).queue_depth()
                        < self.spill_threshold):
                    try:
                        faults.check("fleet.route", tags=tags,
                                     detail=f"{endpoint} -> {owner}")
                        fut = self._pool.get(owner).submit(endpoint,
                                                           **kwargs)
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except BaseException as e:  # noqa: BLE001
                        with self._lock:
                            self._counts["failover"] += 1
                        _FAILOVER.inc(replica=owner)
                        if sp is not None:
                            sp.add_event("failover",
                                         {"replica": owner,
                                          "error": repr(e)})
                        return self._submit_slow(
                            endpoint, kwargs, statics, owner, sp,
                            tags, skip=owner, last_err=e)
                    self._account(owner, owner, False, sp)
                    return fut
            return self._submit_slow(endpoint, kwargs, statics, owner,
                                     sp, tags)

    def _account(self, name: str, owner: Optional[str], spilled: bool,
                 sp) -> None:
        hit = name == owner
        with self._lock:
            self._counts["routed"] += 1
            self._counts["affinity_hit"] += hit
            self._counts["spilled"] += spilled
            self._by_replica[name] += 1
        _ROUTED.inc(replica=name, affinity=str(hit).lower())
        if hit:
            _AFFINITY_HIT.inc(replica=name)
        if spilled:
            _SPILLED.inc(replica=name)
        if sp is not None:
            sp.set_attr("replica", name)
            sp.set_attr("affinity_hit", hit)

    def _submit_slow(self, endpoint: str, kwargs: dict, statics: tuple,
                     owner: Optional[str], sp, tags,
                     skip: Optional[str] = None,
                     last_err: Optional[BaseException] = None) -> Future:
        """The full candidate walk: failover order, degraded demotion,
        load spill (see :meth:`_candidates`). ``skip`` is a candidate
        the fast path already tried (and counted as a failover)."""
        order, owner, spilled = self._candidates(statics)
        for name in order:
            if name == skip:
                continue
            try:
                # chaos seam: per route ATTEMPT, so a fault plan can
                # fail the owner and replay the failover
                faults.check("fleet.route", tags=tags,
                             detail=f"{endpoint} -> {name}")
                fut = self._pool.get(name).submit(endpoint, **kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — failover
                last_err = e
                with self._lock:
                    self._counts["failover"] += 1
                _FAILOVER.inc(replica=name)
                if sp is not None:
                    sp.add_event("failover", {"replica": name,
                                              "error": repr(e)})
                continue
            self._account(name, owner, spilled, sp)
            return fut
        raise NoHealthyReplicaError(
            f"no replica accepted {endpoint!r}: tried "
            f"{list(order) or 'none (empty ring)'}"
        ) from last_err

    # executor-mirroring conveniences

    def submit_sketch(self, transform, A, dimension=None, **kw) -> Future:
        return self.submit("sketch_apply", transform=transform, A=A,
                           dimension=dimension, **kw)

    def submit_fastfood(self, transform, A, **kw) -> Future:
        return self.submit("fastfood_features", transform=transform,
                           A=A, **kw)

    def submit_solve(self, A, B, transform, method: str = "qr",
                     **kw) -> Future:
        return self.submit("solve_l2_sketched", A=A, B=B,
                           transform=transform, method=method, **kw)

    def submit_krr_predict(self, kernel, X_new, X_train, coef,
                           **kw) -> Future:
        return self.submit("krr_predict", kernel=kernel, X_new=X_new,
                           X_train=X_train, coef=coef, **kw)

    # -- introspection -------------------------------------------------

    def owner_of(self, endpoint: str, **kwargs) -> Optional[str]:
        """The (bounded-load) owner a request WOULD have affinity for
        (tests, capacity planning); ``None`` on an empty ring.
        Read-only: probing never caches an assignment or charges
        ownership, so hypothetical queries cannot shift where real
        traffic lands."""
        statics = _serve.request_statics(
            endpoint, pad_floor=self._pool.pad_floor, **kwargs)
        return self._affinity_owner(statics, record=False)

    def routable(self) -> list:
        """Names currently on the ring (DRAINING/STOPPED excluded)."""
        return sorted(self._ring.members())

    def stats(self) -> dict:
        with self._lock:
            c = dict(self._counts)
            by = dict(sorted(self._by_replica.items()))
        routed = c.get("routed", 0)
        with self._lock:
            degraded = sorted(self._degraded)
            removed = sorted(self._removed)
        return {
            "routed": routed,
            "affinity_hit": c.get("affinity_hit", 0),
            "affinity_hit_rate": (
                round(c.get("affinity_hit", 0) / routed, 4)
                if routed else None),
            "failover": c.get("failover", 0),
            "spilled": c.get("spilled", 0),
            "routable": self.routable(),
            "degraded": degraded,
            "removed": removed,
            "by_replica": by,
        }

    def close(self) -> None:
        """Unsubscribe from the health hub (the pool outlives the
        router; idempotent)."""
        self._unsub()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_ROUTERS: "weakref.WeakSet[Router]" = weakref.WeakSet()


def fleet_stats() -> dict:
    """Aggregate routing counters over every live router (the
    ``fleet`` collector block in ``telemetry.snapshot()``)."""
    agg = collections.Counter(routed=0, affinity_hit=0, failover=0,
                              spilled=0)
    by_replica = collections.Counter()
    routers = 0
    for router in list(_ROUTERS):
        s = router.stats()
        routers += 1
        for k in ("routed", "affinity_hit", "failover", "spilled"):
            agg[k] += s[k]
        by_replica.update(s["by_replica"])
    out = dict(agg)
    out["routers"] = routers
    out["affinity_hit_rate"] = (
        round(out["affinity_hit"] / out["routed"], 4)
        if out["routed"] else None)
    out["by_replica"] = {name: {"routed": n}
                         for name, n in sorted(by_replica.items())}
    return out


_telemetry.register_collector("fleet", fleet_stats)


def _iter_routers() -> Iterable[Router]:   # tests/debug
    return list(_ROUTERS)


__all__ = ["NoHealthyReplicaError", "Router", "fleet_stats"]
