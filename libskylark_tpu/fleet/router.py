"""Warm-cache-aware router: the fleet's front door.

``Router.submit`` mirrors the single-executor API (`submit_sketch` /
`submit_fastfood` / `submit_solve` / `submit_krr_predict` return the
same futures) but
picks a replica per request from three live signals:

1. **Sticky bucket affinity.** The request's engine-level bucket
   statics (:func:`libskylark_tpu.engine.request_statics` — the exact
   tuple the executor keys its batched executables on) consistent-hash
   onto the replica ring (:mod:`libskylark_tpu.fleet.ring`) under a
   *bounded-load* ownership rule (first preference-order replica
   owning fewer than ``ceil(classes/replicas)`` classes — plain
   consistent hashing strands replicas when the live class population
   is small), so every request of a class lands on the one replica
   whose executable cache is already warm for it. The fleet compiles
   each (bucket, capacity) class once *total*, not once per replica —
   and affinity also keeps cohorts dense: requests that can coalesce
   meet in one queue instead of fragmenting into N half-empty flushes.
2. **Live load.** The affinity owner is checked against its queue
   depth (the per-replica ``queued`` signal telemetry exports); past
   ``spill_threshold`` the router spills to the least-loaded healthy
   peer — a deliberate affinity miss (counted) that trades one warmup
   compile for not queueing behind a hot spot.
3. **Health.** The router *subscribes* to the resilience health hub
   (:mod:`libskylark_tpu.resilience.health`): a DEGRADED replica is
   deprioritized (routed to only when every healthy peer is gone), a
   DRAINING/STOPPED one leaves the ring immediately — its in-flight
   futures still resolve (the drain flushes them) while new traffic
   sheds to peers. No polling: the DRAINING announcement arrives from
   the draining thread before the queue empties.

Failover: each candidate dispatch is wrapped — a replica that refuses
(load shed, drain race, pipe loss) or an injected ``fleet.route``
fault (:mod:`libskylark_tpu.resilience.faults`) moves the request to
the next replica in deterministic ring preference order. A SIGTERM'd
replica therefore costs zero client-visible failures: queued work
drains, new work fails over (``bench.py --fleet`` records it; the
chaos battery replays it under a fixed seed).

Hedging (``Router(pool, hedge=True)`` / ``SKYLARK_FLEET_HEDGE``):
a straggling in-flight request — one still unresolved after a
p99-derived delay — is mirrored to the second healthy ring-preference
replica and the caller's future settles with whichever attempt
finishes first; the loser is cancelled (or, under
``SKYLARK_FLEET_HEDGE_VERIFY``, completed and compared bitwise — the
determinism guard). Both executions are bit-equal by construction:
the serve endpoints are pure functions of their operands and key
material, and the mirror reuses the identical kwargs and ``_derived``
statics. See docs/fleet "Hedged requests".

Telemetry: ``fleet.routed`` / ``fleet.affinity_hit`` /
``fleet.failover`` / ``fleet.spilled`` / ``fleet.hedged`` /
``fleet.hedge_wins`` / ``fleet.hedge_mismatches`` counters (labeled
per replica), a ``fleet.route`` span parented over the executor's
``serve.submit`` span (same request id), and a ``fleet`` collector
block in ``telemetry.snapshot()`` aggregating every live router,
every live autoscaler, and the process-lifetime hedge/scale totals.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import sys
import threading
import time
import uuid
import warnings
import weakref
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Iterable, Optional

import numpy as np

from libskylark_tpu import qos as _qos
from libskylark_tpu import telemetry as _telemetry
from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors as _errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine import resultcache as _rcache
from libskylark_tpu.engine import serve as _serve
from libskylark_tpu.fleet.pool import ReplicaPool
from libskylark_tpu.fleet.ring import HashRing
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience import health as _health
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

# live (enablement-gated) instruments for scrape-time visibility; the
# always-on rollup is the "fleet" collector below (docs/observability)
_ROUTED = _metrics.counter(
    "fleet.routed", "Requests routed, by replica and affinity outcome")
_AFFINITY_HIT = _metrics.counter(
    "fleet.affinity_hit", "Requests landing on their ring owner")
_FAILOVER = _metrics.counter(
    "fleet.failover", "Route failovers, by refusing replica")
_SPILLED = _metrics.counter(
    "fleet.spilled", "Load spills away from a saturated ring owner")
_HEDGED = _metrics.counter(
    "fleet.hedged", "Straggler requests mirrored to a second replica, "
    "by hedge-target replica")
_HEDGE_WINS = _metrics.counter(
    "fleet.hedge_wins", "Hedged requests where the mirror finished "
    "first, by hedge-target replica")
_HEDGE_MISMATCH = _metrics.counter(
    "fleet.hedge_mismatches", "Hedge verify-mode comparisons where the "
    "two executions diverged (must stay 0 — the endpoints are "
    "deterministic)")
_SESSION_HANDOFFS = _metrics.counter(
    "fleet.session_handoffs", "Stateful sessions re-resolved to a new "
    "owner replica (drain handoff or crash replay), by new owner")


# process-lifetime hedge rollup: hedge events survive their router (a
# benchmarks telemetry snapshot taken after a leg's router is gone
# must still carry them — collectors report live objects only)
_LIFETIME = _metrics.LifetimeCounter(
    "fleet.router_life",
    kinds=("hedged", "hedge_wins", "hedge_mismatches"))


class NoHealthyReplicaError(_serve.ServeOverloadedError):
    """Every replica refused the request (all draining/stopped, or the
    whole preference order failed over). A ``ServeOverloadedError``
    subclass so single-executor retry handling keeps working against a
    fleet."""


class _HedgeEntry:
    """One hedged request's state (see ``Router`` "Hedged requests").
    ``client`` is the future the caller holds; ``primary``/``hedge``
    are the replica attempts racing to settle it."""

    __slots__ = ("endpoint", "kwargs", "statics", "primary",
                 "primary_name", "client", "tags", "t0", "fired",
                 "hedge", "hedge_name", "settled", "errors", "results")

    def __init__(self, endpoint, kwargs, statics, primary, primary_name,
                 tags):
        self.endpoint = endpoint
        self.kwargs = kwargs
        self.statics = statics
        self.primary = primary
        self.primary_name = primary_name
        self.client: Future = Future()
        self.tags = tags
        self.t0 = time.monotonic()
        self.fired = False
        self.hedge: Optional[Future] = None
        self.hedge_name: Optional[str] = None
        self.settled = False
        self.errors: dict = {}
        self.results: dict = {}


class _Hedger:
    """The router's straggler watchdog: a single timer thread over a
    heap of (due-time, entry). An entry whose client settled before
    its due time costs one heap pop and nothing else; one that is
    still unresolved fires a mirror submit to the next healthy
    ring-preference replica."""

    def __init__(self, router: "Router"):
        self._router = router
        self._cond = threading.Condition(
            _locks.make_lock("fleet.hedger"))
        self._heap: list = []
        self._seq = itertools.count()
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="skylark-fleet-hedger", daemon=True)
        self._thread.start()

    def watch(self, entry: _HedgeEntry, due: float) -> None:
        with self._cond:
            heapq.heappush(self._heap, (due, next(self._seq), entry))
            self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while True:
            fire = None
            with self._cond:
                if self._stop:
                    return
                now = time.monotonic()
                if not self._heap:
                    self._cond.wait(timeout=1.0)
                    continue
                due, _, entry = self._heap[0]
                if due > now:
                    self._cond.wait(timeout=due - now)
                    continue
                heapq.heappop(self._heap)
                fire = entry
            # the dispatch runs OUTSIDE the heap lock: a mirror submit
            # can block on a replica queue and must not stall the
            # watchdog for every other in-flight hedge
            if fire is not None:
                try:
                    self._router._fire_hedge(fire)
                except Exception as e:  # noqa: BLE001 — watchdog lives
                    warnings.warn(f"hedge dispatch failed: {e}",
                                  RuntimeWarning, stacklevel=1)


class Router:
    """Front-door router over a :class:`ReplicaPool` (see module doc).

    ::

        pool = fleet.ReplicaPool(4, max_batch=16)
        router = fleet.Router(pool)
        fut = router.submit_sketch(transform, A)
        ...
        router.close(); pool.shutdown()

    ``spill_threshold`` (requests queued on the affinity owner before
    the router spills to the least-loaded peer) defaults to
    ``4 * max_batch`` — a full cohort plus headroom, so microbatches
    still fill before load-balancing fragments them.
    """

    def __init__(self, pool: ReplicaPool, *, vnodes: int = 64,
                 spill_threshold: Optional[int] = None,
                 hedge: Optional[bool] = None,
                 hedge_delay_ms: Optional[float] = None,
                 hedge_verify: Optional[bool] = None,
                 cache: Optional[bool] = None):
        self._pool = pool
        self._ring = HashRing(pool.names(), vnodes=vnodes)
        self.spill_threshold = int(
            spill_threshold if spill_threshold is not None
            else 4 * pool.max_batch)
        self._lock = _locks.make_lock("fleet.router")
        # hedged requests (docs/fleet "Hedged requests"): arguments
        # beat the env defaults; the hedger thread starts lazily on
        # the first hedged submit
        self._hedge_on = bool(_env.FLEET_HEDGE.get()
                              if hedge is None else hedge)
        self._hedge_fixed_ms = (
            _env.FLEET_HEDGE_DELAY_MS.get()
            if hedge_delay_ms is None else float(hedge_delay_ms))
        self._hedge_verify = bool(_env.FLEET_HEDGE_VERIFY.get()
                                  if hedge_verify is None
                                  else hedge_verify)
        self._hedge_lock = _locks.make_lock("fleet.hedge")
        self._hedger: Optional[_Hedger] = None
        # front-door single-flight (docs/caching): concurrent
        # identical submits coalesce onto ONE routed dispatch; the
        # router also computes each request's content digest here,
        # once, and forwards it as ``_digest`` so no replica ever
        # re-hashes the operands. Follows the result-cache gate
        # (``SKYLARK_CACHE``) unless pinned by the argument.
        cache_on = bool(_env.CACHE.get() if cache is None else cache)
        self._flights: Optional[_rcache.SingleFlight] = (
            _rcache.SingleFlight(name="router") if cache_on else None)
        # the router's own pin table mirrors every broadcast
        # registration: a ref submit derives its statics (and its
        # digest) from this local copy, while the tiny ref — not the
        # operand bytes — is what crosses to the chosen replica
        self._residency = _rcache.ResidencyTable(name="router")
        self._latency: "collections.deque" = collections.deque(
            maxlen=4096)
        self._hedge_delay_cache = (0.0, 0.05)   # (stamp, seconds)
        self._degraded: set = set()
        self._removed: set = set()
        self._counts = collections.Counter()
        self._by_replica = collections.Counter()
        # pipelined dist-serve front door (docs/distributed): one
        # shared shard coordinator over the pool, built lazily on the
        # first dist submit — its ring is the shard placement truth
        # for every dist job this router drives
        self._dist_co = None
        # bounded-load ownership (consistent hashing with bounded
        # loads): a key's owner is the FIRST replica in its ring
        # preference order owning fewer than ceil(keys/replicas)
        # distinct keys. Plain ownership strands whole replicas when
        # the key population is small (four bucket classes over four
        # replicas leave one idle with high probability); the bound
        # spreads classes evenly while keeping assignment sticky and
        # deterministic for a fixed arrival order. The map doubles as
        # the routing fast path (one dict hit instead of a ring walk);
        # it clears when membership changes (epoch bump) — keys then
        # reassign, mostly back onto their surviving owners.
        self._epoch = 0
        self._assign: dict = {}        # statics -> (epoch, owner name)
        self._owned = collections.Counter()
        # stateful-session affinity (docs/sessions): sid -> (epoch,
        # owner). Unlike bucket affinity, a session assignment is NOT
        # re-derived on every membership change: the recorded owner
        # holds the session's live state and journal lease, so it
        # stays authoritative for as long as it remains on the ring
        # (ring GROWTH must not move a live session). Only when the
        # owner actually leaves (drain/crash) does the next touch
        # re-resolve (a handoff) to a surviving owner, which resumes
        # the session from SKYLARK_SESSION_DIR; the epoch stamp
        # anchors assignments against hub history for forensics
        self._sessions: dict = {}      # sid -> (epoch, owner name)
        # where this router's current epoch sits on the hub's global
        # transition timeline (resilience.health.transition_seq) —
        # anchors epoch-stamped views (session assignments, ring
        # membership) against hub history in forensics/tests
        self._epoch_hub_seq = _health.transition_seq()
        # seed the view from the replicas' CURRENT states: a router
        # built after a replica started draining must not route to it
        for name in pool.names():
            state = pool.get(name).state()
            if state in (_serve.DRAINING, _serve.STOPPED):
                self._ring.remove(name)
                self._removed.add(name)
            elif state == _serve.DEGRADED:
                self._degraded.add(name)
        # subscribe via a weak method: a router dropped without
        # close() must not be pinned alive by the hub (which would
        # also keep its _ROUTERS entry — and so its counters in every
        # fleet_stats() snapshot — forever); the shim unsubscribes
        # itself on the first publish after collection
        wm = weakref.WeakMethod(self._on_state)
        unsub_cell: list = []

        def _dispatch(source, old, new):
            fn = wm()
            if fn is None:
                if unsub_cell:
                    unsub_cell[0]()
                return
            fn(source, old, new)

        self._unsub = _health.subscribe(_dispatch)
        unsub_cell.append(self._unsub)
        _ROUTERS.add(self)

    # -- health subscription -------------------------------------------

    def _on_state(self, source, old: str, new: str) -> None:
        name = self._pool.resolve_source(source)
        if name is None:
            return                     # some other pool's executor
        with self._lock:
            if new in (_serve.DRAINING, _serve.STOPPED):
                if name in self._ring:
                    self._ring.remove(name)
                    # membership changed: every sticky assignment is
                    # re-derived against the surviving ring
                    self._epoch += 1
                    self._epoch_hub_seq = _health.transition_seq()
                    self._assign.clear()
                    self._owned.clear()
                self._removed.add(name)
                self._degraded.discard(name)
            elif new == _serve.DEGRADED:
                self._degraded.add(name)
            elif new == _serve.SERVING:
                self._degraded.discard(name)
                if name not in self._ring:
                    # a replica the pool grew (autoscale scale-up) or
                    # revived: join the ring and re-derive sticky
                    # ownership against the new membership
                    self._ring.add(name)
                    self._epoch += 1
                    self._epoch_hub_seq = _health.transition_seq()
                    self._assign.clear()
                    self._owned.clear()
                    self._removed.discard(name)

    def _affinity_owner(self, statics: tuple,
                        record: bool = True) -> Optional[str]:
        """Sticky bounded-load owner of a bucket class (see
        ``__init__``); ``None`` on an empty ring. Assignment is lazy
        and cached per statics tuple — the routing fast path. With
        ``record=False`` the derivation is read-only: no sticky
        assignment is stored and no ownership is charged, so
        introspection (``owner_of``) can never perturb where real
        traffic lands."""
        with self._lock:
            hit = self._assign.get(statics)
            if hit is not None and hit[0] == self._epoch:
                return hit[1]
            n_members = len(self._ring)
            if n_members == 0:
                return None
            cap = -(-(len(self._assign) + 1) // n_members)  # ceil
            owner = None
            for name in self._ring.preference(statics):
                if owner is None:
                    owner = name           # unbounded fallback
                if self._owned[name] < cap:
                    owner = name
                    break
            if record:
                self._assign[statics] = (self._epoch, owner)
                self._owned[owner] += 1
            return owner

    # -- routing -------------------------------------------------------

    def _candidates(self, statics: tuple,
                    allow_spill: bool = True) -> tuple:
        """(ordered candidate names, affinity owner, spilled?). The
        bounded-load owner leads; the rest follow in ring preference
        order with DEGRADED members demoted to the tail (still
        candidates — a degraded replica beats a refused request);
        under owner saturation the least-loaded healthy peer is
        promoted to the front (a counted spill). ``allow_spill=False``
        (best_effort traffic) keeps the owner in front regardless of
        its depth — spill headroom is a latency-SLO resource."""
        owner = self._affinity_owner(statics)
        if owner is None:
            return (), None, False
        pref = [owner] + [n for n in self._ring.preference(statics)
                          if n != owner]
        with self._lock:
            degraded = set(self._degraded)
        healthy = [n for n in pref if n not in degraded]
        order = healthy + [n for n in pref if n in degraded]
        spilled = False
        if allow_spill and len(healthy) > 1 and order \
                and order[0] == owner:
            try:
                depth = self._pool.get(owner).queue_depth()
            except KeyError:           # removed by a scale-down race
                depth = None
            if depth is not None and depth >= self.spill_threshold:
                peers = []
                for n in healthy[1:]:
                    try:
                        peers.append((self._pool.get(n).queue_depth(),
                                      n))
                    except KeyError:
                        continue
                if peers:
                    best_depth, best = min(peers)
                    if best_depth < depth:
                        order.remove(best)
                        order.insert(0, best)
                        spilled = True
        return tuple(order), owner, spilled

    def submit(self, endpoint: str, /, **kwargs) -> Future:
        """Route one request; returns the chosen replica's future.
        Accepts exactly the executor ``submit`` kwargs (operands plus
        ``timeout`` / ``deadline`` / ``request_id`` / ``tenant``).

        QoS (docs/qos): the router IS the front door — it resolves
        ``tenant=`` against the parent-process registry, charges the
        token bucket (:class:`~libskylark_tpu.base.errors
        .TenantQuotaError` propagates to the caller; an over-quota
        request never reaches a replica), and forwards the resolved
        class as ``qos_class=`` so thread AND process replicas
        schedule it identically without re-billing. Class shapes the
        routing too: best_effort requests neither spill nor hedge —
        load-balancing headroom and mirror capacity are reserved for
        the classes with latency SLOs."""
        tenant = kwargs.pop("tenant", None)
        qos_class = kwargs.get("qos_class")
        if qos_class is not None:
            # normalize here too: the class steers ROUTING (spill and
            # hedge eligibility below) before any executor coerces it
            qos_class = kwargs["qos_class"] = _qos.coerce_class(
                qos_class)
        if qos_class is None:
            # admission at the front door; the registry's buckets
            # live in THIS process, so a process replica never needs
            # the tenant table. A refusal is counted HERE — the
            # executor-side rate_limited counting never sees a
            # request the router refused
            try:
                tenant, qos_class = _qos.get_registry().admit(tenant)
            except _errors.TenantQuotaError as e:
                _cls = _qos.get_registry().resolve(tenant)[1]
                with self._lock:
                    self._counts["rate_limited"] += 1
                _serve._QOS_RATE_LIMITED.inc(
                    **{"class": _cls, "tenant": e.tenant})
                raise
            kwargs["qos_class"] = qos_class
            # cardinality bound (see TenantRegistry.accounting_name):
            # the label forwarded to replicas is vetted HERE
            tenant = _qos.get_registry().accounting_name(tenant)
        kwargs["tenant"] = tenant or ""
        derive_kwargs = {k: v for k, v in kwargs.items()
                         if k not in ("timeout",)}
        if _rcache.is_ref(derive_kwargs.get("A")):
            # resident-operand ref (docs/caching): statics and digest
            # derive from the router's local pin; ``kwargs["A"]``
            # keeps the ref — each replica resolves it against its
            # own broadcast pin, so a process replica receives a
            # 64-char string where the operand bytes would have been
            derive_kwargs["A"] = self._residency.resolve(
                _rcache.as_ref(derive_kwargs["A"]).digest)
        derived = _serve.derive_request(
            endpoint, pad_floor=self._pool.pad_floor, **derive_kwargs)
        statics = derived[0]
        # the chosen replica reuses this derivation (one prep per
        # routed request); replicas with a different pad_floor would
        # re-derive, but the pool keeps the fleet uniform
        kwargs["_derived"] = derived
        rid = kwargs.get("request_id")
        if rid is None and _telemetry.enabled():
            rid = kwargs["request_id"] = _trace.new_request_id()
        if self._flights is None:
            return self._route(endpoint, kwargs, statics, rid)
        # single-flight at the front door (docs/caching): the content
        # digest is computed HERE, once, and forwarded (``_digest``)
        # so the chosen replica — and its executor's result cache —
        # reuses it without re-hashing the operands. A submit whose
        # digest matches an in-flight leader returns a follower
        # future without touching any replica; the leader's settle
        # fans the one result to every follower, bit-equal.
        digest = kwargs.get("_digest")
        if digest is None:
            digest = kwargs["_digest"] = _serve.request_digest(
                endpoint, derived, kwargs)
        cls = kwargs["qos_class"]
        follower = self._flights.join(digest, cls)
        if follower is not None:
            with self._lock:
                self._counts["coalesced"] += 1
            return follower
        flight = self._flights.lead(digest, cls)
        try:
            fut = self._route(endpoint, kwargs, statics, rid)
        except BaseException as e:
            # the leader never dispatched (quota refusal, empty
            # ring): its coalesced followers fail with the same
            # error, orphan-free
            self._flights.abort(flight, e)
            raise
        fut.add_done_callback(
            lambda f, _fl=flight: self._flights.settle(_fl, f))
        return fut

    def _route(self, endpoint: str, kwargs: dict, statics: tuple,
               rid) -> Future:
        """One routed dispatch (fast path, else the candidate walk) —
        the body :meth:`submit` wraps in the single-flight tier."""
        # the route span is the request's ROOT: the executor's
        # serve.submit span opens inside it (same thread) and parents
        # under it with the same request id — docs/observability
        with _trace.span("fleet.route", attrs={"endpoint": endpoint},
                         request_id=rid) as sp:
            tags = faults.current_tags()
            # fast path: a healthy, unsaturated owner takes the
            # request without materializing the failover order (the
            # submit hot path — the full candidate walk only runs on
            # refusal, saturation, or a degraded owner)
            owner = self._affinity_owner(statics)
            if owner is not None and owner not in self._degraded:
                try:
                    # a scale-down can remove the owner from the pool
                    # between the ring read and here; the slow path's
                    # candidate walk handles the re-derivation
                    owner_depth = self._pool.get(owner).queue_depth()
                except KeyError:
                    owner_depth = None
                if (owner_depth is not None
                        and (owner_depth < self.spill_threshold
                             or kwargs["qos_class"]
                             == _qos.BEST_EFFORT)):
                    try:
                        faults.check("fleet.route", tags=tags,
                                     detail=f"{endpoint} -> {owner}")
                        fut = self._pool.get(owner).submit(endpoint,
                                                           **kwargs)
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except BaseException as e:  # noqa: BLE001
                        with self._lock:
                            self._counts["failover"] += 1
                        _FAILOVER.inc(replica=owner)
                        if sp is not None:
                            sp.add_event("failover",
                                         {"replica": owner,
                                          "error": repr(e)})
                        return self._submit_slow(
                            endpoint, kwargs, statics, owner, sp,
                            tags, skip=owner, last_err=e)
                    self._account(owner, owner, False, sp)
                    return self._maybe_hedge(endpoint, kwargs, statics,
                                             owner, fut, tags)
            return self._submit_slow(endpoint, kwargs, statics, owner,
                                     sp, tags)

    def _account(self, name: str, owner: Optional[str], spilled: bool,
                 sp) -> None:
        hit = name == owner
        with self._lock:
            self._counts["routed"] += 1
            self._counts["affinity_hit"] += hit
            self._counts["spilled"] += spilled
            self._by_replica[name] += 1
        _ROUTED.inc(replica=name, affinity=str(hit).lower())
        if hit:
            _AFFINITY_HIT.inc(replica=name)
        if spilled:
            _SPILLED.inc(replica=name)
        if sp is not None:
            sp.set_attr("replica", name)
            sp.set_attr("affinity_hit", hit)

    def _submit_slow(self, endpoint: str, kwargs: dict, statics: tuple,
                     owner: Optional[str], sp, tags,
                     skip: Optional[str] = None,
                     last_err: Optional[BaseException] = None) -> Future:
        """The full candidate walk: failover order, degraded demotion,
        load spill (see :meth:`_candidates`). ``skip`` is a candidate
        the fast path already tried (and counted as a failover)."""
        order, owner, spilled = self._candidates(
            statics,
            allow_spill=kwargs.get("qos_class") != _qos.BEST_EFFORT)
        for name in order:
            if name == skip:
                continue
            try:
                # chaos seam: per route ATTEMPT, so a fault plan can
                # fail the owner and replay the failover
                faults.check("fleet.route", tags=tags,
                             detail=f"{endpoint} -> {name}")
                fut = self._pool.get(name).submit(endpoint, **kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — failover
                last_err = e
                with self._lock:
                    self._counts["failover"] += 1
                _FAILOVER.inc(replica=name)
                if sp is not None:
                    sp.add_event("failover", {"replica": name,
                                              "error": repr(e)})
                continue
            self._account(name, owner, spilled, sp)
            return self._maybe_hedge(endpoint, kwargs, statics, name,
                                     fut, tags)
        raise NoHealthyReplicaError(
            f"no replica accepted {endpoint!r}: tried "
            f"{list(order) or 'none (empty ring)'}"
        ) from last_err

    # -- pipelined distributed serve (docs/distributed) ----------------

    def _dist_coordinator(self):
        with self._lock:
            co = self._dist_co
        if co is None:
            from libskylark_tpu.dist.coordinator import (
                DistSketchCoordinator)

            co = DistSketchCoordinator(pool=self._pool)
            with self._lock:
                if self._dist_co is None:
                    self._dist_co = co
                else:
                    co = self._dist_co
        return co

    def _submit_dist(self, endpoint: str, plan, source, *,
                     tenant=None, qos_class=None, min_coverage=None,
                     deadline=None, timeout=None, request_id=None,
                     pipeline=None, solve=None,
                     digest_extra=()) -> Future:
        """Front door of one distributed job: admission + digest +
        single-flight happen HERE, once; then the ring-preferred
        replica with an in-process executor owns the job (its result
        cache keys on the forwarded digest), or — process fleets —
        the router drives the shard storm itself. Either way the
        shard tasks fan across the whole pool through the shared
        coordinator."""
        from libskylark_tpu.dist import serve as _dserve

        plan.validate()
        if source.n < plan.n:
            raise _errors.InvalidParametersError(
                f"source holds {source.n} rows < plan.n={plan.n}")
        if qos_class is not None:
            qos_class = _qos.coerce_class(qos_class)
            tenant = str(tenant) if tenant else ""
        else:
            try:
                tenant, qos_class = _qos.get_registry().admit(tenant)
            except _errors.TenantQuotaError as e:
                _cls = _qos.get_registry().resolve(tenant)[1]
                with self._lock:
                    self._counts["rate_limited"] += 1
                _serve._QOS_RATE_LIMITED.inc(
                    **{"class": _cls, "tenant": e.tenant})
                raise
            tenant = _qos.get_registry().accounting_name(tenant)
        rid = request_id
        if rid is None and _telemetry.enabled():
            rid = _trace.new_request_id()
        with self._lock:
            self._counts["dist_jobs"] += 1
        co = self._dist_coordinator()
        # the owning executor: first ring-preference member exposing
        # an in-process executor (thread fleets). Digested once —
        # the executor's cache and this front door share the key.
        owner_ex = None
        for name in self._ring.preference(("dist", plan.fingerprint())):
            try:
                ex = getattr(self._pool.get(name), "executor", None)
            except KeyError:
                continue
            if ex is not None:
                owner_ex = ex
                break

        def _dispatch(digest=None) -> Future:
            if owner_ex is not None:
                return owner_ex._submit_dist(
                    endpoint, plan, source, tenant=tenant,
                    qos_class=qos_class, min_coverage=min_coverage,
                    deadline=deadline, timeout=timeout,
                    request_id=rid, coordinator=co, pipeline=pipeline,
                    _digest=digest, solve=solve,
                    digest_extra=digest_extra)
            with _trace.span("fleet.route",
                             attrs={"endpoint": endpoint},
                             request_id=rid) as sp:
                job = _dserve.DistServeJob(
                    plan, source, coordinator=co, qos_class=qos_class,
                    tenant=tenant, registry=_qos.get_registry(),
                    min_coverage=min_coverage,
                    deadline=(deadline if deadline is not None
                              else timeout),
                    pipeline=pipeline, request_id=rid,
                    parent_ctx=sp.context() if sp is not None
                    else None)
                fut: Future = Future()
                _dserve.run_job_into(job, fut, solve=solve)
            return fut

        if self._flights is None:
            return _dispatch()
        # the effective coverage gate rides the digest (same rule as
        # the executor front door): twins gating at 0.9 and 1.0 are
        # different requests and must not coalesce into one flight
        gate = (_dserve.class_min_coverage(qos_class)
                if min_coverage is None else float(min_coverage))
        digest = _dserve.dist_request_digest(
            endpoint, plan, source,
            extra=(*tuple(digest_extra), ("gate", gate)))
        follower = self._flights.join(digest, qos_class)
        if follower is not None:
            with self._lock:
                self._counts["coalesced"] += 1
            return follower
        flight = self._flights.lead(digest, qos_class)
        try:
            fut = _dispatch(digest)
        except BaseException as e:
            self._flights.abort(flight, e)
            raise
        fut.add_done_callback(
            lambda f, _fl=flight: self._flights.settle(_fl, f))
        return fut

    def submit_dist_sketch(self, plan, source, **kw) -> Future:
        """Pipelined distributed sketch through the fleet — see
        :meth:`MicrobatchExecutor.submit_dist_sketch
        <libskylark_tpu.engine.serve.MicrobatchExecutor
        .submit_dist_sketch>`; the router is the QoS front door and
        the single-flight tier, the pool is the shard fleet."""
        return self._submit_dist("dist_sketch", plan, source, **kw)

    def submit_dist_lstsq(self, source, *, s_dim: int, seed: int = 0,
                          kind: str = "cwt", shard_rows: int = 0,
                          **kw) -> Future:
        """Distributed sketched least squares through the fleet (the
        :func:`~libskylark_tpu.dist.algorithms.sketched_lstsq`
        endpoint)."""
        from libskylark_tpu.dist import serve as _dserve
        from libskylark_tpu.dist.algorithms import lstsq_plan

        plan = lstsq_plan(source, s_dim=s_dim, seed=seed, kind=kind,
                          shard_rows=shard_rows)
        return self._submit_dist("dist_lstsq", plan, source,
                                 solve=_dserve.solve_lstsq, **kw)

    def submit_dist_svd(self, source, rank: int, *, s_dim=None,
                        seed: int = 0, kind: str = "jlt",
                        shard_rows: int = 0, **kw) -> Future:
        """Distributed randomized SVD through the fleet (the
        :func:`~libskylark_tpu.dist.algorithms.randomized_svd`
        endpoint)."""
        from libskylark_tpu.dist import serve as _dserve
        from libskylark_tpu.dist.algorithms import svd_plan

        plan = svd_plan(source, rank, s_dim=s_dim, seed=seed,
                        kind=kind, shard_rows=shard_rows)
        return self._submit_dist(
            "dist_svd", plan, source,
            solve=lambda r: _dserve.solve_svd(r, rank),
            digest_extra=(("rank", int(rank)),), **kw)

    # -- hedged requests (docs/fleet "Hedged requests") ----------------

    def _maybe_hedge(self, endpoint: str, kwargs: dict, statics: tuple,
                     name: str, fut: Future, tags) -> Future:
        """Wrap an accepted dispatch in a straggler watchdog: if the
        replica's future is still unresolved after a p99-derived
        delay, mirror the request to the next healthy ring-preference
        replica and settle the returned future with whichever attempt
        finishes first. Both executions are bit-equal by construction
        — the serve endpoints are deterministic functions of the
        operands and the transform's key material, and the mirror
        reuses the exact same kwargs (including the predigested
        ``_derived`` statics), so taking either result is sound.
        No-op (the replica future passes straight through) when
        hedging is off — or when the request is best_effort: mirror
        capacity is a tail-latency resource the batch class has no
        SLO claim on (docs/qos)."""
        if (not self._hedge_on
                or kwargs.get("qos_class") == _qos.BEST_EFFORT):
            return fut
        if self._hedger is None:
            with self._hedge_lock:
                if self._hedger is None:
                    self._hedger = _Hedger(self)
        entry = _HedgeEntry(endpoint, kwargs, statics, fut, name, tags)
        fut.add_done_callback(
            lambda f: self._attempt_done(entry, f, "primary"))
        self._hedger.watch(entry,
                           time.monotonic() + self._hedge_delay_s())
        return entry.client

    def _hedge_delay_s(self) -> float:
        """The straggler threshold: ``hedge_delay_ms`` when pinned,
        else the p99 of recent client-observed request latencies (the
        same quantity the r10 latency histograms export — seeded from
        the replicas' :meth:`latency_quantile` until this router has
        its own samples). Cached for 0.5 s so the submit hot path
        never sorts the sample window."""
        if self._hedge_fixed_ms is not None:
            return max(float(self._hedge_fixed_ms), 0.0) / 1000.0
        now = time.monotonic()
        stamp, val = self._hedge_delay_cache
        if now - stamp < 0.5:
            return val
        # snapshot under the hedge lock: done-callback threads append
        # concurrently, and sorting a mutating deque raises (the same
        # discipline serve.latency_quantile applies to its histogram)
        with self._hedge_lock:
            lat = sorted(self._latency)
        p99 = _serve._percentile(lat, 0.99)
        if p99 is None:
            qs = [q for q in (r.latency_quantile(0.99)
                              for r in self._pool.replicas())
                  if q is not None]
            p99 = max(qs) if qs else 0.05
        val = min(max(p99, 0.001), 5.0)
        self._hedge_delay_cache = (now, val)
        return val

    def _fire_hedge(self, entry: _HedgeEntry) -> None:
        """Hedger-thread callback at an entry's due time: dispatch the
        mirror unless the primary already resolved. The mirror is
        opportunistic — if every peer refuses it, the primary simply
        keeps its race unopposed."""
        with self._hedge_lock:
            if entry.settled or entry.fired or entry.primary.done():
                return
            # snapshot under the lock: a settling primary clears the
            # payload (heap entries outlive their requests — the
            # watchdog must not pin every fast request's operands
            # until its due time)
            kwargs = entry.kwargs
        if kwargs is None:
            return
        with self._lock:
            degraded = set(self._degraded)
        hfut = target = None
        for nm in self._ring.preference(entry.statics):
            if nm == entry.primary_name or nm in degraded:
                continue
            try:
                # same chaos seam as a route attempt: a fault plan can
                # deterministically fail (or stall) the mirror
                faults.check("fleet.route", tags=entry.tags,
                             detail=f"hedge {entry.endpoint} -> {nm}")
                hfut = self._pool.get(nm).submit(entry.endpoint,
                                                 **kwargs)
                target = nm
                break
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:  # noqa: BLE001 — try the next peer
                continue
        if hfut is None:
            return
        armed = False
        with self._hedge_lock:
            if not entry.settled:
                entry.fired = True
                entry.hedge = hfut
                entry.hedge_name = target
                entry.kwargs = None     # both attempts dispatched
                armed = True
        if not armed:
            hfut.cancel()
            return
        with self._lock:
            self._counts["hedged"] += 1
        _LIFETIME.inc("hedged")
        _HEDGED.inc(replica=target)
        hfut.add_done_callback(
            lambda f: self._attempt_done(entry, f, "hedge"))

    def _attempt_done(self, entry: _HedgeEntry, fut: Future,
                      who: str) -> None:
        """Race arbitration: first successful attempt settles the
        client future; the loser is cancelled (or, in verify mode,
        allowed to finish and compared bitwise — the determinism
        guard). An attempt's failure only fails the client when no
        other attempt can still win."""
        if fut.cancelled():
            return
        err = fut.exception()
        # the future IS done here (we run in its done callback), so
        # result() returns immediately — read it before the lock so
        # nothing that can touch Future machinery runs under it
        value = fut.result() if err is None else None
        settle_exc = settle_val = None
        to_cancel = None
        win = False
        have_val = False
        with self._hedge_lock:
            if err is not None:
                entry.errors[who] = err
                if entry.settled:
                    return
                peer = (entry.hedge if who == "primary"
                        else entry.primary)
                peer_live = (peer is not None and not peer.done()
                             and (who == "hedge" or entry.fired))
                if peer_live:
                    return             # the peer may still win
                entry.settled = True
                entry.kwargs = None    # nothing left to dispatch
                settle_exc = err
            else:
                if self._hedge_verify:
                    entry.results[who] = value
                if entry.settled:
                    pass               # loser finished (verify below)
                else:
                    entry.settled = True
                    entry.kwargs = None
                    settle_val, have_val = value, True
                    win = who == "hedge" and entry.fired
                    if not self._hedge_verify:
                        to_cancel = (entry.hedge if who == "primary"
                                     else entry.primary)
            both = (self._hedge_verify
                    and len(entry.results) == 2)
        if settle_exc is not None:
            try:
                entry.client.set_exception(settle_exc)
            except Exception:  # noqa: BLE001 — already resolved
                pass
            return
        if have_val:
            with self._hedge_lock:
                self._latency.append(time.monotonic() - entry.t0)
            try:
                entry.client.set_result(settle_val)
            except Exception:  # noqa: BLE001 — already resolved
                pass
            if win:
                with self._lock:
                    self._counts["hedge_wins"] += 1
                _LIFETIME.inc("hedge_wins")
                _HEDGE_WINS.inc(replica=entry.hedge_name)
            if to_cancel is not None and not to_cancel.done():
                to_cancel.cancel()
        if both:
            self._verify_hedge(entry)

    def _verify_hedge(self, entry: _HedgeEntry) -> None:
        """Determinism guard (verify mode): both attempts completed —
        their results must be bit-equal. A divergence is a correctness
        bug (a serve endpoint stopped being a pure function of its
        operands), counted and warned, never silently averaged
        away."""
        try:
            a = np.asarray(entry.results["primary"])
            b = np.asarray(entry.results["hedge"])
            equal = a.shape == b.shape and np.array_equal(a, b)
        except Exception:  # noqa: BLE001 — non-array results
            equal = entry.results["primary"] == entry.results["hedge"]
        if not equal:
            with self._lock:
                self._counts["hedge_mismatches"] += 1
            _LIFETIME.inc("hedge_mismatches")
            _HEDGE_MISMATCH.inc(replica=entry.hedge_name or "?")
            warnings.warn(
                f"hedged {entry.endpoint} produced diverging results "
                f"on {entry.primary_name!r} vs {entry.hedge_name!r} — "
                "a serve endpoint is no longer deterministic",
                RuntimeWarning, stacklevel=2)
        entry.results.clear()          # comparison done: drop payloads

    # executor-mirroring conveniences

    def submit_sketch(self, transform, A, dimension=None, **kw) -> Future:
        return self.submit("sketch_apply", transform=transform, A=A,
                           dimension=dimension, **kw)

    def submit_fastfood(self, transform, A, **kw) -> Future:
        return self.submit("fastfood_features", transform=transform,
                           A=A, **kw)

    def submit_solve(self, A, B, transform, method: str = "qr",
                     **kw) -> Future:
        return self.submit("solve_l2_sketched", A=A, B=B,
                           transform=transform, method=method, **kw)

    def submit_krr_predict(self, kernel, X_new, X_train, coef,
                           **kw) -> Future:
        return self.submit("krr_predict", kernel=kernel, X_new=X_new,
                           X_train=X_train, coef=coef, **kw)

    def submit_graph_ase(self, A, k: int, *, seed: int = 0,
                         iters: int = 2, **kw) -> Future:
        return self.submit("graph_ase", A=A, k=k, seed=seed,
                           iters=iters, **kw)

    def submit_graph_ppr(self, A, s, *, alpha: float = 0.85,
                         iters: int = 16, **kw) -> Future:
        return self.submit("graph_ppr", A=A, s=s, alpha=alpha,
                           iters=iters, **kw)

    def submit_condest(self, A, *, steps: int = 8, seed: int = 0,
                       **kw) -> Future:
        return self.submit("condest", A=A, steps=steps, seed=seed,
                           **kw)

    def submit_lowrank(self, transform_s, transform_t, A, k: int,
                       **kw) -> Future:
        return self.submit("lowrank", transform_s=transform_s,
                           transform_t=transform_t, A=A, k=k, **kw)

    def submit_rlsc_predict(self, kernel, X_new, X_train, coef,
                            coding=None, **kw) -> Future:
        return self.submit("rlsc_predict", kernel=kernel, X_new=X_new,
                           X_train=X_train, coef=coef, coding=coding,
                           **kw)

    def submit_compressed_matmul(self, A, B, transform=None, *,
                                 s_dim=None, seed: int = 0,
                                 **kw) -> Future:
        if transform is None:
            # same construction as the executor convenience, so the
            # two front doors build bit-identical default operators
            transform = _serve.default_cmm_transform(
                A, s_dim=s_dim, seed=seed)
        return self.submit("compressed_matmul", transform=transform,
                           A=A, B=B, **kw)

    # -- stateful sessions (docs/sessions) -----------------------------

    def open_sketch_session(self, kind: str, *,
                            session_id: Optional[str] = None,
                            owner: Optional[str] = None,
                            timeout: float = 60.0, **spec_kwargs) -> str:
        """Open a session on one replica and pin the session-affinity
        assignment to it. The owner is the first healthy replica in
        the ring preference order of ``("session", sid)`` — the same
        deterministic construction bucket affinity uses — unless
        ``owner`` pins one explicitly (tests, chaos legs). Returns the
        session id."""
        sid = str(session_id) if session_id else uuid.uuid4().hex[:16]
        tags = faults.current_tags()
        order = ((owner,) if owner
                 else self._session_candidates(sid))
        last_err: Optional[BaseException] = None
        for name in order:
            # same failover walk as every other fleet dispatch: a
            # candidate that REFUSES the open (drain race, dead pipe,
            # an injected ``fleet.route`` fault, a future resolved
            # with a refusal) moves it to the next — the registry
            # open is side-effect-free on refusal. An explicit
            # ``owner`` pin does NOT fail over: a pin means exactly
            # that replica (tests, chaos legs).
            try:
                faults.check("fleet.route", tags=tags,
                             detail=f"session:open {sid} -> {name}")
                fut = self._pool.get(name).session(
                    "open", kind=kind, session_id=sid, **spec_kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — failover
                last_err = e
                if owner:
                    raise
                with self._lock:
                    self._counts["failover"] += 1
                _FAILOVER.inc(replica=name)
                continue
            try:
                sid = fut.result(timeout=timeout)
            except (KeyboardInterrupt, SystemExit):
                raise
            except (_FutTimeout, TimeoutError):
                # a result TIMEOUT is not a refusal: the open may
                # have succeeded (or still land) on this replica, so
                # moving on would orphan a live session whose on-disk
                # state then makes every peer refuse the id. Pin the
                # assignment where the open was dispatched and
                # surface the timeout to the caller instead.
                with self._lock:
                    self._sessions[sid] = (self._epoch, name)
                raise _errors.CommunicationError(
                    f"session open {sid!r} on replica {name!r} did "
                    f"not resolve within {timeout}s; the open may "
                    f"still have landed there — the id stays pinned "
                    f"to {name!r}: retry a session verb against it, "
                    "or evict the id") from None
            except BaseException as e:  # noqa: BLE001 — failover
                # resolved refusal (drain race, shed): the registry
                # open is side-effect-free on refusal, so the next
                # candidate is safe to try
                last_err = e
                if owner:
                    raise
                with self._lock:
                    self._counts["failover"] += 1
                _FAILOVER.inc(replica=name)
                continue
            with self._lock:
                self._sessions[sid] = (self._epoch, name)
            return sid
        raise NoHealthyReplicaError(
            f"no replica accepted the session open for {sid!r}: "
            f"tried {list(order)}") from last_err

    def _session_candidates(self, sid: str) -> tuple:
        """Healthy-first preference order for a session id (DEGRADED
        demoted to the tail, like :meth:`_candidates`)."""
        pref = list(self._ring.preference(("session", sid)))
        if not pref:
            raise NoHealthyReplicaError(
                f"no replica available for session {sid!r} "
                "(empty ring)")
        with self._lock:
            degraded = set(self._degraded)
        return tuple([n for n in pref if n not in degraded]
                     + [n for n in pref if n in degraded])

    def _session_owner(self, sid: str) -> str:
        """Resolve a session's owner: a recorded assignment stays
        authoritative for as long as that replica is on the ring — it
        holds the session's live state and journal lease, so a ring
        membership change that did NOT remove it (an autoscale
        scale-up, a peer draining) must not move the session. Only
        when the owner actually left the ring does the id re-resolve
        against the surviving membership — a **handoff**: the new
        owner resumes the session from ``SKYLARK_SESSION_DIR`` on its
        first touch, fencing the old one at the storage layer."""
        with self._lock:
            entry = self._sessions.get(sid)
            if entry is not None and entry[1] in self._ring:
                if entry[0] != self._epoch:
                    # the membership changed around the owner; refresh
                    # the stamp, keep the assignment
                    self._sessions[sid] = (self._epoch, entry[1])
                return entry[1]
        new = self._session_candidates(sid)[0]
        self._note_session_owner(sid, new)
        return new

    def _note_session_owner(self, sid: str, new: str) -> None:
        with self._lock:
            prev = self._sessions.get(sid)
            handoff = prev is not None and prev[1] != new
            self._sessions[sid] = (self._epoch, new)
            if handoff:
                self._counts["session_handoffs"] += 1
        if handoff:
            _SESSION_HANDOFFS.inc(replica=new)

    def _session_call(self, sid: str, op: str, kwargs: dict) -> Future:
        """Dispatch one session verb to the resolved owner, failing
        over down the candidate order when a replica *refuses* the
        call (dead pipe, drain race) — each attempt under the
        ``fleet.route`` chaos seam. A future that the owner accepted
        but later resolves exceptionally is NOT retried here: the
        idempotent sequence numbers make the client's retry safe, and
        the retry re-resolves ownership (by then the dead owner's
        STOPPED event has bumped the epoch)."""
        tags = faults.current_tags()
        owner = self._session_owner(sid)
        order = [owner] + [n for n in self._session_candidates(sid)
                           if n != owner]
        last_err: Optional[BaseException] = None
        for name in order:
            try:
                faults.check("fleet.route", tags=tags,
                             detail=f"session:{op} {sid} -> {name}")
                fut = self._pool.get(name).session(op, **kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — failover
                last_err = e
                with self._lock:
                    self._counts["failover"] += 1
                _FAILOVER.inc(replica=name)
                continue
            self._note_session_owner(sid, name)

            def _scrub(f, _sid=sid):
                # a session that ended any way other than a routed
                # finalize (TTL eviction, fencing) must not leak its
                # affinity entry forever — the registry tombstone
                # carries the terminal error from here on
                try:
                    evicted = isinstance(f.exception(),
                                         _errors.SessionEvictedError)
                except BaseException:  # noqa: BLE001 — CancelledError
                    evicted = False
                if evicted:
                    with self._lock:
                        self._sessions.pop(_sid, None)

            fut.add_done_callback(_scrub)
            return fut
        raise NoHealthyReplicaError(
            f"no replica accepted session {op!r} for {sid!r}: tried "
            f"{order}") from last_err

    def session_append(self, session_id: str, X, Y=None,
                       seq: Optional[int] = None, **kw) -> Future:
        """Route one append to the session's owner (module doc);
        resolves to ``(seq, rows)``. Supply explicit ``seq`` numbers
        when you intend to retry across a crash — duplicates are
        no-ops on the resuming owner."""
        return self._session_call(
            session_id, "append",
            dict(session_id=session_id, X=X, Y=Y, seq=seq, **kw))

    def session_finalize(self, session_id: str, **kw) -> Future:
        """Route the finalize to the owner and drop the assignment."""
        fut = self._session_call(session_id, "finalize",
                                 dict(session_id=session_id, **kw))

        def _forget(_f):
            with self._lock:
                self._sessions.pop(session_id, None)

        fut.add_done_callback(_forget)
        return fut

    def session_owner(self, session_id: str) -> Optional[str]:
        """The replica the next session verb would land on (resolving,
        but without dispatching anything)."""
        try:
            return self._session_owner(session_id)
        except NoHealthyReplicaError:
            return None

    # -- training jobs (docs/training) ---------------------------------

    def _train_terminal(self, e: BaseException) -> bool:
        # errors that END a train job: re-dispatching elsewhere cannot
        # change the outcome (budget spent; session tombstoned or
        # finished on a peer; spec bad)
        return isinstance(e, (_errors.TrainBudgetExhaustedError,
                              _errors.SessionEvictedError,
                              _errors.InvalidParametersError))

    def submit_train_job(self, spec, operands: Optional[dict] = None,
                         *, session_id: Optional[str] = None) -> Future:
        """Submit a preemptible training job to the fleet
        (docs/training) and return a future for its TERMINAL result —
        the trained model dict, or the terminal error
        (:class:`~libskylark_tpu.base.errors.TrainBudgetExhaustedError`
        with exact progress when the budget runs out first).

        The job lands on the first healthy replica in the session
        ring order for its id (a train job IS a session — same
        key space, same affinity construction) and runs there as
        best-effort slices. If that replica dies or refuses mid-job
        (SIGKILL, drain, shed), the pending future breaks and this
        router **resume-chains**: it dispatches ``train("resume")``
        to the next candidate, which adopts the on-disk session —
        fencing the old owner — and continues bit-equal from the last
        acked slice. The client future survives the whole walk;
        attempts are bounded at two passes over the pool."""
        sid = str(session_id) if session_id \
            else f"train-{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._counts["train_jobs"] += 1
        return self._train_chain(sid, spec, operands, "submit")

    def _train_chain(self, sid: str, spec, operands,
                     initial_op: str) -> Future:
        client: Future = Future()
        tags = faults.current_tags()
        budget = {"left": 2 * max(1, len(self._pool.names()))}

        def _on_done(f: Future) -> None:
            try:
                result = f.result()
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — chain/settle
                if self._train_terminal(e) or budget["left"] <= 0:
                    with self._lock:
                        self._sessions.pop(sid, None)
                    if not client.done():
                        client.set_exception(e)
                    return
                # mid-job loss (dead pipe, drain refusal): the
                # session is on disk — resume it on a surviving peer
                _dispatch("resume", exclude=self.session_owner(sid))
                return
            with self._lock:
                self._sessions.pop(sid, None)
            if not client.done():
                client.set_result(result)

        def _dispatch(op: str, exclude: Optional[str] = None) -> None:
            order = [n for n in self._session_candidates(sid)
                     if n != exclude]
            if exclude is not None and exclude in \
                    self._session_candidates(sid):
                order.append(exclude)   # last resort: it may be back
            last_err: Optional[BaseException] = None
            for name in order:
                if budget["left"] <= 0:
                    break
                budget["left"] -= 1
                try:
                    faults.check("fleet.route", tags=tags,
                                 detail=f"train:{op} {sid} -> {name}")
                    if op == "submit":
                        fut = self._pool.get(name).train(
                            "submit", spec=spec, operands=operands,
                            session_id=sid)
                    else:
                        fut = self._pool.get(name).train(
                            "resume", session_id=sid)
                        with self._lock:
                            self._counts["train_resumes"] += 1
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001 — failover
                    last_err = e
                    with self._lock:
                        self._counts["failover"] += 1
                    _FAILOVER.inc(replica=name)
                    continue
                self._note_session_owner(sid, name)
                fut.add_done_callback(_on_done)
                return
            with self._lock:
                self._sessions.pop(sid, None)
            if not client.done():
                client.set_exception(NoHealthyReplicaError(
                    f"no replica accepted train {op!r} for {sid!r}: "
                    f"tried {order}") if last_err is None
                    else last_err)

        _dispatch(initial_op)
        return client

    def resume_train_job(self, session_id: str) -> Future:
        """Adopt an orphaned on-disk training job (e.g. after a full
        fleet restart, when no live router holds its chain) and return
        a future for its terminal result, resume-chaining across
        replica deaths exactly like :meth:`submit_train_job`."""
        return self._train_chain(str(session_id), None, None, "resume")

    def train_job_status(self, session_id: str) -> dict:
        """Progress snapshot from the job's current owner (raises
        :class:`~libskylark_tpu.base.errors.SessionEvictedError` when
        no replica has it live)."""
        sid = str(session_id)
        owner = self._session_owner(sid)
        fut = self._pool.get(owner).train("status", session_id=sid)
        return fut.result(timeout=30.0)

    # -- operand residency (docs/caching) ------------------------------

    def register_operand(self, A, transform=None, dimension=None,
                         **kwargs) -> "_rcache.OperandRef":
        """Pin one operand resident on EVERY replica in the pool: the
        operand is content-hashed and broadcast, each replica pinning
        the same bytes under the same digest (and precomputing the
        transform's sketch when one is given) — so a later
        ``submit(..., A=ref)`` routed *anywhere* in the fleet skips
        the operand upload, and with a transform the sketch stage
        itself. Blocking by design: registration is a rare
        control-plane call, and returning only after every replica
        pinned means the ref is immediately valid fleet-wide.
        Replicas added later (autoscale-up) do not inherit pins —
        re-register after scaling when residency matters."""
        A = np.asarray(A)
        futs = [(r.name, r.register_operand(
                    A, transform=transform, dimension=dimension,
                    **kwargs))
                for r in self._pool.replicas()]
        if not futs:
            raise NoHealthyReplicaError(
                "register_operand on an empty pool")
        refs = {name: str(f.result()) for name, f in futs}
        if len(set(refs.values())) != 1:
            # content digests are transport-independent by
            # construction; a disagreement means replica divergence
            raise RuntimeError(
                f"replicas disagree on operand digest: {refs}")
        digest = next(iter(refs.values()))
        # the local mirror the ref-submit derivation resolves against
        self._residency.pin(digest, A)
        return _rcache.OperandRef(digest)

    def unregister_operand(self, ref) -> int:
        """Drop a resident operand from every replica (its pinned
        sketches go with it); returns how many replicas held it."""
        ref = str(_rcache.as_ref(ref).digest)
        futs = [r.unregister_operand(ref)
                for r in self._pool.replicas()]
        self._residency.unpin(ref)
        return sum(1 for f in futs if f.result())

    # -- introspection -------------------------------------------------

    def owner_of(self, endpoint: str, **kwargs) -> Optional[str]:
        """The (bounded-load) owner a request WOULD have affinity for
        (tests, capacity planning); ``None`` on an empty ring.
        Read-only: probing never caches an assignment or charges
        ownership, so hypothetical queries cannot shift where real
        traffic lands."""
        statics = _serve.request_statics(
            endpoint, pad_floor=self._pool.pad_floor, **kwargs)
        return self._affinity_owner(statics, record=False)

    def routable(self) -> list:
        """Names currently on the ring (DRAINING/STOPPED excluded)."""
        return sorted(self._ring.members())

    def stats(self) -> dict:
        with self._lock:
            c = dict(self._counts)
            by = dict(sorted(self._by_replica.items()))
        routed = c.get("routed", 0)
        with self._lock:
            degraded = sorted(self._degraded)
            removed = sorted(self._removed)
        # network front-door rollup (docs/networking): present only
        # when the net tier is loaded — the sys.modules guard keeps a
        # pure in-process deployment from importing the socket layer
        net = None
        if "libskylark_tpu.net.server" in sys.modules:
            try:
                from libskylark_tpu.net.server import net_stats
                net = net_stats()
            except Exception:  # noqa: BLE001 — stats never fail serving
                net = None
        return {
            "net": net,
            "routed": routed,
            "affinity_hit": c.get("affinity_hit", 0),
            "affinity_hit_rate": (
                round(c.get("affinity_hit", 0) / routed, 4)
                if routed else None),
            "failover": c.get("failover", 0),
            "spilled": c.get("spilled", 0),
            "hedged": c.get("hedged", 0),
            "hedge_wins": c.get("hedge_wins", 0),
            "hedge_mismatches": c.get("hedge_mismatches", 0),
            "rate_limited": c.get("rate_limited", 0),
            "coalesced": c.get("coalesced", 0),
            "dist_jobs": c.get("dist_jobs", 0),
            "dist_coordinator": (self._dist_co.stats()
                                 if self._dist_co is not None
                                 else None),
            "single_flight": (self._flights.stats()
                              if self._flights is not None else None),
            "session_handoffs": c.get("session_handoffs", 0),
            "train_jobs": c.get("train_jobs", 0),
            "train_resumes": c.get("train_resumes", 0),
            "sessions_assigned": len(self._sessions),
            "session_epoch": self._epoch,
            "session_epoch_hub_seq": self._epoch_hub_seq,
            "routable": self.routable(),
            "degraded": degraded,
            "removed": removed,
            "by_replica": by,
        }

    def close(self) -> None:
        """Unsubscribe from the health hub and stop the hedger (the
        pool outlives the router; idempotent)."""
        self._unsub()
        hedger, self._hedger = self._hedger, None
        if hedger is not None:
            hedger.stop()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_ROUTERS: "weakref.WeakSet[Router]" = weakref.WeakSet()


def fleet_stats() -> dict:
    """Aggregate routing counters over every live router (the
    ``fleet`` collector block in ``telemetry.snapshot()``), plus the
    autoscaler rollup from every live
    :class:`~libskylark_tpu.fleet.autoscale.Autoscaler`."""
    agg = collections.Counter(routed=0, affinity_hit=0, failover=0,
                              spilled=0, hedged=0, hedge_wins=0,
                              hedge_mismatches=0, rate_limited=0,
                              coalesced=0, session_handoffs=0)
    by_replica = collections.Counter()
    routers = 0
    for router in list(_ROUTERS):
        s = router.stats()
        routers += 1
        for k in ("routed", "affinity_hit", "failover", "spilled",
                  "hedged", "hedge_wins", "hedge_mismatches",
                  "rate_limited", "coalesced", "session_handoffs"):
            agg[k] += s[k]
        by_replica.update(s["by_replica"])
    out = dict(agg)
    out["routers"] = routers
    out["affinity_hit_rate"] = (
        round(out["affinity_hit"] / out["routed"], 4)
        if out["routed"] else None)
    out["by_replica"] = {name: {"routed": n}
                         for name, n in sorted(by_replica.items())}
    out.update(_LIFETIME.snapshot())
    # late import: autoscale imports the pool, never this module, so
    # the collector can reach its live-scaler rollup without a cycle
    from libskylark_tpu.fleet import autoscale as _autoscale

    out["autoscale"] = _autoscale.autoscale_stats()
    return out


_telemetry.register_collector("fleet", fleet_stats)


def _iter_routers() -> Iterable[Router]:   # tests/debug
    return list(_ROUTERS)


__all__ = ["NoHealthyReplicaError", "Router", "fleet_stats"]
