"""Shared-memory operand/result transport for process replicas.

Every ndarray crossing a :class:`~libskylark_tpu.fleet.replica
.ProcessReplica` pipe used to be pickled twice — serialized by the
sender, reassembled by the receiver, streamed through a 64 KiB-chunked
OS pipe in between. For the fleet's actual payloads (dense operands in,
dense results out) that is pure overhead: the bytes are already in
exactly the layout the other side wants. This module moves them
through ``multiprocessing.shared_memory`` instead:

- each replica pair owns **two rings** of fixed-size slots (one
  segment per direction, ``SKYLARK_FLEET_SHM_SLOTS`` ×
  ``SKYLARK_FLEET_SHM_SLOT_BYTES`` each);
- the **pipe stays the control channel**: a message that would have
  carried an ndarray carries a tiny :class:`ShmRef` header (slot,
  shape, dtype) instead, and ordering is inherited from the pipe — the
  slot is fully written before the header is sent;
- the **receiver is zero-copy**: a decoded :class:`ShmRef` becomes a
  read-only ``np.ndarray`` view directly over the slot. The slot is
  released when that view (and every array derived from it) is
  garbage-collected — a ``weakref.finalize`` enqueues the slot id and
  the next pipe turnaround carries a ``shmfree`` ack back to the
  writer. The sender pays one ``np.copyto`` into the slot (strided
  sources welcome — no ``ascontiguousarray`` staging copy);
- everything degrades to the **pickle fallback**: values under
  ``SKYLARK_FLEET_SHM_MIN_BYTES``, arrays larger than one slot, object
  dtypes, and any send finding the ring exhausted simply travel the
  pipe as before (``fleet.shm_fallbacks`` counts them). Transport
  choice can never change a result — the fallback path is the r11 wire
  format, bit for bit.

The operand-residency broadcast (``Router.register_operand``,
docs/caching) rides the same rings: a registered operand crosses to
each process replica exactly like submit kwargs, and because the
child's pin freezes a private copy, the ring slot releases as soon as
the decoded view drops — a resident operand never holds transport
capacity, so residency cannot leak ``/dev/shm`` entries either.

**Segment lifecycle (the no-leak contract).** The parent creates both
segments; the child attaches them at entry; once the parent's boot
liveness probe confirms the attach, the parent *immediately unlinks*
the names. POSIX keeps the memory alive for as long as either process
maps it, so steady-state operation runs with **zero** ``/dev/shm``
entries — a SIGTERM'd replica, a ``kill -9``'d child, even a
``kill -9``'d parent cannot leak a segment, because there is no name
left to leak. The only window where names exist is replica boot, and
that window is covered three ways: :meth:`ShmTransport.destroy` runs
from ``ProcessReplica.shutdown`` and the reader-loop's dead-child
path (both tied to the r9/r11 drain hooks), an ``atexit`` sweep
destroys any transport still live at interpreter exit, and the
``multiprocessing`` resource tracker (a separate process) reaps
registered names if the parent dies mid-boot.
"""

from __future__ import annotations

import atexit
import itertools
import os
import weakref
from collections import deque
from typing import Iterable, List, Optional, Tuple

import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.telemetry import metrics as _metrics

#: ``/dev/shm`` name prefix for every segment this module creates —
#: tests (and operators) can assert no entry with this prefix outlives
#: the fleet.
SHM_PREFIX = "skylark_shm_"

_SENDS = _metrics.counter(
    "fleet.shm_sends", "Arrays moved through a shared-memory slot, "
    "by replica and direction")
_FALLBACKS = _metrics.counter(
    "fleet.shm_fallbacks", "Array sends that degraded to the pickle "
    "pipe, by replica and reason")


class ShmRef:
    """Wire header for one array riding a shared-memory slot. Travels
    the pipe in the ndarray's place; the receiver rebuilds a zero-copy
    view from it. Picklable by design (it IS the pickled payload)."""

    __slots__ = ("slot", "shape", "dtype", "nbytes")

    def __init__(self, slot: int, shape: tuple, dtype: str, nbytes: int):
        self.slot = slot
        self.shape = shape
        self.dtype = dtype
        self.nbytes = nbytes

    def __reduce__(self):
        return (ShmRef, (self.slot, self.shape, self.dtype, self.nbytes))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ShmRef(slot={self.slot}, shape={self.shape}, "
                f"dtype={self.dtype})")


def _untrack(shm) -> None:
    """Drop a segment from the ``resource_tracker`` after the
    deliberate unlink (the tracker would otherwise re-unlink — and
    warn about — a name that is already gone). Called exactly once,
    by the owner: a spawn child SHARES the parent's tracker process,
    so the child's attach-time registration (the 3.10
    register-on-attach behavior) dedupes into the parent's and must
    not be separately unregistered — two removes of one cache entry
    make the tracker log spurious KeyErrors."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # noqa: BLE001 — bookkeeping only, never fatal
        pass


class ShmRing:
    """One direction of the transport: a slotted view over one shared
    segment. The *writer* side owns the free list and copies arrays
    in; the *reader* side builds zero-copy views and reports released
    slots back (via the transport's ack plumbing, not directly)."""

    def __init__(self, shm, slots: int, slot_bytes: int, *,
                 writer: bool):
        self._shm = shm
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self._writer = writer
        self._lock = _locks.make_lock("fleet.shm")
        # LIFO free list: the hottest slot is the one most recently
        # released (cache warmth), and order is irrelevant for
        # correctness — slots are independent
        self._free: Optional[List[int]] = (
            list(range(self.slots)) if writer else None)
        self.sends = 0
        # per-reason fallback counts: "ring" (exhausted — raise
        # SKYLARK_FLEET_SHM_SLOTS), "oversize" (raise
        # SKYLARK_FLEET_SHM_SLOT_BYTES), "dtype" (object/empty — not
        # tunable). An operator sizing the rings from the metric must
        # see which knob actually helps.
        self.fallback_reasons = {"ring": 0, "oversize": 0, "dtype": 0}

    @property
    def fallbacks(self) -> int:
        return sum(self.fallback_reasons.values())

    def try_put(self,
                arr: np.ndarray) -> Tuple[Optional[ShmRef],
                                          Optional[str]]:
        """Copy ``arr`` into a free slot. Returns ``(ref, None)`` on a
        send, ``(None, reason)`` on a pickle fallback (oversize /
        unexpressible dtype / ring exhausted) — the caller gets its
        own outcome so per-call accounting never reads shared counters
        racily. Never blocks."""
        assert self._writer, "try_put on the reader side"
        # only simple scalar dtypes ride: their ``.str`` round-trips
        # through np.dtype() on the receiver. Structured/sub-array/
        # object dtypes fall back to pickle — a dtype the header can't
        # express must not become a decode error the pickle path would
        # not have had
        if (arr.dtype.hasobject or arr.dtype.names is not None
                or arr.dtype.subdtype is not None or arr.nbytes == 0):
            with self._lock:
                self.fallback_reasons["dtype"] += 1
            return None, "dtype"
        if arr.nbytes > self.slot_bytes:
            with self._lock:
                self.fallback_reasons["oversize"] += 1
            return None, "oversize"
        with self._lock:
            if not self._free:
                self.fallback_reasons["ring"] += 1
                return None, "ring"
            slot = self._free.pop()
            self.sends += 1
        # the copy runs OUTSIDE the lock: the slot is exclusively ours
        # until the peer acks it back, and np.copyto handles strided
        # sources (the serve layer's _unpad views) in one pass
        view = np.ndarray(arr.shape, arr.dtype, buffer=self._shm.buf,
                          offset=slot * self.slot_bytes)
        np.copyto(view, arr, casting="no")
        del view
        return ShmRef(slot, tuple(arr.shape), arr.dtype.str,
                      int(arr.nbytes)), None

    def release(self, slots: Iterable[int]) -> None:
        """Return acked slots to the free list (writer side; called
        when the peer's ``shmfree`` arrives)."""
        assert self._writer, "release on the reader side"
        with self._lock:
            for s in slots:
                s = int(s)
                if 0 <= s < self.slots and s not in self._free:
                    self._free.append(s)

    def free_slots(self) -> int:
        with self._lock:
            return len(self._free) if self._free is not None else 0

    def validate(self, ref: ShmRef) -> None:
        """Raise on a header a :meth:`view` could not materialize —
        run over a whole payload BEFORE building any view, so a
        malformed payload is rejected while zero slots have gained
        finalizers (the recovery path may then ack every referenced
        slot without racing a half-created view's own release)."""
        dt = np.dtype(ref.dtype)       # raises on a non-round-trip str
        if not 0 <= ref.slot < self.slots:
            raise ValueError(f"slot {ref.slot} out of range")
        nbytes = dt.itemsize * int(np.prod(ref.shape, dtype=np.int64))
        if nbytes != ref.nbytes or nbytes > self.slot_bytes:
            raise ValueError(
                f"header geometry inconsistent: shape {ref.shape} x "
                f"{ref.dtype} = {nbytes} B vs declared {ref.nbytes} B "
                f"(slot holds {self.slot_bytes})")

    def view(self, ref: ShmRef, on_release) -> np.ndarray:
        """Zero-copy read-only array over ``ref``'s slot.
        ``on_release(slot)`` fires when the view (and everything
        derived from it) is garbage-collected — it must be cheap and
        lock-free (it runs wherever GC runs), so the transport just
        appends to a deque and lets the next pipe turnaround carry the
        ack."""
        assert not self._writer, "view on the writer side"
        arr = np.ndarray(ref.shape, np.dtype(ref.dtype),
                         buffer=self._shm.buf,
                         offset=ref.slot * self.slot_bytes)
        arr.flags.writeable = False
        weakref.finalize(arr, on_release, ref.slot)
        return arr


def _encode(obj, ring: ShmRing, min_bytes: int,
            _depth: int = 0) -> Tuple[object, List[int], dict]:
    """Replace large ndarrays in ``obj`` (dict/list/tuple containers,
    two levels deep — the message shapes the replica protocol actually
    sends) with :class:`ShmRef` headers. Returns the encoded object,
    the claimed slots (the caller releases them locally if the pipe
    send then fails), and THIS call's fallback counts by reason —
    per-call, so metric deltas never read shared counters racily."""
    claimed: List[int] = []
    fallbacks: dict = {}

    def enc(x, depth):
        if isinstance(x, np.ndarray):
            if x.nbytes >= min_bytes:
                ref, reason = ring.try_put(x)
                if ref is not None:
                    claimed.append(ref.slot)
                    return ref
                fallbacks[reason] = fallbacks.get(reason, 0) + 1
            return x
        if depth >= 2:
            return x
        if isinstance(x, dict):
            return {k: enc(v, depth + 1) for k, v in x.items()}
        if isinstance(x, list):
            return [enc(v, depth + 1) for v in x]
        if isinstance(x, tuple):
            return tuple(enc(v, depth + 1) for v in x)
        return x

    return enc(obj, _depth), claimed, fallbacks


def _decode(obj, ring: ShmRing, on_release, _depth: int = 0):
    """Inverse of :func:`_encode`: materialize every :class:`ShmRef`
    as a zero-copy view (see :meth:`ShmRing.view`). Pickled fallback
    arrays are marked read-only too, so a process replica's payloads
    have ONE mutability story regardless of which path each array
    happened to ride (a load-dependent writable/read-only flip would
    be a client-visible heisenbug)."""

    def dec(x, depth):
        if isinstance(x, ShmRef):
            return ring.view(x, on_release)
        if isinstance(x, np.ndarray):
            try:
                x.flags.writeable = False
            except ValueError:
                pass                   # non-owning view: leave it
            return x
        if depth >= 2:
            return x
        if isinstance(x, dict):
            return {k: dec(v, depth + 1) for k, v in x.items()}
        if isinstance(x, list):
            return [dec(v, depth + 1) for v in x]
        if isinstance(x, tuple):
            return tuple(dec(v, depth + 1) for v in x)
        return x

    return dec(obj, _depth)


def scan_refs(obj, _depth: int = 0) -> List[ShmRef]:
    """Every :class:`ShmRef` in a payload — the validation pre-pass
    and the slot-recovery path when a payload is rejected: claimed
    slots must go back to the writer or the ring loses capacity
    forever."""
    out: List[ShmRef] = []

    def walk(x, depth):
        if isinstance(x, ShmRef):
            out.append(x)
            return
        if depth >= 2:
            return
        if isinstance(x, dict):
            for v in x.values():
                walk(v, depth + 1)
        elif isinstance(x, (list, tuple)):
            for v in x:
                walk(v, depth + 1)

    walk(obj, _depth)
    return out


_SEQ = itertools.count()
_LIVE: "weakref.WeakSet[ShmTransport]" = weakref.WeakSet()


class ShmTransport:
    """Both rings of one replica pair, from one side's point of view.

    Build with :meth:`create` in the parent (makes the segments) and
    :meth:`attach` in the child (maps them, then *unregisters* them
    from its resource tracker — see :func:`_untrack`). ``tx`` is the
    ring this side writes, ``rx`` the ring it reads; the parent's
    ``tx`` is the child's ``rx`` and vice versa.
    """

    def __init__(self, label: str, tx: ShmRing, rx: ShmRing,
                 min_bytes: int, names: Tuple[str, str],
                 owner: bool):
        self.label = label
        self.tx = tx
        self.rx = rx
        self.min_bytes = int(min_bytes)
        self._names = names
        self._owner = owner
        self._unlinked = not owner
        self._pending_free: "deque[int]" = deque()
        self.recv_views = 0
        if owner:
            _LIVE.add(self)

    # -- construction --------------------------------------------------

    @classmethod
    def create(cls, replica_name: str, *,
               slots: Optional[int] = None,
               slot_bytes: Optional[int] = None,
               min_bytes: Optional[int] = None) -> "ShmTransport":
        from multiprocessing import shared_memory

        slots = int(slots if slots is not None
                    else _env.FLEET_SHM_SLOTS.get())
        slot_bytes = int(slot_bytes if slot_bytes is not None
                         else _env.FLEET_SHM_SLOT_BYTES.get())
        min_bytes = int(min_bytes if min_bytes is not None
                        else _env.FLEET_SHM_MIN_BYTES.get())
        safe = "".join(c if c.isalnum() else "-"
                       for c in str(replica_name))[:32]
        base = f"{SHM_PREFIX}{os.getpid()}_{next(_SEQ)}_{safe}"
        size = slots * slot_bytes
        p2c = shared_memory.SharedMemory(name=base + "_p2c",
                                         create=True, size=size)
        c2p = shared_memory.SharedMemory(name=base + "_c2p",
                                         create=True, size=size)
        return cls(
            str(replica_name),
            tx=ShmRing(p2c, slots, slot_bytes, writer=True),
            rx=ShmRing(c2p, slots, slot_bytes, writer=False),
            min_bytes=min_bytes,
            names=(base + "_p2c", base + "_c2p"), owner=True)

    @classmethod
    def attach(cls, spec: dict) -> "ShmTransport":
        """Child-side mapping from :meth:`child_spec`'s dict."""
        from multiprocessing import shared_memory

        # the attach registers with the (shared) resource tracker; the
        # OWNER unregisters at unlink — see _untrack for why the child
        # must not
        p2c = shared_memory.SharedMemory(name=spec["p2c"])
        c2p = shared_memory.SharedMemory(name=spec["c2p"])
        slots, slot_bytes = int(spec["slots"]), int(spec["slot_bytes"])
        return cls(
            str(spec.get("label", "child")),
            tx=ShmRing(c2p, slots, slot_bytes, writer=True),
            rx=ShmRing(p2c, slots, slot_bytes, writer=False),
            min_bytes=int(spec["min_bytes"]),
            names=(spec["p2c"], spec["c2p"]), owner=False)

    def child_spec(self) -> dict:
        """The attach recipe that rides the spawn args."""
        return {"p2c": self._names[0], "c2p": self._names[1],
                "slots": self.tx.slots, "slot_bytes": self.tx.slot_bytes,
                "min_bytes": self.min_bytes, "label": self.label}

    # -- data path -----------------------------------------------------

    def encode(self, obj) -> Tuple[object, List[int]]:
        out, claimed, fallbacks = _encode(obj, self.tx, self.min_bytes)
        if claimed:
            _SENDS.inc(len(claimed), replica=self.label)
        for reason, n in fallbacks.items():
            _FALLBACKS.inc(n, replica=self.label, reason=reason)
        return out, claimed

    def decode(self, obj):
        # two-phase: validate every header FIRST (no views created),
        # so a malformed payload fails before any slot has a
        # finalizer and recover() can safely ack them all
        for ref in scan_refs(obj):
            self.rx.validate(ref)
        return _decode(obj, self.rx, self._pending_free.append)

    def unclaim(self, slots: List[int]) -> None:
        """Return locally-claimed slots after a failed pipe send (the
        header never left, so the peer will never ack them)."""
        self.tx.release(slots)

    def release(self, slots: Iterable[int]) -> None:
        """Peer ack arrived: the slots we wrote are free again."""
        self.tx.release(slots)

    def recover(self, payload) -> None:
        """A payload was rejected (validation failed, so no view owns
        any of its slots): queue every referenced slot for the ack
        turnaround — the request is lost (its future gets the error)
        but the ring capacity must not be (an unacked slot is gone
        for the replica's lifetime, and the resulting \"ring\"
        fallbacks would point operators at the wrong knob)."""
        for ref in scan_refs(payload):
            self._pending_free.append(ref.slot)

    def drain_acks(self) -> List[int]:
        """Slots whose received views have been garbage-collected
        since the last call — the caller ships them to the peer as a
        ``shmfree`` message. Safe against concurrent appends (GC can
        fire mid-drain; a missed slot rides the next turnaround)."""
        out: List[int] = []
        while True:
            try:
                out.append(self._pending_free.popleft())
            except IndexError:
                return out

    def stats(self) -> dict:
        return {"sends": self.tx.sends, "fallbacks": self.tx.fallbacks,
                "fallback_reasons": dict(self.tx.fallback_reasons),
                "free_slots": self.tx.free_slots(),
                "slot_bytes": self.tx.slot_bytes,
                "slots": self.tx.slots}

    # -- lifecycle -----------------------------------------------------

    def unlink(self) -> None:
        """Remove both ``/dev/shm`` names (parent side, right after
        the child's attach is confirmed). Existing mappings — both
        processes' rings and every outstanding zero-copy view — stay
        valid; the memory is freed when the last mapping dies.
        Idempotent."""
        if self._unlinked:
            return
        self._unlinked = True
        for ring in (self.tx, self.rx):
            try:
                # unlink also unregisters from the resource tracker
                ring._shm.unlink()
            except FileNotFoundError:
                # someone else removed the name; drop the now-stale
                # tracker registration ourselves
                _untrack(ring._shm)
            except Exception:  # noqa: BLE001 — cleanup must not raise
                pass

    def untrack_local(self) -> None:
        """Drop THIS process's resource-tracker registrations for both
        segments. Only for an attacher that does NOT share the owner's
        tracker process (a standalone subprocess — mp-spawn children
        share the parent's tracker and must not call this): without
        it, the attacher's tracker would try to unlink the owner's
        names at its exit and log spurious warnings."""
        for ring in (self.tx, self.rx):
            _untrack(ring._shm)

    def destroy(self) -> None:
        """Unlink (if the boot window never got there) and drop the
        mappings where no live view pins them. Idempotent; called from
        replica shutdown, the dead-child reader path, and the atexit
        sweep."""
        self.unlink()
        for ring in (self.tx, self.rx):
            try:
                ring._shm.close()
            except BufferError:
                # an outstanding zero-copy view still references the
                # mapping; it dies with the view (or the process)
                pass
            except Exception:  # noqa: BLE001 — cleanup must not raise
                pass


def _atexit_sweep() -> None:  # pragma: no cover - interpreter exit
    for t in list(_LIVE):
        t.destroy()


atexit.register(_atexit_sweep)


def shm_entries() -> List[str]:
    """Live ``/dev/shm`` entries with this module's prefix (leak
    detection in tests and the fleet smoke)."""
    try:
        return sorted(n for n in os.listdir("/dev/shm")
                      if n.startswith(SHM_PREFIX))
    except OSError:
        return []


__all__ = ["SHM_PREFIX", "ShmRef", "ShmRing", "ShmTransport",
           "shm_entries"]
