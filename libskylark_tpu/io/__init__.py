"""IO layer: libsvm / arc-list / HDF5 readers and writers, streaming sketch.

TPU-native analog of the reference's IO stack (ref: utility/io/libsvm_io.hpp,
utility/io/arc_list.hpp, utility/io/hdf5_io.hpp, ml/io.hpp,
python-skylark/skylark/io.py, python-skylark/skylark/streaming.py).

Where the reference reads on MPI rank 0 and scatters chunks, the TPU-native
shape is: parse on the host into numpy/CSC buffers, then let the caller
``jax.device_put`` with a sharding — the host is the reference's "root" and
device placement is the scatter.
"""

from libskylark_tpu.io.libsvm import (
    read_libsvm,
    read_dir_libsvm,
    write_libsvm,
)
from libskylark_tpu.io.arclist import read_arc_list, write_arc_list
from libskylark_tpu.io.hdf5 import (
    have_hdf5,
    read_hdf5,
    write_hdf5,
)
from libskylark_tpu.io.streaming import StreamingCWT
from libskylark_tpu.io.chunked import (
    iter_libsvm_batches,
    iter_hdf5_batches,
    prefetch_batches,
    read_libsvm_sharded,
    scan_libsvm_dims,
    stream_sketch_libsvm,
)
from libskylark_tpu.io.webhdfs import webhdfs_lines

__all__ = [
    "read_libsvm",
    "read_dir_libsvm",
    "write_libsvm",
    "read_arc_list",
    "write_arc_list",
    "have_hdf5",
    "read_hdf5",
    "write_hdf5",
    "StreamingCWT",
    "iter_libsvm_batches",
    "iter_hdf5_batches",
    "prefetch_batches",
    "read_libsvm_sharded",
    "scan_libsvm_dims",
    "stream_sketch_libsvm",
    "webhdfs_lines",
]
