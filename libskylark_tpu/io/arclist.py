"""Arc-list (edge list) graph IO.

TPU-native analog of ref: utility/io/arc_list.hpp (``ReadArcList`` — parse
``from to [weight]`` lines, '#' comments, optional symmetrization, square
matrix sized by the max vertex index) feeding the graph drivers
(ref: nla/skylark_svd.cpp:158-176, ml/skylark_graph_se.cpp).

The reference splits the file across MPI ranks and queue_update()s into a
``sparse_vc_star_matrix_t``; here the host parses into COO and the result is
a local :class:`SparseMatrix` whose device COO can be sharded by the caller.
"""

from __future__ import annotations


import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.sparse import SparseMatrix


def read_arc_list(
    source,
    symmetrize: bool = False,
    dtype=np.float32,
) -> SparseMatrix:
    """Parse an edge list into a square sparse adjacency matrix.

    Lines are ``from to [weight]`` (whitespace separated, weight defaults
    to 1); lines starting with ``#`` are skipped (ref: arc_list.hpp parse()).
    ``symmetrize=True`` also inserts the reverse edge, as the graph drivers
    do for undirected graphs. Duplicate edges sum.
    """
    from libskylark_tpu.io import native

    parsed = native.parse_arc_list(source)
    if parsed is not None:
        src, dst, w = parsed
    else:
        if hasattr(source, "read"):
            lines = source.read().splitlines()
        else:
            with open(source, "r") as f:
                lines = f.read().splitlines()
        srcs, dsts, ws = [], [], []
        for line in lines:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            toks = line.split()
            if len(toks) < 2:
                raise errors.IOError_(f"invalid arc-list line {line!r}")
            try:
                srcs.append(int(toks[0]))
                dsts.append(int(toks[1]))
                ws.append(float(toks[2]) if len(toks) > 2 else 1.0)
            except ValueError as e:
                raise errors.IOError_(
                    f"invalid arc-list line {line!r}") from e
        src = np.asarray(srcs, dtype=np.int64)
        dst = np.asarray(dsts, dtype=np.int64)
        w = np.asarray(ws, dtype=np.float64)

    if src.size and (src.min() < 0 or dst.min() < 0):
        raise errors.IOError_("negative vertex index in arc list")
    nv = int(max(src.max(), dst.max())) + 1 if src.size else 0
    if symmetrize:
        off_diag = src != dst
        src, dst, w = (
            np.concatenate([src, dst[off_diag]]),
            np.concatenate([dst, src[off_diag]]),
            np.concatenate([w, w[off_diag]]),
        )
    return SparseMatrix.from_coo(src, dst, w.astype(dtype), (nv, nv))


def write_arc_list(path, A: SparseMatrix, digits: int = 8) -> None:
    """Write a sparse matrix as ``from to weight`` lines."""
    sp = A.to_scipy().tocoo()
    fmt = f"%.{digits}g"
    with open(path, "w") as f:
        for i, j, v in zip(sp.row, sp.col, sp.data):
            f.write(f"{int(i)} {int(j)} {fmt % v}\n")
