"""Streaming / oversized-dataset ingestion: bounded-memory readers.

TPU-native analog of the reference's streaming ingestion layer — the HDFS
line streamer (ref: utility/hdfs.hpp:11 ``hdfs_line_streamer_t``) and the
chunked root-reads-and-scatters libsvm/HDF5 readers
(ref: utility/io/libsvm_io.hpp:812-1371 ReadDirLIBSVM, :1395-1876 HDFS
variants, ml/io.hpp:256-507). Those exist so a dataset larger than one
node's memory can flow into a distributed matrix; here the same
capability is: iterate bounded batches off the source and land them
directly in device HBM (optionally sharded over a mesh axis), never
materializing the whole dataset host-side.

Transport seam (the libhdfs analog): every reader accepts either a path
or any *iterable of text lines* — a local file handle, a gzip stream, or
a remote/HDFS client's line iterator plug in identically. libhdfs itself
is not linked in this environment; the seam is where it would attach.

Composition with sketching: ``stream_sketch_libsvm`` pipes batches
through :class:`~libskylark_tpu.io.streaming.StreamingCWT`, whose
counter-based streams make the result equal to the one-shot sketch of the
full file (order-independent — stronger than the reference's
arrival-order streaming sketch, ref: python-skylark/skylark/streaming.py).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience.policy import RetryPolicy
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

ROWS = "rows"

# telemetry (docs/observability): batch yields are counted when the
# switch is on (the hot streaming loop stays one branch when off);
# HDF5 slice reads open ``io.chunked.read`` spans so retries and NFS
# blips are attributable on the trace timeline.
_BATCHES = _metrics.counter(
    "io.chunked.batches", "Batches yielded by the chunked readers")


def _io_retry() -> RetryPolicy:
    """Default policy for re-executable chunk reads (HDF5 slices):
    transient failures back off and re-read. One-shot line streams
    (libsvm over a socket) can't re-pull a batch — their recovery path
    is upstream (the WebHDFS reconnect-resume) or checkpoint-resume
    (``StreamingCWT.sketch(checkpoint=...)``)."""
    return RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=1.0)

# Default prefetch depth for the double-buffered streaming overlap:
# 2 slots = the classic double buffer (one batch on device computing,
# the next one parsing/transferring). SKYLARK_STREAM_PREFETCH sets the
# depth; 0 disables the overlap everywhere it defaults on.


def default_prefetch_depth() -> int:
    return max(0, _env.STREAM_PREFETCH.get())


class _PrefetchDone:
    """Sentinel + terminal state of a prefetch worker."""

    def __init__(self):
        self.exc: Optional[BaseException] = None


def prefetch_batches(
    batches: Iterable[Tuple],
    depth: Optional[int] = None,
    to_device: bool = True,
) -> Iterator[Tuple]:
    """Double-buffered minibatch prefetch: a background thread pulls up
    to ``depth`` batches ahead of the consumer, so the host-side parse
    (and, with ``to_device``, the host→device transfer of the leading
    array — jax dispatch makes the copy asynchronous) overlaps with the
    consumer's device compute on the CURRENT batch.

    Yields exactly the input tuples in exactly the input order, with the
    first element ``jax.device_put`` when ``to_device`` (bit-exact: a
    device transfer moves bytes, it never rounds) — the
    layout-independence invariant is untouched because nothing about the
    VALUES or their processing order changes, only WHEN they move.

    ``depth=0`` (or ``None`` with SKYLARK_STREAM_PREFETCH=0) is the
    synchronous passthrough. A producer exception is re-raised at the
    consumer's position, after the batches that preceded it. If the
    consumer abandons the iterator early (``close()``/GC), the worker is
    told to stop and drops its queue."""
    if depth is None:
        depth = default_prefetch_depth()
    if depth <= 0:
        for item in batches:
            if to_device and isinstance(item, tuple) and item:
                item = (jax.device_put(item[0]),) + item[1:]
            yield item
        return

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    done = _PrefetchDone()

    def _put(obj) -> bool:
        while not stop.is_set():
            try:
                q.put(obj, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker():
        try:
            for item in batches:
                if to_device and isinstance(item, tuple) and item:
                    # async H2D of the array the sketch consumes; labels
                    # and metadata stay host-side
                    item = (jax.device_put(item[0]),) + item[1:]
                if not _put(item):
                    return
        except BaseException as e:  # re-raised at the consumer
            done.exc = e
        finally:
            _put(done)

    t = threading.Thread(target=_worker, name="skylark-prefetch",
                         daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is done:
                if done.exc is not None:
                    raise done.exc
                return
            yield item
    finally:
        stop.set()
        # unblock a worker stuck on a full queue, then let it exit
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def grid_spans(lo: int, hi: int, batch_rows: int
               ) -> Iterator[Tuple[int, int]]:
    """Split ``[lo, hi)`` on the ABSOLUTE ``batch_rows`` grid: batch
    boundaries are multiples of ``batch_rows`` regardless of where the
    range starts, so a resumed range read (``lo`` = a prior batch end)
    yields the same subsequent boundaries — the bit-equal-resume
    invariant the HDF5 range reader and the distributed shard-task
    ingest (:mod:`libskylark_tpu.dist.plan`) both build on.
    ``batch_rows <= 0`` yields the whole range as one span."""
    if batch_rows <= 0:
        if lo < hi:
            yield lo, hi
        return
    at = lo
    while at < hi:
        nxt = min(hi, (at // batch_rows + 1) * batch_rows)
        yield at, nxt
        at = nxt


def _line_iter(source) -> Iterator[str]:
    """Path / file-like / iterable-of-lines → line iterator (the
    transport seam; see module doc)."""
    if isinstance(source, (str, bytes)) or hasattr(source, "__fspath__"):
        def gen():
            with open(source, "r") as f:
                yield from f
        return gen()
    if hasattr(source, "read"):
        return iter(source)
    return iter(source)


def scan_libsvm_dims(source, max_n: int = -1) -> Tuple[int, int, int]:
    """One streaming O(1)-memory pass → (n_examples, d, n_targets)
    (the reference's first of two passes, ref: libsvm_io.hpp:44-82)."""
    n, d, nt = 0, 0, -1
    for line in _line_iter(source):
        if max_n >= 0 and n == max_n:
            break
        line = line.strip()
        if not line or line.startswith("#"):
            break
        toks = line.split()
        if nt < 0:
            nt = 0
            while nt < len(toks) and ":" not in toks[nt]:
                nt += 1
        for t in toks[nt:]:
            d = max(d, int(t.split(":", 1)[0]))
        n += 1
    return n, d, max(nt, 0)


def iter_libsvm_batches(
    source,
    batch_rows: int,
    d: Optional[int] = None,
    sparse: bool = False,
    max_n: int = -1,
    dtype=np.float32,
) -> Iterator[Tuple[Union[np.ndarray, "object"], np.ndarray]]:
    """Yield ``(X_batch, Y_batch)`` with at most ``batch_rows`` examples
    each, parsing the source incrementally (host memory: one batch).

    ``d`` (the feature dimension) must be supplied for streaming sources
    that can only be read once; for paths it defaults to a
    :func:`scan_libsvm_dims` pre-pass. ``sparse=True`` yields
    :class:`~libskylark_tpu.base.sparse.SparseMatrix` batches.
    """
    from libskylark_tpu.base.sparse import SparseMatrix
    from libskylark_tpu.io.libsvm import _parse_lines

    if d is None:
        if not (isinstance(source, (str, bytes))
                or hasattr(source, "__fspath__")):
            raise errors.InvalidParametersError(
                "iter_libsvm_batches over a one-shot stream needs an "
                "explicit feature dimension d (hint: scan_libsvm_dims on "
                "a separate pass/replica of the stream)"
            )
        _, d, _ = scan_libsvm_dims(source, max_n)

    if batch_rows <= 0:
        raise errors.InvalidParametersError(f"bad batch_rows {batch_rows}")

    it = _line_iter(source)
    seen = 0
    done = False
    while not done:
        lines = []
        while len(lines) < batch_rows:
            if max_n >= 0 and seen + len(lines) >= max_n:
                done = True
                break
            try:
                line = next(it)
            except StopIteration:
                done = True
                break
            if not line.strip() or line.lstrip().startswith("#"):
                done = True
                break
            lines.append(line)
        if not lines:
            break
        # chaos seam: a parser/transport failure surfaces here, once per
        # batch — no retry (the line iterator is one-shot; see _io_retry)
        faults.check("io.chunked.batch", detail=f"batch@{seen}")
        targets, indices, values, _, nt = _parse_lines(lines, -1)
        n = len(targets)
        seen += n
        Y = np.zeros((n, nt), dtype=np.float64)
        for i, y in enumerate(targets):
            Y[i, : len(y)] = y
        Yout = Y[:, 0].astype(dtype) if nt == 1 else Y.astype(dtype)
        if sparse:
            rows = np.concatenate(
                [np.full(len(ix), i, dtype=np.int64)
                 for i, ix in enumerate(indices)]
            ) if n else np.zeros(0, np.int64)
            cols = (np.concatenate(indices) if indices
                    else np.zeros(0, np.int64))
            vals = (np.concatenate(values) if values
                    else np.zeros(0, np.float64)).astype(dtype)
            if cols.size and cols.max() >= d:
                raise errors.IOError_(
                    f"feature index {cols.max() + 1} exceeds declared d={d}"
                )
            # counted at the yield, not at intake: a parse/validation
            # failure must not count a batch the consumer never got
            _BATCHES.inc(source="libsvm")
            yield SparseMatrix.from_coo(rows, cols, vals, (n, d)), Yout
        else:
            X = np.zeros((n, d), dtype=dtype)
            for i, (ix, v) in enumerate(zip(indices, values)):
                if ix.size and ix.max() >= d:
                    raise errors.IOError_(
                        f"feature index {ix.max() + 1} exceeds declared "
                        f"d={d}"
                    )
                X[i, ix] = v
            _BATCHES.inc(source="libsvm")
            yield X, Yout


def iter_array_batches(
    X, batch_rows: int, Y=None,
) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
    """Yield ``(X_batch, Y_batch)`` row slices off in-memory arrays —
    the canonical row-batch stream the stateful serve sessions
    (:mod:`libskylark_tpu.sessions`) and their bit-equality gates
    consume. Slicing is a view (no copy) and preserves bytes exactly,
    so a session fed these batches finalizes bit-equal to the one-shot
    sketch of ``X`` for the order-independent transforms (CWT — the
    :mod:`io.streaming` invariant promoted into the serve layer).
    ``Y=None`` yields ``(X_batch, None)``."""
    X = np.asarray(X)
    if Y is not None:
        Y = np.asarray(Y)
        if Y.shape[0] != X.shape[0]:
            raise errors.InvalidParametersError(
                f"iter_array_batches: X has {X.shape[0]} rows but Y "
                f"has {Y.shape[0]}")
    if batch_rows <= 0:
        raise errors.InvalidParametersError(f"bad batch_rows {batch_rows}")
    for lo in range(0, X.shape[0], batch_rows):
        hi = min(lo + batch_rows, X.shape[0])
        _BATCHES.inc(source="array")
        yield X[lo:hi], (Y[lo:hi] if Y is not None else None)


def iter_hdf5_batches(
    path, batch_rows: int, dtype=np.float32,
    retry: Optional[RetryPolicy] = None,
    start_row: int = 0, stop_row: Optional[int] = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield ``(X_batch, Y_batch)`` row slices off an HDF5 file written in
    the reference's dense layout (ref: ml/io.hpp:256-507 reads the file in
    root-side chunks; h5py's partial reads provide the same bound).

    ``start_row``/``stop_row`` bound the read to a row range — the
    shard-task ingest path (:mod:`libskylark_tpu.dist`) reads only its
    own rows. Batch boundaries stay on the absolute ``batch_rows``
    grid regardless of the range, so a resumed/re-executed range read
    yields byte-identical batches.

    HDF5 slice reads are re-executable, so transient read failures
    (``io.chunked.read`` fault site; NFS blips on real deployments)
    retry under ``retry`` (default :func:`_io_retry`) instead of
    killing the stream."""
    from libskylark_tpu.io.hdf5 import _require_h5py

    h5py = _require_h5py()
    retry = retry or _io_retry()

    def read_once(ds, lo, hi, name):
        faults.check("io.chunked.read", detail=f"{name}[{lo}:{hi}]")
        return np.asarray(ds[lo:hi], dtype=dtype)

    def read_slice(ds, lo, hi, name):
        # span around the whole retry ladder, so per-attempt retry
        # events (resilience.policy) attach to THIS span
        with _trace.span("io.chunked.read",
                         attrs={"dataset": name, "lo": lo, "hi": hi}):
            return retry.call(read_once, ds, lo, hi, name)

    with h5py.File(path, "r") as f:
        X, Y = f["X"], f["Y"]  # the reference's dense layout (io/hdf5.py)
        n = X.shape[0]
        if stop_row is not None:
            n = min(n, int(stop_row))
        for lo, hi in grid_spans(max(0, int(start_row)), n,
                                 batch_rows):
            batch = (read_slice(X, lo, hi, "X"),
                     read_slice(Y, lo, hi, "Y"))
            # counted after both slice reads survived their retry
            # ladders: "batches yielded" must match what the consumer
            # actually received
            _BATCHES.inc(source="hdf5")
            yield batch


def read_libsvm_sharded(
    source,
    mesh,
    axis: str = ROWS,
    batch_rows: int = 4096,
    max_n: int = -1,
    dtype=np.float32,
    dims: Optional[Tuple[int, ...]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Stream a libsvm source directly into a row-sharded device array.

    The distributed analog of the reference's chunked scatter reader
    (ref: ml/io.hpp:529-668: rank 0 reads chunks, sends each to its
    owner): each shard is device_put to EVERY device the sharding assigns
    it to (on a multi-axis mesh, P(axis, None) replicates a shard across
    the other axes) as soon as its rows are parsed — peak HOST memory is
    one batch plus one shard, independent of n. Ragged n (not divisible
    by the mesh axis) zero-pads the last shard; the returned array is
    sliced back to n rows.

    A path source is scanned first (the reference's two-pass discipline,
    ref: libsvm_io.hpp:44-82). One-shot stream sources (e.g.
    :func:`libskylark_tpu.io.webhdfs.webhdfs_lines`) can't be re-read:
    pass ``dims=(n, d)`` (or ``(n, d, n_targets)``) from a prior
    :func:`scan_libsvm_dims` over a fresh stream.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if dims is not None:
        n, d = int(dims[0]), int(dims[1])
        nt = int(dims[2]) if len(dims) > 2 else 1
        # an explicit max_n truncates the plan itself (the path branch
        # gets this from scan_libsvm_dims, which caps n); then bound the
        # read at n rows so a stream that has grown since the scan must
        # not overrun the shard plan
        if 0 <= max_n < n:
            n = max_n
        max_n = n
    elif isinstance(source, (str, bytes)) or hasattr(source, "__fspath__"):
        n, d, nt = scan_libsvm_dims(source, max_n)
    else:
        raise errors.InvalidParametersError(
            "read_libsvm_sharded on a one-shot stream needs dims=(n, d): "
            "scan a fresh stream with scan_libsvm_dims first (paths are "
            "scanned automatically)"
        )
    if n == 0:
        raise errors.IOError_(
            f"read_libsvm_sharded: no examples in {source!r}"
        )
    p = mesh.shape[axis]
    bs = -(-n // p)                     # shard rows (ceil — ragged ok)
    y_cols = max(nt, 1)
    spec = NamedSharding(mesh, P(axis, None))

    # owner devices of each row-shard, from the sharding itself — NOT
    # mesh-order guesswork (a 2D mesh replicates each shard across the
    # non-sharded axes)
    owners: list[list] = [[] for _ in range(p)]
    for dev, idx in spec.devices_indices_map((p * bs, d)).items():
        start = idx[0].start or 0
        owners[start // bs].append(dev)

    def place(parts, shard_np, si):
        for dev in owners[si]:
            parts.append(jax.device_put(shard_np, dev))

    xs, ys = [], []
    x_parts, y_parts = [], []
    filled = 0
    si = 0
    consumed = 0
    # parse-ahead only (to_device=False): placement here is per-owner
    # device, so the H2D half of the overlap is the place() calls below;
    # the background thread keeps the line parser off their critical path
    for Xb, Yb in prefetch_batches(
        iter_libsvm_batches(source, batch_rows, d=d, max_n=max_n,
                            dtype=dtype),
        to_device=False,
    ):
        Yb = Yb.reshape(len(Xb), -1)
        consumed += len(Xb)
        while len(Xb):
            take = min(bs - filled, len(Xb))
            xs.append(Xb[:take])
            ys.append(Yb[:take])
            Xb, Yb = Xb[take:], Yb[take:]
            filled += take
            if filled == bs:
                place(x_parts, np.concatenate(xs), si)
                place(y_parts, np.concatenate(ys), si)
                xs, ys = [], []
                filled = 0
                si += 1
    if dims is not None and consumed < n:
        # … and a stream that has SHRUNK must not have its missing rows
        # fabricated as zero-padding (silent data corruption)
        raise errors.IOError_(
            f"read_libsvm_sharded: dims promised {n} examples but the "
            f"stream yielded {consumed}"
        )
    if filled or si < p:
        # ragged tail: zero-pad the final shard; later shards are zeros
        tail_x = np.concatenate(xs) if xs else np.zeros((0, d), dtype)
        tail_y = (np.concatenate(ys) if ys
                  else np.zeros((0, y_cols), dtype))
        pad = bs - len(tail_x)
        tail_x = np.pad(tail_x, ((0, pad), (0, 0)))
        tail_y = np.pad(tail_y, ((0, pad), (0, 0)))
        place(x_parts, tail_x, si)
        place(y_parts, tail_y, si)
        si += 1
        zx = np.zeros((bs, d), dtype)
        zy = np.zeros((bs, y_cols), dtype)
        while si < p:
            place(x_parts, zx, si)
            place(y_parts, zy, si)
            si += 1

    X = jax.make_array_from_single_device_arrays(
        (p * bs, d), spec, x_parts)[:n]
    Y = jax.make_array_from_single_device_arrays(
        (p * bs, y_cols), spec, y_parts)[:n]
    if nt <= 1:
        Y = Y[:, 0]
    return X, Y


def stream_sketch_libsvm(
    source,
    s: int,
    context,
    batch_rows: int = 4096,
    num_classes: int = 0,
    max_n: int = -1,
    checkpoint=None,
    checkpoint_every: int = 0,
    prefetch_depth: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sketch a libsvm source down to ``s`` rows in bounded memory:
    chunked parse → :class:`StreamingCWT`. Equals the one-shot
    ``CWT.apply`` on the full file (counter-stream order independence).
    ``prefetch_depth`` tunes the double-buffered parse/transfer overlap
    (see :meth:`StreamingCWT.sketch`; default SKYLARK_STREAM_PREFETCH).

    Needs a re-readable path (one pass to size the streams, one to
    sketch); for a one-shot stream, run :func:`scan_libsvm_dims` on a
    replica yourself and feed :func:`iter_libsvm_batches` to
    :class:`StreamingCWT` directly."""
    from libskylark_tpu.io.streaming import StreamingCWT

    if not (isinstance(source, (str, bytes))
            or hasattr(source, "__fspath__")):
        raise errors.InvalidParametersError(
            "stream_sketch_libsvm needs a re-readable path (streams: "
            "scan_libsvm_dims on a replica + iter_libsvm_batches + "
            "StreamingCWT)"
        )
    n, d, _ = scan_libsvm_dims(source, max_n)
    sk = StreamingCWT(n, s, context)
    batches = iter_libsvm_batches(source, batch_rows, d=d, max_n=max_n)
    return sk.sketch(batches, num_classes=num_classes,
                     checkpoint=checkpoint,
                     checkpoint_every=checkpoint_every,
                     prefetch_depth=prefetch_depth)
