"""HDF5 dataset IO, layout-compatible with the reference.

Dense layout (ref: ml/io.hpp write_hdf5:18-115): datasets ``X`` (n×d
float64) and ``Y`` (n). Sparse layout (ref: ml/io.hpp:124-205,256-507):
``dimensions`` = [d, n, nnz] ints, ``indptr`` (n+1, per-example CSC with
examples as columns of a d×n matrix), ``indices`` (feature indices),
``values``, ``Y`` — i.e. scipy CSR over examples, verbatim.

Gated on h5py at call time; ``have_hdf5()`` reports availability the way the
reference's CMake gates on SKYLARK_HAVE_HDF5 (ref: config.h.in:95-123).
"""

from __future__ import annotations

from typing import Tuple, Union

import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.sparse import SparseMatrix


def have_hdf5() -> bool:
    try:
        import h5py  # noqa: F401

        return True
    except ImportError:
        return False


def _require_h5py():
    try:
        import h5py

        return h5py
    except ImportError as e:
        raise errors.UnsupportedError(
            # SKYLARK_HAVE_HDF5 is the reference repo's C++ config
            # symbol, not an env var  # skylark-lint: disable=env-registry
            "h5py not available; HDF5 IO disabled "
            "(ref: config.h.in SKYLARK_HAVE_HDF5 gate)"
        ) from e


def write_hdf5(path, X, Y) -> None:
    """Write ``(X, Y)`` (examples as rows) to HDF5 in the reference layout."""
    h5py = _require_h5py()
    Y = np.asarray(Y, dtype=np.float64).reshape(-1)
    with h5py.File(path, "w") as f:
        if isinstance(X, SparseMatrix):
            sp = X.to_scipy().tocsr()
            n, d = sp.shape
            f.create_dataset(
                "dimensions", data=np.array([d, n, sp.nnz], dtype=np.int64))
            f.create_dataset("indptr", data=sp.indptr.astype(np.int64))
            f.create_dataset("indices", data=sp.indices.astype(np.int64))
            f.create_dataset("values", data=sp.data.astype(np.float64))
        else:
            X = np.asarray(X, dtype=np.float64)
            f.create_dataset("X", data=X)
        f.create_dataset("Y", data=Y)


def read_hdf5(
    path, sparse: bool = False, min_d: int = 0, dtype=np.float32
) -> Tuple[Union[np.ndarray, SparseMatrix], np.ndarray]:
    """Read ``(X, Y)`` (examples as rows) from the reference HDF5 layout."""
    h5py = _require_h5py()
    with h5py.File(path, "r") as f:
        Y = np.asarray(f["Y"]).astype(dtype)
        if sparse or "X" not in f:
            dims = np.asarray(f["dimensions"])
            d, n, nnz = int(dims[0]), int(dims[1]), int(dims[2])
            d = max(d, min_d)
            indptr = np.asarray(f["indptr"]).astype(np.int64)
            indices = np.asarray(f["indices"]).astype(np.int64)
            values = np.asarray(f["values"]).astype(dtype)
            if len(indptr) != n + 1 or len(indices) != nnz:
                raise errors.IOError_(
                    f"inconsistent sparse HDF5 file {path}")
            rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
            X: Union[np.ndarray, SparseMatrix] = SparseMatrix.from_coo(
                rows, indices, values, (n, d))
        else:
            X = np.asarray(f["X"]).astype(dtype)
            if min_d > X.shape[1]:
                X = np.pad(X, ((0, 0), (0, min_d - X.shape[1])))
    return X, Y
