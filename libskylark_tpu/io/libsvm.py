"""LIBSVM-format readers/writers.

TPU-native analog of ref: utility/io/libsvm_io.hpp (``ReadLIBSVM`` local:29,
sparse:314, ``WriteLIBSVM``:682,732, dir-sharded ``ReadDirLIBSVM``:812-1371)
and ml/io.hpp's format dispatch (:871-890).

Format semantics preserved from the reference reader:
- one example per line: ``label [label2 ...] idx:val idx:val ...``;
- the number of targets is inferred from the first line as the count of
  leading tokens that contain no ``:`` (ref: libsvm_io.hpp:56-67);
- feature indices are 1-based; the feature dimension is the max index seen,
  floored by ``min_d`` (ref: libsvm_io.hpp:72-82);
- empty lines and lines starting with ``#`` terminate/skip parsing
  (ref: libsvm_io.hpp:50-51);
- ``max_n`` caps the number of examples read (ref: libsvm_io.hpp:47).

Where the reference makes two passes to preallocate El buffers and scatters
chunks from MPI rank 0 (ref: ml/io.hpp:529-668), here the host parses into
numpy (dense) or CSC (sparse) buffers once; device placement + sharding is
the caller's ``jax.device_put`` and plays the role of the scatter.

When the native accelerator library is available (``libskylark_tpu.io.native``)
the hot tokenizing loop runs in C++; the pure-Python path is the fallback,
mirroring the reference's pure-Python sketch fallbacks
(ref: python-skylark/skylark/sketch.py:752).
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple, Union

import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.sparse import SparseMatrix

ROWS = "rows"
COLUMNS = "columns"


def _open_lines(source) -> List[str]:
    if hasattr(source, "read"):
        return source.read().splitlines()
    with open(source, "r") as f:
        return f.read().splitlines()


def _parse_lines(
    lines: Sequence[str], max_n: int
) -> Tuple[List[np.ndarray], List[np.ndarray], List[np.ndarray], int, int]:
    """Single-pass parse -> per-line (targets, indices, values) + (d, nt)."""
    targets: List[np.ndarray] = []
    indices: List[np.ndarray] = []
    values: List[np.ndarray] = []
    d = 0
    nt = -1
    for line in lines:
        if max_n >= 0 and len(targets) == max_n:
            break
        line = line.strip()
        # ref: libsvm_io.hpp:50-51 — blank/comment line ends the read
        if not line or line.startswith("#"):
            break
        toks = line.split()
        if nt < 0:
            nt = 0
            while nt < len(toks) and ":" not in toks[nt]:
                nt += 1
        try:
            y = np.array([float(t) for t in toks[:nt]], dtype=np.float64)
            pairs = [t.split(":") for t in toks[nt:]]
            idx = np.array([int(p[0]) for p in pairs], dtype=np.int64)
            val = np.array([float(p[1]) for p in pairs], dtype=np.float64)
        except (ValueError, IndexError) as e:
            raise errors.IOError_(f"malformed libsvm line: {line!r}") from e
        if idx.size and idx.min() < 1:
            raise errors.IOError_(
                f"libsvm feature indices are 1-based; got {idx.min()}"
            )
        if idx.size:
            d = max(d, int(idx.max()))
        targets.append(y)
        indices.append(idx - 1)  # to 0-based
        values.append(val)
    if nt < 0:
        nt = 0
    return targets, indices, values, d, nt


def read_libsvm(
    source,
    direction: str = ROWS,
    sparse: bool = False,
    min_d: int = 0,
    max_n: int = -1,
    dtype=np.float32,
) -> Tuple[Union[np.ndarray, SparseMatrix], np.ndarray]:
    """Read a LIBSVM file into ``(X, Y)``.

    ``direction=ROWS`` gives X with examples as rows (n×d) — the natural JAX
    layout; ``COLUMNS`` gives d×n (the reference's ``base::COLUMNS``, its
    default for ML drivers). Dense ``X`` is a numpy array; ``sparse=True``
    yields a :class:`SparseMatrix` (CSC). ``Y`` is (n,) for single-target
    files, (n, nt) otherwise (transposed accordingly for COLUMNS).
    """
    if direction not in (ROWS, COLUMNS):
        raise errors.InvalidParametersError(f"bad direction {direction!r}")

    from libskylark_tpu.io import native

    parsed = native.parse_libsvm(source, max_n)
    if parsed is not None:
        targets, indices, values, d, nt = parsed
    else:
        targets, indices, values, d, nt = _parse_lines(
            _open_lines(source), max_n)
    n = len(targets)
    d = max(d, min_d)

    Y = np.zeros((n, nt), dtype=np.float64)
    for i, y in enumerate(targets):
        Y[i, : len(y)] = y
    if nt == 1:
        Yout = Y[:, 0].astype(dtype)
    else:
        Yout = Y.astype(dtype)

    if sparse:
        if n:
            rows = np.concatenate(
                [np.full(len(ix), i, dtype=np.int64)
                 for i, ix in enumerate(indices)])
            cols = np.concatenate(indices) if indices else np.zeros(0, np.int64)
            vals = (np.concatenate(values) if values
                    else np.zeros(0, np.float64)).astype(dtype)
        else:
            rows = cols = np.zeros(0, np.int64)
            vals = np.zeros(0, dtype)
        if direction == ROWS:
            X = SparseMatrix.from_coo(rows, cols, vals, (n, d))
        else:
            X = SparseMatrix.from_coo(cols, rows, vals, (d, n))
            if nt != 1:
                Yout = Yout.T
        return X, Yout

    X = np.zeros((n, d), dtype=dtype)
    for i, (ix, v) in enumerate(zip(indices, values)):
        X[i, ix] = v
    if direction == COLUMNS:
        X = np.ascontiguousarray(X.T)
        if nt != 1:
            Yout = Yout.T
    return X, Yout


def read_dir_libsvm(
    dirname: str,
    direction: str = ROWS,
    sparse: bool = False,
    min_d: int = 0,
    max_n: int = -1,
    dtype=np.float32,
):
    """Read every regular file in ``dirname`` (sorted) as one libsvm dataset
    (ref: utility/io/libsvm_io.hpp ReadDirLIBSVM:812 — directory-sharded
    files are a single logical matrix)."""
    names = sorted(
        os.path.join(dirname, f)
        for f in os.listdir(dirname)
        if os.path.isfile(os.path.join(dirname, f))
    )
    if not names:
        raise errors.IOError_(f"no files in {dirname}")
    # Trim each shard at its own first blank/comment line (the per-file
    # terminate semantics of the reference, which parses files separately),
    # then concatenate — so a trailing newline in one shard can't swallow
    # the rest of the dataset.
    import io as _io

    buf = _io.StringIO(
        "\n".join(
            ln for name in names for ln in _trim_shard(_open_lines(name))
        )
    )
    return read_libsvm(buf, direction, sparse, min_d, max_n, dtype)


def _trim_shard(lines: List[str]) -> List[str]:
    """Truncate a shard at its first blank/comment line (per-file terminate
    semantics) so shards can be concatenated safely."""
    out: List[str] = []
    for line in lines:
        if not line.strip() or line.strip().startswith("#"):
            break
        out.append(line)
    return out


def write_libsvm(path, X, Y, digits: int = 8) -> None:
    """Write ``(X, Y)`` (examples as rows) in libsvm format
    (ref: utility/io/libsvm_io.hpp WriteLIBSVM:682,732). Zero entries are
    skipped; indices written 1-based."""
    if isinstance(X, SparseMatrix):
        sp = X.to_scipy().tocsr()
        n = sp.shape[0]
        rows = [sp.indices[sp.indptr[i]:sp.indptr[i + 1]] for i in range(n)]
        vals = [sp.data[sp.indptr[i]:sp.indptr[i + 1]] for i in range(n)]
    else:
        X = np.asarray(X)
        n = X.shape[0]
        rows = [np.nonzero(X[i])[0] for i in range(n)]
        vals = [X[i][rows[i]] for i in range(n)]
    Y = np.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    if Y.shape[0] != n:
        raise errors.InvalidParametersError(
            f"X has {n} examples but Y has {Y.shape[0]}")
    fmt = f"%.{digits}g"
    with open(path, "w") as f:
        for i in range(n):
            labels = " ".join(fmt % y for y in Y[i])
            feats = " ".join(
                f"{int(j) + 1}:{fmt % v}" for j, v in zip(rows[i], vals[i]))
            f.write(labels + (" " + feats if feats else "") + "\n")
