"""ctypes bridge to the native C++ parsing accelerators.

The reference's IO hot loops are compiled C++ (ref: utility/io/libsvm_io.hpp
tokenizing passes, compiled into every CLI; capi/ being the compiled layer
generally). Here the analogous native component is ``libskylark_io.so``,
built from ``native/io_parsers.cpp`` by ``native/build.py`` (g++ -O3). All
entry points degrade to ``None`` when the library is missing, which tells
the caller to use the pure-Python fallback — mirroring the reference
Python layer's lib-missing fallbacks (ref: python sketch.py:752).
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    from libskylark_tpu.native import build

    path = build.ensure_built(quiet=True)
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.sl_libsvm_count.restype = ctypes.c_int
    lib.sl_libsvm_count.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong),  # n
        ctypes.POINTER(ctypes.c_longlong),  # nt
        ctypes.POINTER(ctypes.c_longlong),  # d
        ctypes.POINTER(ctypes.c_longlong),  # nnz
        ctypes.c_longlong,  # max_n
    ]
    lib.sl_libsvm_fill.restype = ctypes.c_int
    lib.sl_libsvm_fill.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),  # Y
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),    # rowptr
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),    # colind
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),  # values
    ]
    lib.sl_arclist_count.restype = ctypes.c_int
    lib.sl_arclist_count.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.sl_arclist_fill.restype = ctypes.c_int
    lib.sl_arclist_fill.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
    ]
    _LIB = lib
    return _LIB


def _read_bytes(source) -> Optional[bytes]:
    if hasattr(source, "read"):
        data = source.read()
        if hasattr(source, "seek"):
            source.seek(0)
        return data.encode() if isinstance(data, str) else data
    with open(source, "rb") as f:
        return f.read()


def parse_libsvm(source, max_n: int = -1):
    """Native libsvm parse -> (targets, indices, values, d, nt) per-line
    lists matching the pure-Python parser's output, or None if the native
    library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    data = _read_bytes(source)
    n = ctypes.c_longlong()
    nt = ctypes.c_longlong()
    d = ctypes.c_longlong()
    nnz = ctypes.c_longlong()
    rc = lib.sl_libsvm_count(
        data, len(data), ctypes.byref(n), ctypes.byref(nt),
        ctypes.byref(d), ctypes.byref(nnz), max_n)
    if rc != 0:
        from libskylark_tpu.base import errors

        raise errors.IOError_(f"native libsvm parse failed (code {rc})")
    n, nt, d, nnz = n.value, nt.value, d.value, nnz.value
    Y = np.zeros(n * max(nt, 1), dtype=np.float64)
    rowptr = np.zeros(n + 1, dtype=np.int64)
    colind = np.zeros(max(nnz, 1), dtype=np.int64)
    values = np.zeros(max(nnz, 1), dtype=np.float64)
    rc = lib.sl_libsvm_fill(data, len(data), n, nt, nnz,
                            Y, rowptr, colind, values)
    if rc != 0:
        from libskylark_tpu.base import errors

        raise errors.IOError_(f"native libsvm fill failed (code {rc})")
    targets = [Y[i * nt:(i + 1) * nt] for i in range(n)]
    indices = [colind[rowptr[i]:rowptr[i + 1]] for i in range(n)]
    vals = [values[rowptr[i]:rowptr[i + 1]] for i in range(n)]
    return targets, indices, vals, int(d), int(nt)


def parse_arc_list(source):
    """Native arc-list parse -> (src, dst, w) numpy arrays, or None."""
    lib = _load()
    if lib is None:
        return None
    data = _read_bytes(source)
    ne = ctypes.c_longlong()
    rc = lib.sl_arclist_count(data, len(data), ctypes.byref(ne))
    if rc != 0:
        from libskylark_tpu.base import errors

        raise errors.IOError_(f"native arc-list parse failed (code {rc})")
    ne = ne.value
    src = np.zeros(max(ne, 1), dtype=np.int64)
    dst = np.zeros(max(ne, 1), dtype=np.int64)
    w = np.zeros(max(ne, 1), dtype=np.float64)
    rc = lib.sl_arclist_fill(data, len(data), ne, src, dst, w)
    if rc != 0:
        from libskylark_tpu.base import errors

        raise errors.IOError_(f"native arc-list fill failed (code {rc})")
    return src[:ne], dst[:ne], w[:ne]
