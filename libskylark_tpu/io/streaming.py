"""Streaming sketch over minibatch iterators.

TPU-native analog of ref: python-skylark/skylark/streaming.py:4-30 — a
CountSketch (CWT) applied incrementally to an iterator of ``(X, Y)``
minibatches, producing the sketched dataset ``(S·X, S·Y)`` without ever
materializing the full data. Unlike the reference's ``numpy.random.seed``
stream (which depends on arrival order), the bucket/sign streams here come
from the framework's counter-based CWT, so the result equals the one-shot
``CWT.apply`` on the concatenated data — the layout-independence invariant
(ref: base/randgen.hpp:98-115) extended to streaming.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base.context import Context
from libskylark_tpu.ml.coding import dummy_coding
from libskylark_tpu.sketch.hash import CWT


class StreamingCWT:
    """Sketch a stream of row-minibatches down to ``s`` rows.

    ``n`` is the total number of rows across the stream (the sketched
    dimension — must be known up front, as in the reference where the
    CWT hash stream is over row indices).
    """

    def __init__(self, n: int, s: int, context: Context):
        self._n = int(n)
        self._s = int(s)
        self._cwt = CWT(self._n, self._s, context)

    @property
    def transform(self) -> CWT:
        return self._cwt

    def sketch(
        self,
        batches: Iterable[Tuple[np.ndarray, np.ndarray]],
        num_classes: int = 0,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Consume ``(X, Y)`` minibatches; return ``(SX, SY)``.

        ``num_classes > 2`` dummy-codes labels to ±1 one-vs-all before
        sketching (ref: streaming.py:13-17 + ml/utils dummycode).
        """
        h_all = np.asarray(self._cwt.bucket_indices())
        v_all = np.asarray(self._cwt.values(jnp.float32))
        SX: Optional[jnp.ndarray] = None
        SY: Optional[jnp.ndarray] = None
        row0 = 0
        for X, Y in batches:
            X = jnp.asarray(X)
            Y = np.asarray(Y)
            nb = X.shape[0]
            if row0 + nb > self._n:
                raise ValueError(
                    f"stream longer than declared n={self._n}")
            if num_classes > 2:
                Yb, _ = dummy_coding(
                    Y.reshape(-1), coding=list(range(num_classes)))
                Yb = jnp.asarray(Yb)
            else:
                Yb = jnp.asarray(Y.astype(np.float32))
                if Yb.ndim == 1:
                    Yb = Yb[:, None]
            h = jnp.asarray(h_all[row0:row0 + nb])
            v = jnp.asarray(v_all[row0:row0 + nb])
            SXb = jnp.zeros((self._s, X.shape[1]), X.dtype).at[h].add(
                v[:, None] * X)
            SYb = jnp.zeros((self._s, Yb.shape[1]), Yb.dtype).at[h].add(
                v[:, None] * Yb)
            SX = SXb if SX is None else SX + SXb
            SY = SYb if SY is None else SY + SYb
            row0 += nb
        if SX is None:
            raise ValueError("empty stream")
        if SY.shape[1] == 1:
            SY = SY[:, 0]
        return SX, SY
