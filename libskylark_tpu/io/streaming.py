"""Streaming sketch over minibatch iterators.

TPU-native analog of ref: python-skylark/skylark/streaming.py:4-30 — a
CountSketch (CWT) applied incrementally to an iterator of ``(X, Y)``
minibatches, producing the sketched dataset ``(S·X, S·Y)`` without ever
materializing the full data. Unlike the reference's ``numpy.random.seed``
stream (which depends on arrival order), the bucket/sign streams here come
from the framework's counter-based CWT, so the result equals the one-shot
``CWT.apply`` on the concatenated data — the layout-independence invariant
(ref: base/randgen.hpp:98-115) extended to streaming.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base.context import Context
from libskylark_tpu.ml.coding import dummy_coding
from libskylark_tpu.sketch.hash import CWT


# Checkpoint digest-scheme version — the ml/admm.py ``_IDENTITY_SCHEME``
# discipline applied to the streaming checkpoints: bumped whenever the
# bytes feeding the resume digests change meaning. Scheme 2 = sha256
# config identity + byte-budgeted ``sample_digest`` batch-0 hash (the
# current format). Scheme 1, never written under this field, fingerprinted
# batch 0 with a float device statistic. A checkpoint recording a
# DIFFERENT scheme refuses with a format diagnosis — without the tag it
# would fail the digest comparison and misdiagnose as "different stream"
# (ADVICE r5).
_DIGEST_SCHEME = 2


class StreamingCWT:
    """Sketch a stream of row-minibatches down to ``s`` rows.

    ``n`` is the total number of rows across the stream (the sketched
    dimension — must be known up front, as in the reference where the
    CWT hash stream is over row indices).
    """

    def __init__(self, n: int, s: int, context: Context):
        self._n = int(n)
        self._s = int(s)
        self._cwt = CWT(self._n, self._s, context)

    @property
    def transform(self) -> CWT:
        return self._cwt

    def _identity(self, num_classes: int) -> str:
        """Resume fingerprint: the sketch configuration. The stream's
        CONTENT can't be hashed without consuming it; the first batch is
        verified positionally at resume time instead (see ``sketch``)."""
        import hashlib

        h = hashlib.sha256()
        h.update(repr((self._n, self._s, int(num_classes))).encode())
        h.update(self._cwt.to_json().encode())
        return h.hexdigest()

    @staticmethod
    def _batch_hash(X) -> str:
        """Exact byte digest of a bounded batch prefix — platform- and
        JAX-version-independent (a float device statistic could
        spuriously refuse a TPU-saved/CPU-resumed stream, or collide;
        r3 advisor)."""
        from libskylark_tpu.utility.checkpoint import sample_digest

        return sample_digest(X)

    def sketch(
        self,
        batches: Iterable[Tuple[np.ndarray, np.ndarray]],
        num_classes: int = 0,
        checkpoint=None,
        checkpoint_every: int = 0,
        prefetch_depth: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Consume ``(X, Y)`` minibatches; return ``(SX, SY)``.

        ``num_classes > 2`` dummy-codes labels to ±1 one-vs-all before
        sketching (ref: streaming.py:13-17 + ml/utils dummycode).

        ``prefetch_depth`` enables the double-buffered streaming
        overlap (:func:`libskylark_tpu.io.chunked.prefetch_batches`): a
        background thread parses batch k+1 and starts its host→device
        transfer while batch k's scatter-add computes on device.
        Defaults to SKYLARK_STREAM_PREFETCH (2; 0 disables). The result
        is BIT-EQUAL to the unprefetched pass — and to the one-shot
        ``CWT.apply`` on the concatenated data (the layout-independence
        invariant): prefetch moves bytes earlier, it never changes a
        value or the accumulation order.

        ``checkpoint`` (directory path or
        :class:`~libskylark_tpu.utility.TrainCheckpointer`) persists the
        accumulators every ``checkpoint_every`` batches; a rerun over
        the same directory fast-forwards the stream past the rows
        already folded in (re-reading but not re-sketching them) and
        continues — result identical to the uninterrupted pass (the
        accumulation is a sum of per-batch scatters; the counter-based
        hash streams are position-keyed, not order-keyed). Resume
        validates the sketch configuration AND the first re-read batch
        against the checkpoint (a different stream must refuse); the
        batching must be byte-identical across runs (a batch straddling
        the saved row offset refuses)."""
        from libskylark_tpu.base import errors

        h_all = np.asarray(self._cwt.bucket_indices())
        v_all = np.asarray(self._cwt.values(jnp.float32))
        SX: Optional[jnp.ndarray] = None
        SY: Optional[jnp.ndarray] = None
        row0 = 0

        ckpt = None
        ckpt_owned = False
        ident = None
        resume_rows = 0         # rows already folded into (SX, SY)
        last_saved = -1         # step of the newest in-loop save
        saved_b0 = None         # batch-0 hash recorded at first save
        b0 = None               # batch-0 hash of THIS pass
        if checkpoint is not None:
            from libskylark_tpu.utility.checkpoint import (
                TrainCheckpointer,
                as_checkpointer,
            )

            ident = self._identity(num_classes)
            ckpt_owned = not isinstance(checkpoint, TrainCheckpointer)
            ckpt = as_checkpointer(checkpoint)

        def _close():
            if ckpt is not None and ckpt_owned:
                ckpt.close()

        try:
            if ckpt is not None and ckpt.latest_step() is not None:
                step0, meta = ckpt.metadata()
                scheme = meta.get("digest_scheme")
                if scheme is not None and scheme != _DIGEST_SCHEME:
                    # a digest under another scheme is incomparable —
                    # diagnose the FORMAT, don't let the comparison
                    # below misread it as a different stream
                    raise errors.InvalidParametersError(
                        f"checkpoint was written under digest scheme "
                        f"{scheme}; this build uses {_DIGEST_SCHEME} — "
                        "stream identity cannot be compared across "
                        "schemes; re-ingest from scratch")
                if meta.get("identity") != ident:
                    raise errors.InvalidParametersError(
                        "checkpoint belongs to a different streaming "
                        "sketch (n/s/context/num_classes differ) — "
                        "refusing to resume")
                resume_rows = int(meta["rows"])
                saved_b0 = meta.get("batch0_hash")
                if saved_b0 is not None and not isinstance(saved_b0, str):
                    # pre-digest checkpoints stored a float fingerprint;
                    # comparing it to the sha256 digest would always
                    # mismatch and misdiagnose as "different stream"
                    raise errors.InvalidParametersError(
                        "checkpoint was written by an older build "
                        "(float batch-0 fingerprint); stream identity "
                        "cannot be verified — re-ingest from scratch")
                _, state, _ = ckpt.restore(step0)
                SX = jnp.asarray(state["SX"])
                SY = jnp.asarray(state["SY"])
                if resume_rows >= self._n:
                    # finished stream: return without re-reading it
                    return self._finish(SX, SY)
            row0 = resume_rows

            from libskylark_tpu.io.chunked import prefetch_batches

            batches_seen = 0
            rows_scanned = 0
            # on a resume, the fast-forward below discards every
            # already-folded-in batch — prefetching must not pay a
            # host→device transfer per discarded batch, so the worker
            # stays parse-ahead-only (the in-loop jnp.asarray moves the
            # kept batches); a fresh pass gets the full H2D overlap
            for X, Y in prefetch_batches(batches, depth=prefetch_depth,
                                         to_device=resume_rows == 0):
                # np.shape reads the shape attribute — no device sync
                # on a prefetched (device-resident) batch
                nb = int(np.shape(X)[0])
                if rows_scanned == 0 and (ckpt is not None):
                    b0 = self._batch_hash(X)
                    # exact digest equality (NaN bytes compare like any
                    # bytes, so NaN-laden batches round-trip fine)
                    if saved_b0 is not None and b0 != saved_b0:
                        raise errors.InvalidParametersError(
                            "checkpoint belongs to a different stream "
                            "(first batch differs) — refusing to resume")
                rows_scanned += nb
                if rows_scanned <= resume_rows:
                    continue        # fast-forward past folded-in rows
                if rows_scanned - nb < resume_rows:
                    raise errors.InvalidParametersError(
                        f"stream batching changed across runs: a batch "
                        f"straddles the checkpointed row offset "
                        f"{resume_rows} — refusing to resume")

                X = jnp.asarray(X)
                Y = np.asarray(Y)
                if row0 + nb > self._n:
                    raise ValueError(
                        f"stream longer than declared n={self._n}")
                if num_classes > 2:
                    Yb, _ = dummy_coding(
                        Y.reshape(-1), coding=list(range(num_classes)))
                    Yb = jnp.asarray(Yb)
                else:
                    Yb = jnp.asarray(Y.astype(np.float32))
                    if Yb.ndim == 1:
                        Yb = Yb[:, None]
                h = jnp.asarray(h_all[row0:row0 + nb])
                v = jnp.asarray(v_all[row0:row0 + nb])
                if SX is None:
                    SX = jnp.zeros((self._s, X.shape[1]), X.dtype)
                    SY = jnp.zeros((self._s, Yb.shape[1]), Yb.dtype)
                # scatter each batch into the CARRIED accumulator (not
                # zeros-then-sum): per bucket, updates land in row order
                # exactly as the one-shot CWT.apply scatter applies them,
                # so the streamed result is BIT-EQUAL to the one-shot
                # sketch of the concatenated data — the layout-
                # independence invariant at full strength (partial sums
                # per batch would reassociate the f32 additions)
                SX = SX.at[h].add(v[:, None] * X)
                SY = SY.at[h].add(v[:, None] * Yb)
                row0 += nb
                batches_seen += 1
                if ckpt is not None and checkpoint_every > 0 \
                        and batches_seen % int(checkpoint_every) == 0 \
                        and row0 < self._n:
                    self._save(ckpt, ident, row0, SX, SY, b0)
                    last_saved = row0
            if rows_scanned < resume_rows:
                # the re-supplied stream ended DURING fast-forward
                # (shorter than the checkpointed offset, or empty):
                # returning the restored partial accumulators would pass
                # off a truncated/different stream as the final sketch
                # (r3 advisor). Strictly '<' on purpose: a stream ending
                # EXACTLY at the offset is consistent with the
                # checkpoint (batch 0 verified, every folded row
                # re-supplied, nothing new) — a no-progress rerun
                # returning the same partial state, the same contract as
                # the partial pass that wrote the checkpoint. Partial
                # vs finished is distinguished by rows < n, not here.
                raise errors.InvalidParametersError(
                    f"stream ended at {rows_scanned} rows, before the "
                    f"checkpointed offset {resume_rows} — truncated or "
                    "different stream; refusing to resume")
            if SX is None:
                raise ValueError("empty stream")
            if ckpt is not None and row0 > resume_rows \
                    and row0 != last_saved:
                # guard against re-saving the in-loop step: orbax's
                # behavior on an existing step is version-dependent
                # (silent no-op here, StepAlreadyExistsError elsewhere)
                self._save(ckpt, ident, row0, SX, SY, b0)
            return self._finish(SX, SY)
        finally:
            _close()

    @staticmethod
    def _save(ckpt, ident, rows, SX, SY, b0) -> None:
        ckpt.save(int(rows), {"SX": SX, "SY": SY},
                  {"identity": ident, "rows": int(rows),
                   "batch0_hash": b0,
                   "digest_scheme": _DIGEST_SCHEME})

    @staticmethod
    def _finish(SX, SY):
        if SY.shape[1] == 1:
            SY = SY[:, 0]
        return SX, SY
