"""WebHDFS transport for the streaming readers.

TPU-native analog of the reference's libhdfs line streamer
(ref: utility/hdfs.hpp:11 ``hdfs_line_streamer_t``, used by the HDFS
reader variants in utility/io/libsvm_io.hpp:1395-1876). The reference
links libhdfs (JNI) and reads through a buffered ``hdfsRead`` loop; here
the transport speaks HDFS's standard REST interface (WebHDFS,
``GET /webhdfs/v1/<path>?op=OPEN``) over stdlib ``urllib`` — no native
client required — and yields decoded text lines, which is exactly the
seam every reader in :mod:`libskylark_tpu.io.chunked` accepts
(``iter_libsvm_batches(webhdfs_lines(...))``,
``read_libsvm_sharded(webhdfs_lines(...), mesh)``, ...).

The namenode answers OPEN with a 307 redirect to the datanode that owns
the first block; ``urllib`` follows it transparently. Reads stream in
``buffer_bytes`` chunks with a carry for the partial last line — memory
stays O(buffer), matching the reference's bounded ``hdfsRead`` buffer
discipline.

Offline environments: the transport is exercised against a local REST
stub in tests/test_io_chunked.py (a real HDFS namenode is just the same
protocol on another host).
"""

from __future__ import annotations

import urllib.parse
import urllib.request
from typing import Iterator, Optional

from libskylark_tpu.base import errors


def _open_url(namenode: str, path: str, user: Optional[str],
              offset: int, length: Optional[int],
              buffer_bytes: int, timeout: float):
    if not path.startswith("/"):
        path = "/" + path
    params = {"op": "OPEN", "buffersize": str(buffer_bytes)}
    if user:
        params["user.name"] = user
    if offset:
        params["offset"] = str(offset)
    if length is not None:
        params["length"] = str(length)
    url = (namenode.rstrip("/") + "/webhdfs/v1" +
           urllib.parse.quote(path) + "?" + urllib.parse.urlencode(params))
    try:
        return urllib.request.urlopen(url, timeout=timeout)
    except Exception as e:  # pragma: no cover - network-specific messages
        raise errors.IOError_(
            f"webhdfs OPEN failed for {path!r} via {namenode!r}: {e}"
        ) from e


def webhdfs_lines(
    namenode: str,
    path: str,
    user: Optional[str] = None,
    offset: int = 0,
    length: Optional[int] = None,
    buffer_bytes: int = 1 << 20,
    encoding: str = "utf-8",
    timeout: float = 60.0,
) -> Iterator[str]:
    """Stream the lines of an HDFS file through WebHDFS.

    ``namenode`` is the REST endpoint (``http://host:9870``); ``path``
    the absolute HDFS path. Yields text lines (newline stripped by the
    consumer — same contract as a file handle). O(buffer_bytes) memory.
    """
    resp = _open_url(namenode, path, user, offset, length,
                     buffer_bytes, timeout)
    carry = b""
    try:
        while True:
            chunk = resp.read(buffer_bytes)
            if not chunk:
                break
            carry += chunk
            # split out complete lines; keep the partial tail
            if b"\n" in carry:
                complete, carry = carry.rsplit(b"\n", 1)
                for line in complete.split(b"\n"):
                    yield line.decode(encoding) + "\n"
    finally:
        resp.close()
    if carry:
        yield carry.decode(encoding)
