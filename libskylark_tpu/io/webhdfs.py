"""WebHDFS transport for the streaming readers.

TPU-native analog of the reference's libhdfs line streamer
(ref: utility/hdfs.hpp:11 ``hdfs_line_streamer_t``, used by the HDFS
reader variants in utility/io/libsvm_io.hpp:1395-1876). The reference
links libhdfs (JNI) and reads through a buffered ``hdfsRead`` loop; here
the transport speaks HDFS's standard REST interface (WebHDFS,
``GET /webhdfs/v1/<path>?op=OPEN``) over stdlib ``urllib`` — no native
client required — and yields decoded text lines, which is exactly the
seam every reader in :mod:`libskylark_tpu.io.chunked` accepts
(``iter_libsvm_batches(webhdfs_lines(...))``,
``read_libsvm_sharded(webhdfs_lines(...), mesh)``, ...).

The namenode answers OPEN with a 307 redirect to the datanode that owns
the first block; ``urllib`` follows it transparently. Reads stream in
``buffer_bytes`` chunks with a carry for the partial last line — memory
stays O(buffer), matching the reference's bounded ``hdfsRead`` buffer
discipline.

Resilience (:mod:`libskylark_tpu.resilience`): both halves of the
transport run under a :class:`~libskylark_tpu.resilience.RetryPolicy`
(default: 4 attempts, decorrelated-jitter backoff, transient-error
predicate; ``SKYLARK_WEBHDFS_RETRIES`` overrides the attempt count).

- **OPEN** retries transient connection failures per attempt; the final
  failure re-raises as :class:`~libskylark_tpu.base.errors.IOError_`
  with the URL and the attempt count appended to its trace.
- **read** failures *reconnect and resume*: WebHDFS OPEN takes a byte
  ``offset``, and the streamer counts consumed bytes, so a dropped
  datanode connection reopens at ``offset + consumed`` and continues —
  the yielded line stream is bit-identical to an uninterrupted read
  (the partial-line carry is host memory; it survives the reconnect).

Fault-injection sites ``io.webhdfs.open`` (per connection attempt) and
``io.webhdfs.read`` (per chunk) make both paths deterministically
chaos-testable (tests/test_resilience.py).

Offline environments: the transport is exercised against a local REST
stub in tests/test_io_chunked.py (a real HDFS namenode is just the same
protocol on another host).
"""

from __future__ import annotations

import dataclasses
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator, Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience.policy import DeadlineExceededError, RetryPolicy
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

# Unified-registry adapter (docs/observability): reconnects were
# previously visible only in the final error's trace — the counter
# makes every survived blip a first-class number. Always counted: a
# reconnect already paid for a dropped connection + reopen.
_RECONNECTS = _metrics.counter(
    "io.webhdfs.reconnects",
    "Mid-stream WebHDFS connection drops that reconnected and resumed")


def _is_transient(e: BaseException) -> bool:
    """Worth a retry: connection/timeout trouble, short/dropped reads
    (``http.client.IncompleteRead`` et al.), and server-side (5xx /
    429) HTTP failures. Client errors (404, 403, ...) and logic errors
    fail immediately — they would fail identically forever."""
    import http.client

    if isinstance(e, DeadlineExceededError):
        return False     # exhausted budgets stop, never retry
    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500 or e.code == 429
    return isinstance(
        e, (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            http.client.HTTPException, errors.IOError_))


def default_retry() -> RetryPolicy:
    """The transport's default policy (``SKYLARK_WEBHDFS_RETRIES``
    bounds attempts, default 4)."""
    attempts = max(1, _env.WEBHDFS_RETRIES.get())
    return RetryPolicy(max_attempts=attempts, base_delay=0.1,
                       max_delay=2.0, retry_on=_is_transient)


def _open_url(namenode: str, path: str, user: Optional[str],
              offset: int, length: Optional[int],
              buffer_bytes: int, timeout: float,
              retry: Optional[RetryPolicy] = None):
    retry = retry or default_retry()
    if not path.startswith("/"):
        path = "/" + path
    params = {"op": "OPEN", "buffersize": str(buffer_bytes)}
    if user:
        params["user.name"] = user
    if offset:
        params["offset"] = str(offset)
    if length is not None:
        params["length"] = str(length)
    url = (namenode.rstrip("/") + "/webhdfs/v1" +
           urllib.parse.quote(path) + "?" + urllib.parse.urlencode(params))
    attempts = {"n": 0}

    def attempt(timeout=timeout):
        # default mirrors the caller's value: with timeout=None the
        # policy injects no kwarg (urlopen treats None as "no timeout",
        # same as before the retry wiring)
        attempts["n"] += 1
        faults.check("io.webhdfs.open", detail=url)
        return urllib.request.urlopen(url, timeout=timeout)

    try:
        # per-attempt timeout = the caller's urlopen timeout; the policy
        # threads it through so a hung connect consumes one attempt, not
        # the whole budget. The span covers the whole retry ladder —
        # per-attempt retry events attach to it (resilience.policy).
        with _trace.span("io.webhdfs.open",
                         attrs={"path": path, "offset": offset}):
            return dataclasses.replace(
                retry, timeout_arg="timeout", attempt_timeout=timeout,
            ).call(attempt)
    except (KeyboardInterrupt, SystemExit):
        raise               # cancellation is not an I/O failure — a
        #                     rewrap would make Ctrl-C retryable upstream
    except BaseException as e:  # noqa: BLE001 — rewrap with provenance
        err = errors.IOError_(
            f"webhdfs OPEN failed for {path!r} via {namenode!r}: {e}")
        err.append_trace(f"url={url}")
        err.append_trace(
            f"attempts={attempts['n']}/{retry.max_attempts}")
        raise err from e


def webhdfs_lines(
    namenode: str,
    path: str,
    user: Optional[str] = None,
    offset: int = 0,
    length: Optional[int] = None,
    buffer_bytes: int = 1 << 20,
    encoding: str = "utf-8",
    timeout: float = 60.0,
    retry: Optional[RetryPolicy] = None,
) -> Iterator[str]:
    """Stream the lines of an HDFS file through WebHDFS.

    ``namenode`` is the REST endpoint (``http://host:9870``); ``path``
    the absolute HDFS path. Yields text lines (newline stripped by the
    consumer — same contract as a file handle). O(buffer_bytes) memory.

    Transient mid-stream failures reconnect at the consumed byte offset
    under ``retry`` (see module docstring) — the line stream is
    bit-identical to an uninterrupted read.
    """
    retry = retry or default_retry()
    delays = retry.delays()
    reconnects = 0
    consumed = 0          # bytes successfully read off the wire
    carry = b""
    while True:
        want = None if length is None else length - consumed
        if want is not None and want <= 0:
            break
        resp = _open_url(namenode, path, user, offset + consumed, want,
                         buffer_bytes, timeout, retry=retry)
        clean_eof = False
        try:
            while True:
                faults.check("io.webhdfs.read", detail=path)
                chunk = resp.read(buffer_bytes)
                if not chunk:
                    clean_eof = True
                    break
                consumed += len(chunk)
                if reconnects:
                    # progress after a reconnect: the retry budget is
                    # per-INCIDENT, not per-stream — a week-long stream
                    # must survive unlimited isolated blips, just never
                    # max_attempts consecutive dead connections
                    reconnects = 0
                    delays = retry.delays()
                carry += chunk
                # split out complete lines; keep the partial tail
                if b"\n" in carry:
                    complete, carry = carry.rsplit(b"\n", 1)
                    for line in complete.split(b"\n"):
                        yield line.decode(encoding) + "\n"
        except (GeneratorExit, KeyboardInterrupt, SystemExit):
            raise                   # abandonment/cancellation — not a
            #                         transport failure, never rewrapped
        except BaseException as e:  # noqa: BLE001 — predicate decides
            reconnects += 1
            if not retry.retryable(e) or reconnects >= retry.max_attempts:
                if isinstance(e, errors.SkylarkError):
                    e.append_trace(
                        f"webhdfs read of {path!r} died at byte "
                        f"{offset + consumed} "
                        f"(connection {reconnects}/{retry.max_attempts})")
                    raise
                err = errors.IOError_(
                    f"webhdfs read failed for {path!r} at byte "
                    f"{offset + consumed}: {e}")
                err.append_trace(
                    f"connections={reconnects}/{retry.max_attempts}")
                raise err from e
            _RECONNECTS.inc_always()
            retry.sleep(next(delays))
            continue      # reopen at offset + consumed, carry intact
        finally:
            try:
                resp.close()
            except Exception:  # pragma: no cover - close-on-dead-socket
                pass
        if clean_eof:
            break
    if carry:
        yield carry.decode(encoding)
