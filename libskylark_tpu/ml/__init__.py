"""ML layer: kernels, KRR/RLSC, ADMM kernel machines, models, graph
algorithms (SURVEY.md §2.5)."""

from libskylark_tpu.ml import (
    admm,
    coding,
    graph,
    kernels,
    krr,
    metrics,
    model,
    modeling,
    nonlinear,
    rlsc,
)
from libskylark_tpu.ml.admm import BlockADMMSolver
from libskylark_tpu.ml.metrics import classification_accuracy, rmse
from libskylark_tpu.ml.modeling import LinearizedKernelModel
from libskylark_tpu.ml.nonlinear import (
    NystromRLS,
    RLS,
    SketchPCR,
    SketchRLS,
)
from libskylark_tpu.ml.graph import (
    Graph,
    approximate_ase,
    find_local_cluster,
    time_dependent_ppr,
)
from libskylark_tpu.ml.coding import dummy_coding, dummy_decode
from libskylark_tpu.ml.model import HilbertModel
from libskylark_tpu.ml.kernels import (
    ExpSemigroup,
    Gaussian,
    Kernel,
    KERNELS,
    Laplacian,
    Linear,
    Matern,
    Polynomial,
    deserialize_kernel,
    make_kernel,
)
from libskylark_tpu.ml.krr import (
    FeatureMapPrecond,
    KrrParams,
    approximate_kernel_ridge,
    faster_kernel_ridge,
    kernel_ridge,
    krr_predict,
    large_scale_kernel_ridge,
    sketched_approximate_kernel_ridge,
)
from libskylark_tpu.ml.rlsc import (
    RlscParams,
    approximate_kernel_rlsc,
    faster_kernel_rlsc,
    kernel_rlsc,
    large_scale_kernel_rlsc,
    sketched_approximate_kernel_rlsc,
)

__all__ = [
    "admm",
    "metrics",
    "modeling",
    "nonlinear",
    "classification_accuracy",
    "rmse",
    "LinearizedKernelModel",
    "RLS",
    "SketchRLS",
    "NystromRLS",
    "SketchPCR",
    "graph",
    "Graph",
    "approximate_ase",
    "find_local_cluster",
    "time_dependent_ppr",
    "model",
    "BlockADMMSolver",
    "HilbertModel",
    "coding",
    "kernels",
    "krr",
    "rlsc",
    "dummy_coding",
    "dummy_decode",
    "Kernel",
    "KERNELS",
    "Linear",
    "Gaussian",
    "Polynomial",
    "Laplacian",
    "ExpSemigroup",
    "Matern",
    "deserialize_kernel",
    "make_kernel",
    "KrrParams",
    "FeatureMapPrecond",
    "kernel_ridge",
    "krr_predict",
    "approximate_kernel_ridge",
    "sketched_approximate_kernel_ridge",
    "faster_kernel_ridge",
    "large_scale_kernel_ridge",
    "RlscParams",
    "kernel_rlsc",
    "approximate_kernel_rlsc",
    "sketched_approximate_kernel_rlsc",
    "faster_kernel_rlsc",
    "large_scale_kernel_rlsc",
]
