"""Block-ADMM solver for kernel machines.

TPU-native analog of ref: ml/BlockADMM.hpp:16-611 (``BlockADMMSolver``):
consensus ADMM over feature-block partitions. Per iteration: prox of the loss
on the predictions, prox of the regularizer on the consensus weights, then a
per-block local ridge solve against a cached (ZⱼᵀZⱼ + I)⁻¹ factorization,
with consensus formed by averaging.

Parallelism mapping (SURVEY.md §2.9 P6/P7): the reference's OpenMP loop over
feature blocks and MPI data partitions both collapse into XLA — the whole
iteration is one jitted function; per-block matmuls batch onto the MXU and a
data-sharded X flows through the feature maps with collectives inserted
automatically. The MPI-rank consensus average ``Wbar = (Σᵢ Wᵢ + W)/(P+1)``
(ref: :575-590) therefore has P = 1: there is a single logical program, so
the data-partition consensus is exact rather than averaged. The feature-block
consensus (the (NumPartitions+1) factors, ref: :466-469,568-570) is preserved
exactly.

Feature maps are regenerated from their (seed, counter) inside the jitted
step by default — the generation is fused on-chip, so caching transforms
(ref: ``CacheTransforms``) trades HBM for nothing unless the maps are
FFT-heavy; it remains available via ``cache_transforms=True``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from libskylark_tpu.algorithms.prox import Loss, Regularizer
from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.precision import with_solver_precision
from libskylark_tpu.ml.kernels import Kernel
from libskylark_tpu.ml.model import HilbertModel
from libskylark_tpu.resilience.preemption import (
    preemption_requested as _preemption_requested,
)
from libskylark_tpu.sketch import ROWWISE, SketchTransform
from libskylark_tpu.telemetry import metrics as _telemetry_metrics
from libskylark_tpu.utility.timer import get_timer, timers_enabled

# Per-iteration training telemetry (docs/observability). Gated on the
# global switch inside the loop: reading ``objective`` forces a host
# sync, which only an observability-mode run should pay (the default
# loop stays async — the phase timers' timing note applies here too).
_ADMM_ITERS = _telemetry_metrics.counter(
    "ml.admm.iterations", "BlockADMM training iterations executed")
_ADMM_OBJECTIVE = _telemetry_metrics.gauge(
    "ml.admm.objective", "Most recent BlockADMM training objective")
_ADMM_RELDEL = _telemetry_metrics.gauge(
    "ml.admm.reldel",
    "Most recent relative consensus-iterate change (convergence signal)")

# Resume-identity scheme version: bumped whenever the _identity() hash
# inputs change (scheme 4 = byte-budgeted sample_digest with the
# sampled-bytes bound — wide-row operands sample ≥16 rows, not ≥1024;
# scheme 3 = byte-budgeted with a 1024-row floor, r4 advisor; scheme 2
# = fixed 16-row samples; scheme 1, never written under this field,
# hashed float device statistics). A checkpoint from another scheme
# refuses with a format diagnosis rather than a misleading "different
# training run".
_IDENTITY_SCHEME = 4


def _partition(num_features: int, num_partitions: int) -> list[int]:
    """Equal split with remainder spread forward (ref: BlockADMM.hpp:145-153)."""
    sizes, nf, np_ = [], num_features, num_partitions
    for _ in range(num_partitions):
        sj = nf // np_
        sizes.append(sj)
        nf -= sj
        np_ -= 1
    return sizes


class BlockADMMSolver:
    """Consensus block-ADMM trainer producing a :class:`HilbertModel`.

    Three construction modes mirror the reference's constructors:

    - ``BlockADMMSolver(loss, regularizer, lam, num_features, num_partitions)``
      — linear (blocks are column slices of X; ref: :128-158).
    - ``BlockADMMSolver.from_kernel(context, loss, regularizer, lam,
      num_features, kernel, tag, num_partitions)`` — kernel random features
      per block (ref: :160-230).
    - ``BlockADMMSolver.with_maps(loss, regularizer, maps, lam, scale_maps)``
      — guru: explicit transforms (ref: :232-258).
    """

    def __init__(
        self,
        loss: Loss,
        regularizer: Regularizer,
        lam: float,
        num_features: int,
        num_partitions: int = 1,
        feature_maps: Optional[Sequence[SketchTransform]] = None,
        scale_maps: bool = False,
    ):
        self.loss = loss
        self.regularizer = regularizer
        self.lam = float(lam)
        self.num_features = int(num_features)
        self.feature_maps = list(feature_maps) if feature_maps else []
        self.scale_maps = bool(scale_maps)
        if self.feature_maps:
            self.block_sizes = [m.sketch_dim for m in self.feature_maps]
            if sum(self.block_sizes) != self.num_features:
                raise errors.InvalidParametersError(
                    "feature maps do not cover num_features"
                )
        else:
            self.block_sizes = _partition(num_features, num_partitions)
        self.starts = list(np.cumsum([0] + self.block_sizes[:-1]))
        # Tuning knobs (ref: set_rho/set_maxiter/set_tol, defaults :143).
        # The reference defaults TOL=0.1 but never reads it; here tol drives
        # the relative-objective-change stop, so the default is tight.
        self.rho = 1.0
        self.maxiter = 1000
        self.tol = 1e-6
        self.cache_transforms = False

    @classmethod
    def from_kernel(
        cls,
        context: Context,
        loss: Loss,
        regularizer: Regularizer,
        lam: float,
        num_features: int,
        kernel: Kernel,
        tag: str = "regular",
        num_partitions: int = 1,
    ) -> "BlockADMMSolver":
        sizes = _partition(num_features, num_partitions)
        maps = [kernel.create_rft(sj, context, tag) for sj in sizes]
        return cls(
            loss, regularizer, lam, num_features,
            feature_maps=maps, scale_maps=True,
        )

    @classmethod
    def with_maps(
        cls,
        loss: Loss,
        regularizer: Regularizer,
        maps: Sequence[SketchTransform],
        lam: float,
        scale_maps: bool = True,
    ) -> "BlockADMMSolver":
        nf = sum(m.sketch_dim for m in maps)
        return cls(loss, regularizer, lam, nf,
                   feature_maps=maps, scale_maps=scale_maps)

    # -- internals --

    def _block_features(self, X: jnp.ndarray, j: int) -> jnp.ndarray:
        """Zⱼ (n, sⱼ): feature-map apply or column slice (ref: :404-425)."""
        if self.feature_maps:
            Z = self.feature_maps[j].apply(X, ROWWISE)
            if self.scale_maps:
                Z = Z * math.sqrt(self.block_sizes[j] / X.shape[1])
            return Z
        start = self.starts[j]
        return X[:, start : start + self.block_sizes[j]]

    # The iteration's three reusable parts. ``train`` composes them
    # below; the train-job slice engine (libskylark_tpu/train/slices.py)
    # composes the SAME parts into bounded k-iteration slices, so a
    # sliced job and a foreground train() iterate identical math — a
    # numerics change here changes both together.

    def init_carry(self, n: int, k: int, dt) -> tuple:
        """The zero consensus carry: (Wbar, O, Obar, nu, mu, mu_ij,
        ZtObar_ij, del_o) — ref: BlockADMM.hpp:322-339."""
        D = self.num_features
        return (
            jnp.zeros((D, k), dt),   # Wbar
            jnp.zeros((k, n), dt),   # O
            jnp.zeros((k, n), dt),   # Obar
            jnp.zeros((k, n), dt),   # nu
            jnp.zeros((D, k), dt),   # mu
            jnp.zeros((D, k), dt),   # mu_ij
            jnp.zeros((D, k), dt),   # ZtObar_ij
            jnp.zeros((k, n), dt),   # del_o
        )

    def build_caches(self, X, dt, timer=None):
        """Per-block Cholesky factorizations of (ZⱼᵀZⱼ + I) — the
        hoisted iter-1 work of the reference (ref: :435-441). Returns
        ``(cache_mats, cache_lowers, Zs)``: factor arrays (jit
        arguments), static lower flags (closure constants), and the
        cached Zⱼ when ``cache_transforms`` is on. Deterministic given
        X and the maps' (seed, counter) — a resume rebuilds the same
        bytes."""
        cache_mats = []
        cache_lowers = []
        Zs = []
        for j in range(len(self.block_sizes)):
            if timer is not None:
                with timer.phase("TRANSFORM"):
                    Z = self._block_features(X, j)
            else:
                Z = self._block_features(X, j)
            sj = self.block_sizes[j]

            def _factor(Z=Z, sj=sj):
                return jsl.cho_factor(Z.T @ Z + jnp.eye(sj, dtype=dt))

            if timer is not None:
                with timer.phase("FACTORIZATION"):
                    c, low = _factor()
            else:
                c, low = _factor()
            cache_mats.append(c)
            cache_lowers.append(bool(low))
            if self.cache_transforms:
                Zs.append(Z)
        return cache_mats, tuple(cache_lowers), Zs

    def make_step(self, n: int, k: int, dt, cache_lowers: tuple):
        """One consensus-ADMM iteration as a pure function
        ``(carry, X, Y, cache_mats, Zs) -> (carry, (objective,
        reldel))`` — ref: BlockADMM.hpp:291-600.

        X/Y and every array derived from them (the cached block
        factorizations, optionally the cached Zⱼ) are jit ARGUMENTS,
        not closures: on a multi-host mesh they span non-addressable
        devices, and jax forbids closing over such arrays (each would
        be baked into the executable as a constant). Static flags
        (cho lowers) stay in the closure."""
        loss, reg = self.loss, self.regularizer
        lam, rho = self.lam, self.rho
        starts, sizes = self.starts, self.block_sizes
        D = self.num_features
        P = len(self.block_sizes)

        def step(carry, X, Y, cache_mats, Zs):
            Wbar, O, Obar, nu, mu, mu_ij, ZtObar_ij, del_o = carry

            mu_ij = mu_ij - Wbar                     # ref: :378-380
            Obar = Obar - nu
            with jax.named_scope("PROXLOSS"):        # trace-visible phases
                O = loss.prox(Obar, 1.0 / rho, Y)    # ref: :385
                W = reg.prox(Wbar, lam / rho, mu)    # ref: :389

            sum_o = jnp.zeros((k, n), dt)
            wbar_output = jnp.zeros((k, n), dt)
            Wi = jnp.zeros((D, k), dt)
            new_mu_ij = mu_ij
            new_ZtObar = ZtObar_ij

            dsum = (del_o / (P + 1.0) + nu).T        # (n, k); ref: :464-469

            # ZMULT phase of the reference — the per-block solves + gemms
            for j in range(P):
                start, sj = starts[j], sizes[j]
                sl = slice(start, start + sj)
                Z = Zs[j] if self.cache_transforms else self._block_features(X, j)
                wbar_output = wbar_output + (Z @ Wbar[sl]).T
                rhs = Wbar[sl] - mu_ij[sl] + ZtObar_ij[sl] + Z.T @ dsum
                Wi_J = jsl.cho_solve(
                    (cache_mats[j], cache_lowers[j]), rhs)  # ref: :475-476
                o = (Z @ Wi_J).T                     # (k, n); ref: :478-480
                new_mu_ij = new_mu_ij.at[sl].add(Wi_J)
                new_ZtObar = new_ZtObar.at[sl].set(Z.T @ o.T)
                Wi = Wi.at[sl].set(Wi_J)
                sum_o = sum_o + o

            sum_o = O - sum_o                        # ref: :505-507
            del_o = sum_o
            objective = loss.evaluate(wbar_output, Y) + lam * reg.evaluate(Wbar)

            Obar = O - sum_o / (P + 1.0)             # ref: :566-568
            nu = nu + O - Obar                       # ref: :570-571

            # Consensus: single logical rank -> exact (W + Wi)/2
            # (ref: :575-590 with MPI size P=1).
            Wbar_new = (Wi + W) / 2.0
            mu = mu + W - Wbar_new                   # ref: :586-589

            reldel = jnp.linalg.norm(Wbar_new - Wbar) / jnp.maximum(
                jnp.linalg.norm(Wbar_new), jnp.finfo(dt).tiny
            )
            return (
                (Wbar_new, O, Obar, nu, mu, new_mu_ij, new_ZtObar, del_o),
                (objective, reldel),
            )

        return step

    @with_solver_precision
    def train(
        self,
        X,
        Y,
        Xv=None,
        Yv=None,
        regression: bool = False,
        num_targets: Optional[int] = None,
        verbose: bool = False,
        checkpoint=None,
        checkpoint_every: int = 10,
    ) -> HilbertModel:
        """Run ADMM (ref: BlockADMM.hpp:291-600). X is (n, d) rows=examples;
        Y is (n,) — real targets for regression, integer class labels
        (0..k−1) for classification. Returns the trained model; if
        (Xv, Yv) is given, validation error/accuracy is reported per
        iteration through ``verbose``.

        ``checkpoint`` (a directory path or
        :class:`~libskylark_tpu.utility.TrainCheckpointer`) persists the
        full consensus carry every ``checkpoint_every`` iterations —
        asynchronously, so the save streams out while later iterations
        compute — and a rerun over the same directory resumes from the
        newest step, bit-identical to the uninterrupted run (the step is
        deterministic given the data and the maps' (seed, counter)).
        Resume refuses checkpoints from a different run (data, maps,
        hyperparameters, or dtype — a fingerprint is validated), and a
        run that already finished (maxiter reached or tol convergence,
        recorded in the metadata) is returned as-is rather than trained
        further. The reference restarts a killed run from zero (no
        counterpart; its §5 checkpoint row is empty)."""
        X = jnp.asarray(X)
        Y = jnp.asarray(Y).reshape(-1)
        n, d = X.shape
        if regression:
            k = 1
        else:
            # label stats via device reductions, not np.asarray(Y): on a
            # multi-host mesh Y spans non-addressable devices and cannot
            # be fetched to one host — the reductions come back as
            # replicated scalars, which every process can read
            if int(jnp.min(Y)) < 0:
                raise errors.InvalidParametersError(
                    "classification labels must be integers in 0..k-1 "
                    "(recode ±1 labels to 0/1)"
                )
            k = (
                int(num_targets)
                if num_targets is not None
                else int(jnp.max(Y)) + 1
            )
        D = self.num_features
        dt = X.dtype

        model = HilbertModel(
            self.feature_maps, self.scale_maps, D, k,
            regression, input_size=d,
        )

        # Per-phase profile (ref: BlockADMM.hpp:357-365 SKYLARK_TIMER
        # phases); enabled by SKYLARK_TPU_PROFILE=1 / utility.set_enabled.
        # Reset so each train() reports its own run, not cumulative totals.
        timer = get_timer("admm")
        timer.reset()

        loss, reg = self.loss, self.regularizer
        lam, rho = self.lam, self.rho

        def _on_data_devices(arrs):
            """Replicate the consensus state onto X's device set (the
            project's `[*,*]` vocabulary, parallel/mesh.py). With X
            passed as a jit ARGUMENT (multi-host requirement above), a
            default-device carry would conflict with a sharded X —
            explicit arguments must agree on their device set, unlike
            the closed-over constants they replaced."""
            from jax.sharding import NamedSharding

            sh = getattr(X, "sharding", None)
            if (isinstance(sh, NamedSharding)
                    and len(sh.device_set) > 1):
                from libskylark_tpu.parallel import distribute, replicated

                rep = replicated(sh.mesh)
                return tuple(distribute(a, rep) for a in arrs)
            return tuple(arrs)

        carry = _on_data_devices(self.init_carry(n, k, dt))

        # Resume identity: a checkpoint is only valid for the SAME
        # training run — same data, maps, losses, and hyperparameters.
        # Restoring a carry into a different objective would converge to
        # something that matches neither run, silently. The fingerprint
        # covers everything the iteration reads.
        def _identity() -> str:
            import hashlib

            from libskylark_tpu.utility.checkpoint import sample_digest

            h = hashlib.sha256()
            # loss/reg hashed with their constructor state (two
            # LogisticLosses with different Newton budgets iterate
            # different proxes), and the compute dtype (an f32 carry must
            # not resume into an f64 run)
            h.update(repr((
                type(loss).__name__, sorted(vars(loss).items()),
                type(reg).__name__, sorted(vars(reg).items()),
                lam, rho, list(self.block_sizes), self.scale_maps,
                int(D), int(k), int(n), int(d), bool(regression),
                str(dt),
            )).encode())
            for fm in self.feature_maps:
                h.update(fm.to_json().encode())
            # data fingerprint: exact byte digests of a bounded strided
            # row sample — platform/JAX-version independent (the r3
            # float device-reduction statistic made checkpoints
            # effectively platform-pinned and could collide; r3
            # advisor). Coverage trade documented in sample_digest:
            # shape changes and anything touching a sampled row refuse;
            # edits confined to unsampled rows are not caught.
            h.update(sample_digest(X).encode())
            h.update(sample_digest(Y).encode())
            return h.hexdigest()

        ckpt = None
        ckpt_owned = False
        start_it = 1
        ident = None
        resume_finished = False
        if checkpoint is not None:
            ident = _identity()
            from libskylark_tpu.utility.checkpoint import (
                TrainCheckpointer,
                as_checkpointer,
                device_state,
            )

            # a path argument means this train() owns the checkpointer's
            # lifecycle: it must finalize the async writes before
            # returning, or a rerun over the directory races the
            # still-in-flight final save
            ckpt_owned = not isinstance(checkpoint, TrainCheckpointer)
            ckpt = as_checkpointer(checkpoint)
            try:
                if ckpt.latest_step() is not None:
                    # metadata first: identity must be validated BEFORE
                    # state restore (a mismatched state would die inside
                    # orbax on shapes, not on this friendly error)
                    step0, meta = ckpt.metadata()
                    if meta.get("identity_scheme") != _IDENTITY_SCHEME:
                        # pre-digest checkpoints hashed float statistics
                        # into the identity; comparing across schemes
                        # would always mismatch and misdiagnose as
                        # changed data/hyperparameters (review finding)
                        raise errors.InvalidParametersError(
                            f"checkpoint at {checkpoint} was written by "
                            "an older build (incompatible resume-"
                            "identity scheme) — retrain from scratch")
                    if meta.get("identity") != ident:
                        raise errors.InvalidParametersError(
                            f"checkpoint at {checkpoint} belongs to a "
                            "different training run (data, feature maps "
                            "or hyperparameters differ) — refusing to "
                            "resume"
                        )
                    if step0 > self.maxiter:
                        raise errors.InvalidParametersError(
                            f"checkpoint at {checkpoint} is at iteration "
                            f"{step0} > maxiter={self.maxiter}; returning "
                            "it would silently over-train — raise maxiter "
                            "or point at a fresh directory"
                        )
                    # target=the zero carry: restores with the live
                    # structure/dtypes (and shardings, once jitted)
                    _, state, _ = ckpt.restore(step0, target=list(carry))
                    carry = _on_data_devices(device_state(state, dt))
                    start_it = step0 + 1
                    # a run that stopped on tol convergence is DONE:
                    # "resuming" it one more iteration per rerun would
                    # drift from the uninterrupted result. But a rerun
                    # with a DIFFERENT tol (e.g. tol=0, the documented
                    # force-maxiter knob) is asking for different
                    # stopping behavior — silently returning the
                    # converged model would ignore it; refuse instead.
                    resume_finished = bool(meta.get("converged", False))
                    saved_tol = meta.get("tol")
                    if resume_finished and saved_tol is not None \
                            and saved_tol != float(self.tol):
                        raise errors.InvalidParametersError(
                            f"checkpoint at {checkpoint} finished by "
                            f"converging at tol={saved_tol}; this "
                            f"run requests tol={self.tol}. Refusing to "
                            "return the converged model as-is — use a "
                            "fresh checkpoint directory to re-train "
                            "with the new tolerance"
                        )
            except BaseException:
                if ckpt_owned:
                    ckpt.close()
                raise

        # Cached per-block factorizations (ZⱼᵀZⱼ + I)⁻¹ (ref: :435-441 at
        # iter 1; hoisted since Zⱼ is deterministic given the maps) —
        # built only when iterations will actually run, so resuming a
        # finished run returns without paying TRANSFORM/FACTORIZATION.
        cache_mats, cache_lowers, Zs = [], (), []
        if not resume_finished and start_it <= self.maxiter:
            cache_mats, cache_lowers, Zs = self.build_caches(
                X, dt, timer=timer)
        step_jit = jax.jit(self.make_step(n, k, dt, cache_lowers))

        def _save(it, carry, converged=False):
            with timer.phase("CHECKPOINT"):
                ckpt.save(it, list(carry),
                          {"identity": ident,
                           "identity_scheme": _IDENTITY_SCHEME,
                           "iteration": int(it),
                           "converged": bool(converged),
                           "tol": float(self.tol)})

        it = start_it - 1
        converged = False
        try:
            for it in [] if resume_finished else \
                    range(start_it, self.maxiter + 1):
                with timer.phase("ITERATIONS"):
                    carry, (objective, reldel) = step_jit(
                        carry, X, Y, cache_mats, Zs)
                    if timers_enabled():
                        jax.block_until_ready(carry)  # device time here
                if _telemetry_metrics.enabled():
                    _ADMM_ITERS.inc()
                    _ADMM_OBJECTIVE.set(float(objective))
                    _ADMM_RELDEL.set(float(reldel))
                model.coef = carry[0]
                if verbose:
                    msg = f"iteration {it} objective {float(objective):.6g}"
                    if Xv is not None:
                        with timer.phase("PREDICTION"):
                            acc = self._validate(model, Xv, Yv, regression)
                        msg += f" accuracy {acc:.4g}"
                    print(msg)
                # Convergence on relative change of the consensus iterate.
                # (The reference carries TOL but never reads it in the
                # train loop — here the knob is honored; set tol=0 to
                # force maxiter sweeps.)
                if self.tol > 0 and it > 1 and float(reldel) <= self.tol:
                    converged = True
                    break
                if ckpt is not None and _preemption_requested():
                    # preemption-safe drain: stop at this iteration
                    # boundary; the post-loop final save cuts the
                    # checkpoint and the finally's close() blocks until
                    # it is durable — a rerun resumes at it+1, bit-
                    # identical (see docs/resilience, the SIGTERM demo
                    # in examples/preemptible_training.py)
                    break
                if ckpt is not None and checkpoint_every > 0 \
                        and it % int(checkpoint_every) == 0 \
                        and it < self.maxiter:
                    _save(it, carry)

            if ckpt is not None and it >= start_it:
                _save(it, carry, converged)  # final (post-break/maxiter)
        finally:
            if ckpt is not None and ckpt_owned:
                ckpt.close()

        model.coef = carry[0]
        if timers_enabled():
            import sys

            timer.report(stream=sys.stdout)
        return model

    @staticmethod
    def _validate(model: HilbertModel, Xv, Yv, regression: bool) -> float:
        """Validation metric (ref: :509-538): relative L2 error for
        regression, percent accuracy for classification."""
        labels, DV = model.predict(jnp.asarray(Xv))
        Yv = np.asarray(Yv).reshape(-1)
        if regression:
            err = np.linalg.norm(np.asarray(DV).reshape(-1) - Yv)
            return float(err / max(np.linalg.norm(Yv), 1e-30))
        return float((np.asarray(labels) == Yv).mean() * 100.0)
