"""Label coding: one-vs-all ±1 dummy coding and argmax decoding.

TPU-native analog of ref: ml/coding.hpp:7-146 (``DummyCoding`` /
``DummyDecode``, local & distributed variants — here one jnp function covers
every layout).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np


def dummy_coding(
    labels, coding: Sequence = None, dtype=jnp.float32
) -> Tuple[jnp.ndarray, list]:
    """Labels (n,) → (n, k) matrix with +1 at the label's column, −1 elsewhere
    (ref: ml/coding.hpp:7-63). Returns (Y, coding) where ``coding`` lists the
    distinct label values in column order; pass it back in to reuse a coding
    computed on training data.
    """
    labels = np.asarray(labels).reshape(-1)
    if coding is None:
        coding = sorted(set(labels.tolist()))
    coding = list(coding)
    index = {v: i for i, v in enumerate(coding)}
    cols = np.array([index[v] for v in labels.tolist()], dtype=np.int32)
    Y = jnp.where(
        jnp.arange(len(coding))[None, :] == jnp.asarray(cols)[:, None], 1.0, -1.0
    ).astype(dtype)
    return Y, coding


def dummy_decode(Y: jnp.ndarray, coding: Sequence) -> np.ndarray:
    """(n, k) score matrix → (n,) labels by argmax over columns
    (ref: ml/coding.hpp:65-120)."""
    idx = np.asarray(jnp.argmax(jnp.asarray(Y), axis=1))
    coding = np.asarray(coding)
    return coding[idx]
