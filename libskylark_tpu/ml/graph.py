"""Graph algorithms: adjacency spectral embedding and seeded local community
detection via time-dependent personalized PageRank.

TPU-native analog of ref: ml/graph/spectral_embedding.hpp (ApproximateASE),
ml/graph/local_computations.hpp (TimeDependentPPR, FindLocalCluster), and the
driver-side graph container (ref: ml/skylark_community.cpp:20-95,
base/graph_adapters.hpp:6-29).

Division of labor mirrors the reference: the spectral embedding is bulk
linear algebra and runs through the randomized symmetric SVD on device; the
local diffusion is an inherently sequential queue-driven push algorithm over
a tiny active set ("all **local/sequential**", SURVEY.md §2.5) and runs on
host in numpy — putting it on the TPU would serialize scalar work through
the accelerator.
"""

from __future__ import annotations

import math
from typing import Dict, Hashable, Iterable, Optional, Set, Tuple

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context
from libskylark_tpu.nla.spectral import chebyshev_diff_matrix, chebyshev_points
from libskylark_tpu.nla.svd import ApproximateSVDParams, approximate_symmetric_svd


class Graph:
    """Undirected graph over hashable vertices
    (ref: ml/skylark_community.cpp:20-95 — adjacency via hash maps;
    ``num_edges`` counts both directions of every edge, i.e. the graph
    volume, matching the reference's ``_num_edges += 2`` per edge)."""

    def __init__(self, edges: Iterable[Tuple[Hashable, Hashable]] = ()):
        self._adj: Dict[Hashable, dict] = {}
        self._num_edges = 0
        for u, v in edges:
            self.add_edge(u, v)

    def add_edge(self, u, v) -> None:
        if u == v:
            return
        nu = self._adj.setdefault(u, {})
        if v in nu:
            return
        nu[v] = None  # dict as insertion-ordered set: O(1) membership
        self._adj.setdefault(v, {})[u] = None
        self._num_edges += 2

    @property
    def vertices(self) -> list:
        return list(self._adj.keys())

    def num_vertices(self) -> int:
        return len(self._adj)

    def num_edges(self) -> int:
        return self._num_edges

    def degree(self, v) -> int:
        return len(self._adj[v])

    def neighbors(self, v):
        return self._adj[v].keys()

    def has_vertex(self, v) -> bool:
        return v in self._adj

    def adjacency_matrix(self, dtype=np.float32):
        """Dense adjacency + index map (ref: GraphType::adjacency_matrix).
        Returns (A, indexmap) where indexmap[i] is the vertex of row i —
        the densified :meth:`adjacency_sparse` (one edge walk, one
        ordering contract)."""
        S, indexmap = self.adjacency_sparse(dtype)
        return S.to_scipy().toarray(), indexmap

    def adjacency_sparse(self, dtype=np.float32):
        """Sparse (CSC) adjacency + index map — the scalable operand for
        spectral embedding (the reference reads arc-lists into a
        sparse_vc_star matrix and never densifies,
        ref: utility/io/arc_list.hpp + ml/skylark_graph_se.cpp)."""
        from libskylark_tpu.base.sparse import SparseMatrix

        indexmap = self.vertices
        index = {v: i for i, v in enumerate(indexmap)}
        rows, cols = [], []
        for u, nbrs in self._adj.items():
            for v in nbrs:
                rows.append(index[u])
                cols.append(index[v])
        n = len(indexmap)
        vals = np.ones(len(rows), dtype)
        return SparseMatrix.from_coo(
            np.asarray(rows, np.int64), np.asarray(cols, np.int64),
            vals, (n, n)
        ), indexmap


def approximate_ase(
    G: Graph,
    k: int,
    context: Context,
    params: Optional[ApproximateSVDParams] = None,
    sparse: Optional[bool] = None,
):
    """Approximate Adjacency Spectral Embedding (Lyzinski et al.;
    ref: ml/graph/spectral_embedding.hpp:19-94): X = V·√|Λ| from the
    randomized symmetric eigendecomposition of the adjacency matrix.
    Returns (X, indexmap) with X (n, k) on device.

    ``sparse``: operate on the CSC adjacency without densifying (default:
    automatically for graphs past 2048 vertices)."""
    if sparse is None:
        sparse = len(G.vertices) > 2048
    if sparse:
        A, indexmap = G.adjacency_sparse()
    else:
        Ad, indexmap = G.adjacency_matrix()
        A = jnp.asarray(Ad)
    V, w = approximate_symmetric_svd(A, k, context, params)
    X = V * jnp.sqrt(jnp.abs(w))[None, :]
    return X, indexmap


# ---------------------------------------------------------------------------
# Time-dependent PPR (Avron & Horesh, "Community Detection Using
# Time-Dependent PageRank") — host-side push algorithm.
# ---------------------------------------------------------------------------

_N_CACHE: Dict[Tuple[float, float], int] = {}
_D_CACHE: Dict[Tuple[int, float], Tuple[np.ndarray, np.ndarray]] = {}


def _min_chebyshev_order(epsilon: float, gamma: float) -> int:
    """Smallest discretization order meeting the error bound
    (ref: local_computations.hpp:64-78 — Bessel-function tail bound)."""
    key = (epsilon, gamma)
    if key not in _N_CACHE:
        from scipy.special import iv

        minN = 10
        C = 20.0 * math.exp(-gamma / 2.0)
        while (
            C * math.sqrt(minN) * iv(minN, gamma) * 0.8**minN
            > epsilon / (gamma * (1 + (2 / math.pi) * math.log(minN - 1)))
        ):
            minN += 1
        _N_CACHE[key] = minN
    return _N_CACHE[key]


def _diffusion_matrix(N: int, gamma: float) -> Tuple[np.ndarray, np.ndarray]:
    """The push-step matrix D (ref: local_computations.hpp:85-118):
    QR-factor (D_cheb + I); the top N−1 rows of D apply R₁⁻¹Q₁ᵀ (the
    least-squares solve) and the last row holds Q's last column (the
    residual direction q). Returns (D, q)."""
    key = (N, gamma)
    if key not in _D_CACHE:
        D0, _ = chebyshev_diff_matrix(N, 0.0, gamma)
        D0 = D0 + np.eye(N)
        Q, R = np.linalg.qr(D0)
        q = Q[:, N - 1].copy()
        D = np.empty((N, N))
        D[N - 1, :] = q
        from scipy.linalg import solve_triangular

        D[: N - 1, :] = solve_triangular(
            R[: N - 1, : N - 1], Q[:, : N - 1].T
        )
        _D_CACHE[key] = (D, q)
    return _D_CACHE[key]


def time_dependent_ppr(
    G: Graph,
    s: Dict[Hashable, float],
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
):
    """Localized time-dependent personalized PageRank
    (ref: ml/graph/local_computations.hpp:50-265).

    ``s`` maps seed vertices to weights. Returns (y, x): ``y`` maps each
    touched vertex to its NX diffusion values at the time samples ``x``
    (descending Chebyshev samples in [0, gamma]).
    """
    minN = _min_chebyshev_order(epsilon, gamma)
    N = minN if minN % NX == 0 else (minN // NX + 1) * NX
    NR = N // NX

    D, q = _diffusion_matrix(N, gamma)
    x1 = chebyshev_points(N, 0.0, gamma)
    x = x1[np.arange(NX) * NR].copy()

    # Push threshold per node: B = C·deg (ref: :126-130).
    LC = 1 + (2 / math.pi) * math.log(N - 1)
    if alpha < 1:
        C = (1 - alpha) * epsilon / ((1 - math.exp((alpha - 1) * gamma)) * LC)
    else:
        C = epsilon / (gamma * LC)

    # State per node: [r (N), y (NX)] plus an in-queue flag.
    rymap: Dict[Hashable, np.ndarray] = {}
    inq: Dict[Hashable, bool] = {}
    from collections import deque

    violating = deque()

    def _entry(node):
        if node not in rymap:
            rymap[node] = np.zeros(N + NX)
            inq[node] = False
        return rymap[node]

    # Seed init (ref: :138-166).
    for node, v in s.items():
        if not G.has_vertex(node):
            raise errors.InvalidParametersError(f"seed {node!r} not in graph")
        ry = _entry(node)
        ry[:N] = -alpha * v
        ry[N:] = v
        inq[node] = True
        violating.append(node)
    for node in s:
        for onode in G.neighbors(node):
            _entry(onode)
    for node in s:
        ry = rymap[node]
        v = alpha * ry[N] / G.degree(node)
        for onode in G.neighbors(node):
            ro = rymap[onode]
            ro[:N] += v
            if not inq[onode] and np.any(np.abs(ro[:N]) > C * G.degree(onode)):
                violating.append(onode)
                inq[onode] = True

    # Main push loop (ref: :195-250).
    while violating:
        node = violating.popleft()
        ry = rymap[node]
        dyp = D @ ry[:N]
        ry[N:] += dyp[np.arange(NX) * NR]
        ry[:N] = dyp[N - 1] * q
        inq[node] = False

        c = alpha / G.degree(node)
        for onode in G.neighbors(node):
            ryo = _entry(onode)
            ryo[: N - 1] += c * dyp[: N - 1]
            if not inq[onode]:
                B = C * G.degree(onode)
                if np.any(np.abs(ryo[: N - 1]) > B) or abs(ryo[N - 1]) > B:
                    violating.append(onode)
                    inq[onode] = True

    y = {
        node: ry[N:].copy()
        for node, ry in rymap.items()
        if ry[N] != 0
    }
    return y, x


def find_local_cluster(
    G: Graph,
    seeds: Iterable[Hashable],
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
    recursive: bool = False,
) -> Tuple[Set, float]:
    """Seeded community detection by sweep-cut conductance minimization over
    the TD-PPR diffusion (ref: ml/graph/local_computations.hpp:288-374).
    Returns (cluster, conductance)."""
    currentcond = -1.0
    cluster: Set = set(seeds)
    Gvol = G.num_edges()

    while True:
        s = {v: 1.0 / len(cluster) for v in cluster}
        y, _ = time_dependent_ppr(G, s, alpha, gamma, epsilon, NX)

        improve = False
        for t in range(NX):
            # Sweep order: descending degree-normalized diffusion (ref: :313-322).
            vals = sorted(
                ((-yv[t] / G.degree(node), node) for node, yv in y.items()),
                key=lambda sv: sv[0],
            )
            volS, cutS = 0, 0
            bestcond, bestprefix = 1.0, 0
            currentset: Set = set()
            for i, (_, node) in enumerate(vals):
                volS += G.degree(node)
                for onode in G.neighbors(node):
                    cutS += -1 if onode in currentset else 1
                denom = min(volS, Gvol - volS)
                condS = cutS / denom if denom > 0 else 1.0
                if condS < bestcond:
                    bestcond, bestprefix = condS, i
                currentset.add(node)

            if currentcond == -1 or bestcond < 0.999999 * currentcond:
                improve = True
                cluster = {node for _, node in vals[: bestprefix + 1]}
                currentcond = bestcond

        if not (recursive and improve):
            break

    return cluster, currentcond
