"""Graph algorithms: adjacency spectral embedding and seeded local community
detection via time-dependent personalized PageRank.

TPU-native analog of ref: ml/graph/spectral_embedding.hpp (ApproximateASE),
ml/graph/local_computations.hpp (TimeDependentPPR, FindLocalCluster), and the
driver-side graph container (ref: ml/skylark_community.cpp:20-95,
base/graph_adapters.hpp:6-29).

Division of labor mirrors the reference: the spectral embedding is bulk
linear algebra and runs through the randomized symmetric SVD on device; the
local diffusion is an inherently sequential queue-driven push algorithm over
a tiny active set ("all **local/sequential**", SURVEY.md §2.5) and runs on
host in numpy — putting it on the TPU would serialize scalar work through
the accelerator.
"""

from __future__ import annotations

import math
from typing import Dict, Hashable, Iterable, Optional, Set, Tuple

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context
from libskylark_tpu.nla.spectral import chebyshev_diff_matrix, chebyshev_points
from libskylark_tpu.nla.svd import ApproximateSVDParams, approximate_symmetric_svd


class Graph:
    """Undirected graph over hashable vertices
    (ref: ml/skylark_community.cpp:20-95 — adjacency via hash maps;
    ``num_edges`` counts both directions of every edge, i.e. the graph
    volume, matching the reference's ``_num_edges += 2`` per edge)."""

    def __init__(self, edges: Iterable[Tuple[Hashable, Hashable]] = ()):
        self._adj: Dict[Hashable, dict] = {}
        self._num_edges = 0
        for u, v in edges:
            self.add_edge(u, v)

    def add_edge(self, u, v) -> None:
        if u == v:
            return
        nu = self._adj.setdefault(u, {})
        if v in nu:
            return
        nu[v] = None  # dict as insertion-ordered set: O(1) membership
        self._adj.setdefault(v, {})[u] = None
        self._num_edges += 2

    @property
    def vertices(self) -> list:
        return list(self._adj.keys())

    def num_vertices(self) -> int:
        return len(self._adj)

    def num_edges(self) -> int:
        return self._num_edges

    def degree(self, v) -> int:
        return len(self._adj[v])

    def neighbors(self, v):
        return self._adj[v].keys()

    def has_vertex(self, v) -> bool:
        return v in self._adj

    def adjacency_matrix(self, dtype=np.float32):
        """Dense adjacency + index map (ref: GraphType::adjacency_matrix).
        Returns (A, indexmap) where indexmap[i] is the vertex of row i —
        the densified :meth:`adjacency_sparse` (one edge walk, one
        ordering contract)."""
        S, indexmap = self.adjacency_sparse(dtype)
        return S.to_scipy().toarray(), indexmap

    def adjacency_sparse(self, dtype=np.float32):
        """Sparse (CSC) adjacency + index map — the scalable operand for
        spectral embedding (the reference reads arc-lists into a
        sparse_vc_star matrix and never densifies,
        ref: utility/io/arc_list.hpp + ml/skylark_graph_se.cpp)."""
        from libskylark_tpu.base.sparse import SparseMatrix

        indexmap = self.vertices
        index = {v: i for i, v in enumerate(indexmap)}
        rows, cols = [], []
        for u, nbrs in self._adj.items():
            for v in nbrs:
                rows.append(index[u])
                cols.append(index[v])
        n = len(indexmap)
        vals = np.ones(len(rows), dtype)
        return SparseMatrix.from_coo(
            np.asarray(rows, np.int64), np.asarray(cols, np.int64),
            vals, (n, n)
        ), indexmap


def approximate_ase(
    G: Graph,
    k: int,
    context: Context,
    params: Optional[ApproximateSVDParams] = None,
    sparse: Optional[bool] = None,
):
    """Approximate Adjacency Spectral Embedding (Lyzinski et al.;
    ref: ml/graph/spectral_embedding.hpp:19-94): X = V·√|Λ| from the
    randomized symmetric eigendecomposition of the adjacency matrix.
    Returns (X, indexmap) with X (n, k) on device.

    ``sparse``: operate on the CSC adjacency without densifying (default:
    automatically for graphs past 2048 vertices)."""
    if sparse is None:
        sparse = len(G.vertices) > 2048
    if sparse:
        A, indexmap = G.adjacency_sparse()
    else:
        Ad, indexmap = G.adjacency_matrix()
        A = jnp.asarray(Ad)
    V, w = approximate_symmetric_svd(A, k, context, params)
    X = V * jnp.sqrt(jnp.abs(w))[None, :]
    return X, indexmap


# ---------------------------------------------------------------------------
# Time-dependent PPR (Avron & Horesh, "Community Detection Using
# Time-Dependent PageRank") — host-side push algorithm.
# ---------------------------------------------------------------------------

_N_CACHE: Dict[Tuple[float, float], int] = {}
_D_CACHE: Dict[Tuple[int, float], Tuple[np.ndarray, np.ndarray]] = {}


def _min_chebyshev_order(epsilon: float, gamma: float) -> int:
    """Smallest discretization order meeting the error bound
    (ref: local_computations.hpp:64-78 — Bessel-function tail bound)."""
    key = (epsilon, gamma)
    if key not in _N_CACHE:
        from scipy.special import iv

        minN = 10
        C = 20.0 * math.exp(-gamma / 2.0)
        while (
            C * math.sqrt(minN) * iv(minN, gamma) * 0.8**minN
            > epsilon / (gamma * (1 + (2 / math.pi) * math.log(minN - 1)))
        ):
            minN += 1
        _N_CACHE[key] = minN
    return _N_CACHE[key]


def _diffusion_matrix(N: int, gamma: float) -> Tuple[np.ndarray, np.ndarray]:
    """The push-step matrix D (ref: local_computations.hpp:85-118):
    QR-factor (D_cheb + I); the top N−1 rows of D apply R₁⁻¹Q₁ᵀ (the
    least-squares solve) and the last row holds Q's last column (the
    residual direction q). Returns (D, q)."""
    key = (N, gamma)
    if key not in _D_CACHE:
        D0, _ = chebyshev_diff_matrix(N, 0.0, gamma)
        D0 = D0 + np.eye(N)
        Q, R = np.linalg.qr(D0)
        q = Q[:, N - 1].copy()
        D = np.empty((N, N))
        D[N - 1, :] = q
        from scipy.linalg import solve_triangular

        D[: N - 1, :] = solve_triangular(
            R[: N - 1, : N - 1], Q[:, : N - 1].T
        )
        _D_CACHE[key] = (D, q)
    return _D_CACHE[key]


def time_dependent_ppr(
    G: Graph,
    s: Dict[Hashable, float],
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
):
    """Localized time-dependent personalized PageRank
    (ref: ml/graph/local_computations.hpp:50-265).

    ``s`` maps seed vertices to weights. Returns (y, x): ``y`` maps each
    touched vertex to its NX diffusion values at the time samples ``x``
    (descending Chebyshev samples in [0, gamma]).
    """
    minN = _min_chebyshev_order(epsilon, gamma)
    N = minN if minN % NX == 0 else (minN // NX + 1) * NX
    NR = N // NX

    D, q = _diffusion_matrix(N, gamma)
    x1 = chebyshev_points(N, 0.0, gamma)
    x = x1[np.arange(NX) * NR].copy()

    # Push threshold per node: B = C·deg (ref: :126-130).
    LC = 1 + (2 / math.pi) * math.log(N - 1)
    if alpha < 1:
        C = (1 - alpha) * epsilon / ((1 - math.exp((alpha - 1) * gamma)) * LC)
    else:
        C = epsilon / (gamma * LC)

    # State per node: [r (N), y (NX)] plus an in-queue flag.
    rymap: Dict[Hashable, np.ndarray] = {}
    inq: Dict[Hashable, bool] = {}
    from collections import deque

    violating = deque()

    def _entry(node):
        if node not in rymap:
            rymap[node] = np.zeros(N + NX)
            inq[node] = False
        return rymap[node]

    # Seed init (ref: :138-166).
    for node, v in s.items():
        if not G.has_vertex(node):
            raise errors.InvalidParametersError(f"seed {node!r} not in graph")
        ry = _entry(node)
        ry[:N] = -alpha * v
        ry[N:] = v
        inq[node] = True
        violating.append(node)
    for node in s:
        for onode in G.neighbors(node):
            _entry(onode)
    for node in s:
        ry = rymap[node]
        v = alpha * ry[N] / G.degree(node)
        for onode in G.neighbors(node):
            ro = rymap[onode]
            ro[:N] += v
            if not inq[onode] and np.any(np.abs(ro[:N]) > C * G.degree(onode)):
                violating.append(onode)
                inq[onode] = True

    # Main push loop (ref: :195-250).
    while violating:
        node = violating.popleft()
        ry = rymap[node]
        dyp = D @ ry[:N]
        ry[N:] += dyp[np.arange(NX) * NR]
        ry[:N] = dyp[N - 1] * q
        inq[node] = False

        c = alpha / G.degree(node)
        for onode in G.neighbors(node):
            ryo = _entry(onode)
            ryo[: N - 1] += c * dyp[: N - 1]
            if not inq[onode]:
                B = C * G.degree(onode)
                if np.any(np.abs(ryo[: N - 1]) > B) or abs(ryo[N - 1]) > B:
                    violating.append(onode)
                    inq[onode] = True

    y = {
        node: ry[N:].copy()
        for node, ry in rymap.items()
        if ry[N] != 0
    }
    return y, x


# ---------------------------------------------------------------------------
# Pure, vmap-batchable serve endpoints (docs/qos "Heterogeneous serve
# endpoints"; served by engine/serve.py submit_graph_ase /
# submit_graph_ppr over the r18 sparse CSR lanes — adjacency matrices
# are exactly the sparse regime those lanes optimize).
# ---------------------------------------------------------------------------


def ase_serve_apply(key_data, data, indices, indptr, *, k: int,
                    iters: int, shape) -> jnp.ndarray:
    """One request's adjacency spectral embedding X = V.sqrt(|w|) as a
    pure function of a raw PRNG key and the padded CSR adjacency
    lanes: in-executable densify (the exact integer scatter), ``iters``
    rounds of QR subspace iteration from a key-derived Gaussian block,
    then the k x k Rayleigh-Ritz eigendecomposition. Every knob is
    static, rows past the true ``n`` are exact zero rows (zero-padded
    adjacency has zero rows/columns there, so the embedding's padded
    rows are exact zeros the executor slices off). Fixed iteration
    count — the convergence-adaptive diagnostic stays
    :func:`approximate_ase`; this is its serving-shaped twin."""
    import jax.random as jr

    from libskylark_tpu.sketch.sparse_serve import scatter_dense

    A = scatter_dense(data, indices, indptr, shape=tuple(shape))
    key = jr.wrap_key_data(jnp.asarray(key_data))
    Omega = jr.normal(key, (A.shape[1], k), A.dtype)
    Q, _ = jnp.linalg.qr(A @ Omega)
    for _ in range(max(int(iters), 1) - 1):
        Q, _ = jnp.linalg.qr(A @ Q)
    B = Q.T @ (A @ Q)
    B = 0.5 * (B + B.T)                # symmetrize roundoff
    w, U = jnp.linalg.eigh(B)
    order = jnp.argsort(-jnp.abs(w))   # dominant-|eigenvalue| first
    w = w[order]
    V = Q @ U[:, order]
    return V * jnp.sqrt(jnp.abs(w))[None, :]


def graph_ase_serve(A, k: int, *, seed: int = 0, iters: int = 2,
                    dtype=np.float32):
    """Eager twin of the ``graph_ase`` serve endpoint: pads the
    adjacency to its pow2 class and runs :func:`ase_serve_apply` on
    the identical operand bits — what a capacity-1 serve dispatch
    computes, as a plain call (the bit-equality reference the qos
    tests pin). ``A`` is a :class:`Graph`, a
    :class:`~libskylark_tpu.base.sparse.SparseMatrix`, or anything
    scipy-sparse-coercible. Returns the (n, k) embedding as a host
    array (plus the index map when ``A`` is a :class:`Graph`)."""
    S, indexmap = coerce_adjacency(A, dtype)
    X = _eager_csr_endpoint(
        S, dtype,
        lambda kd, lanes, shape: ase_serve_apply(
            kd, *lanes, k=int(k), iters=int(iters), shape=shape),
        seed=seed)[: S.height, :]
    return (X, indexmap) if indexmap is not None else X


def ppr_serve_apply(data, indices, indptr, s, *, alpha: float,
                    iters: int, shape) -> jnp.ndarray:
    """One request's personalized-PageRank vector by ``iters`` fixed
    power-iteration steps over the CSR adjacency:
    ``p <- (1-alpha) s + alpha W p`` with ``W`` the degree-normalized
    walk matrix. Deterministic, vmap-safe, zero-padding-exact (padded
    coordinates have zero degree — their normalizer clamps to 1 and
    their score stays the exact 0.0 the seed vector carries). The
    queue-driven time-dependent push solver
    (:func:`time_dependent_ppr`) remains the host-side diagnostic;
    this is the bulk serving-shaped variant."""
    from libskylark_tpu.sketch.sparse_serve import scatter_dense

    A = scatter_dense(data, indices, indptr, shape=tuple(shape))
    deg = jnp.sum(A, axis=0)
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1e-30), 0.0)
    total = jnp.maximum(jnp.sum(s), 1e-30)
    s = s / total
    p = s
    for _ in range(max(int(iters), 1)):
        p = (1.0 - alpha) * s + alpha * (A @ (p * inv_deg))
    return p


def graph_ppr_serve(A, s, *, alpha: float = 0.85, iters: int = 16,
                    dtype=np.float32):
    """Eager twin of the ``graph_ppr`` serve endpoint (same contract
    as :func:`graph_ase_serve`). ``s`` is the (n,) personalization
    vector in adjacency row order (build it from a seed dict with the
    :class:`Graph` index map)."""
    S, indexmap = coerce_adjacency(A, dtype)
    s = np.asarray(s, dtype=dtype)
    if s.shape != (S.height,):
        raise errors.InvalidParametersError(
            f"personalization vector shape {s.shape} != "
            f"({S.height},)")
    p = _eager_csr_endpoint(
        S, dtype,
        lambda kd, lanes, shape: ppr_serve_apply(
            *lanes, jnp.asarray(np.pad(s, (0, shape[0] - S.height))),
            alpha=float(alpha), iters=int(iters), shape=shape),
        seed=0)[: S.height]
    return (p, indexmap) if indexmap is not None else p


def coerce_adjacency(A, dtype=np.float32):
    """``(SparseMatrix adjacency, indexmap-or-None)`` from a
    :class:`Graph`, a SparseMatrix, scipy sparse, or a dense square
    array — the shared intake of the graph serve endpoints."""
    from libskylark_tpu.base.sparse import SparseMatrix

    if isinstance(A, Graph):
        S, indexmap = A.adjacency_sparse(dtype)
        return S, indexmap
    if isinstance(A, SparseMatrix):
        S = A
    else:
        try:
            import scipy.sparse as sp

            if sp.issparse(A):
                S = SparseMatrix.from_scipy(A)
            else:
                S = SparseMatrix.from_scipy(
                    sp.csr_matrix(np.asarray(A, dtype=dtype)))
        except ImportError:  # pragma: no cover - scipy is a hard dep
            raise errors.InvalidParametersError(
                "graph endpoints need a Graph/SparseMatrix/scipy "
                "operand") from None
    if S.height != S.width:
        raise errors.InvalidParametersError(
            f"adjacency must be square, got {S.shape}")
    return S, None


def _eager_csr_endpoint(S, dtype, fn, *, seed: int):
    """Shared eager-twin driver: pack ``S`` exactly as the serve
    layer's CSR lanes (pow2-padded dims, pow2 nnz class, monotone
    indptr padding) and run ``fn(key_data, (data, indices, indptr),
    shape)`` on the identical bits — under ``jax.jit``, so the twin
    executes the same compiled XLA program shape the serve flush does
    (eager op-by-op dispatch fuses differently at the last ulp)."""
    import jax
    import jax.random as jr

    from libskylark_tpu.base import env as _env
    from libskylark_tpu.engine import bucket as bucketing
    from libskylark_tpu.engine.serve import MicrobatchExecutor

    shape = bucketing.pad_shape(S.shape, (0, 1))
    nnz_cls = bucketing.nnz_class(S.nnz, _env.SPARSE_NNZ_FLOOR.get())
    # the serve layer's own packing — the bit-equality contract
    # depends on the twin's lanes being byte-identical to a serve
    # request's, so there must be exactly one implementation
    d, idx, ptr = MicrobatchExecutor._pack_csr(
        S, shape[0], nnz_cls, np.dtype(dtype))
    kd = np.asarray(jr.key_data(jr.key(int(seed))), dtype=np.uint32)
    run = jax.jit(lambda kd_, lanes: fn(kd_, lanes, shape))
    return np.asarray(run(kd, (jnp.asarray(d), jnp.asarray(idx),
                               jnp.asarray(ptr))))


def find_local_cluster(
    G: Graph,
    seeds: Iterable[Hashable],
    alpha: float = 0.85,
    gamma: float = 5.0,
    epsilon: float = 0.001,
    NX: int = 4,
    recursive: bool = False,
) -> Tuple[Set, float]:
    """Seeded community detection by sweep-cut conductance minimization over
    the TD-PPR diffusion (ref: ml/graph/local_computations.hpp:288-374).
    Returns (cluster, conductance)."""
    currentcond = -1.0
    cluster: Set = set(seeds)
    Gvol = G.num_edges()

    while True:
        s = {v: 1.0 / len(cluster) for v in cluster}
        y, _ = time_dependent_ppr(G, s, alpha, gamma, epsilon, NX)

        improve = False
        for t in range(NX):
            # Sweep order: descending degree-normalized diffusion (ref: :313-322).
            vals = sorted(
                ((-yv[t] / G.degree(node), node) for node, yv in y.items()),
                key=lambda sv: sv[0],
            )
            volS, cutS = 0, 0
            bestcond, bestprefix = 1.0, 0
            currentset: Set = set()
            for i, (_, node) in enumerate(vals):
                volS += G.degree(node)
                for onode in G.neighbors(node):
                    cutS += -1 if onode in currentset else 1
                denom = min(volS, Gvol - volS)
                condS = cutS / denom if denom > 0 else 1.0
                if condS < bestcond:
                    bestcond, bestprefix = condS, i
                currentset.add(node)

            if currentcond == -1 or bestcond < 0.999999 * currentcond:
                improve = True
                cluster = {node for _, node in vals[: bestprefix + 1]}
                currentcond = bestcond

        if not (recursive and improve):
            break

    return cluster, currentcond
