"""Kernels: Gram matrices and random-feature-map factories.

TPU-native analog of ref: ml/kernels.hpp (kernel_t interface :12-87,
kernel_container_t :89-176, linear_t :192, gaussian_t :243, polynomial_t :413,
laplacian_t :583, expsemigroup_t :748, matern_t :800).

Each kernel offers:
- ``gram(X, Y)`` — K[i,j] = k(xᵢ, yⱼ); rows are examples. One fused XLA
  expression replaces the reference's distance-matrix + EntrywiseMap pair;
  the 4 matrix-type overloads and symmetric_gram triangles collapse (computing
  half a Gram matrix saves nothing on the MXU).
- ``create_rft(S, context, tag)`` — random feature map factory
  (ref: kernel_t::create_rft tag dispatch) with tags "regular", "fast",
  "quasi", "sparse" (the reference's regular/fast/quasi feature-transform
  tags, sketch/transforms dispatch in ml/kernels.hpp:267-295).
- JSON (de)serialization matching the reference's ptree fields
  (ref: ml/kernels.hpp:249-258).

The reference leaves ``gram`` unimplemented ("TODO") for expsemigroup and
matern; here both get closed forms (the semigroup kernel from the Laplace
transform of the Lévy distribution underlying its RLT; Matérn via
half-integer closed forms or the general Bessel form on host).
"""

from __future__ import annotations

import json
import math
from typing import Any, Union

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Allocation, Context
from libskylark_tpu.base.distance import (
    euclidean_distance_matrix,
    l1_distance_matrix,
)

_KERNEL_REGISTRY: dict[str, type["Kernel"]] = {}


def _as_dense(X) -> jnp.ndarray:
    """Accept dense arrays or :class:`SparseMatrix` (distance-based Gram
    matrices are dense regardless, so sparse inputs densify on device;
    ref: ml/kernels.hpp gram overloads across matrix types)."""
    from libskylark_tpu.base.sparse import SparseMatrix

    if isinstance(X, SparseMatrix):
        return X.todense()
    return jnp.asarray(X)


def _inner_gram(X, Y=None) -> jnp.ndarray:
    """X·Yᵀ for the inner-product kernels (linear/polynomial), staying O(nnz)
    for :class:`SparseMatrix` inputs instead of densifying
    (ref: base/Gemm.hpp:335-519 sparse×dense kernels)."""
    from libskylark_tpu.base.sparse import SparseMatrix, spmm

    if isinstance(X, SparseMatrix):
        Yd = _as_dense(X if Y is None else Y)
        return spmm(X, Yd.T)             # (n, d)·(d, m)
    Xd = jnp.asarray(X)
    if isinstance(Y, SparseMatrix):
        return spmm(Y, Xd.T).T
    Yd = Xd if Y is None else jnp.asarray(Y)
    return Xd @ Yd.T


def _register(cls: type["Kernel"]) -> type["Kernel"]:
    _KERNEL_REGISTRY[cls.kernel_type] = cls
    return cls


class Kernel:
    """Kernel interface (ref: ml/kernels.hpp:12-87)."""

    kernel_type = "kernel"

    def __init__(self, N: int):
        self._N = int(N)

    @property
    def input_dim(self) -> int:
        """ref: kernel_t::get_dim."""
        return self._N

    def gram(self, X, Y=None) -> jnp.ndarray:
        """K[i,j] = k(X[i], Y[j]); Y defaults to X (the reference's
        symmetric_gram)."""
        raise errors.NotImplementedYetError(
            f"{self.kernel_type}: gram not implemented"
        )

    def symmetric_gram(self, X) -> jnp.ndarray:
        return self.gram(X, X)

    def create_rft(
        self,
        S: int,
        context: Union[Context, Allocation],
        tag: str = "regular",
    ):
        """Feature-map factory (ref: kernel_t::create_rft/create_qrft).
        Returns a SketchTransform whose rowwise apply maps (n, N) data to
        (n, S) features with E[Z·Zᵀ] ≈ gram."""
        raise errors.NotImplementedYetError(
            f"{self.kernel_type}: no feature map for tag {tag!r}"
        )

    # -- serialization (ref: ml/kernels.hpp to_ptree methods) --

    def _extra_params(self) -> dict[str, Any]:
        return {}

    def to_dict(self) -> dict[str, Any]:
        d = {
            "skylark_object_type": "kernel",
            "kernel_type": self.kernel_type,
            "N": self._N,
        }
        d.update(self._extra_params())
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def __repr__(self) -> str:
        ps = ", ".join(f"{k}={v}" for k, v in self._extra_params().items())
        return f"{type(self).__name__}(N={self._N}{', ' + ps if ps else ''})"


def _bad_tag(kernel: "Kernel", tag: str):
    return errors.InvalidParametersError(
        f"{kernel.kernel_type} kernel has no {tag!r} feature transform"
    )


@_register
class Linear(Kernel):
    """k(x,y) = ⟨x,y⟩ (ref: ml/kernels.hpp:192-240). Feature maps are plain
    sketches: JLT (regular), FJLT (fast), CWT (sparse)."""

    kernel_type = "linear"

    def gram(self, X, Y=None):
        return _inner_gram(X, Y)

    def create_rft(self, S, context, tag="regular"):
        from libskylark_tpu import sketch as sk

        if tag == "regular":
            return sk.JLT(self._N, S, context)
        if tag == "fast":
            return sk.FJLT(self._N, S, context)
        if tag == "sparse":
            return sk.CWT(self._N, S, context)
        raise _bad_tag(self, tag)


@_register
class Gaussian(Kernel):
    """k(x,y) = exp(−‖x−y‖²/(2σ²)) (ref: ml/kernels.hpp:243-410)."""

    kernel_type = "gaussian"

    def __init__(self, N: int, sigma: float = 1.0):
        super().__init__(N)
        self._sigma = float(sigma)

    @property
    def sigma(self) -> float:
        return self._sigma

    def gram(self, X, Y=None):
        X = _as_dense(X)
        Y = X if Y is None else _as_dense(Y)
        D = euclidean_distance_matrix(X, Y)
        return jnp.exp(-D / (2.0 * self._sigma**2))

    def create_rft(self, S, context, tag="regular"):
        from libskylark_tpu import sketch as sk

        if tag == "regular":
            return sk.GaussianRFT(self._N, S, context, sigma=self._sigma)
        if tag == "fast":
            return sk.FastGaussianRFT(self._N, S, context, sigma=self._sigma)
        if tag == "quasi":
            return sk.GaussianQRFT(self._N, S, context, sigma=self._sigma)
        raise _bad_tag(self, tag)

    def _extra_params(self):
        return {"sigma": self._sigma}


@_register
class Polynomial(Kernel):
    """k(x,y) = (γ⟨x,y⟩ + c)^q (ref: ml/kernels.hpp:413-580); feature map =
    TensorSketch (PPT)."""

    kernel_type = "polynomial"

    def __init__(self, N: int, q: int = 2, c: float = 1.0, gamma: float = 1.0):
        super().__init__(N)
        self._q = int(q)
        self._c = float(c)
        self._gamma = float(gamma)

    def gram(self, X, Y=None):
        return (self._gamma * _inner_gram(X, Y) + self._c) ** self._q

    def create_rft(self, S, context, tag="regular"):
        from libskylark_tpu import sketch as sk

        if tag in ("regular", "fast"):
            return sk.PPT(
                self._N, S, context, q=self._q, c=self._c, gamma=self._gamma
            )
        raise _bad_tag(self, tag)

    def _extra_params(self):
        return {"q": self._q, "c": self._c, "gamma": self._gamma}


@_register
class Laplacian(Kernel):
    """k(x,y) = exp(−‖x−y‖₁/σ) (ref: ml/kernels.hpp:583-744)."""

    kernel_type = "laplacian"

    def __init__(self, N: int, sigma: float = 1.0):
        super().__init__(N)
        self._sigma = float(sigma)

    def gram(self, X, Y=None):
        X = _as_dense(X)
        Y = X if Y is None else _as_dense(Y)
        D = l1_distance_matrix(X, Y)
        return jnp.exp(-D / self._sigma)

    def create_rft(self, S, context, tag="regular"):
        from libskylark_tpu import sketch as sk

        if tag == "regular":
            return sk.LaplacianRFT(self._N, S, context, sigma=self._sigma)
        if tag == "quasi":
            return sk.LaplacianQRFT(self._N, S, context, sigma=self._sigma)
        raise _bad_tag(self, tag)

    def _extra_params(self):
        return {"sigma": self._sigma}


@_register
class ExpSemigroup(Kernel):
    """Exponential semigroup kernel on R₊: k(x,y) = exp(−β·Σᵢ√(xᵢ+yᵢ))
    (ref: ml/kernels.hpp:748-798; gram is TODO in the reference — this closed
    form is the Laplace transform of the scaled Lévy distribution the RLT
    samples from, E[e^{−w·s}] = e^{−β√s} for w ~ (β²/2)·StandardLevy)."""

    kernel_type = "expsemigroup"

    def __init__(self, N: int, beta: float = 1.0):
        super().__init__(N)
        self._beta = float(beta)

    def gram(self, X, Y=None):
        X = _as_dense(X)
        Y = X if Y is None else _as_dense(Y)
        S = jnp.sqrt(jnp.maximum(X[:, None, :] + Y[None, :, :], 0.0))
        return jnp.exp(-self._beta * jnp.sum(S, axis=-1))

    def create_rft(self, S, context, tag="regular"):
        from libskylark_tpu import sketch as sk

        if tag == "regular":
            return sk.ExpSemigroupRLT(self._N, S, context, beta=self._beta)
        if tag == "quasi":
            return sk.ExpSemigroupQRLT(self._N, S, context, beta=self._beta)
        raise _bad_tag(self, tag)

    def _extra_params(self):
        return {"beta": self._beta}


@_register
class Matern(Kernel):
    """Matérn kernel k(r) = 2^{1−ν}/Γ(ν) · (√(2ν)·r/l)^ν · K_ν(√(2ν)·r/l)
    (ref: ml/kernels.hpp:800-846; gram is TODO in the reference).

    Half-integer ν ∈ {1/2, 3/2, 5/2} use the standard closed forms (pure XLA,
    jittable); other ν fall back to scipy's Bessel K_ν on host — hence the
    half-integer default."""

    kernel_type = "matern"

    def __init__(self, N: int, nu: float = 1.5, l: float = 1.0):
        super().__init__(N)
        self._nu = float(nu)
        self._l = float(l)

    def gram(self, X, Y=None):
        X = _as_dense(X)
        Y = X if Y is None else _as_dense(Y)
        r = jnp.sqrt(euclidean_distance_matrix(X, Y))
        nu, l = self._nu, self._l
        if nu == 0.5:
            return jnp.exp(-r / l)
        if nu == 1.5:
            s = math.sqrt(3.0) * r / l
            return (1.0 + s) * jnp.exp(-s)
        if nu == 2.5:
            s = math.sqrt(5.0) * r / l
            return (1.0 + s + s * s / 3.0) * jnp.exp(-s)
        try:
            from scipy.special import gamma as _gamma, kv as _kv
        except ImportError as e:  # pragma: no cover
            raise errors.NotImplementedYetError(
                f"Matern gram with non-half-integer nu={nu} needs scipy"
            ) from e
        rh = np.asarray(r, dtype=np.float64)
        s = np.sqrt(2.0 * nu) * rh / l
        tiny = np.finfo(np.float64).tiny
        s = np.maximum(s, tiny ** 0.25)
        K = (2.0 ** (1.0 - nu) / _gamma(nu)) * (s**nu) * _kv(nu, s)
        K[rh <= 0] = 1.0
        return jnp.asarray(K, dtype=r.dtype)

    def create_rft(self, S, context, tag="regular"):
        from libskylark_tpu import sketch as sk

        if tag == "regular":
            return sk.MaternRFT(self._N, S, context, nu=self._nu, l=self._l)
        if tag == "fast":
            return sk.FastMaternRFT(self._N, S, context, nu=self._nu, l=self._l)
        raise _bad_tag(self, tag)

    def _extra_params(self):
        return {"nu": self._nu, "l": self._l}


def deserialize_kernel(obj: Union[str, dict[str, Any]]) -> Kernel:
    """Reconstruct a kernel from JSON (the analog of the reference's
    kernel_container_t type erasure + ptree fields)."""
    d = json.loads(obj) if isinstance(obj, str) else dict(obj)
    ktype = d.get("kernel_type")
    cls = _KERNEL_REGISTRY.get(ktype)
    if cls is None:
        raise errors.InvalidParametersError(f"unknown kernel type {ktype!r}")
    kwargs = {
        k: v
        for k, v in d.items()
        if k not in ("skylark_object_type", "kernel_type", "N", "skylark_version")
    }
    return cls(int(d["N"]), **kwargs)


def make_kernel(kernel_type: str, N: int, **kwargs) -> Kernel:
    """Factory by name (the analog of the reference CLI's KernelType enum,
    ref: ml/options.hpp:41-45)."""
    cls = _KERNEL_REGISTRY.get(kernel_type)
    if cls is None:
        raise errors.InvalidParametersError(f"unknown kernel type {kernel_type!r}")
    return cls(N, **kwargs)


KERNELS = _KERNEL_REGISTRY
