"""Kernel ridge regression — the 5-regime solver family.

TPU-native analog of ref: ml/krr.hpp:6-690:

=============================  ==============================================
``kernel_ridge``               exact Gram + Cholesky solve (:47-90)
``approximate_kernel_ridge``   random features + (optionally sketched) ridge
                               regression (:92-196)
``sketched_approximate_kernel_ridge``
                               features computed in splits, each sketched
                               down before the solve — memory-bounded
                               (:197-309)
``faster_kernel_ridge``        exact Gram solved by CG with a random-features
                               preconditioner applied via Sherman-Morrison-
                               Woodbury (:310-499)
``large_scale_kernel_ridge``   block coordinate descent over split feature
                               maps with cached Cholesky factors (:500-690)
=============================  ==============================================

Convention: rows are examples — X is (n, d), Y is (n, t); feature maps apply
ROWWISE giving Z (n, s); W is (s, t); Gram coefficients A are (n, t). This is
the reference's ``direction == base::ROWS`` orientation; the COLUMNS variant
is a transpose away and not duplicated.

Every regime runs as ONE compiled program per (shapes, feature maps,
solver knobs) class, served from the :mod:`libskylark_tpu.engine`
executable cache — the feature maps are allocated eagerly (they are
part of the returned model and advance the Context counter exactly
once), then the solve itself is a single device dispatch. Iterative
regimes keep their convergence state device-resident: ``faster_``'s PCG
is the :func:`libskylark_tpu.algorithms.krylov.cg` ``lax.while_loop``,
and ``large_scale_``'s BCD sweeps are a ``lax.while_loop`` whose carry
holds the block solutions, the residual, and the relative-update scalar
— zero host round-trips per iteration (the old implementation synced
``float(jnp.sum(...))`` on every sweep)."""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
from jax import lax

from libskylark_tpu import engine
from libskylark_tpu.algorithms.krylov import KrylovParams, cg
from libskylark_tpu.algorithms.precond import FunctionPrecond, IdPrecond
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.ml.kernels import Kernel
from libskylark_tpu.base.precision import with_solver_precision


@dataclasses.dataclass
class KrrParams(Params):
    """ref: ml/krr.hpp:6-44 krr_params_t."""

    use_fast: bool = False          # fast feature transforms (FJLT/Fastfood)
    sketched_rr: bool = False       # sketch the regression problem
    sketch_size: int = -1           # -1 -> 4*s
    fast_sketch: bool = False       # CWT instead of FJLT for the sketch
    iter_lim: int = 1000
    res_print: int = 10
    tolerance: float = 1e-3
    max_split: int = 0              # feature-split bound (0 = input dim)


def _feature_tag(params: KrrParams) -> str:
    return "fast" if params.use_fast else "regular"


def _ridge_solve(Z: jnp.ndarray, Y: jnp.ndarray, lam) -> jnp.ndarray:
    """W = argmin ‖Z·W − Y‖²_F + λ‖W‖²_F (the El::Ridge(√λ) analog)."""
    s = Z.shape[1]
    G = Z.T @ Z + lam * jnp.eye(s, dtype=Z.dtype)
    L = jsl.cholesky(G, lower=True)
    return jsl.cho_solve((L, True), Z.T @ Y)


def _split_sizes(s: int, d: int, max_split: int) -> list[int]:
    """Feature-split schedule (ref: ml/krr.hpp:246-248,527-529): chunks of
    ``sinc`` = max_split/2 (or d when unbounded), final chunk absorbing up to
    2·sinc."""
    sinc = d if max_split == 0 else max(1, max_split // 2)
    sizes, remains = [], s
    while remains > 0:
        thiss = remains if remains <= 2 * sinc else sinc
        sizes.append(thiss)
        remains -= thiss
    return sizes


def _is_tracer(*arrays) -> bool:
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _run_compiled(fn, name, extras, X, Y, lam):
    """One-executable dispatch of a solver program closed over its
    (eagerly allocated) feature maps. Inside a user jit the program is
    inlined — the outer trace owns compilation; otherwise the global
    executable cache serves it, keyed on the closure's collaborator
    digests (``extras``) rather than closure object identity, so two
    calls with feature maps of the same (seed, counter) share one
    executable. Operand donation is opt-in (donate="auto")."""
    lam = jnp.asarray(lam, X.dtype)
    if _is_tracer(X, Y, lam):
        return fn(X, Y, lam)
    cf = engine.compiled(fn, name=name, donate_argnums=(0, 1),
                         donate="auto",
                         key_fn=lambda *a, **k: extras)
    return cf(X, Y, lam)


@with_solver_precision
def kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    params: Optional[KrrParams] = None,
) -> jnp.ndarray:
    """Exact KRR: A = (K + λI)⁻¹·Y via Cholesky (ref: ml/krr.hpp:47-90
    SymmetricGram + HPDSolve). Predict with gram(X_new, X) @ A."""
    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    params.log(1, "kernel_ridge: solving (K + lambda I) A = Y")

    def solve(X, Y, lam):
        n = X.shape[0]
        K = k.symmetric_gram(X) + lam * jnp.eye(n, dtype=X.dtype)
        L = jsl.cholesky(K, lower=True)
        return jsl.cho_solve((L, True), Y)

    return _run_compiled(solve, "kernel_ridge", (engine.digest(k),),
                         X, Y, lam)


def krr_predict_kernel(k: Kernel, X_new: jnp.ndarray,
                       X_train: jnp.ndarray, A: jnp.ndarray) -> jnp.ndarray:
    """The KRR predict program — cross-gram times the fitted Gram
    coefficients — as one pure traceable function. Rows of ``X_new``
    are independent (the cross-gram is computed row-by-row), so
    zero-padding the query rows only appends garbage predictions that
    the caller slices off: the serving layer vmaps THIS function over a
    padded query batch with the model (``X_train``, ``A``) broadcast."""
    return k.gram(X_new, X_train) @ A


@with_solver_precision
def krr_predict(k: Kernel, X_new: jnp.ndarray, X_train: jnp.ndarray,
                A: jnp.ndarray) -> jnp.ndarray:
    """Predict with a :func:`kernel_ridge` model: gram(X_new, X) @ A
    (ref: ml/krr.hpp:47-90 — the serving half of the exact regime).
    Eager calls run as one engine-compiled executable keyed on the
    kernel's hyperparameter digest; inside a user jit the program
    inlines into the outer trace."""
    X_new = jnp.asarray(X_new)
    X_train = jnp.asarray(X_train)
    A = jnp.asarray(A)
    squeeze = A.ndim == 1
    if squeeze:
        A = A[:, None]

    def run(X_new, X_train, A):
        return krr_predict_kernel(k, X_new, X_train, A)

    if _is_tracer(X_new, X_train, A):
        out = run(X_new, X_train, A)
    else:
        cf = engine.compiled(run, name="krr_predict",
                             key_fn=lambda *a: (engine.digest(k),))
        out = cf(X_new, X_train, A)
    return out[:, 0] if squeeze else out


@with_solver_precision
def approximate_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    params: Optional[KrrParams] = None,
):
    """Random-features KRR (ref: ml/krr.hpp:92-196): Z = S(X) with an
    s-feature map, then ridge-solve for W — optionally after sketching the
    (n, s) regression down to (t, s) rows with FJLT (or CWT when
    ``fast_sketch``). Returns (S, W); predict with S.apply(X_new, ROWWISE) @ W.
    """
    from libskylark_tpu import sketch as sk

    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    S = k.create_rft(s, context, _feature_tag(params))
    if params.sketched_rr:
        n = X.shape[0]
        t = 4 * s if params.sketch_size == -1 else params.sketch_size
        R = (
            sk.CWT(n, t, context)
            if params.fast_sketch
            else sk.FJLT(n, t, context)
        )
    else:
        R = None

    def solve(X, Y, lam):
        Z = S.apply(X, sk.ROWWISE)
        if R is not None:
            SZ = R.apply(Z, sk.COLUMNWISE)
            SY = R.apply(Y, sk.COLUMNWISE)
        else:
            SZ, SY = Z, Y
        return _ridge_solve(SZ, SY, lam)

    W = _run_compiled(
        solve, "approximate_kernel_ridge",
        (engine.digest(S), None if R is None else engine.digest(R)),
        X, Y, lam)
    return S, W


@with_solver_precision
def sketched_approximate_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    t: int = -1,
    params: Optional[KrrParams] = None,
):
    """Memory-bounded variant (ref: ml/krr.hpp:197-309): the s features are
    produced by a list of split maps (each scaled by √(s_c/s)); each block is
    immediately compressed by a shared row sketch R to t rows, so the full
    (n, s) feature matrix never exists. Returns (transforms, W); at predict
    time apply each map, scale by √(s_c/s), and concatenate (``scale_maps``
    is always true here — the reference returns it as a flag)."""
    from libskylark_tpu import sketch as sk

    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    n, d = X.shape
    t = 4 * s if t == -1 else t

    R = sk.CWT(n, t, context) if params.fast_sketch else sk.FJLT(n, t, context)
    transforms = [
        k.create_rft(thiss, context, _feature_tag(params))
        for thiss in _split_sizes(s, d, params.max_split)
    ]

    def solve(X, Y, lam):
        SY = R.apply(Y, sk.COLUMNWISE)
        blocks = []
        for S in transforms:
            Z = S.apply(X, sk.ROWWISE) * math.sqrt(S.sketch_dim / s)
            blocks.append(R.apply(Z, sk.COLUMNWISE))  # (t, s_c)
        SZ = jnp.concatenate(blocks, axis=1)  # (t, s)
        return _ridge_solve(SZ, SY, lam)

    W = _run_compiled(
        solve, "sketched_approximate_kernel_ridge",
        (engine.digest(R),) + tuple(engine.digest(S) for S in transforms),
        X, Y, lam)
    return transforms, W


class FeatureMapPrecond(FunctionPrecond):
    """Random-features preconditioner for (K + λI)
    (ref: ml/krr.hpp:310-398 feature_map_precond_t): with U = (s, n) features,
    approximate K ≈ UᵀU, so apply (λI + UᵀU)⁻¹ via SMW:
    P(B) = B/λ − Uᵀ·(I + U·Uᵀ/λ)⁻¹·(U·B)/λ².

    :meth:`from_features` builds the same preconditioner from an
    already-applied feature matrix — the form the compiled
    ``faster_kernel_ridge`` pipeline uses inside its trace (the SMW
    algebra lives HERE, once).
    """

    def __init__(self, k, lam, X, s, context, use_fast: bool = False):
        from libskylark_tpu import sketch as sk

        X = jnp.asarray(X)
        S = k.create_rft(s, context, "fast" if use_fast else "regular")
        self._init_from_features(S.apply(X, sk.ROWWISE).T, lam)

    @classmethod
    def from_features(cls, U: jnp.ndarray, lam) -> "FeatureMapPrecond":
        """Preconditioner from a pre-computed (s, n) feature matrix."""
        self = cls.__new__(cls)
        self._init_from_features(U, lam)
        return self

    def _init_from_features(self, U: jnp.ndarray, lam) -> None:
        C = jnp.eye(U.shape[0], dtype=U.dtype) + (U @ U.T) / lam
        L = jsl.cholesky(C, lower=True)

        def apply(B):
            CUB = jsl.cho_solve((L, True), U @ B)
            return B / lam - (U.T @ CUB) / (lam * lam)

        FunctionPrecond.__init__(self, apply)
        self.U = U
        self.L = L
        self.lam = lam


@with_solver_precision
def faster_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    params: Optional[KrrParams] = None,
) -> jnp.ndarray:
    """Exact-Gram KRR solved by preconditioned CG with the random-features
    SMW preconditioner (ref: ml/krr.hpp:400-499). ``s == 0`` falls back to
    unpreconditioned CG. Returns A = (K + λI)⁻¹·Y.

    The whole solve — feature-map apply, SMW factor, Gram build, and the
    PCG ``lax.while_loop`` — is one compiled program: convergence state
    lives on device and no scalar crosses the host boundary per
    iteration."""
    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    S = (None if s == 0
         else k.create_rft(s, context, _feature_tag(params)))
    cg_params = KrylovParams(
        tolerance=params.tolerance, iter_lim=params.iter_lim
    )

    def solve(X, Y, lam):
        from libskylark_tpu import sketch as sk

        n = X.shape[0]
        K = k.symmetric_gram(X) + lam * jnp.eye(n, dtype=X.dtype)
        if S is None:
            P = IdPrecond()
        else:
            P = FeatureMapPrecond.from_features(
                S.apply(X, sk.ROWWISE).T, lam)
        A, _ = cg(K, Y, cg_params, precond=P)
        return A

    return _run_compiled(
        solve, "faster_kernel_ridge",
        (engine.digest(k), None if S is None else engine.digest(S),
         cg_params.tolerance, cg_params.iter_lim),
        X, Y, lam)


def _bcd_program(transforms, iter_lim: int, tolerance: float):
    """The block-coordinate-descent solve (ref: ml/krr.hpp:500-690) as
    one traceable program ``run(X, Y, lam) -> (W, iters, reldel)``;
    ``lam`` is a runtime argument (executables serve every λ).

    First sweep builds and caches the per-block Cholesky factors; the
    remaining sweeps are a ``lax.while_loop`` whose carry holds the
    block solutions, the residual, the sweep counter, and the
    relative-update scalar — the convergence test happens on device, so
    the loop makes zero host round-trips (the regression test traces
    this program end-to-end to prove it)."""
    from libskylark_tpu import sketch as sk

    def run(X, Y, lam):
        dt = X.dtype
        t = Y.shape[1]
        W0 = tuple(jnp.zeros((S.sketch_dim, t), dtype=dt)
                   for S in transforms)

        # First sweep: build + cache Cholesky factors (ref: :568-612).
        Ls = []
        W, R = [], Y
        for c, S in enumerate(transforms):
            Z = S.apply(X, sk.ROWWISE)  # (n, s_c)
            G = Z.T @ Z + lam * jnp.eye(Z.shape[1], dtype=dt)
            L = jsl.cholesky(G, lower=True)
            Ls.append(L)
            ZR = Z.T @ R - lam * W0[c]
            delW = jsl.cho_solve((L, True), ZR)
            W.append(W0[c] + delW)
            R = R - Z @ delW
        W = tuple(W)

        # More sweeps with cached factors (ref: :625-682), device-resident.
        def body(state):
            W, R, it, _ = state
            delsize = jnp.zeros((), dt)
            out = []
            for c, S in enumerate(transforms):
                Z = S.apply(X, sk.ROWWISE)
                ZR = Z.T @ R - lam * W[c]
                delW = jsl.cho_solve((Ls[c], True), ZR)
                out.append(W[c] + delW)
                R = R - Z @ delW
                delsize = delsize + jnp.sum(delW * delW)
            wnorm = jnp.sqrt(sum(jnp.sum(w * w) for w in out))
            reldel = jnp.sqrt(delsize) / jnp.maximum(wnorm, 1e-30)
            return (tuple(out), R, it + 1, reldel)

        def cond(state):
            _, _, it, reldel = state
            return (it < iter_lim) & (reldel >= tolerance)

        W, R, it, reldel = lax.while_loop(
            cond, body,
            (W, R, jnp.int32(1), jnp.asarray(jnp.inf, dt)))
        return jnp.concatenate(W, axis=0), it, reldel

    return run


@with_solver_precision
def large_scale_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    params: Optional[KrrParams] = None,
):
    """Block coordinate descent over split feature maps
    (ref: ml/krr.hpp:500-690): per block c, cache L_c = chol(Z_cᵀZ_c + λI) on
    the first sweep, then iterate
    ΔW_c = L_c⁻ᵀL_c⁻¹·(Z_cᵀR − λW_c),  W_c += ΔW_c,  R −= Z_c·ΔW_c
    until the relative update falls below tolerance. The feature maps are
    regenerated from their (seed, counter) every sweep instead of being stored
    — the reference's memory-saving trick, which the counter-based RNG makes
    free. Returns (transforms, W) with W the concatenated block solution;
    predict by applying each map in order and multiplying the stacked
    features with W.

    The sweeps run as one compiled ``lax.while_loop`` program
    (:func:`_bcd_program`) — convergence is decided on device and only
    the final (solution, iteration count) crosses back to the host."""
    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    n, d = X.shape

    transforms = [
        k.create_rft(thiss, context, _feature_tag(params))
        for thiss in _split_sizes(s, d, params.max_split)
    ]

    run = _bcd_program(transforms, int(params.iter_lim),
                       float(params.tolerance))
    W, it, reldel = _run_compiled(
        run, "large_scale_kernel_ridge",
        tuple(engine.digest(S) for S in transforms)
        + (int(params.iter_lim), float(params.tolerance)),
        X, Y, lam)
    if not _is_tracer(it):
        # post-solve reporting only — the loop itself never synced
        params.log(2, f"large_scale_krr: {int(it)} sweeps, "
                      f"relupdate = {float(reldel):.2e}")
    return transforms, W
