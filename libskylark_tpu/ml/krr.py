"""Kernel ridge regression — the 5-regime solver family.

TPU-native analog of ref: ml/krr.hpp:6-690:

=============================  ==============================================
``kernel_ridge``               exact Gram + Cholesky solve (:47-90)
``approximate_kernel_ridge``   random features + (optionally sketched) ridge
                               regression (:92-196)
``sketched_approximate_kernel_ridge``
                               features computed in splits, each sketched
                               down before the solve — memory-bounded
                               (:197-309)
``faster_kernel_ridge``        exact Gram solved by CG with a random-features
                               preconditioner applied via Sherman-Morrison-
                               Woodbury (:310-499)
``large_scale_kernel_ridge``   block coordinate descent over split feature
                               maps with cached Cholesky factors (:500-690)
=============================  ==============================================

Convention: rows are examples — X is (n, d), Y is (n, t); feature maps apply
ROWWISE giving Z (n, s); W is (s, t); Gram coefficients A are (n, t). This is
the reference's ``direction == base::ROWS`` orientation; the COLUMNS variant
is a transpose away and not duplicated.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from libskylark_tpu.algorithms.krylov import KrylovParams, cg
from libskylark_tpu.algorithms.precond import FunctionPrecond, IdPrecond
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.ml.kernels import Kernel
from libskylark_tpu.base.precision import with_solver_precision


@dataclasses.dataclass
class KrrParams(Params):
    """ref: ml/krr.hpp:6-44 krr_params_t."""

    use_fast: bool = False          # fast feature transforms (FJLT/Fastfood)
    sketched_rr: bool = False       # sketch the regression problem
    sketch_size: int = -1           # -1 -> 4*s
    fast_sketch: bool = False       # CWT instead of FJLT for the sketch
    iter_lim: int = 1000
    res_print: int = 10
    tolerance: float = 1e-3
    max_split: int = 0              # feature-split bound (0 = input dim)


def _feature_tag(params: KrrParams) -> str:
    return "fast" if params.use_fast else "regular"


def _ridge_solve(Z: jnp.ndarray, Y: jnp.ndarray, lam: float) -> jnp.ndarray:
    """W = argmin ‖Z·W − Y‖²_F + λ‖W‖²_F (the El::Ridge(√λ) analog)."""
    s = Z.shape[1]
    G = Z.T @ Z + lam * jnp.eye(s, dtype=Z.dtype)
    L = jsl.cholesky(G, lower=True)
    return jsl.cho_solve((L, True), Z.T @ Y)


def _split_sizes(s: int, d: int, max_split: int) -> list[int]:
    """Feature-split schedule (ref: ml/krr.hpp:246-248,527-529): chunks of
    ``sinc`` = max_split/2 (or d when unbounded), final chunk absorbing up to
    2·sinc."""
    sinc = d if max_split == 0 else max(1, max_split // 2)
    sizes, remains = [], s
    while remains > 0:
        thiss = remains if remains <= 2 * sinc else sinc
        sizes.append(thiss)
        remains -= thiss
    return sizes


@with_solver_precision
def kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    params: Optional[KrrParams] = None,
) -> jnp.ndarray:
    """Exact KRR: A = (K + λI)⁻¹·Y via Cholesky (ref: ml/krr.hpp:47-90
    SymmetricGram + HPDSolve). Predict with gram(X_new, X) @ A."""
    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    n = X.shape[0]
    K = k.symmetric_gram(X) + lam * jnp.eye(n, dtype=X.dtype)
    params.log(1, "kernel_ridge: solving (K + lambda I) A = Y")
    L = jsl.cholesky(K, lower=True)
    return jsl.cho_solve((L, True), Y if Y.ndim > 1 else Y[:, None])


@with_solver_precision
def approximate_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    params: Optional[KrrParams] = None,
):
    """Random-features KRR (ref: ml/krr.hpp:92-196): Z = S(X) with an
    s-feature map, then ridge-solve for W — optionally after sketching the
    (n, s) regression down to (t, s) rows with FJLT (or CWT when
    ``fast_sketch``). Returns (S, W); predict with S.apply(X_new, ROWWISE) @ W.
    """
    from libskylark_tpu import sketch as sk

    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    S = k.create_rft(s, context, _feature_tag(params))
    Z = S.apply(X, sk.ROWWISE)

    if params.sketched_rr:
        n = Z.shape[0]
        t = 4 * s if params.sketch_size == -1 else params.sketch_size
        R = (
            sk.CWT(n, t, context)
            if params.fast_sketch
            else sk.FJLT(n, t, context)
        )
        SZ = R.apply(Z, sk.COLUMNWISE)
        SY = R.apply(Y, sk.COLUMNWISE)
    else:
        SZ, SY = Z, Y

    W = _ridge_solve(SZ, SY, lam)
    return S, W


@with_solver_precision
def sketched_approximate_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    t: int = -1,
    params: Optional[KrrParams] = None,
):
    """Memory-bounded variant (ref: ml/krr.hpp:197-309): the s features are
    produced by a list of split maps (each scaled by √(s_c/s)); each block is
    immediately compressed by a shared row sketch R to t rows, so the full
    (n, s) feature matrix never exists. Returns (transforms, W); at predict
    time apply each map, scale by √(s_c/s), and concatenate (``scale_maps``
    is always true here — the reference returns it as a flag)."""
    from libskylark_tpu import sketch as sk

    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    n, d = X.shape
    t = 4 * s if t == -1 else t

    R = sk.CWT(n, t, context) if params.fast_sketch else sk.FJLT(n, t, context)
    SY = R.apply(Y, sk.COLUMNWISE)

    transforms = []
    blocks = []
    for thiss in _split_sizes(s, d, params.max_split):
        S = k.create_rft(thiss, context, _feature_tag(params))
        transforms.append(S)
        Z = S.apply(X, sk.ROWWISE) * math.sqrt(thiss / s)
        blocks.append(R.apply(Z, sk.COLUMNWISE))  # (t, thiss)
    SZ = jnp.concatenate(blocks, axis=1)  # (t, s)

    W = _ridge_solve(SZ, SY, lam)
    return transforms, W


class FeatureMapPrecond(FunctionPrecond):
    """Random-features preconditioner for (K + λI)
    (ref: ml/krr.hpp:310-398 feature_map_precond_t): with U = (s, n) features,
    approximate K ≈ UᵀU, so apply (λI + UᵀU)⁻¹ via SMW:
    P(B) = B/λ − Uᵀ·(I + U·Uᵀ/λ)⁻¹·(U·B)/λ².
    """

    def __init__(self, k, lam, X, s, context, use_fast: bool = False):
        from libskylark_tpu import sketch as sk

        X = jnp.asarray(X)
        S = k.create_rft(s, context, "fast" if use_fast else "regular")
        U = S.apply(X, sk.ROWWISE).T  # (s, n)
        C = jnp.eye(s, dtype=U.dtype) + (U @ U.T) / lam
        L = jsl.cholesky(C, lower=True)

        def apply(B):
            CUB = jsl.cho_solve((L, True), U @ B)
            return B / lam - (U.T @ CUB) / (lam * lam)

        super().__init__(apply)
        self.U = U
        self.L = L
        self.lam = lam


@with_solver_precision
def faster_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    params: Optional[KrrParams] = None,
) -> jnp.ndarray:
    """Exact-Gram KRR solved by preconditioned CG with the random-features
    SMW preconditioner (ref: ml/krr.hpp:400-499). ``s == 0`` falls back to
    unpreconditioned CG. Returns A = (K + λI)⁻¹·Y."""
    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    n = X.shape[0]
    K = k.symmetric_gram(X) + lam * jnp.eye(n, dtype=X.dtype)

    P = (
        IdPrecond()
        if s == 0
        else FeatureMapPrecond(k, lam, X, s, context, use_fast=params.use_fast)
    )
    cg_params = KrylovParams(
        tolerance=params.tolerance, iter_lim=params.iter_lim
    )
    A, _ = cg(K, Y, cg_params, precond=P)
    return A


@with_solver_precision
def large_scale_kernel_ridge(
    k: Kernel,
    X: jnp.ndarray,
    Y: jnp.ndarray,
    lam: float,
    s: int,
    context: Context,
    params: Optional[KrrParams] = None,
):
    """Block coordinate descent over split feature maps
    (ref: ml/krr.hpp:500-690): per block c, cache L_c = chol(Z_cᵀZ_c + λI) on
    the first sweep, then iterate
    ΔW_c = L_c⁻ᵀL_c⁻¹·(Z_cᵀR − λW_c),  W_c += ΔW_c,  R −= Z_c·ΔW_c
    until the relative update falls below tolerance. The feature maps are
    regenerated from their (seed, counter) every sweep instead of being stored
    — the reference's memory-saving trick, which the counter-based RNG makes
    free. Returns (transforms, W) with W the concatenated block solution;
    predict by applying each map in order and multiplying the stacked
    features with W."""
    from libskylark_tpu import sketch as sk

    params = params or KrrParams()
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    if Y.ndim == 1:
        Y = Y[:, None]
    n, d = X.shape
    t = Y.shape[1]

    transforms = [
        k.create_rft(thiss, context, _feature_tag(params))
        for thiss in _split_sizes(s, d, params.max_split)
    ]

    W_blocks = [
        jnp.zeros((S.sketch_dim, t), dtype=X.dtype) for S in transforms
    ]
    R = Y
    Ls = []

    # First sweep: build + cache Cholesky factors (ref: :568-612).
    for c, S in enumerate(transforms):
        Z = S.apply(X, sk.ROWWISE)  # (n, s_c)
        G = Z.T @ Z + lam * jnp.eye(Z.shape[1], dtype=Z.dtype)
        L = jsl.cholesky(G, lower=True)
        Ls.append(L)
        ZR = Z.T @ R - lam * W_blocks[c]
        delW = jsl.cho_solve((L, True), ZR)
        W_blocks[c] = W_blocks[c] + delW
        R = R - Z @ delW

    # More sweeps with cached factors (ref: :625-682).
    for it in range(1, params.iter_lim):
        delsize = 0.0
        for c, S in enumerate(transforms):
            Z = S.apply(X, sk.ROWWISE)
            ZR = Z.T @ R - lam * W_blocks[c]
            delW = jsl.cho_solve((Ls[c], True), ZR)
            W_blocks[c] = W_blocks[c] + delW
            R = R - Z @ delW
            delsize += float(jnp.sum(delW * delW))
        wnorm = math.sqrt(sum(float(jnp.sum(w * w)) for w in W_blocks))
        reldel = math.sqrt(delsize) / max(wnorm, 1e-30)
        params.log(2, f"large_scale_krr: iter {it}, relupdate = {reldel:.2e}")
        if reldel < params.tolerance:
            params.log(2, "large_scale_krr: convergence!")
            break

    return transforms, jnp.concatenate(W_blocks, axis=0)
