"""Prediction metrics (ref: python-skylark/skylark/metrics.py:8-30)."""

from __future__ import annotations

import numpy as np


def classification_accuracy(pred, truth) -> float:
    """Percentage of matching labels (ref: metrics.py:8)."""
    pred = np.asarray(pred).reshape(-1)
    truth = np.asarray(truth).reshape(-1)
    if pred.shape != truth.shape:
        raise ValueError(f"shape mismatch {pred.shape} vs {truth.shape}")
    return float(np.mean(pred == truth) * 100.0)


def rmse(pred, truth) -> float:
    """Root-mean-square error (regression analog used by the ML drivers,
    ref: ml/model.hpp:24 metric reporting)."""
    pred = np.asarray(pred).reshape(-1)
    truth = np.asarray(truth).reshape(-1)
    return float(np.sqrt(np.mean((pred - truth) ** 2)))
