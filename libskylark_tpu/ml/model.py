"""Hilbert-space model: linear coefficients on top of feature maps.

TPU-native analog of ref: ml/model.hpp:50-277 (``hilbert_model_t``): a
coefficient matrix plus a list of serialized feature transforms. Prediction
applies each stored map to the input, scales by √(s_j/d) when the maps were
scaled during training (the reference's ``_scale_maps`` convention,
ref: model.hpp:176-178), accumulates the per-block linear pieces, and decodes
classification outputs by sign/argmax (ref: model.hpp:190-210).

Save/load round-trips through JSON with every feature map embedded as its
(seed, counter) serialization — the model file fully determines prediction,
exactly like the reference's ptree model files (ref: model.hpp:103-137).
"""

from __future__ import annotations

import json
import math
from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from libskylark_tpu import __version__
from libskylark_tpu.base import errors
from libskylark_tpu.sketch import ROWWISE, SketchTransform, deserialize_sketch


class HilbertModel:
    """Linear-on-features model (ref: ml/model.hpp:50)."""

    def __init__(
        self,
        maps: Sequence[SketchTransform],
        scale_maps: bool,
        num_features: int,
        num_outputs: int,
        regression: bool,
        input_size: Optional[int] = None,
        coef: Optional[jnp.ndarray] = None,
        label_coding: Optional[Sequence] = None,
    ):
        # classification: original label value of each output column, so
        # predictions decode back to the training labels (class k of the
        # coef matrix ↔ label_coding[k]); None = labels were already 0..k−1
        self.label_coding = list(label_coding) if label_coding else None
        self.maps = list(maps)
        self.scale_maps = bool(scale_maps)
        self.regression = bool(regression)
        self.starts = []
        nf = 0
        for m in self.maps:
            self.starts.append(nf)
            nf += m.sketch_dim
        if self.maps and nf != num_features:
            raise errors.InvalidParametersError(
                f"feature maps produce {nf} features, expected {num_features}"
            )
        self.num_features = int(num_features)
        self.num_outputs = int(num_outputs)
        self.input_size = int(
            input_size
            if input_size is not None
            else (self.maps[0].input_dim if self.maps else num_features)
        )
        self.coef = (
            jnp.zeros((self.num_features, self.num_outputs), jnp.float32)
            if coef is None
            else jnp.asarray(coef)
        )

    # -- prediction (ref: model.hpp:146-210) --

    def decision_values(self, X) -> jnp.ndarray:
        """DV = Σⱼ scaleⱼ·Zⱼ(X)·Wⱼ — the raw scores (n, k)."""
        X = jnp.asarray(X)
        if not self.maps:
            return X @ self.coef
        d = self.input_size
        DV = jnp.zeros((X.shape[0], self.num_outputs), X.dtype)
        for m, start in zip(self.maps, self.starts):
            sj = m.sketch_dim
            Z = m.apply(X, ROWWISE)
            if self.scale_maps:
                Z = Z * math.sqrt(sj / d)
            DV = DV + Z @ self.coef[start : start + sj]
        return DV

    def materialize(self) -> "HilbertModel":
        """Pin every feature map's operator in device memory (the maps
        that support :class:`~libskylark_tpu.sketch.transform.
        OperatorCache`) — the serving regime: repeated ``predict`` calls
        stop regenerating/re-uploading operators per call. Returns
        ``self``; ``dematerialize`` drops the caches."""
        for mp in self.maps:
            if hasattr(mp, "materialize"):
                mp.materialize()
        return self

    def dematerialize(self) -> "HilbertModel":
        for mp in self.maps:
            if hasattr(mp, "dematerialize"):
                mp.dematerialize()
        return self

    def predict(self, X):
        """Returns (labels, decision_values). Regression: labels are the
        decision values. Classification: sign for one output, argmax column
        index otherwise (ref: model.hpp:190-210)."""
        DV = self.decision_values(X)
        if self.regression:
            return DV, DV
        if self.num_outputs == 1:
            labels = jnp.where(DV[:, 0] >= 0, 1, -1)
        else:
            labels = jnp.argmax(DV, axis=1)
        return labels, DV

    # -- serialization (ref: model.hpp:103-137,221-240) --

    def to_dict(self) -> dict[str, Any]:
        return {
            "skylark_object_type": "model:linear-on-features",
            "skylark_version": __version__,
            "num_features": self.num_features,
            "num_outputs": self.num_outputs,
            "input_size": self.input_size,
            "regression": self.regression,
            "feature_mapping": {
                "number_maps": len(self.maps),
                "scale_maps": self.scale_maps,
                "maps": [m.to_dict() for m in self.maps],
            },
            "coef_matrix": np.asarray(self.coef).tolist(),
            **(
                {"label_coding": self.label_coding}
                if self.label_coding is not None
                else {}
            ),
        }

    def save(self, fname: str, header: str = "") -> None:
        with open(fname, "w") as f:
            if header:
                for line in header.rstrip("\n").split("\n"):
                    f.write(f"# {line}\n" if not line.startswith("#") else line + "\n")
            json.dump(self.to_dict(), f)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "HilbertModel":
        fm = d["feature_mapping"]
        try:
            maps = [deserialize_sketch(m) for m in fm["maps"]]
        except errors.SketchError as e:
            # Model files embed sketch serializations; a stream-format
            # mismatch means the model predates the current stream format
            # and must be retrained / re-serialized (see README "Stream
            # format versioning").
            raise errors.SketchError(
                "model file embeds a feature map from an incompatible "
                f"stream format — retrain or re-serialize the model ({e})"
            ) from e
        return HilbertModel(
            maps,
            bool(fm["scale_maps"]),
            int(d["num_features"]),
            int(d["num_outputs"]),
            bool(d["regression"]),
            input_size=int(d["input_size"]),
            coef=jnp.asarray(d["coef_matrix"], jnp.float32),
            label_coding=d.get("label_coding"),
        )

    @staticmethod
    def load(fname_or_json: Union[str, dict]) -> "HilbertModel":
        """Load from a file path, a JSON string, or a dict. Files may start
        with '#' comment lines (ref: model.hpp:85-92)."""
        if isinstance(fname_or_json, dict):
            return HilbertModel.from_dict(fname_or_json)
        s = fname_or_json
        if "\n" in s or s.lstrip().startswith("{"):
            text = s
        else:
            with open(s) as f:
                text = f.read()
        lines = [l for l in text.split("\n") if not l.startswith("#")]
        return HilbertModel.from_dict(json.loads("\n".join(lines)))
