"""Model-file consumers (ref: python-skylark/skylark/ml/modeling.py:5-40).

``LinearizedKernelModel`` loads a model file written by skylark_ml /
:class:`~libskylark_tpu.ml.model.HilbertModel` and serves predictions —
the reference's thin Python wrapper over the JSON model format.
"""

from __future__ import annotations

from libskylark_tpu.ml.model import HilbertModel


class LinearizedKernelModel:
    """ref: modeling.py LinearizedKernelModel:5 — wraps a saved model."""

    def __init__(self, fname: str):
        self._model = HilbertModel.load(fname)

    @property
    def hilbert_model(self) -> HilbertModel:
        return self._model

    def get_input_dimension(self) -> int:
        return self._model.input_size

    def predict(self, X):
        labels, _ = self._model.predict(X)
        m = self._model
        if (not m.regression and m.label_coding is not None
                and m.num_outputs > 1):
            import numpy as np

            # decode class indices to the original training label values,
            # same as the skylark_ml test path
            return np.asarray(m.label_coding)[np.asarray(labels).ravel()]
        return labels

    def decision_values(self, X):
        return self._model.decision_values(X)
