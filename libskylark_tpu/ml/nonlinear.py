"""Kernel regression/classification toolkit: RLS, sketched RLS, Nyström RLS,
sketched PCR.

TPU-native analog of ref: python-skylark/skylark/ml/nonlinear.py:8-440.
Each model follows the reference's train/predict protocol; labels for
multiclass problems are integer classes, dummy-coded to ±1 one-vs-all
internally (ref: utils.dummycoding + 2Y−1). All dense algebra runs on
device; sampling and streams come from the framework Context.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np

from libskylark_tpu.base import errors, randgen
from libskylark_tpu.base.context import Context
from libskylark_tpu.ml.coding import dummy_coding, dummy_decode


def _code_labels(Y, multiclass: bool):
    if not multiclass:
        Yc = jnp.asarray(np.asarray(Y, dtype=np.float32))
        return (Yc[:, None] if Yc.ndim == 1 else Yc), None
    Ym, coding = dummy_coding(Y)
    return Ym, coding


def _decode(pred, coding):
    if coding is None:
        return pred[:, 0] if pred.shape[1] == 1 else pred
    return dummy_decode(pred, coding)


class RLS:
    """Exact kernel regularized least squares (ref: nonlinear.py rls:8-107):
    α = (K + λI)⁻¹·Y, predict via cross-gram with the training data."""

    def __init__(self, kernel):
        self._kernel = kernel
        self.model = None

    def train(self, X, Y, regularization: float = 1.0,
              multiclass: bool = True):
        X = jnp.asarray(X) if not hasattr(X, "todense") else X.todense()
        m = X.shape[0]
        K = self._kernel.gram(X)
        Ym, coding = _code_labels(Y, multiclass)
        A = K + regularization * jnp.eye(m, dtype=K.dtype)
        alpha = jsl.cho_solve(jsl.cho_factor(A), Ym.astype(K.dtype))
        self.model = {"alpha": alpha, "data": X, "coding": coding,
                      "regularization": float(regularization)}
        return self

    def predict(self, Xt):
        if self.model is None:
            raise errors.MLError("predict before train")
        Xt = jnp.asarray(Xt) if not hasattr(Xt, "todense") else Xt.todense()
        K = self._kernel.gram(Xt, self.model["data"])
        pred = K @ self.model["alpha"]
        return _decode(pred, self.model["coding"])


class SketchRLS:
    """Random-features RLS (ref: nonlinear.py sketchrls:109-219):
    Z = rft(X), w = (ZᵀZ + λI)⁻¹ Zᵀ Y."""

    def __init__(self, kernel):
        self._kernel = kernel
        self.model = None
        self._rft = None

    def train(self, X, Y, context: Context, random_features: int = 100,
              regularization: float = 1.0, multiclass: bool = True,
              tag: str = "regular"):
        from libskylark_tpu.sketch import ROWWISE

        self._rft = self._kernel.create_rft(random_features, context, tag)
        Z = self._rft.apply(X, ROWWISE)
        Ym, coding = _code_labels(Y, multiclass)
        s = Z.shape[1]
        A = Z.T @ Z + regularization * jnp.eye(s, dtype=Z.dtype)
        w = jsl.cho_solve(jsl.cho_factor(A), Z.T @ Ym.astype(Z.dtype))
        self.model = {"weights": w, "coding": coding,
                      "regularization": float(regularization)}
        return self

    def predict(self, Xt):
        from libskylark_tpu.sketch import ROWWISE

        if self.model is None:
            raise errors.MLError("predict before train")
        Zt = self._rft.apply(Xt, ROWWISE)
        pred = Zt @ self.model["weights"]
        return _decode(pred, self.model["coding"])


class NystromRLS:
    """Nyström-feature RLS (ref: nonlinear.py nystromrls:221-291): sample
    landmark rows (uniform or by ridge leverage scores), whiten the landmark
    gram by its inverse square root, regress on Z = K(X, landmarks)·U."""

    def __init__(self, kernel):
        self._kernel = kernel
        self.model = None

    def train(self, X, Y, context: Context, random_features: int = 100,
              regularization: float = 1.0, probdist: str = "uniform",
              multiclass: bool = True):
        X = jnp.asarray(X) if not hasattr(X, "todense") else X.todense()
        m = X.shape[0]
        s = int(random_features)
        if probdist == "uniform":
            p = np.full(m, 1.0 / m)
        elif probdist == "leverages":
            K = self._kernel.gram(X)
            M = K + regularization * jnp.eye(m, dtype=K.dtype)
            lev = jnp.diagonal(
                K @ jnp.linalg.inv(M)
            )
            p = np.maximum(np.asarray(lev, dtype=np.float64), 0)
            p = p / p.sum()
        else:
            raise errors.InvalidParametersError(
                f"probdist must be 'uniform' or 'leverages', got {probdist!r}")
        # deterministic non-uniform sample via inverse-CDF on context stream
        u = np.asarray(randgen.stream_slice(
            context.allocate().key, randgen.Uniform(), 0, s,
            dtype=jnp.float32), dtype=np.float64)
        cdf = np.cumsum(p)
        cdf[-1] = 1.0
        idx = np.searchsorted(cdf, u, side="right")
        SX = X[jnp.asarray(idx.astype(np.int32))]

        K_II = self._kernel.gram(SX)
        eps = 1e-8
        evals, evecs = jnp.linalg.eigh(
            K_II + eps * jnp.eye(s, dtype=K_II.dtype))
        evals = jnp.maximum(evals, eps)
        U = evecs / jnp.sqrt(evals)[None, :]
        Z = self._kernel.gram(X, SX) @ U
        Ym, coding = _code_labels(Y, multiclass)
        A = Z.T @ Z + regularization * jnp.eye(s, dtype=Z.dtype)
        w = jsl.cho_solve(jsl.cho_factor(A), Z.T @ Ym.astype(Z.dtype))
        self.model = {"weights": w, "SX": SX, "U": U, "coding": coding}
        return self

    def predict(self, Xt):
        if self.model is None:
            raise errors.MLError("predict before train")
        Xt = jnp.asarray(Xt) if not hasattr(Xt, "todense") else Xt.todense()
        Zt = self._kernel.gram(Xt, self.model["SX"]) @ self.model["U"]
        pred = Zt @ self.model["weights"]
        return _decode(pred, self.model["coding"])


class SketchPCR:
    """Sketched principal component regression
    (ref: nonlinear.py sketchpcr:293-440): project random features onto the
    approximate k-dominant subspace (nla.lowrank), regress there."""

    def __init__(self, kernel):
        self._kernel = kernel
        self.model = None
        self._rft = None

    def train(self, X, Y, context: Context, rank: int,
              s: Optional[int] = None, t: Optional[int] = None,
              multiclass: bool = True, tag: str = "regular"):
        from libskylark_tpu.nla.lowrank import (
            approximate_dominant_subspace_basis,
        )

        s = 2 * rank if s is None else int(s)
        t = 2 * s if t is None else int(t)
        Z, S, R, V = approximate_dominant_subspace_basis(
            X, rank, s, t, context, kernel=self._kernel, tag=tag)
        Ym, coding = _code_labels(Y, multiclass)
        # Z orthonormal: least squares is just the projection
        w0 = Z.T @ Ym.astype(Z.dtype)
        weights = jsl.solve_triangular(R, V @ w0, lower=False)
        self._rft = S
        self.model = {"weights": weights, "coding": coding,
                      "rank": int(rank), "s": s, "t": t}
        return self

    def predict(self, Xt):
        from libskylark_tpu.sketch import ROWWISE

        if self.model is None:
            raise errors.MLError("predict before train")
        Zt = self._rft.apply(Xt, ROWWISE)
        pred = Zt @ self.model["weights"]
        return _decode(pred, self.model["coding"])
