"""Regularized least squares classification (RLSC).

TPU-native analog of ref: ml/rlsc.hpp:6-311 — thin classification wrappers
around the KRR family: dummy-code the labels into a ±1 one-vs-all target
matrix, run the matching KRR solver, return the solution together with the
coding (label order) needed to decode argmax predictions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.ml import krr
from libskylark_tpu.ml.coding import dummy_coding
from libskylark_tpu.ml.kernels import Kernel


@dataclasses.dataclass
class RlscParams(Params):
    """ref: ml/rlsc.hpp:6-43 rlsc_params_t."""

    use_fast: bool = False
    sketched_rls: bool = False
    sketch_size: int = -1
    fast_sketch: bool = False
    iter_lim: int = 1000
    res_print: int = 10
    tolerance: float = 1e-3
    max_split: int = 0


def _krr_params(params: RlscParams) -> krr.KrrParams:
    """ref: rlsc.hpp:78-84 — forward the shared knobs, demote log level."""
    return krr.KrrParams(
        am_i_printing=params.am_i_printing,
        log_level=params.log_level - 1,
        prefix=params.prefix + "\t",
        use_fast=params.use_fast,
        sketched_rr=params.sketched_rls,
        sketch_size=params.sketch_size,
        fast_sketch=params.fast_sketch,
        iter_lim=params.iter_lim,
        res_print=params.res_print,
        tolerance=params.tolerance,
        max_split=params.max_split,
    )


def kernel_rlsc(
    k: Kernel, X, labels, lam: float, params: Optional[RlscParams] = None
):
    """Exact RLSC (ref: ml/rlsc.hpp:44-92). Returns (A, coding); predict with
    ``dummy_decode(gram(X_new, X) @ A, coding)``."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    A = krr.kernel_ridge(k, X, Y, lam, _krr_params(params))
    return A, coding


def approximate_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    params: Optional[RlscParams] = None,
):
    """Random-features RLSC (ref: ml/rlsc.hpp:94-145). Returns
    (S, W, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    S, W = krr.approximate_kernel_ridge(
        k, X, Y, lam, s, context, _krr_params(params)
    )
    return S, W, coding


def sketched_approximate_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    t: int = -1,
    params: Optional[RlscParams] = None,
):
    """Sketched split-features RLSC (ref: ml/rlsc.hpp:147-201). Returns
    (transforms, W, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    transforms, W = krr.sketched_approximate_kernel_ridge(
        k, X, Y, lam, s, context, t, _krr_params(params)
    )
    return transforms, W, coding


def faster_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    params: Optional[RlscParams] = None,
):
    """CG + random-features-preconditioner RLSC (ref: ml/rlsc.hpp:203-252).
    Returns (A, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    A = krr.faster_kernel_ridge(k, X, Y, lam, s, context, _krr_params(params))
    return A, coding


def large_scale_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    params: Optional[RlscParams] = None,
):
    """Block-coordinate-descent RLSC (ref: ml/rlsc.hpp:254-311). Returns
    (transforms, W, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    transforms, W = krr.large_scale_kernel_ridge(
        k, X, Y, lam, s, context, _krr_params(params)
    )
    return transforms, W, coding
