"""Regularized least squares classification (RLSC).

TPU-native analog of ref: ml/rlsc.hpp:6-311 — thin classification wrappers
around the KRR family: dummy-code the labels into a ±1 one-vs-all target
matrix, run the matching KRR solver, return the solution together with the
coding (label order) needed to decode argmax predictions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.ml import krr
from libskylark_tpu.ml.coding import dummy_coding
from libskylark_tpu.ml.kernels import Kernel


@dataclasses.dataclass
class RlscParams(Params):
    """ref: ml/rlsc.hpp:6-43 rlsc_params_t."""

    use_fast: bool = False
    sketched_rls: bool = False
    sketch_size: int = -1
    fast_sketch: bool = False
    iter_lim: int = 1000
    res_print: int = 10
    tolerance: float = 1e-3
    max_split: int = 0


def _krr_params(params: RlscParams) -> krr.KrrParams:
    """ref: rlsc.hpp:78-84 — forward the shared knobs, demote log level."""
    return krr.KrrParams(
        am_i_printing=params.am_i_printing,
        log_level=params.log_level - 1,
        prefix=params.prefix + "\t",
        use_fast=params.use_fast,
        sketched_rr=params.sketched_rls,
        sketch_size=params.sketch_size,
        fast_sketch=params.fast_sketch,
        iter_lim=params.iter_lim,
        res_print=params.res_print,
        tolerance=params.tolerance,
        max_split=params.max_split,
    )


def kernel_rlsc(
    k: Kernel, X, labels, lam: float, params: Optional[RlscParams] = None
):
    """Exact RLSC (ref: ml/rlsc.hpp:44-92). Returns (A, coding); predict with
    ``dummy_decode(gram(X_new, X) @ A, coding)``."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    A = krr.kernel_ridge(k, X, Y, lam, _krr_params(params))
    return A, coding


def approximate_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    params: Optional[RlscParams] = None,
):
    """Random-features RLSC (ref: ml/rlsc.hpp:94-145). Returns
    (S, W, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    S, W = krr.approximate_kernel_ridge(
        k, X, Y, lam, s, context, _krr_params(params)
    )
    return S, W, coding


def sketched_approximate_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    t: int = -1,
    params: Optional[RlscParams] = None,
):
    """Sketched split-features RLSC (ref: ml/rlsc.hpp:147-201). Returns
    (transforms, W, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    transforms, W = krr.sketched_approximate_kernel_ridge(
        k, X, Y, lam, s, context, t, _krr_params(params)
    )
    return transforms, W, coding


def faster_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    params: Optional[RlscParams] = None,
):
    """CG + random-features-preconditioner RLSC (ref: ml/rlsc.hpp:203-252).
    Returns (A, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    A = krr.faster_kernel_ridge(k, X, Y, lam, s, context, _krr_params(params))
    return A, coding


# ---------------------------------------------------------------------------
# Pure, vmap-batchable serve endpoint (docs/qos "Heterogeneous serve
# endpoints"; served by engine/serve.py submit_rlsc_predict).
# ---------------------------------------------------------------------------


def rlsc_predict_kernel(k: Kernel, X_new, X_train, A) -> jnp.ndarray:
    """The RLSC predict program — argmax over the one-vs-all KRR
    scores — as one pure traceable function: the classification twin
    of :func:`libskylark_tpu.ml.krr.krr_predict_kernel`. Rows of
    ``X_new`` are independent, so the serving layer vmaps THIS over a
    padded query batch with the model (``X_train``, ``A``) broadcast;
    padded query rows produce garbage class indices the caller slices
    off. Returns int32 class indices into the dummy coding."""
    from libskylark_tpu.ml.krr import krr_predict_kernel

    scores = krr_predict_kernel(k, X_new, X_train, A)
    return jnp.argmax(scores, axis=1).astype(jnp.int32)


def rlsc_predict(k: Kernel, X_new, X_train, A, coding=None):
    """Eager RLSC prediction (ref: the ``dummy_decode(gram @ A)``
    recipe in :func:`kernel_rlsc`'s docstring, as a first-class call):
    argmax class indices, decoded to labels when ``coding`` (the label
    order :func:`~libskylark_tpu.ml.coding.dummy_coding` returned) is
    given. The serve endpoint's bit-equality reference."""
    X_new = jnp.asarray(X_new)
    squeeze = X_new.ndim == 1
    if squeeze:
        X_new = X_new[None, :]
    idx = np.asarray(rlsc_predict_kernel(
        k, X_new, jnp.asarray(X_train), jnp.asarray(A)))
    if coding is not None:
        out = np.asarray([coding[i] for i in idx])
    else:
        out = idx
    return out[0] if squeeze else out


def large_scale_kernel_rlsc(
    k: Kernel,
    X,
    labels,
    lam: float,
    s: int,
    context: Context,
    params: Optional[RlscParams] = None,
):
    """Block-coordinate-descent RLSC (ref: ml/rlsc.hpp:254-311). Returns
    (transforms, W, coding)."""
    params = params or RlscParams()
    Y, coding = dummy_coding(labels, dtype=jnp.asarray(X).dtype)
    transforms, W = krr.large_scale_kernel_ridge(
        k, X, Y, lam, s, context, _krr_params(params)
    )
    return transforms, W, coding
