"""Native (C++) host-side components, built on demand with g++.

The compute path of this framework is JAX/XLA/Pallas; the native layer
covers the host-side hot loops the reference implements in compiled C++ —
currently the IO tokenizers (ref: utility/io/libsvm_io.hpp,
utility/io/arc_list.hpp). See ``io_parsers.cpp`` and ``build.py``.
"""

from libskylark_tpu.native.build import ensure_built, lib_path

__all__ = ["ensure_built", "lib_path"]
