"""Build driver for the native library (g++ -O3 -shared -fPIC).

The reference builds its compiled layer with CMake into ``libcskylark.so``
(ref: python-skylark/setup.py.in:11); here the single-TU parser library is
cheap enough to compile on first use and cache next to the source. A missing
or broken toolchain degrades silently to the pure-Python parsers.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "io_parsers.cpp")
_SO = os.path.join(_HERE, "libskylark_io.so")


def lib_path() -> str:
    return _SO


def ensure_built(quiet: bool = False) -> Optional[str]:
    """Return the path to the built .so, compiling if stale/missing.

    Returns None if the toolchain is unavailable or compilation fails.
    """
    try:
        if (os.path.exists(_SO)
                and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
            return _SO
    except OSError:
        return None
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _SO, _SRC]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        if not quiet:
            raise RuntimeError(
                f"native build failed:\n{proc.stderr}")
        return None
    return _SO
