// Native IO parsing accelerators for libskylark_tpu.
//
// TPU-native framework's compiled host-side component, standing in for the
// reference's compiled C++ IO hot loops (ref: utility/io/libsvm_io.hpp
// two-pass tokenizing readers; utility/io/arc_list.hpp parse()). Exposed as
// a plain C ABI consumed via ctypes (the reference exposes its compiled
// layer the same way: capi/*.cpp -> libcskylark.so -> python ctypes,
// ref: python-skylark/skylark/sketch.py:35).
//
// Every function returns 0 on success, a small positive error code
// otherwise (the reference's errno discipline, ref: base/exception.hpp
// SKYLARK_CATCH_AND_RETURN_ERROR_CODE).
//
// Format semantics are byte-for-byte those of the Python fallback in
// libskylark_tpu/io/libsvm.py / arclist.py:
//   libsvm: blank or '#' line terminates; nt = leading no-':' tokens of the
//           first line; indices 1-based in file, 0-based out; d = max idx.
//   arc list: blank or '#' lines are skipped; "from to [weight]".

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

struct Cursor {
    const char* p;
    const char* end;
    bool done() const { return p >= end; }
};

// Returns the [begin, end) of the next line and advances past it.
bool next_line(Cursor& c, const char*& lb, const char*& le) {
    if (c.done()) return false;
    lb = c.p;
    const char* nl = static_cast<const char*>(
        memchr(c.p, '\n', static_cast<size_t>(c.end - c.p)));
    if (nl == nullptr) {
        le = c.end;
        c.p = c.end;
    } else {
        le = nl;
        c.p = nl + 1;
    }
    // trim trailing \r and spaces
    while (le > lb && (le[-1] == '\r' || le[-1] == ' ' || le[-1] == '\t'))
        --le;
    // trim leading spaces
    while (lb < le && (*lb == ' ' || *lb == '\t')) ++lb;
    return true;
}

bool is_blank_or_comment(const char* lb, const char* le) {
    return lb >= le || *lb == '#';
}

// Advance over whitespace; return false at end of line.
bool skip_ws(const char*& p, const char* le) {
    while (p < le && (*p == ' ' || *p == '\t')) ++p;
    return p < le;
}

// Token = [p, q) of non-whitespace.
void token_end(const char* p, const char* le, const char*& q) {
    q = p;
    while (q < le && *q != ' ' && *q != '\t') ++q;
}

}  // namespace

extern "C" {

// Pass 1: count examples (n), targets (nt), max feature dim (d), total
// nonzeros (nnz). max_n < 0 means unlimited.
int sl_libsvm_count(const char* data, long long len,
                    long long* n_out, long long* nt_out,
                    long long* d_out, long long* nnz_out,
                    long long max_n) {
    Cursor c{data, data + len};
    long long n = 0, nt = -1, d = 0, nnz = 0;
    const char *lb, *le;
    while (next_line(c, lb, le)) {
        if (max_n >= 0 && n == max_n) break;
        if (is_blank_or_comment(lb, le)) break;  // terminates, per reference
        const char* p = lb;
        long long line_nt = 0;
        bool counting_nt = (nt < 0);
        while (skip_ws(p, le)) {
            const char* q;
            token_end(p, le, q);
            const char* colon = static_cast<const char*>(
                memchr(p, ':', static_cast<size_t>(q - p)));
            if (colon == nullptr) {
                if (counting_nt) ++line_nt;
                // otherwise: a label token (not counted again)
            } else {
                counting_nt = false;
                char* endp = nullptr;
                long long idx = strtoll(p, &endp, 10);
                if (endp != colon || idx < 1) return 2;  // malformed/0-based
                if (idx > d) d = idx;
                ++nnz;
            }
            p = q;
        }
        if (nt < 0) nt = line_nt;
        ++n;
    }
    if (nt < 0) nt = 0;
    *n_out = n;
    *nt_out = nt;
    *d_out = d;
    *nnz_out = nnz;
    return 0;
}

// Pass 2: fill Y (n*nt, row-major), rowptr (n+1), colind (nnz, 0-based),
// values (nnz). Caller allocates from pass-1 counts.
int sl_libsvm_fill(const char* data, long long len,
                   long long n, long long nt, long long nnz,
                   double* Y, long long* rowptr,
                   long long* colind, double* values) {
    Cursor c{data, data + len};
    const char *lb, *le;
    long long i = 0, k = 0;
    while (i < n && next_line(c, lb, le)) {
        if (is_blank_or_comment(lb, le)) break;
        rowptr[i] = k;
        const char* p = lb;
        long long t = 0;
        while (skip_ws(p, le)) {
            const char* q;
            token_end(p, le, q);
            const char* colon = static_cast<const char*>(
                memchr(p, ':', static_cast<size_t>(q - p)));
            char* endp = nullptr;
            if (colon == nullptr) {
                if (t >= nt) return 3;  // more labels than first line
                Y[i * nt + t] = strtod(p, &endp);
                if (endp == p) return 2;
                ++t;
            } else if (t < nt) {
                return 2;  // fewer labels than the first line declared —
                           // the Python parser rejects this line too
            } else {
                long long idx = strtoll(p, &endp, 10);
                if (endp != colon || idx < 1) return 2;
                double v = strtod(colon + 1, &endp);
                if (endp == colon + 1) return 2;
                if (k >= nnz) return 4;
                colind[k] = idx - 1;
                values[k] = v;
                ++k;
            }
            p = q;
        }
        ++i;
    }
    if (i != n || k != nnz) return 4;
    rowptr[n] = k;
    return 0;
}

// Arc list pass 1: count edges.
int sl_arclist_count(const char* data, long long len, long long* ne_out) {
    Cursor c{data, data + len};
    const char *lb, *le;
    long long ne = 0;
    while (next_line(c, lb, le)) {
        if (is_blank_or_comment(lb, le)) continue;  // skipped, per reference
        ++ne;
    }
    *ne_out = ne;
    return 0;
}

// Arc list pass 2: fill src/dst/w arrays (length ne). Weight defaults 1.
int sl_arclist_fill(const char* data, long long len, long long ne,
                    long long* src, long long* dst, double* w) {
    Cursor c{data, data + len};
    const char *lb, *le;
    long long e = 0;
    while (next_line(c, lb, le)) {
        if (is_blank_or_comment(lb, le)) continue;
        if (e >= ne) return 4;
        const char* p = lb;
        char* endp = nullptr;
        if (!skip_ws(p, le)) return 2;
        long long a = strtoll(p, &endp, 10);
        if (endp == p) return 2;
        p = endp;
        if (!skip_ws(p, le)) return 2;  // < 2 tokens
        long long b = strtoll(p, &endp, 10);
        if (endp == p) return 2;
        p = endp;
        double weight = 1.0;
        if (skip_ws(p, le)) {
            weight = strtod(p, &endp);
            if (endp == p) return 2;
        }
        src[e] = a;
        dst[e] = b;
        w[e] = weight;
        ++e;
    }
    if (e != ne) return 4;
    return 0;
}

}  // extern "C"
