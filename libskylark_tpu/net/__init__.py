"""Network serve front door: wire protocol, TCP server, client.

The delivery path for the serve stack (docs/networking): a
deterministic pickle-free framed protocol (:mod:`~libskylark_tpu.net
.wire`), a threaded TCP server adapting connections onto the fleet
router (:mod:`~libskylark_tpu.net.server`), and a retry-safe blocking
client with the same future-shaped surface as ``Router.submit``
(:mod:`~libskylark_tpu.net.client`). Everything above the socket —
QoS admission, single-flight coalescing, caching, sessions, training
— is the existing in-process stack; the net tier only moves frames.
"""

from __future__ import annotations

from libskylark_tpu.net.client import NetClient
from libskylark_tpu.net.server import NetServer, net_stats
from libskylark_tpu.net.wire import PeerClosed

__all__ = ["NetClient", "NetServer", "PeerClosed", "net_stats"]
