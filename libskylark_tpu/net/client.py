"""Blocking TCP client for the serve front door (docs/networking).

``NetClient`` speaks :mod:`libskylark_tpu.net.wire` to a
:class:`~libskylark_tpu.net.server.NetServer` and exposes the same
future-shaped surface as :class:`~libskylark_tpu.fleet.router.Router`:
``submit(verb, **kwargs)`` returns a
:class:`concurrent.futures.Future` immediately; transport kwargs
(``tenant`` / ``qos_class`` / ``deadline`` / ``timeout``) ride the
frame header, operand kwargs ride the tagged codec.

**Retry is safe by construction, so it is on by default.** A request
frame is deterministic bytes; when the connection dies with requests
inflight the client reconnects (bounded attempts, seeded decorrelated
jitter — the :mod:`~libskylark_tpu.resilience.policy` discipline) and
re-sends *the identical bytes*. The server decodes the identical
kwargs, the router re-derives the identical content digest, and the
single-flight table (docs/caching) either joins the still-running
original flight or hits the result cache — the engine flushes exactly
once no matter how many times the wire tore. Structured server errors
(quota, overload, protocol, deadline) are **never** retried by the
transport loop: they surface as the same typed exception the server
raised, ``retry_after_s`` intact, and the *caller* decides — exactly
the in-process contract.

GOAWAY handling: a draining server announces itself; the client stops
sending on that connection but keeps reading until every inflight
response lands (the server's drain settles them), then transparently
reconnects for the next request. A drain is therefore invisible to
callers — futures resolve, new work finds the next server generation.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors as _errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.net import wire as _wire
from libskylark_tpu.resilience.policy import Deadline
from libskylark_tpu.telemetry import trace as _trace


def _close_socket(sock: socket.socket) -> None:
    """Shutdown-then-close: a bare ``close()`` only drops the fd
    refcount and leaves threads blocked in ``recv`` sleeping forever —
    the shutdown delivers the EOF that wakes them."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _SendFailed(Exception):
    """Internal: a send hit a transport fault and ``_conn_lost`` now
    owns the request's fate (re-send or budget-exhausted failure) —
    the caller must NOT touch the future."""


class _Pending:
    """One unacknowledged request: the exact frame bytes to re-send,
    the caller's future, and the transport-retry ledger."""

    __slots__ = ("frame", "future", "attempts", "gen", "verb")

    def __init__(self, frame: bytes, future: Future, gen: int,
                 verb: str):
        self.frame = frame
        self.future = future
        self.attempts = 0
        self.gen = gen
        self.verb = verb


class NetClient:
    """Blocking client for the serve front door.

    ::

        c = net.NetClient(srv.address, tenant="team-a")
        fut = c.submit("sketch_apply", A=A, transform=S, dimension=dim)
        SA = fut.result(timeout=30)
        c.close()

    ``retry_budget`` transport reconnect-resends per request and
    ``retry_backoff_s`` base backoff default from the
    ``SKYLARK_NET_RETRY_*`` knobs; ``seed`` pins the jitter stream
    (tests)."""

    def __init__(self, address: Tuple[str, int], *,
                 tenant: Optional[str] = None,
                 qos_class: Optional[str] = None,
                 retry_budget: Optional[int] = None,
                 retry_backoff_s: Optional[float] = None,
                 connect_timeout: float = 5.0,
                 seed: Optional[int] = None):
        self.address = (str(address[0]), int(address[1]))
        self.tenant = tenant
        self.qos_class = qos_class
        self.retry_budget = int(
            retry_budget if retry_budget is not None
            else _env.NET_RETRY_BUDGET.get())
        self.retry_backoff_s = float(
            retry_backoff_s if retry_backoff_s is not None
            else _env.NET_RETRY_BACKOFF_S.get())
        self.connect_timeout = float(connect_timeout)
        self._rng = random.Random(
            seed if seed is not None else hash(self.address) & 0xFFFF)
        self._lock = _locks.make_lock("net.client")
        self._sock: Optional[socket.socket] = None
        self._gen = 0
        self._seq = 0
        self._pending: Dict[int, _Pending] = {}
        self._closed = False
        self._goaways = 0
        self._transport_retries = 0

    # -- the future-shaped surface -------------------------------------

    def submit(self, verb: str, /, *, tenant: Optional[str] = None,
               qos_class: Optional[str] = None,
               deadline=None, timeout: Optional[float] = None,
               **kwargs) -> Future:
        """Send one request; returns a Future resolving to the verb's
        result or raising the server's typed exception. ``deadline``
        (seconds or a :class:`~libskylark_tpu.resilience.policy
        .Deadline`) ships as *remaining budget* — the server restarts
        the clock at receipt so the wire hop is never double-counted."""
        if self._closed:
            raise RuntimeError("NetClient is closed")
        deadline_s = None
        if deadline is not None:
            d = Deadline.coerce(deadline)
            deadline_s = max(0.0, d.remaining())
        ctx = _trace.get_context()
        rid = ctx.request_id if ctx is not None else None
        if rid is None:
            rid = _trace.new_request_id()
        trace = {"request_id": rid}
        if ctx is not None:
            trace["trace_id"] = ctx.trace_id
            trace["span_id"] = ctx.span_id
        with self._lock:
            self._seq += 1
            seq = self._seq
        frame = _wire.pack_request(
            verb, kwargs, seq=seq,
            tenant=tenant if tenant is not None else self.tenant,
            qos_class=(qos_class if qos_class is not None
                       else self.qos_class),
            deadline_s=deadline_s, timeout=timeout, trace=trace)
        fut: Future = Future()
        fut.set_running_or_notify_cancel()
        pend = _Pending(frame, fut, -1, verb)
        with self._lock:
            self._pending[seq] = pend
        try:
            self._send(seq, pend)
        except _SendFailed:
            pass        # retry machinery owns the request now
        except BaseException as e:  # noqa: BLE001 — fail the future
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            with self._lock:
                self._pending.pop(seq, None)
            fut.set_exception(self._as_comm_error(e))
        return fut

    # convenience wrappers mirroring Router's blocking surface --------

    def ping(self, timeout: float = 5.0) -> str:
        return self.submit("ping").result(timeout=timeout)

    def stats(self, timeout: float = 10.0) -> dict:
        return self.submit("stats").result(timeout=timeout)

    def open_sketch_session(self, kind: str, *, timeout: float = 30.0,
                            **spec_kwargs) -> str:
        return self.submit("session.open", kind=kind,
                           **spec_kwargs).result(timeout=timeout)

    def session_append(self, session_id: str, X, Y=None, *,
                       seq: Optional[int] = None) -> Future:
        kw = {"session_id": session_id, "X": X}
        if Y is not None:
            kw["Y"] = Y
        if seq is not None:
            kw["seq"] = seq
        return self.submit("session.append", **kw)

    def session_finalize(self, session_id: str, **kwargs) -> Future:
        return self.submit("session.finalize", session_id=session_id,
                           **kwargs)

    def register_operand(self, A, *, timeout: float = 30.0, **kwargs):
        """Pin ``A`` resident fleet-wide; returns the
        :class:`~libskylark_tpu.engine.resultcache.OperandRef` whose
        digest string later submits pass as ``A=ref``."""
        return self.submit("operand.register", A=A,
                           **kwargs).result(timeout=timeout)

    def unregister_operand(self, ref, *, timeout: float = 30.0) -> int:
        return self.submit("operand.unregister",
                           ref=ref).result(timeout=timeout)

    def train_job_status(self, session_id: str, *,
                         timeout: float = 30.0) -> dict:
        return self.submit("train.status",
                           session_id=session_id).result(timeout=timeout)

    def client_stats(self) -> dict:
        with self._lock:
            return {
                "address": list(self.address),
                "pending": len(self._pending),
                "generation": self._gen,
                "goaways_seen": self._goaways,
                "transport_retries": self._transport_retries,
                "connected": self._sock is not None,
            }

    # -- transport -----------------------------------------------------

    def _send(self, seq: int, pend: _Pending) -> None:
        try:
            sock, gen = self._ensure_conn()
        except (OSError, _errors.CommunicationError) as e:
            # connect failed: charge this request's budget and let the
            # recovery loop (or budget exhaustion) decide
            pend.gen = self._gen
            self._retry_or_fail(seq, pend, e)
            raise _SendFailed() from e
        pend.gen = gen
        try:
            sock.sendall(pend.frame)
        except OSError as e:
            self._conn_lost(gen)
            raise _SendFailed() from e

    def _ensure_conn(self) -> Tuple[socket.socket, int]:
        with self._lock:
            if self._closed:
                raise RuntimeError("NetClient is closed")
            if self._sock is not None:
                return self._sock, self._gen
            self._gen += 1
            gen = self._gen
        sock = socket.create_connection(
            self.address, timeout=self.connect_timeout)
        sock.settimeout(None)
        with self._lock:
            if self._gen != gen or self._closed:
                sock.close()
                raise _errors.CommunicationError(
                    "connection superseded during connect")
            self._sock = sock
        reader = threading.Thread(
            target=self._read_loop, args=(sock, gen),
            name=f"net-client-read-{gen}", daemon=True)
        reader.start()
        return sock, gen

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        try:
            while True:
                header, bodies = _wire.read_frame(sock.recv)
                t = header.get("t")
                if t == _wire.GOAWAY:
                    self._on_goaway(sock, gen)
                    continue
                seq = header.get("seq")
                if seq is None:
                    # unsequenced error: connection-scoped refusal
                    # (e.g. accepted-then-refused at max_connections)
                    self._fail_gen(gen, _wire.unpack_error(header))
                    return
                with self._lock:
                    pend = self._pending.pop(int(seq), None)
                if pend is None:
                    continue            # late reply to a retried seq
                if t == _wire.RES:
                    try:
                        pend.future.set_result(
                            _wire.unpack_result(header, bodies))
                    except Exception as e:  # noqa: BLE001
                        pend.future.set_exception(e)
                elif t == _wire.ERR:
                    pend.future.set_exception(_wire.unpack_error(header))
                else:
                    pend.future.set_exception(_errors.WireProtocolError(
                        f"unexpected frame type {t!r} from server"))
        except (_wire.PeerClosed, _errors.WireProtocolError, OSError):
            pass
        except Exception:  # noqa: BLE001 — reader must not leak
            pass
        finally:
            self._conn_lost(gen)

    def _on_goaway(self, sock: socket.socket, gen: int) -> None:
        """Server drain announcement: stop routing NEW requests here
        (drop the socket reference — the reader keeps running so
        inflight responses still land), reconnect lazily."""
        with self._lock:
            self._goaways += 1
            if self._gen == gen and self._sock is sock:
                self._sock = None

    def _conn_lost(self, gen: int) -> None:
        """A connection generation died. Re-send every request that
        was inflight on it (identical bytes — digest-keyed idempotency
        makes this safe) up to the per-request retry budget.

        A dead socket is noticed twice — by the sender's failed
        ``sendall`` AND by the reader thread's EOF — so each harvested
        pending is CLAIMED (``gen = -1``) under the lock: the second
        notice matches nothing and cannot double-bill the attempt or
        re-send the frame twice (the duplicate would later wake an
        idle server reader, which is how a chaos plan's fault ends up
        consumed by the wrong connection). Claiming, rather than
        marking the whole generation dead, keeps the late notice
        harmless without suppressing it: the notices race the sender's
        ``p.gen`` stamp, and whichever arrives after the stamp must
        still be able to harvest.
        """
        with self._lock:
            if self._sock is not None and self._gen == gen:
                _close_socket(self._sock)
                self._sock = None
            if self._closed:
                items = []
            else:
                items = [(seq, p) for seq, p in self._pending.items()
                         if p.gen == gen]
                for _, p in items:
                    p.gen = -1      # claimed by this recovery
        retry = []
        for seq, pend in items:
            if self._charge_attempt(seq, pend):
                retry.append((seq, pend))
        if retry:
            t = threading.Thread(
                target=self._recover, args=(retry,),
                name=f"net-client-recover-{gen}", daemon=True)
            t.start()

    def _charge_attempt(self, seq: int, pend: _Pending) -> bool:
        """Bill one transport attempt; fail the future and return
        False once the budget is gone."""
        pend.attempts += 1
        if pend.attempts <= self.retry_budget:
            return True
        with self._lock:
            self._pending.pop(seq, None)
        if not pend.future.done():
            pend.future.set_exception(_errors.CommunicationError(
                f"connection lost; retry budget "
                f"({self.retry_budget}) exhausted for "
                f"{pend.verb!r} seq={seq}"))
        return False

    def _retry_or_fail(self, seq: int, pend: _Pending,
                       cause: BaseException) -> None:
        if self._charge_attempt(seq, pend):
            t = threading.Thread(
                target=self._recover, args=([(seq, pend)],),
                name="net-client-reconnect", daemon=True)
            t.start()

    def _recover(self, items) -> None:
        # decorrelated jitter (policy.RetryPolicy's discipline): the
        # sleep grows with the worst attempt count in the batch, and
        # the jitter is seeded so tests replay byte-identically
        attempt = max(p.attempts for _, p in items)
        base = self.retry_backoff_s * (2.0 ** (attempt - 1))
        delay = min(2.0, base + self._rng.uniform(0, base))
        time.sleep(delay)
        with self._lock:
            self._transport_retries += len(items)
        for seq, pend in items:
            with self._lock:
                if seq not in self._pending:
                    continue            # already settled (late reply)
            try:
                self._send(seq, pend)
            except _SendFailed:
                # the retry machinery re-billed and re-queued (or
                # failed) THIS item and everything already re-sent on
                # the dead generation; keep walking the batch so the
                # not-yet-sent items (still carrying the older dead
                # generation) are not stranded
                continue
            except BaseException as e:  # noqa: BLE001
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                with self._lock:
                    self._pending.pop(seq, None)
                if not pend.future.done():
                    pend.future.set_exception(self._as_comm_error(e))
                return

    def _fail_gen(self, gen: int, exc: BaseException) -> None:
        with self._lock:
            items = [(s, p) for s, p in self._pending.items()
                     if p.gen == gen]
            for s, _ in items:
                self._pending.pop(s, None)
        for _, pend in items:
            pend.future.set_exception(exc)

    @staticmethod
    def _as_comm_error(e: BaseException) -> BaseException:
        if isinstance(e, _errors.SkylarkError):
            return e
        return _errors.CommunicationError(f"{type(e).__name__}: {e}")

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        """Drop the connection and fail anything still pending (a
        deliberate local close is not a retryable transport fault)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            sock = self._sock
            self._sock = None
            items = list(self._pending.items())
            self._pending.clear()
        if sock is not None:
            _close_socket(sock)
        for _, pend in items:
            if not pend.future.done():
                pend.future.set_exception(_errors.CommunicationError(
                    "NetClient closed with requests inflight"))

    def __enter__(self) -> "NetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["NetClient"]
