"""Threaded TCP front door over the fleet router (docs/networking).

``NetServer`` adapts socket connections onto an existing
:class:`~libskylark_tpu.fleet.router.Router`: every serve endpoint
family plus the session / train / operand-residency verbs crosses the
wire as one :mod:`~libskylark_tpu.net.wire` request frame and comes
back as a result or structured-error frame. The server owns *no*
scheduling policy of its own — QoS admission, affinity routing,
single-flight coalescing, and caching all stay in the router it
fronts; the network tier only adds the four things a socket needs:

- **edge admission errors as wire errors** — a
  :class:`~libskylark_tpu.base.errors.TenantQuotaError` or
  ``ServeOverloadedError`` raised at the router front door becomes an
  error frame carrying the stable code and ``retry_after_s``, so a
  remote client backs off exactly like an in-process one;
- **bounded per-connection inflight windows** — the reader thread
  acquires a window slot *before* dispatching and the slot is
  released only after the response bytes are written, so a slow
  reader stops being read from (TCP backpressure) instead of
  buffering responses without bound;
- **disconnect-mid-request detach** — a connection that dies with
  requests inflight abandons its server-side futures without
  cancelling the underlying flight (coalesced followers on other
  connections still get their result; the computation is never
  poisoned);
- **drain discipline at the socket layer** — ``drain()`` (and the
  process SIGTERM path via
  :func:`~libskylark_tpu.resilience.preemption.on_preemption`, which
  runs after the executors settle) sends a GOAWAY frame on every
  live connection, stops accepting, waits for inflight responses to
  flush, then closes — the r11/r15 replica-drain contract, one layer
  down.

Fault sites ``net.accept`` / ``net.read`` / ``net.write`` ride the
chaos table (:mod:`libskylark_tpu.resilience.faults`): a fired fault
aborts one accept, one frame read, or one frame write — the client's
bounded reconnect-retry is what absorbs it (docs/networking).
"""

from __future__ import annotations

import collections
import socket
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

from libskylark_tpu import telemetry as _telemetry
from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors as _errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.engine import serve as _serve
from libskylark_tpu.net import wire as _wire
from libskylark_tpu.resilience import faults
from libskylark_tpu.resilience import preemption as _preemption
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

_CONNECTIONS = _metrics.gauge(
    "net.connections", "Live TCP connections on the serve front door")
_REQUESTS = _metrics.counter(
    "net.requests", "Wire requests dispatched, by verb")
_WIRE_ERRORS = _metrics.counter(
    "net.wire_errors", "Structured wire error frames sent, by code")
_BYTES_IN = _metrics.counter(
    "net.bytes_in", "Request bytes received on the serve front door")
_BYTES_OUT = _metrics.counter(
    "net.bytes_out", "Response bytes written on the serve front door")
_DRAINS = _metrics.counter(
    "net.drains", "Socket-layer drains (GOAWAY + settle) completed")

#: process-lifetime rollup that survives server teardown (the smoke
#: gates read these after ``close()``)
_LIFETIME = _metrics.LifetimeCounter(
    "net", kinds=("accepted", "refused", "requests", "wire_errors",
                  "bytes_in", "bytes_out", "drains",
                  "disconnected_inflight", "retries_represented"))

_SERVERS: "weakref.WeakSet[NetServer]" = weakref.WeakSet()

#: verbs that run synchronously on the connection's reader thread
#: (control plane: rare, and their router calls are blocking by
#: design) — everything else resolves through a Future
_BLOCKING_VERBS = ("session.open", "train.status", "operand.register",
                   "operand.unregister", "stats", "ping")


def _dist_source(kwargs: dict):
    from libskylark_tpu.dist.plan import ArraySource

    X = kwargs.pop("X")
    Y = kwargs.pop("Y", None)
    return ArraySource(X, Y)


def _wire_safe(value):
    """Results the tagged codec can't express directly, converted to
    their documented wire forms (docs/networking, "Verbs"): a dist
    merge result becomes a plain dict of its public fields."""
    if hasattr(value, "SX") and hasattr(value, "coverage"):
        return {
            "SX": value.SX, "SY": value.SY,
            "coverage": float(value.coverage),
            "degraded": bool(value.degraded),
            "missing": [list(r) for r in getattr(value, "missing", ())],
        }
    return value


class _Conn:
    """One accepted connection: a reader thread (frame → dispatch), a
    writer thread (bounded response queue → socket), and the inflight
    window between them."""

    def __init__(self, server: "NetServer", sock: socket.socket,
                 peer: Tuple[str, int]):
        self.server = server
        self.sock = sock
        self.peer = peer
        self.alive = True
        self.goaway_sent = False
        self._lock = _locks.make_lock("net.conn")
        self._window = threading.Semaphore(server.inflight_window)
        self._pending: Dict[int, Future] = {}
        # the writer queue is bounded too (belt to the window's
        # braces): even a bug that leaked window slots could not
        # buffer more than 2x window responses
        self._outq: "collections.deque" = collections.deque()
        self._out_cv = threading.Condition(self._lock)
        self._reader = threading.Thread(
            target=self._read_loop, name=f"net-read-{peer}", daemon=True)
        self._writer = threading.Thread(
            target=self._write_loop, name=f"net-write-{peer}",
            daemon=True)
        self._reader.start()
        self._writer.start()

    # -- outbound ------------------------------------------------------

    def enqueue(self, frame: bytes, releases_window: bool) -> None:
        with self._lock:
            if not self.alive:
                if releases_window:
                    self._window.release()
                return
            self._outq.append((frame, releases_window))
            self._out_cv.notify()

    def goaway(self, drain_timeout_s: float) -> None:
        with self._lock:
            if self.goaway_sent or not self.alive:
                return
            self.goaway_sent = True
        self.enqueue(_wire.pack_goaway(drain_timeout_s), False)
        self.server._count("goaways_sent")

    def pending_count(self) -> int:
        """Work the drain must wait for: registered futures plus
        responses not yet fully written to the socket."""
        with self._lock:
            return len(self._pending) + len(self._outq)

    def inflight_count(self) -> int:
        """Registered-but-unsettled requests only. Distinct from
        :meth:`pending_count`: a settled response waiting in the
        write queue is counted by ``responses_sent`` already, so
        adding ``_outq`` here would double-count it in the
        ``pending + responses_sent`` conservation the stats surface
        advertises."""
        with self._lock:
            return len(self._pending)

    def _write_loop(self) -> None:
        while True:
            with self._lock:
                while self.alive and not self._outq:
                    self._out_cv.wait(0.5)
                if not self._outq:
                    return
                # peek, don't pop: the frame must stay visible to
                # pending_count() until sendall returns, or a drain
                # polling for quiescence can close the socket under a
                # mid-flight write (only this thread ever pops)
                frame, releases = self._outq[0]
            try:
                faults.check("net.write", tags=faults.current_tags(),
                             detail=f"{self.peer} {len(frame)}B")
                self.sock.sendall(frame)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:  # noqa: BLE001 — peer gone/injected
                if releases:
                    self._window.release()
                self._die()
                return
            with self._lock:
                if self._outq:      # _die may have cleared it
                    self._outq.popleft()
            self.server._count("bytes_out", len(frame))
            _BYTES_OUT.inc(len(frame))
            if releases:
                self._window.release()

    # -- inbound -------------------------------------------------------

    def _read_loop(self) -> None:
        try:
            while self.alive:
                header, bodies = _wire.read_frame(self.sock.recv)
                # the fault site fires AFTER a frame actually arrived
                # (a read error on real bytes, before processing) —
                # checking before the blocking read would let an idle
                # connection's reader, descheduled since its last
                # frame, consume a counted fault plan entry minutes
                # after the traffic it was meant to hit
                faults.check("net.read", tags=faults.current_tags(),
                             detail=str(self.peer))
                self._on_frame(header, bodies)
        except _wire.PeerClosed:
            pass
        except (KeyboardInterrupt, SystemExit):
            raise
        except _errors.WireProtocolError as e:
            # a malformed frame means the stream lost sync: report
            # once (unsequenced — we can't trust the frame's seq) and
            # tear down; the client reconnects and re-sends
            self.server._wire_error(self, None, e)
        except BaseException:  # noqa: BLE001 — socket torn down
            pass
        finally:
            self._die()

    def _on_frame(self, header: dict, bodies) -> None:
        self.server._count(
            "bytes_in",
            sum(b.nbytes for b in bodies) if bodies else 0)
        if header.get("t") != _wire.REQ:
            raise _errors.WireProtocolError(
                f"unexpected frame type {header.get('t')!r} from client")
        seq = int(header.get("seq", -1))
        self.server._note_transport_digest(header.get("digest"))
        # window admission BEFORE dispatch: a client that pipelines
        # past the window stops being read until responses flush
        self._window.acquire()
        try:
            verb, kwargs = _wire.unpack_request(header, bodies)
            self.server._dispatch(self, seq, verb, kwargs, header)
        except BaseException as e:  # noqa: BLE001 — reply, don't die
            self._window.release()
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            self.server._wire_error(self, seq, e)

    # -- settle --------------------------------------------------------

    def register(self, seq: int, fut: Future) -> None:
        with self._lock:
            self._pending[seq] = fut

    def settle(self, seq: int, fut: Future) -> None:
        with self._lock:
            self._pending.pop(seq, None)
            dead = not self.alive
        if dead:
            # disconnect-mid-request: the future already ran (or was
            # shared with coalesced followers) — detach, never cancel
            self._window.release()
            return
        exc = fut.exception()
        if exc is not None:
            self.server._wire_error(self, seq, exc, releases_window=True)
            return
        try:
            frame = _wire.pack_result(seq, _wire_safe(fut.result()))
        except BaseException as e:  # noqa: BLE001 — unencodable result
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            self.server._wire_error(self, seq, e, releases_window=True)
            return
        self.server._count("responses_sent")
        self.enqueue(frame, True)

    def _die(self) -> None:
        with self._lock:
            if not self.alive:
                return
            self.alive = False
            inflight = len(self._pending)
            self._pending.clear()
            self._outq.clear()
            self._out_cv.notify_all()
        if inflight:
            self.server._count("disconnected_inflight", inflight)
            _LIFETIME.inc("disconnected_inflight", inflight)
        # shutdown-then-close: a bare close() leaves the peer thread
        # of this connection blocked in recv forever
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.server._forget(self)

    def close(self) -> None:
        self._die()


class NetServer:
    """The TCP serve front door (docs/networking).

    ::

        pool = fleet.ReplicaPool(2, cache=True)
        router = fleet.Router(pool, cache=True)
        srv = net.NetServer(router)          # SKYLARK_NET_* defaults
        host, port = srv.address
        ...
        srv.drain()                          # GOAWAY + settle + close
        srv.close()

    ``port=0`` (the default) binds an ephemeral port — read
    ``srv.address`` after construction. The server registers itself
    with the preemption tier: a process SIGTERM drains the executors
    first (r9/r11), then this server's GOAWAY/settle hook runs inside
    the same teardown, so a remote client never sees the shutdown as
    anything but a drained connection."""

    def __init__(self, router, *, host: Optional[str] = None,
                 port: Optional[int] = None,
                 max_connections: Optional[int] = None,
                 inflight_window: Optional[int] = None,
                 drain_timeout_s: Optional[float] = None):
        self.router = router
        self.host = host if host is not None else _env.NET_HOST.get()
        self.inflight_window = int(
            inflight_window if inflight_window is not None
            else _env.NET_INFLIGHT_WINDOW.get())
        self.max_connections = int(
            max_connections if max_connections is not None
            else _env.NET_MAX_CONNECTIONS.get())
        self.drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else _env.NET_DRAIN_TIMEOUT_S.get())
        bind_port = int(port if port is not None else _env.NET_PORT.get())
        self._lock = _locks.make_lock("net.server")
        self._conns: "weakref.WeakSet[_Conn]" = weakref.WeakSet()
        self._counts: "collections.Counter" = collections.Counter()
        self._recent_digests: "collections.OrderedDict" = (
            collections.OrderedDict())
        self._draining = False
        self._closed = False
        self._listener = socket.create_server(
            (self.host, bind_port), reuse_port=False)
        self._listener.settimeout(0.25)
        self.address: Tuple[str, int] = self._listener.getsockname()[:2]
        self._verbs = self._build_verbs()
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="net-accept", daemon=True)
        self._acceptor.start()
        self._unhook = _preemption.on_preemption(self._on_preempt)
        _SERVERS.add(self)

    # -- accept --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return                      # listener closed (drain)
            try:
                faults.check("net.accept", tags=faults.current_tags(),
                             detail=str(peer))
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException:  # noqa: BLE001 — injected accept fail
                self._count("refused")
                _LIFETIME.inc("refused")
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._lock:
                live = len(self._conns)
                overloaded = (self._draining
                              or live >= self.max_connections)
            if overloaded:
                # refuse with a structured frame, not a silent RST:
                # the client backs off like any other overload
                self._count("refused")
                _LIFETIME.inc("refused")
                try:
                    sock.sendall(_wire.pack_error(
                        None, _serve.ServeOverloadedError(
                            "connection refused: "
                            + ("draining" if self._draining else
                               f"at max_connections={self.max_connections}"
                               ))))
                    sock.close()
                except OSError:
                    pass
                continue
            sock.settimeout(None)
            conn = _Conn(self, sock, peer)
            with self._lock:
                self._conns.add(conn)
                live = len(self._conns)
            self._count("accepted")
            _LIFETIME.inc("accepted")
            _CONNECTIONS.set(live)

    def _forget(self, conn: _Conn) -> None:
        with self._lock:
            self._conns.discard(conn)
            live = len(self._conns)
        _CONNECTIONS.set(live)

    # -- dispatch ------------------------------------------------------

    def _build_verbs(self) -> dict:
        r = self.router
        verbs: dict = {ep: (lambda kw, tk, _ep=ep:
                            r.submit(_ep, **kw, **tk))
                       for ep in _serve.ENDPOINTS}

        def dist_sketch(kw, tk):
            src = _dist_source(kw)
            return r.submit_dist_sketch(kw.pop("plan"), src, **kw, **tk)

        def dist_lstsq(kw, tk):
            src = _dist_source(kw)
            return r.submit_dist_lstsq(src, **kw, **tk)

        def dist_svd(kw, tk):
            src = _dist_source(kw)
            return r.submit_dist_svd(src, kw.pop("rank"), **kw, **tk)

        verbs["dist_sketch"] = dist_sketch
        verbs["dist_lstsq"] = dist_lstsq
        verbs["dist_svd"] = dist_svd
        verbs["session.open"] = (
            lambda kw, tk: r.open_sketch_session(kw.pop("kind"), **kw))
        verbs["session.append"] = (
            lambda kw, tk: r.session_append(kw.pop("session_id"), **kw))
        verbs["session.finalize"] = (
            lambda kw, tk: r.session_finalize(kw.pop("session_id"),
                                              **kw))
        verbs["train.submit"] = (
            lambda kw, tk: r.submit_train_job(
                kw.pop("spec"), kw.pop("operands", None),
                session_id=kw.pop("session_id", None)))
        verbs["train.resume"] = (
            lambda kw, tk: r.resume_train_job(kw.pop("session_id")))
        verbs["train.status"] = (
            lambda kw, tk: r.train_job_status(kw.pop("session_id")))
        verbs["operand.register"] = (
            lambda kw, tk: r.register_operand(kw.pop("A"), **kw))
        verbs["operand.unregister"] = (
            lambda kw, tk: int(r.unregister_operand(kw.pop("ref"))))
        verbs["stats"] = lambda kw, tk: r.stats()
        verbs["ping"] = lambda kw, tk: "pong"
        return verbs

    def _dispatch(self, conn: _Conn, seq: int, verb: str, kwargs: dict,
                  header: dict) -> None:
        handler = self._verbs.get(verb)
        if handler is None:
            raise _errors.WireProtocolError(f"unknown verb {verb!r}")
        self._count("requests")
        self._count_verb(verb)
        _REQUESTS.inc(verb=verb)
        _LIFETIME.inc("requests")
        transport: dict = {}
        if "tenant" in header:
            transport["tenant"] = header["tenant"]
        if "qos" in header:
            transport["qos_class"] = header["qos"]
        if "deadline_s" in header:
            # remaining-budget semantics: the clock (re)starts at
            # receipt, so network latency never silently eats the
            # budget twice (docs/networking)
            transport["deadline"] = float(header["deadline_s"])
        if "timeout" in header:
            transport["timeout"] = float(header["timeout"])
        trace = header.get("trace") or {}
        rid = trace.get("request_id")
        if rid is not None:
            transport["request_id"] = rid
        parent = None
        if trace.get("trace_id") and trace.get("span_id"):
            parent = _trace.SpanContext(
                str(trace["trace_id"]), str(trace["span_id"]), rid)
        if verb in _BLOCKING_VERBS:
            transport = {}      # control plane: no admission/deadline
        with _trace.span("net.serve", attrs={"verb": verb},
                         parent=parent, request_id=rid):
            result = handler(dict(kwargs), transport)
        if isinstance(result, Future):
            conn.register(seq, result)
            result.add_done_callback(
                lambda f, _c=conn, _s=seq: _c.settle(_s, f))
        else:
            conn.settle(seq, _Resolved(result))

    # -- errors / accounting -------------------------------------------

    def _wire_error(self, conn: _Conn, seq: Optional[int],
                    exc: BaseException,
                    releases_window: bool = False) -> None:
        code = _wire.exc_code(exc)
        self._count("wire_errors")
        self._count_code(code)
        _WIRE_ERRORS.inc(code=str(code))
        _LIFETIME.inc("wire_errors")
        conn.enqueue(_wire.pack_error(seq, exc), releases_window)

    def _note_transport_digest(self, digest) -> None:
        """Duplicate transport digests = a client re-presented a
        request after reconnect (observability only — flight adoption
        keys on the router's content digest, which the identical
        bytes re-derive)."""
        if not digest:
            return
        with self._lock:
            if digest in self._recent_digests:
                self._counts["retries_represented"] += 1
                _LIFETIME.inc("retries_represented")
                return
            self._recent_digests[digest] = None
            while len(self._recent_digests) > 4096:
                self._recent_digests.popitem(last=False)

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] += n
        if key == "bytes_in":
            _BYTES_IN.inc(n)
            _LIFETIME.inc("bytes_in", n)
        elif key == "bytes_out":
            _LIFETIME.inc("bytes_out", n)

    def _count_verb(self, verb: str) -> None:
        with self._lock:
            self._counts[f"verb:{verb}"] += 1

    def _count_code(self, code: int) -> None:
        with self._lock:
            self._counts[f"code:{code}"] += 1

    # -- drain / close -------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """GOAWAY every live connection, stop accepting, wait for
        inflight responses to flush (bounded by ``timeout``, default
        ``SKYLARK_NET_DRAIN_TIMEOUT_S``), then close. Returns whether
        quiescence was reached inside the budget. Idempotent."""
        timeout = self.drain_timeout_s if timeout is None else timeout
        with self._lock:
            already = self._draining
            self._draining = True
        if not already:
            try:
                self._listener.close()
            except OSError:
                pass
            for conn in list(self._conns):
                conn.goaway(timeout)
        deadline = time.monotonic() + max(0.0, float(timeout))
        quiesced = False
        while time.monotonic() < deadline:
            if all(c.pending_count() == 0 for c in list(self._conns)):
                quiesced = True
                break
            time.sleep(0.005)
        else:
            quiesced = all(c.pending_count() == 0
                           for c in list(self._conns))
        for conn in list(self._conns):
            conn.close()
        if not already:
            self._count("drains")
            _DRAINS.inc()
            _LIFETIME.inc("drains")
        return quiesced

    def _on_preempt(self) -> None:
        # SIGTERM: the executor drain already settled queued work
        # (hook order — drain_serving runs first), so the remaining
        # job is the socket layer's: GOAWAY, flush, close
        self.drain()

    def close(self) -> None:
        """Tear down without the drain grace (tests; ``drain()`` first
        for the graceful path). Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._unhook()
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in list(self._conns):
            conn.close()

    def __enter__(self) -> "NetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- stats ---------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            c = dict(self._counts)
            conns = list(self._conns)
        by_verb = {k.split(":", 1)[1]: v for k, v in c.items()
                   if k.startswith("verb:")}
        by_code = {k.split(":", 1)[1]: v for k, v in c.items()
                   if k.startswith("code:")}
        return {
            "address": list(self.address),
            "connections_live": len(conns),
            "pending": sum(cn.inflight_count() for cn in conns),
            "accepted": c.get("accepted", 0),
            "refused": c.get("refused", 0),
            "requests": c.get("requests", 0),
            "responses_sent": c.get("responses_sent", 0),
            "wire_errors": c.get("wire_errors", 0),
            "bytes_in": c.get("bytes_in", 0),
            "bytes_out": c.get("bytes_out", 0),
            "goaways_sent": c.get("goaways_sent", 0),
            "drains": c.get("drains", 0),
            "disconnected_inflight": c.get("disconnected_inflight", 0),
            "retries_represented": c.get("retries_represented", 0),
            "draining": self._draining,
            "by_verb": by_verb,
            "by_code": by_code,
        }


class _Resolved(Future):
    """A pre-resolved future (uniform settle path for blocking verbs)."""

    def __init__(self, value):
        super().__init__()
        self.set_result(value)


def net_stats() -> dict:
    """Aggregate front-door counters over every live server (the
    ``net`` collector block in ``telemetry.snapshot()`` — rendered as
    ``skylark_net_*`` on the Prometheus surface), plus the
    process-lifetime rollup that survives server teardown."""
    agg = collections.Counter(
        accepted=0, refused=0, requests=0, responses_sent=0,
        wire_errors=0, bytes_in=0, bytes_out=0, goaways_sent=0,
        drains=0, disconnected_inflight=0, retries_represented=0)
    by_verb: "collections.Counter" = collections.Counter()
    by_code: "collections.Counter" = collections.Counter()
    servers = 0
    live = 0
    for srv in list(_SERVERS):
        s = srv.stats()
        servers += 1
        live += s["connections_live"]
        for k in agg:
            agg[k] += s[k]
        by_verb.update(s["by_verb"])
        by_code.update(s["by_code"])
    out = dict(agg)
    out["servers"] = servers
    out["connections_live"] = live
    out["by_verb"] = {k: {"requests": v}
                      for k, v in sorted(by_verb.items())}
    out["by_code"] = {k: {"errors": v}
                      for k, v in sorted(by_code.items())}
    out.update(_LIFETIME.snapshot())
    return out


_telemetry.register_collector("net", net_stats)

__all__ = ["NetServer", "net_stats"]
