"""Deterministic, pickle-free framed wire protocol for the serve tier.

The journal-v2 / train-state codec discipline (sessions/journal.py,
train/slices.py) promoted to a socket: every message is one
**self-delimiting, CRC-guarded frame** whose header is canonical JSON
and whose array payloads are raw ``.npy`` streams written with
``allow_pickle=False``. Nothing on the wire can execute code on
decode, and the same logical message always encodes to the same bytes
(sorted JSON keys, versioned npy format) — which is what makes a
client retry *re-send the identical request* and the server's
content-addressed single-flight table (docs/caching) adopt it onto
the original flight instead of recomputing.

Frame anatomy (docs/networking, "Frame anatomy")::

    MAGIC(4) | u32 payload_len | u32 crc32(payload) | payload
    payload = u32 header_len | header_json | body_0 .. body_{k-1}
    body_i  = one .npy stream (np.lib.format, allow_pickle=False)

``MAGIC = b"SKW1"`` carries the protocol version; a reader that sees
anything else has lost frame sync and must tear the connection down
(:class:`~libskylark_tpu.base.errors.WireProtocolError` — resyncing a
corrupt stream by scanning would risk executing a half-frame as a
fresh one). The CRC guards the *payload*; the length fields guard
the CRC (a torn length reads as short payload → CRC mismatch).

Values (request kwargs, response results) cross the wire through a
small recursive **tagged codec** (:func:`encode_value` /
:func:`decode_value`): JSON scalars inline; ndarrays (any dtype,
order, or striding) and numpy scalars as npy bodies; CSR sparse
operands as their three part arrays (never densified); sketch
transforms, kernels, shard plans, and train specs as their existing
``to_dict`` registry forms (``deserialize_sketch`` /
``deserialize_kernel`` / ``ShardPlan.from_dict`` /
``TrainJobSpec.from_dict``); operand-residency refs as their digest
strings. Anything else is a :class:`WireProtocolError` at *encode*
time — the codec refuses to invent a representation.

Error frames carry the stable :mod:`libskylark_tpu.base.errors` code
table (code 117 = protocol violation; ``WIRE_OVERLOADED_CODE`` 118 =
``engine.serve.ServeOverloadedError``), the message, and the
structured retry fields (``retry_after_s``, ``tenant``) so a client
reconstructs the *same* exception type with the same backoff hint the
server raised (docs/networking, "Error codes").
"""

from __future__ import annotations

import hashlib
import io
import json
import struct
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from libskylark_tpu.base import errors as _errors

MAGIC = b"SKW1"
_LEN = struct.Struct("<II")          # payload length, crc32(payload)
_HLEN = struct.Struct("<I")          # header length inside the payload

#: Sanity bound on one frame (header + bodies). Operands bigger than
#: this belong on the residency path (``operand.register`` + ref
#: submits), not inline in every request.
MAX_FRAME_BYTES = 1 << 30

#: Sanity bound on the JSON header alone — a "header length" beyond
#: this is a torn or hostile stream, not a real request.
MAX_HEADER_BYTES = 1 << 24

# frame types
REQ = "req"
RES = "res"
ERR = "err"
GOAWAY = "goaway"


class PeerClosed(Exception):
    """Clean EOF at a frame boundary — the peer hung up between
    frames. Not a protocol violation (mid-frame EOF is)."""


# ---------------------------------------------------------------------------
# the tagged value codec
# ---------------------------------------------------------------------------


def _is_jsonable_scalar(v) -> bool:
    return v is None or isinstance(v, (bool, int, float, str))


def encode_value(v, bodies: List[np.ndarray]):
    """Encode one value to its JSON-safe tagged spec, appending any
    array payloads to ``bodies`` (the frame's npy section, in spec
    order). Deterministic: the same value always yields the same spec
    and the same body bytes."""
    from libskylark_tpu.base.sparse import SparseMatrix
    from libskylark_tpu.engine import resultcache as _rcache
    from libskylark_tpu.ml.kernels import Kernel
    from libskylark_tpu.sketch.transform import Dimension, SketchTransform

    if _is_jsonable_scalar(v):
        return {"k": "py", "v": v}
    if isinstance(v, np.ndarray):
        bodies.append(v)
        return {"k": "nd", "i": len(bodies) - 1}
    if isinstance(v, np.generic):
        bodies.append(np.asarray(v))
        return {"k": "n0", "i": len(bodies) - 1}
    if isinstance(v, Dimension):
        return {"k": "dim", "v": v.value}
    if isinstance(v, SparseMatrix):
        data, indices, indptr = v.csr_parts()
        base = len(bodies)
        bodies.extend((data, indices, indptr))
        return {"k": "csr", "i": base, "shape": [int(s) for s in v.shape]}
    if isinstance(v, SketchTransform):
        return {"k": "sketch", "d": v.to_dict()}
    if isinstance(v, Kernel):
        return {"k": "kernel", "d": v.to_dict()}
    if _rcache.is_ref(v):
        return {"k": "ref", "v": str(_rcache.as_ref(v).digest)}
    # late imports: train/dist are optional layers above the codec
    from libskylark_tpu.dist.plan import ShardPlan
    from libskylark_tpu.train.jobs import TrainJobSpec

    if isinstance(v, ShardPlan):
        return {"k": "plan", "d": v.to_dict()}
    if isinstance(v, TrainJobSpec):
        return {"k": "tspec", "d": v.to_dict()}
    if isinstance(v, tuple):
        return {"k": "tup", "x": [encode_value(x, bodies) for x in v]}
    if isinstance(v, list):
        return {"k": "list", "x": [encode_value(x, bodies) for x in v]}
    if isinstance(v, dict):
        bad = [k for k in v if not isinstance(k, str)]
        if bad:
            raise _errors.WireProtocolError(
                f"wire dicts need str keys, got {type(bad[0]).__name__}")
        return {"k": "map",
                "x": {k: encode_value(v[k], bodies) for k in sorted(v)}}
    if hasattr(v, "__array__"):
        # device arrays (jax) and other array-likes: ship the host copy
        bodies.append(np.asarray(v))
        return {"k": "nd", "i": len(bodies) - 1}
    raise _errors.WireProtocolError(
        f"value of type {type(v).__name__} has no wire encoding")


def decode_value(spec, bodies: List[np.ndarray]):
    """Inverse of :func:`encode_value`."""
    from libskylark_tpu.base.sparse import SparseMatrix
    from libskylark_tpu.engine import resultcache as _rcache
    from libskylark_tpu.ml.kernels import deserialize_kernel
    from libskylark_tpu.sketch.transform import (
        Dimension, deserialize_sketch,
    )

    if not isinstance(spec, dict) or "k" not in spec:
        raise _errors.WireProtocolError(f"malformed value spec {spec!r}")
    k = spec["k"]
    try:
        if k == "py":
            return spec["v"]
        if k == "nd":
            return bodies[spec["i"]]
        if k == "n0":
            return bodies[spec["i"]][()]
        if k == "dim":
            return Dimension(spec["v"])
        if k == "csr":
            i = spec["i"]
            return SparseMatrix.from_csr(
                bodies[i], bodies[i + 1], bodies[i + 2],
                tuple(spec["shape"]))
        if k == "sketch":
            return deserialize_sketch(spec["d"])
        if k == "kernel":
            return deserialize_kernel(spec["d"])
        if k == "ref":
            return _rcache.OperandRef(spec["v"])
        if k == "plan":
            from libskylark_tpu.dist.plan import ShardPlan

            return ShardPlan.from_dict(spec["d"])
        if k == "tspec":
            from libskylark_tpu.train.jobs import TrainJobSpec

            return TrainJobSpec.from_dict(spec["d"])
        if k == "tup":
            return tuple(decode_value(x, bodies) for x in spec["x"])
        if k == "list":
            return [decode_value(x, bodies) for x in spec["x"]]
        if k == "map":
            return {name: decode_value(x, bodies)
                    for name, x in spec["x"].items()}
    except _errors.SkylarkError:
        raise
    except Exception as e:  # noqa: BLE001 — decode is a trust boundary
        raise _errors.WireProtocolError(
            f"failed to decode {k!r} value: {e}") from e
    raise _errors.WireProtocolError(f"unknown value tag {k!r}")


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------


def encode_frame(header: dict, bodies: Tuple[np.ndarray, ...] = ()) -> bytes:
    """One complete frame as bytes (header JSON + npy bodies, length-
    and CRC-prefixed)."""
    hdr = json.dumps(header, sort_keys=True,
                     separators=(",", ":")).encode("utf-8")
    buf = io.BytesIO()
    buf.write(_HLEN.pack(len(hdr)))
    buf.write(hdr)
    for arr in bodies:
        np.lib.format.write_array(buf, np.asarray(arr),
                                  allow_pickle=False)
    payload = buf.getvalue()
    if len(payload) > MAX_FRAME_BYTES:
        raise _errors.WireProtocolError(
            f"frame payload {len(payload)} bytes exceeds "
            f"MAX_FRAME_BYTES {MAX_FRAME_BYTES}")
    return (MAGIC + _LEN.pack(len(payload), zlib.crc32(payload))
            + payload)


def decode_payload(payload: bytes) -> Tuple[dict, List[np.ndarray]]:
    """Header + bodies from one CRC-verified frame payload."""
    if len(payload) < _HLEN.size:
        raise _errors.WireProtocolError("frame payload shorter than "
                                        "its header-length field")
    (hlen,) = _HLEN.unpack_from(payload, 0)
    if hlen > MAX_HEADER_BYTES or _HLEN.size + hlen > len(payload):
        raise _errors.WireProtocolError(
            f"frame header length {hlen} exceeds payload")
    try:
        header = json.loads(
            payload[_HLEN.size:_HLEN.size + hlen].decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise _errors.WireProtocolError(
            f"frame header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise _errors.WireProtocolError("frame header is not an object")
    bodies: List[np.ndarray] = []
    buf = io.BytesIO(payload)
    buf.seek(_HLEN.size + hlen)
    n_bodies = int(header.get("nb", 0))
    for _ in range(n_bodies):
        try:
            bodies.append(np.lib.format.read_array(
                buf, allow_pickle=False))
        except Exception as e:  # noqa: BLE001 — torn/hostile npy
            raise _errors.WireProtocolError(
                f"frame body failed to decode: {e}") from e
    if buf.read(1):
        raise _errors.WireProtocolError(
            "frame payload has trailing bytes past its declared bodies")
    return header, bodies


def read_frame(recv: Callable[[int], bytes]) -> Tuple[dict,
                                                      List[np.ndarray]]:
    """Read one frame through ``recv(n) -> exactly-n-or-fewer bytes``
    (a socket ``recv``). Raises :class:`PeerClosed` on clean EOF at a
    frame boundary, :class:`~libskylark_tpu.base.errors
    .WireProtocolError` on bad magic, bad CRC, or mid-frame EOF."""
    head = _recv_exact(recv, len(MAGIC) + _LEN.size, at_boundary=True)
    if head is None:
        raise PeerClosed()
    if head[:len(MAGIC)] != MAGIC:
        raise _errors.WireProtocolError(
            f"bad frame magic {head[:len(MAGIC)]!r} (stream lost sync)")
    plen, crc = _LEN.unpack_from(head, len(MAGIC))
    if plen > MAX_FRAME_BYTES:
        raise _errors.WireProtocolError(
            f"frame length {plen} exceeds MAX_FRAME_BYTES")
    payload = _recv_exact(recv, plen)
    if zlib.crc32(payload) != crc:
        raise _errors.WireProtocolError("frame CRC mismatch")
    return decode_payload(payload)


def _recv_exact(recv: Callable[[int], bytes], n: int,
                at_boundary: bool = False) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        chunk = recv(min(n - got, 1 << 20))
        if not chunk:
            if at_boundary and got == 0:
                return None
            raise _errors.WireProtocolError(
                f"connection closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


# ---------------------------------------------------------------------------
# requests / responses / errors
# ---------------------------------------------------------------------------


def pack_request(verb: str, kwargs: dict, *, seq: int,
                 tenant: Optional[str] = None,
                 qos_class: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 timeout: Optional[float] = None,
                 trace: Optional[dict] = None) -> bytes:
    """One request frame. ``kwargs`` are the verb's operand kwargs
    (transport fields ride the header, never the kwarg map). The
    header's ``digest`` is blake2b over the encoded kwarg section —
    the transport idempotency token a reconnect-retry re-presents;
    flight adoption itself keys on the router's *content* digest,
    which the identical re-sent bytes re-derive (docs/networking,
    "Retry & idempotency")."""
    bodies: List[np.ndarray] = []
    kw = {k: encode_value(kwargs[k], bodies) for k in sorted(kwargs)}
    h = hashlib.blake2b(
        json.dumps(kw, sort_keys=True).encode(), digest_size=16)
    for arr in bodies:
        a = np.ascontiguousarray(arr)
        h.update(str(a.dtype).encode() + repr(a.shape).encode())
        h.update(a.tobytes())
    header = {
        "t": REQ, "verb": str(verb), "seq": int(seq), "kw": kw,
        "nb": len(bodies), "digest": h.hexdigest(),
    }
    if tenant is not None:
        header["tenant"] = str(tenant)
    if qos_class is not None:
        header["qos"] = str(qos_class)
    if deadline_s is not None:
        header["deadline_s"] = float(deadline_s)
    if timeout is not None:
        header["timeout"] = float(timeout)
    if trace:
        header["trace"] = trace
    return encode_frame(header, tuple(bodies))


def unpack_request(header: dict,
                   bodies: List[np.ndarray]) -> Tuple[str, dict]:
    """(verb, kwargs) from a request frame's header + bodies."""
    verb = header.get("verb")
    kw = header.get("kw")
    if not isinstance(verb, str) or not isinstance(kw, dict):
        raise _errors.WireProtocolError(
            "request frame missing verb/kw fields")
    return verb, {k: decode_value(v, bodies) for k, v in kw.items()}


def pack_result(seq: int, value) -> bytes:
    bodies: List[np.ndarray] = []
    spec = encode_value(value, bodies)
    return encode_frame(
        {"t": RES, "seq": int(seq), "value": spec, "nb": len(bodies)},
        tuple(bodies))


def unpack_result(header: dict, bodies: List[np.ndarray]):
    return decode_value(header.get("value"), bodies)


def pack_error(seq: Optional[int], exc: BaseException) -> bytes:
    """One structured error frame: stable code, message, and the
    retry fields (``retry_after_s`` / ``tenant``) the matching
    exception classes carry."""
    code = exc_code(exc)
    header = {
        "t": ERR, "code": code, "error": type(exc).__name__,
        "message": str(exc),
        "retry_after_s": float(getattr(exc, "retry_after_s", 0.0)),
    }
    if seq is not None:
        header["seq"] = int(seq)
    tenant = getattr(exc, "tenant", None)
    if tenant:
        header["tenant"] = str(tenant)
    return encode_frame(header)


def exc_code(exc: BaseException) -> int:
    """The wire error code for one exception (docs/networking, "Error
    codes"): SkylarkError subclasses carry their own stable code;
    ``ServeOverloadedError`` (a RuntimeError by design) maps to
    :data:`~libskylark_tpu.base.errors.WIRE_OVERLOADED_CODE`;
    everything else degrades to the base code 100 with the type name
    prefixed into the message by :func:`pack_error`'s caller."""
    from libskylark_tpu.engine.serve import ServeOverloadedError

    if isinstance(exc, ServeOverloadedError):
        return _errors.WIRE_OVERLOADED_CODE
    if isinstance(exc, _errors.SkylarkError):
        return int(getattr(exc, "code", _errors.SkylarkError.code))
    return _errors.SkylarkError.code


def unpack_error(header: dict) -> BaseException:
    """Reconstruct the typed exception an error frame describes, with
    retry fields intact (the ``retry_after_s`` fidelity contract)."""
    from libskylark_tpu.engine.serve import ServeOverloadedError

    code = int(header.get("code", _errors.SkylarkError.code))
    message = str(header.get("message", ""))
    retry_after = float(header.get("retry_after_s", 0.0))
    if code == _errors.WIRE_OVERLOADED_CODE:
        exc: BaseException = ServeOverloadedError(message)
        exc.retry_after_s = retry_after
        return exc
    if code == _errors.TenantQuotaError.code:
        return _errors.TenantQuotaError(
            message, tenant=str(header.get("tenant", "")),
            retry_after_s=retry_after)
    exc = _errors.from_code(code, message)
    if retry_after:
        exc.retry_after_s = retry_after
    return exc


def pack_goaway(drain_timeout_s: float) -> bytes:
    return encode_frame(
        {"t": GOAWAY, "drain_timeout_s": float(drain_timeout_s)})


#: header fields carrying span identity across the wire — the client
#: puts its SpanContext here; the server opens its ``net.serve`` span
#: with ``parent=SpanContext(**trace)`` so the request's tree is one
#: trace end to end (docs/observability).
TRACE_FIELDS = ("trace_id", "span_id", "request_id")


def trace_header(ctx) -> Optional[Dict[str, Optional[str]]]:
    if ctx is None:
        return None
    return {f: getattr(ctx, f, None) for f in TRACE_FIELDS}


__all__ = [
    "ERR", "GOAWAY", "MAGIC", "MAX_FRAME_BYTES", "PeerClosed", "REQ",
    "RES", "decode_payload", "decode_value", "encode_frame",
    "encode_value", "exc_code", "pack_error", "pack_goaway",
    "pack_request", "pack_result", "read_frame", "trace_header",
    "unpack_error", "unpack_request", "unpack_result",
]
