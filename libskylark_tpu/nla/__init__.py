"""NLA layer: randomized SVD, least squares, condition estimation, spectral
helpers (SURVEY.md §2.4)."""

from libskylark_tpu.nla import (
    condest,
    krank,
    least_squares,
    lowrank,
    randlobpcg,
    spectral,
    svd,
)
from libskylark_tpu.nla.condest import condest as estimate_condition
from libskylark_tpu.nla.krank import (
    RandomizedRangeFinder,
    RangeAssistedEVD,
    RangeAssistedSVD,
    randomized_svd,
    srft_matrix,
)
from libskylark_tpu.nla.lowrank import approximate_dominant_subspace_basis
from libskylark_tpu.nla.randlobpcg import (
    lobpcg_rand_evd,
    power_iterations_rand_evd,
)
from libskylark_tpu.nla.least_squares import (
    approximate_least_squares,
    fast_least_squares,
)
from libskylark_tpu.nla.spectral import chebyshev_diff_matrix, chebyshev_points
from libskylark_tpu.nla.svd import (
    ApproximateSVDParams,
    approximate_svd,
    approximate_symmetric_svd,
    power_iteration,
)

__all__ = [
    "condest",
    "krank",
    "lowrank",
    "randlobpcg",
    "RandomizedRangeFinder",
    "RangeAssistedSVD",
    "RangeAssistedEVD",
    "randomized_svd",
    "srft_matrix",
    "approximate_dominant_subspace_basis",
    "lobpcg_rand_evd",
    "power_iterations_rand_evd",
    "least_squares",
    "spectral",
    "svd",
    "estimate_condition",
    "approximate_least_squares",
    "fast_least_squares",
    "chebyshev_points",
    "chebyshev_diff_matrix",
    "ApproximateSVDParams",
    "approximate_svd",
    "approximate_symmetric_svd",
    "power_iteration",
]
