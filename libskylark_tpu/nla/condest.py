"""Condition number estimation via Golub-Kahan bidiagonalization.

TPU-native analog of ref: nla/CondEst.hpp:67-305, which runs LSQR and feeds
its bidiagonal coefficients to LAPACK ``dbdsqr``. Here we run the same
Golub-Kahan recurrence (LSQR's core) for a fixed number of steps collecting
(alpha, beta), then take the singular values of the small lower-bidiagonal
matrix B_k: σ_max(B_k) ↗ σ_max(A) and σ_min(B_k) ↘ σ_min(A) as k grows.
Convergence heuristics mirror the reference's C1-C4 idea: stop when both
extremes stabilize to a relative tolerance.

Two drivers share the recurrence:
- local operands (dense / SparseMatrix) → float64 numpy/scipy on host, the
  ``dbdsqr``-grade diagnostic path;
- :class:`DistSparseMatrix` → the recurrence runs ON DEVICE through
  ``spmm``/``spmm_t`` (the SUMMA products, one psum each), with the
  reorthogonalization as device dots against the stored Krylov bases —
  the operand is never gathered to one host (the reference likewise
  drives the recurrence against the distributed operand,
  ref: nla/CondEst.hpp:67-305). Only the (k+1)×k bidiagonal SVD runs on
  host.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from libskylark_tpu.base.context import Context
from libskylark_tpu.base.precision import with_solver_precision


@with_solver_precision
def condest(
    A,
    context: Context,
    max_iter: int = 100,
    tol: float = 1e-3,
) -> Tuple[float, float, float]:
    """Estimate (cond, sigma_max, sigma_min) of A (m ≥ n recommended).

    ``A`` may be a dense array, a :class:`SparseMatrix` (scipy matvecs on
    host, float64), or a :class:`DistSparseMatrix` (device-side recurrence
    over the distributed operand — see module docstring). Deterministic
    given the context (the start vector comes from an allocation key).
    Host-side driver loop; each step is two matvecs.
    """
    from libskylark_tpu.base.dist_sparse import DistSparseMatrix
    from libskylark_tpu.base.sparse import SparseMatrix

    if isinstance(A, DistSparseMatrix):
        return _condest_device(A, context, max_iter, tol)
    # Full float64 with reorthogonalization: Golub-Kahan in f32 loses
    # orthogonality within tens of steps and manufactures spurious small
    # singular values, wrecking the sigma_min estimate. This is a
    # host-side diagnostic (the reference's is serial LAPACK too,
    # ref: nla/CondEst.hpp:12-16), so f64 numpy is the right tool.
    # Sparse operands stay sparse: scipy matvecs drive the same loop.
    if isinstance(A, SparseMatrix):
        A = A.to_scipy().astype(np.float64)
    else:
        A = np.asarray(jax.device_get(A), dtype=np.float64)
    m, n = A.shape
    key = context.allocate().key
    b = np.asarray(jr.normal(key, (m,), jnp.float32), dtype=np.float64)
    return _golub_kahan(
        matvec=lambda x: A @ x,
        rmatvec=lambda x: A.T @ x,
        b=b,
        shape=(m, n),
        max_iter=max_iter,
        tol=tol,
        dot=lambda x, y: float(x @ y),
        norm=lambda x: float(np.linalg.norm(x)),
    )


def _condest_device(D, context: Context, max_iter: int, tol: float
                    ) -> Tuple[float, float, float]:
    """Golub-Kahan against a DistSparseMatrix, on device.

    u lives sharded on ``row_axis`` (spmm output), v on ``col_axis``
    (spmm_t output); the Krylov bases are kept as device vectors and the
    reorthogonalization coefficients stay device scalars (no host
    readback inside the projection loop — only the two per-step norms
    sync, for the breakdown/convergence checks). f32 with full two-sided
    reorthogonalization holds the bidiagonal to oracle grade at the
    moderate k this estimator needs (validated against the f64 host path
    in tests/test_nla.py)."""
    m, n = D.shape
    key = context.allocate().key
    b = jr.normal(key, (m,), jnp.float32)
    return _golub_kahan(
        matvec=D.spmm,
        rmatvec=D.spmm_t,
        b=b,
        shape=(m, n),
        max_iter=max_iter,
        tol=tol,
        dot=jnp.vdot,
        norm=lambda x: float(jnp.linalg.norm(x)),
    )


def _golub_kahan(
    matvec: Callable,
    rmatvec: Callable,
    b,
    shape: Tuple[int, int],
    max_iter: int,
    tol: float,
    dot: Callable,
    norm: Callable,
) -> Tuple[float, float, float]:
    """The shared recurrence. ``matvec``/``rmatvec`` close over the
    operand (numpy, scipy, or DistSparseMatrix products); vectors stay in
    whatever space the closures produce."""
    m, n = shape
    beta = norm(b)
    u = b / beta
    v = rmatvec(u)
    alpha = norm(v)
    v = v / alpha

    Us = [u]
    Vs = [v]
    alphas = [alpha]
    betas: list[float] = []
    prev = None
    # The Krylov space is exhausted after min(m, n) steps; beyond that the
    # recurrence only manufactures noise-level coefficients.
    max_iter = min(max_iter, min(m, n) - 1)
    for it in range(max_iter):
        u = matvec(v) - alpha * u
        # Two-sided reorthogonalization: without it the bidiagonal stops
        # being a valid orthogonal projection and its singular values can
        # escape [sigma_min, sigma_max] (interlacing breaks).
        for up in Us:
            u = u - dot(up, u) * up
        beta = norm(u)
        if beta <= 1e-12 * max(alphas):
            break
        u = u / beta
        Us.append(u)
        v = rmatvec(u) - beta * v
        for vp in Vs:
            v = v - dot(vp, v) * vp
        alpha = norm(v)
        if alpha <= 1e-12 * max(alphas):
            betas.append(beta)
            break
        v = v / alpha
        Vs.append(v)
        betas.append(beta)
        alphas.append(alpha)

        if it >= 3 and (it % 5 == 0 or it == max_iter - 1):
            sv = _bidiag_svals(matvec, Us, Vs, alphas, betas, dot, norm)
            cur = (sv[0], sv[-1])
            if prev is not None:
                rel_max = abs(cur[0] - prev[0]) / max(cur[0], 1e-30)
                rel_min = abs(cur[1] - prev[1]) / max(cur[1], 1e-30)
                if rel_max < tol and rel_min < tol:
                    prev = cur
                    break
            prev = cur

    sv = _bidiag_svals(matvec, Us, Vs, alphas, betas, dot, norm)
    smax, smin = float(sv[0]), float(sv[-1])
    return (smax / max(smin, np.finfo(np.float64).tiny), smax, smin)


# ---------------------------------------------------------------------------
# Pure, vmap-batchable serve endpoint (docs/qos "Heterogeneous serve
# endpoints"; served by engine/serve.py submit_condest).
# ---------------------------------------------------------------------------


def condest_serve_apply(key_data, A, *, steps: int) -> jnp.ndarray:
    """One request's ``(cond, sigma_max, sigma_min)`` — as a (3,)
    vector — by a FIXED number of Golub-Kahan steps with full
    two-sided reorthogonalization, all on device: the serving-shaped
    twin of :func:`condest`. The step count is static (a bucket
    component), the start vector comes from the raw PRNG key, and the
    small ``(steps+1) x steps`` bidiagonal's SVD runs inside the same
    executable — pure in (key bits, operand bits), so a vmapped
    flush is bit-equal per lane to its capacity-1 dispatch. Zero
    padding is benign: padded rows/columns of ``A`` are zero, the
    Krylov vectors stay inside the true row/column spaces, and the
    rectangular bidiagonal's singular values still interlace
    ``[sigma_min, sigma_max]`` of the true operand. Adaptive
    convergence (and the f64 reorthogonalization grade) stays with
    the host-side :func:`condest` diagnostic."""
    import jax.random as jr

    tiny = jnp.asarray(np.finfo(np.float32).tiny, A.dtype)

    def _nrm(x):
        return jnp.maximum(jnp.linalg.norm(x), tiny)

    key = jr.wrap_key_data(jnp.asarray(key_data))
    b = jr.normal(key, (A.shape[0],), A.dtype)
    beta = _nrm(b)
    u = b / beta
    v = A.T @ u
    alpha = _nrm(v)
    v = v / alpha

    Us = [u]
    Vs = [v]
    alphas = [alpha]
    betas = []
    for _ in range(max(int(steps), 1)):
        u = A @ v - alpha * u
        for up in Us:
            u = u - (up @ u) * up
        beta = _nrm(u)
        u = u / beta
        Us.append(u)
        v = A.T @ u - beta * v
        for vp in Vs:
            v = v - (vp @ v) * vp
        alpha = _nrm(v)
        v = v / alpha
        Vs.append(v)
        betas.append(beta)
        alphas.append(alpha)

    # the trailing-beta rectangular bidiagonal (see _bidiag_svals)
    u_t = A @ Vs[-1] - alphas[-1] * Us[-1]
    for up in Us:
        u_t = u_t - (up @ u_t) * up
    k = len(alphas)
    B = jnp.zeros((k + 1, k), A.dtype)
    B = B.at[jnp.arange(k), jnp.arange(k)].set(jnp.stack(alphas))
    if k > 1:
        B = B.at[jnp.arange(1, k), jnp.arange(k - 1)].set(
            jnp.stack(betas[: k - 1]))
    B = B.at[k, k - 1].set(_nrm(u_t))
    sv = jnp.linalg.svd(B, compute_uv=False)
    smax = sv[0]
    smin = jnp.maximum(sv[-1], tiny)
    return jnp.stack([smax / smin, smax, sv[-1]])


def condest_serve(A, *, steps: int = 8, seed: int = 0,
                  dtype=np.float32):
    """Eager twin of the ``condest`` serve endpoint: pads ``A`` to the
    serve layer's pow2 class and runs :func:`condest_serve_apply` on
    the identical bits (the qos tests' bit-equality reference).
    Returns the ``(cond, sigma_max, sigma_min)`` triple as floats."""
    import jax.random as jr

    from libskylark_tpu.engine import bucket as bucketing

    A = np.asarray(A, dtype=np.dtype(dtype))
    if A.ndim != 2:
        raise ValueError(f"condest expects a matrix, got {A.shape}")
    padded = bucketing.pad_shape(A.shape, (0, 1))
    Ap = np.zeros(padded, dtype=A.dtype)
    Ap[: A.shape[0], : A.shape[1]] = A
    kd = np.asarray(jr.key_data(jr.key(int(seed))), dtype=np.uint32)
    # the twin runs the literal capacity-1 serve program shape (one
    # lane-indexed stack): XLA fuses the recurrence differently when
    # the lane indexing is absent, and the bit-equality contract is
    # against the serve dispatch, not against eager op-by-op order
    run = jax.jit(lambda kds, As: jnp.stack(
        [condest_serve_apply(kds[0], As[0], steps=int(steps))]))
    out = np.asarray(run(kd[None], jnp.asarray(Ap)[None])[0])
    return float(out[0]), float(out[1]), float(out[2])


def _bidiag_svals(matvec, Us, Vs, alphas, betas, dot, norm) -> np.ndarray:
    """Singular values of the *rectangular* (k+1)×k Golub-Kahan bidiagonal
    (host-side LAPACK, the ``dbdsqr`` analog — ref: nla/CondEst.hpp:12-16).

    The trailing beta row is required: B_rect = U_{k+1}ᵀ·A·V_k has
    σ_i(B) = σ_i(A·V_k) ∈ [σ_min(A), σ_max(A)]; the square truncation does
    not interlace and can report spuriously small σ_min.
    """
    k = len(alphas)
    u_t = matvec(Vs[-1]) - alphas[-1] * Us[-1]
    for up in Us:
        u_t = u_t - dot(up, u_t) * up
    beta_t = norm(u_t)
    B = np.zeros((k + 1, k))
    for i, a in enumerate(alphas):
        B[i, i] = a
    for i, b in enumerate(betas[: k - 1]):
        B[i + 1, i] = b
    B[k, k - 1] = beta_t
    return np.linalg.svd(B, compute_uv=False)
