"""Condition number estimation via Golub-Kahan bidiagonalization.

TPU-native analog of ref: nla/CondEst.hpp:67-305, which runs LSQR and feeds
its bidiagonal coefficients to LAPACK ``dbdsqr``. Here we run the same
Golub-Kahan recurrence (LSQR's core) for a fixed number of steps collecting
(alpha, beta), then take the singular values of the small lower-bidiagonal
matrix B_k: σ_max(B_k) ↗ σ_max(A) and σ_min(B_k) ↘ σ_min(A) as k grows.
Convergence heuristics mirror the reference's C1-C4 idea: stop when both
extremes stabilize to a relative tolerance.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from libskylark_tpu.base.context import Context
from libskylark_tpu.base.precision import with_solver_precision


@with_solver_precision
def condest(
    A,
    context: Context,
    max_iter: int = 100,
    tol: float = 1e-3,
) -> Tuple[float, float, float]:
    """Estimate (cond, sigma_max, sigma_min) of A (m ≥ n recommended).

    ``A`` may be a dense array, a :class:`SparseMatrix`, or a
    :class:`DistSparseMatrix` (sparse operands drive the loop through
    scipy matvecs). Deterministic given the context (the start vector
    comes from an allocation key). Host-side driver loop; each step is
    two matvecs.
    """
    from libskylark_tpu.base.dist_sparse import DistSparseMatrix
    from libskylark_tpu.base.sparse import SparseMatrix
    # Full float64 with one-sided reorthogonalization: Golub-Kahan in f32
    # loses orthogonality within tens of steps and manufactures spurious
    # small singular values, wrecking the sigma_min estimate. This is a
    # host-side diagnostic (the reference's is serial LAPACK too,
    # ref: nla/CondEst.hpp:12-16), so f64 numpy is the right tool.
    # Sparse operands stay sparse: scipy matvecs drive the same loop.
    if isinstance(A, SparseMatrix):
        A = A.to_scipy().astype(np.float64)
    elif isinstance(A, DistSparseMatrix):
        A = A.to_local().to_scipy().astype(np.float64)
    else:
        A = np.asarray(jax.device_get(A), dtype=np.float64)
    m, n = A.shape
    key = context.allocate().key
    b = np.asarray(jr.normal(key, (m,), jnp.float32), dtype=np.float64)

    beta = float(np.linalg.norm(b))
    u = b / beta
    v = A.T @ u
    alpha = float(np.linalg.norm(v))
    v = v / alpha

    Us = [u]
    Vs = [v]
    alphas = [alpha]
    betas = []
    prev = None
    # The Krylov space is exhausted after min(m, n) steps; beyond that the
    # recurrence only manufactures noise-level coefficients.
    max_iter = min(max_iter, min(m, n) - 1)
    for it in range(max_iter):
        u = A @ v - alpha * u
        # Two-sided reorthogonalization: without it the bidiagonal stops
        # being a valid orthogonal projection and its singular values can
        # escape [sigma_min, sigma_max] (interlacing breaks).
        for up in Us:
            u -= (up @ u) * up
        beta = float(np.linalg.norm(u))
        if beta <= 1e-12 * max(alphas):
            break
        u = u / beta
        Us.append(u)
        v = A.T @ u - beta * v
        for vp in Vs:
            v -= (vp @ v) * vp
        alpha = float(np.linalg.norm(v))
        if alpha <= 1e-12 * max(alphas):
            betas.append(beta)
            break
        v = v / alpha
        Vs.append(v)
        betas.append(beta)
        alphas.append(alpha)

        if it >= 3 and (it % 5 == 0 or it == max_iter - 1):
            sv = _bidiag_svals(A, Us, Vs, alphas, betas)
            cur = (sv[0], sv[-1])
            if prev is not None:
                rel_max = abs(cur[0] - prev[0]) / max(cur[0], 1e-30)
                rel_min = abs(cur[1] - prev[1]) / max(cur[1], 1e-30)
                if rel_max < tol and rel_min < tol:
                    prev = cur
                    break
            prev = cur

    sv = _bidiag_svals(A, Us, Vs, alphas, betas)
    smax, smin = float(sv[0]), float(sv[-1])
    return (smax / max(smin, np.finfo(np.float64).tiny), smax, smin)


def _bidiag_svals(A, Us, Vs, alphas, betas) -> np.ndarray:
    """Singular values of the *rectangular* (k+1)×k Golub-Kahan bidiagonal
    (host-side LAPACK, the ``dbdsqr`` analog — ref: nla/CondEst.hpp:12-16).

    The trailing beta row is required: B_rect = U_{k+1}ᵀ·A·V_k has
    σ_i(B) = σ_i(A·V_k) ∈ [σ_min(A), σ_max(A)]; the square truncation does
    not interlace and can report spuriously small σ_min.
    """
    k = len(alphas)
    u_t = A @ Vs[-1] - alphas[-1] * Us[-1]
    for up in Us:
        u_t -= (up @ u_t) * up
    beta_t = float(np.linalg.norm(u_t))
    B = np.zeros((k + 1, k))
    for i, a in enumerate(alphas):
        B[i, i] = a
    for i, b in enumerate(betas[: k - 1]):
        B[i + 1, i] = b
    B[k, k - 1] = beta_t
    return np.linalg.svd(B, compute_uv=False)
