"""Randomized low-rank toolkit: range finders and range-assisted factorizations.

TPU-native analog of ref: python-skylark/skylark/nla/krank.py:39-655 — the
Halko–Martinsson–Tropp (SIAM Rev. 2011) algorithm collection: range finders
(Algs 4.1-4.5), range-assisted SVD (Algs 5.1/5.2) and EVD (Algs 5.3-5.6),
plus the SRFT sketch matrix. Dense linear algebra runs on device (jnp);
the interpolative-decomposition variants call scipy on host, as the
reference does.

The reference draws with ``numpy.random``; here every random draw comes
from the framework :class:`~libskylark_tpu.base.context.Context` counter
streams, so results are deterministic and layout-independent
(ref: base/randgen.hpp:98-115). The reference's complex-DFT SRFT is
replaced by the real DCT — the subsampled randomized *cosine* transform —
because TPU-native code keeps everything in real dtypes (complex cannot
cross host↔device on this backend; the embedding guarantees are the same).
"""

from __future__ import annotations

import warnings
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors, randgen
from libskylark_tpu.base.context import Allocation, Context


def _normal(alloc: Allocation, n: int, cols: int, dtype) -> jnp.ndarray:
    flat = randgen.stream_slice(
        alloc.key, randgen.Normal(), 0, n * cols, dtype=dtype)
    return flat.reshape(n, cols)


def srft_matrix(n: int, s: int, context: Context, dtype=jnp.float32
                ) -> jnp.ndarray:
    """Realized (n, s) subsampled randomized (cosine) transform:
    √(n/s) · D · Fᵀ · R with D = Rademacher diagonal, F = orthonormal DCT,
    R = uniform column sample (ref: krank.py SRFT_matrix:39-66; DFT→DCT,
    see module docstring). ``A @ srft_matrix(...)`` sketches columns."""
    from libskylark_tpu.sketch import fut

    signs = randgen.stream_slice(
        context.allocate().key, randgen.Rademacher(), 0, n, dtype=dtype)
    idx = randgen.stream_slice(
        context.allocate().key, randgen.UniformInt(0, n - 1),
        0, s, dtype=jnp.int32)
    F = fut.dct(jnp.eye(n, dtype=dtype), axis=0) * fut.DCT(n).scale()
    S = signs[:, None] * F.T[:, idx]
    return float(np.sqrt(n / s)) * S


class RandomizedRangeFinder:
    """Orthonormal Q approximating range(A) (ref: krank.py:164-345).

    Methods: ``generic`` (Alg 4.1, needs s), ``adaptive`` (Alg 4.2, needs
    epsilon/r/max_iters), ``power_iteration`` (Alg 4.3, s/q),
    ``subspace_iteration`` (Alg 4.4, s/q), ``fast_generic`` (Alg 4.5, s —
    SRFT sketch)."""

    args = {
        "generic": {"s": None},
        "adaptive": {"epsilon": None, "r": None, "max_iters": 100},
        "power_iteration": {"s": None, "q": 1},
        "subspace_iteration": {"s": None, "q": 1},
        "fast_generic": {"s": None},
    }

    def __init__(self, A, method: str, params: dict, context: Context):
        if method not in self.args:
            raise errors.InvalidParametersError(f"unknown method {method!r}")
        kwargs = dict(self.args[method])
        kwargs.update(params)
        if None in kwargs.values():
            missing = [k for k, v in kwargs.items() if v is None]
            raise errors.InvalidParametersError(
                f"missing arguments {missing} for method {method!r}")
        self.A = jnp.asarray(A)
        self.method = method
        self.kwargs = kwargs
        self.context = context

    def compute(self) -> jnp.ndarray:
        return getattr(self, f"_{self.method}")()

    def _generic(self):
        n = self.A.shape[1]
        s = int(self.kwargs["s"])
        S = _normal(self.context.allocate(), n, s, self.A.dtype)
        Q, _ = jnp.linalg.qr(self.A @ S)
        return Q

    def _power_iteration(self):
        n = self.A.shape[1]
        s, q = int(self.kwargs["s"]), int(self.kwargs["q"])
        S = _normal(self.context.allocate(), n, s, self.A.dtype)
        Y = self.A @ S
        for _ in range(q):
            Y = self.A @ (self.A.T @ Y)
        Q, _ = jnp.linalg.qr(Y)
        return Q

    def _subspace_iteration(self):
        n = self.A.shape[1]
        s, q = int(self.kwargs["s"]), int(self.kwargs["q"])
        S = _normal(self.context.allocate(), n, s, self.A.dtype)
        Q, _ = jnp.linalg.qr(self.A @ S)
        for _ in range(q):
            W, _ = jnp.linalg.qr(self.A.T @ Q)
            Q, _ = jnp.linalg.qr(self.A @ W)
        return Q

    def _fast_generic(self):
        n = self.A.shape[1]
        s = int(self.kwargs["s"])
        S = srft_matrix(n, s, self.context, self.A.dtype)
        Q, _ = jnp.linalg.qr(self.A @ S)
        return Q

    def _adaptive(self):
        """Alg 4.2 — grow Q one vector at a time until the residual norms of
        ``r`` probe vectors drop below ε/(10·√(2/π)) (ref: krank.py:270-301).
        Inherently sequential; runs the recurrence on host."""
        A = np.asarray(self.A)
        eps = float(self.kwargs["epsilon"])
        r = int(self.kwargs["r"])
        max_iters = int(self.kwargs["max_iters"])
        m, n = A.shape
        alloc = self.context.allocate()
        draws = np.asarray(_normal(alloc, n, r + max_iters, jnp.float32))
        w_next = r
        ys = [A @ draws[:, i] for i in range(r)]
        threshold = eps / (10.0 * np.sqrt(2.0 / np.pi))
        Q = np.empty((m, 0), dtype=A.dtype)
        iters = 0
        j = -1
        while (max(np.linalg.norm(y) for y in ys[j + 1:]) > threshold
               and iters < max_iters and w_next < draws.shape[1]):
            j += 1
            y = ys[j] - Q @ (Q.T @ ys[j])
            q = y / np.linalg.norm(y)
            Q = np.hstack([Q, q[:, None]])
            z = A @ draws[:, w_next]
            w_next += 1
            ys.append(z - Q @ (Q.T @ z))
            for i in range(j + 1, j + r):
                ys[i] = ys[i] - q * (q @ ys[i])
            iters += 1
        if iters == max_iters:
            warnings.warn(f"adaptive range finder: no convergence "
                          f"after {iters} iterations")
        return jnp.asarray(Q)


class RangeAssistedSVD:
    """A ≈ U·diag(σ)·Vᵀ given a range basis Q (ref: krank.py:347-460).
    Methods: ``direct`` (Alg 5.1), ``row_extraction`` (Alg 5.2, host scipy
    interpolative decomposition)."""

    args = {"direct": {}, "row_extraction": {}}

    def __init__(self, A, Q, method: str = "direct", params: dict = None):
        if method not in self.args:
            raise errors.InvalidParametersError(f"unknown method {method!r}")
        self.A = jnp.asarray(A)
        self.Q = jnp.asarray(Q)
        self.method = method

    def compute(self) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        return getattr(self, f"_{self.method}")()

    def _direct(self):
        B = self.Q.T @ self.A
        U, sigma, Vt = jnp.linalg.svd(B, full_matrices=False)
        return self.Q @ U, sigma, Vt

    def _row_extraction(self):
        import scipy.linalg.interpolative as sli

        A = np.asarray(self.A)
        Q = np.asarray(self.Q, dtype=np.float64)
        k = Q.shape[1]
        # Row ID of Q = column ID of Qᵀ: Q ≈ Xr · Q[J, :] with Xr (m, k)
        idx, proj = sli.interp_decomp(Q.T, k, rand=False)
        Xr = sli.reconstruct_interp_matrix(idx, proj).T.astype(A.dtype)
        J = idx[:k]
        Aj = A[J, :]                      # A ≈ Xr · A[J, :]  (HMT Alg 5.2)
        W, R = np.linalg.qr(Aj.T)         # A[J, :] = Rᵀ·Wᵀ
        Z = Xr @ R.T
        U, sigma, Vhat_t = np.linalg.svd(Z, full_matrices=False)
        V = W @ Vhat_t.T
        return jnp.asarray(U), jnp.asarray(sigma), jnp.asarray(V.T)


class RangeAssistedEVD:
    """Symmetric A ≈ U·diag(w)·Uᵀ given a range basis Q
    (ref: krank.py:461-603). Methods: ``direct`` (Alg 5.3),
    ``row_extraction`` (Alg 5.4), ``nystrom`` (Alg 5.5, PSD A),
    ``one_pass`` (Alg 5.6, needs s + context)."""

    args = {"direct": {}, "row_extraction": {}, "nystrom": {},
            "one_pass": {"s": None}}

    def __init__(self, A, Q, method: str = "direct", params: dict = None,
                 context: Optional[Context] = None):
        if method not in self.args:
            raise errors.InvalidParametersError(f"unknown method {method!r}")
        kwargs = dict(self.args[method])
        kwargs.update(params or {})
        if None in kwargs.values():
            raise errors.InvalidParametersError(
                f"method {method!r} needs {list(kwargs)}")
        if method == "one_pass" and context is None:
            raise errors.InvalidParametersError("one_pass needs a context")
        self.A = jnp.asarray(A)
        self.Q = jnp.asarray(Q)
        self.method = method
        self.kwargs = kwargs
        self.context = context

    def compute(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return getattr(self, f"_{self.method}")()

    def _direct(self):
        B = self.Q.T @ (self.A @ self.Q)
        w, V = jnp.linalg.eigh(B)
        return w, self.Q @ V

    def _row_extraction(self):
        import scipy.linalg.interpolative as sli

        A = np.asarray(self.A)
        Q = np.asarray(self.Q, dtype=np.float64)
        k = Q.shape[1]
        # Row ID as in Alg 5.2; then A ≈ Xr·A[J,J]·Xrᵀ  (HMT Alg 5.4)
        idx, proj = sli.interp_decomp(Q.T, k, rand=False)
        Xr = sli.reconstruct_interp_matrix(idx, proj).T.astype(A.dtype)
        J = idx[:k]
        V, R = np.linalg.qr(Xr)
        Ajj = A[np.ix_(J, J)]
        Z = R @ Ajj @ R.T
        w, W = np.linalg.eigh(Z)
        return jnp.asarray(w), jnp.asarray(V @ W)

    def _nystrom(self):
        import jax.scipy.linalg as jsl

        B1 = self.A @ self.Q
        B2 = self.Q.T @ B1
        # B2 is PSD but singular whenever Q has more columns than rank(A);
        # a trace-scaled jitter keeps the Cholesky finite (the reference
        # assumes exact-rank Q and would NaN here)
        s = B2.shape[0]
        jitter = 1e-6 * (jnp.trace(B2) / s + 1e-30)
        C = jnp.linalg.cholesky(
            B2 + jitter * jnp.eye(s, dtype=B2.dtype))     # lower: B2 = C·Cᵀ
        # HMT Alg 5.5: F = B1·C⁻ᵀ, eigenvalues = σ(F)²
        Ft = jsl.solve_triangular(C, B1.T, lower=True)
        U, sigma, _ = jnp.linalg.svd(Ft.T, full_matrices=False)
        return sigma**2, U

    def _one_pass(self):
        n = self.A.shape[1]
        s = int(self.kwargs["s"])
        S = _normal(self.context.allocate(), n, s, self.A.dtype)
        Y = self.A @ S
        Y = self.Q @ (self.Q.T @ Y)
        B, *_ = jnp.linalg.lstsq(S.T @ self.Q, Y.T @ self.Q)
        w, V = jnp.linalg.eigh(0.5 * (B.T + B))
        return w, self.Q @ V


def randomized_svd(A, k: int, context: Context, q: int = 1):
    """Convenience: power-iteration range finder (s = 2k) + direct SVD,
    truncated to rank k (ref: krank.py randomized_SVD:605-655)."""
    A = jnp.asarray(A)
    finder = RandomizedRangeFinder(
        A, "power_iteration", {"s": min(2 * k, min(A.shape)), "q": q},
        context)
    Q = finder.compute()
    U, sigma, Vt = RangeAssistedSVD(A, Q).compute()
    return U[:, :k], sigma[:k], Vt[:k, :]
