"""High-level least squares: ApproximateLeastSquares and FastLeastSquares.

TPU-native analog of ref: nla/least_squares.hpp:41-241.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from libskylark_tpu.algorithms import regression
from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context


def approximate_least_squares(
    A: jnp.ndarray,
    B: jnp.ndarray,
    context: Context,
    sketch_size: Optional[int] = None,
    sketch: str = "fjlt",
):
    """Sketch-and-solve least squares (Drineas et al.); default sketch size
    4×Width(A) with an FJLT (ref: nla/least_squares.hpp:41-83). Sparse
    operands (``SparseMatrix``/``DistSparseMatrix``) default to a CWT
    sketch (the FJLT needs a dense fast transform)."""
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.base.sparse import is_sparse_operand

    if is_sparse_operand(A):
        if sketch == "fjlt":
            sketch = "cwt"
    else:
        A = jnp.asarray(A)
    m, n = A.shape
    s = int(sketch_size) if sketch_size else 4 * n
    s = min(max(s, n + 1), m)
    if sketch == "fjlt":
        T = sk.FJLT(m, s, context)
    elif sketch == "cwt":
        T = sk.CWT(m, s, context)
    elif sketch == "jlt":
        T = sk.JLT(m, s, context)
    else:
        raise errors.InvalidParametersError(
            f"unknown sketch {sketch!r}; expected 'fjlt', 'cwt', or 'jlt'"
        )
    return regression.solve_l2_sketched(A, B, T)


def fast_least_squares(
    A: jnp.ndarray,
    B: jnp.ndarray,
    context: Context,
    params: Optional[regression.AcceleratedParams] = None,
):
    """Accurate sketch-preconditioned solve — Blendenpik with condition
    fallback (ref: nla/least_squares.hpp:216-236). Returns (X, lsqr_iters).

    Dense operands dispatch as two engine-compiled executables (precond
    build + the LSQR while_loop) with a single host sync for the
    condition-fallback branch — see
    :func:`libskylark_tpu.algorithms.regression.solve_l2_accelerated`."""
    return regression.solve_l2_accelerated(
        A, B, context, method="blendenpik", params=params
    )
