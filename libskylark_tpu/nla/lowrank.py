"""Dominant-subspace approximation via two-level sketching.

TPU-native analog of ref: python-skylark/skylark/nla/lowrank.py:7-48
(``approximate_domsubspace_basis``) — the sketch-based construction of a
basis Z whose span (1+ε)-approximates the k-dominant subspace of A (or of
φ(A) for a kernel feature map): sketch twice (sizes s and t), QR the first
sketch, SVD the cross product, truncate.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from libskylark_tpu.base.context import Context


def approximate_dominant_subspace_basis(
    A,
    k: int,
    s: int,
    t: int,
    context: Context,
    kernel=None,
    tag: str = "regular",
) -> Tuple[jnp.ndarray, object, jnp.ndarray, jnp.ndarray]:
    """Returns (Z, S, R, V) with Z = QR(S(A)).Q @ V; S is the (kept) feature
    transform so test points map through the same sketch
    (ref: lowrank.py:7-48). ``s = Ω(k/ε)``, ``t = Ω(k/ε²)`` give the
    (1+ε)‖A_k − A‖_F guarantee."""
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.ml.kernels import Linear

    A = jnp.asarray(A) if not hasattr(A, "todense") else A
    d = A.shape[1]
    if kernel is None:
        kernel = Linear(d)
    S = kernel.create_rft(s, context, tag)
    X = S.apply(A, sk.ROWWISE)
    T = kernel.create_rft(t, context, tag)
    Y = T.apply(A, sk.ROWWISE)
    U, R = jnp.linalg.qr(X)
    M, _, _ = jnp.linalg.svd(U.T @ Y, full_matrices=False)
    V = M[:, :k]
    Z = U @ V
    return Z, S, R, V


# ---------------------------------------------------------------------------
# Pure, vmap-batchable serve endpoint (docs/qos "Heterogeneous serve
# endpoints"; served by engine/serve.py submit_lowrank).
# ---------------------------------------------------------------------------


def lowrank_serve_apply(kd_s, scale_s, kd_t, scale_t, A, *, dist,
                        s: int, t: int, k: int) -> jnp.ndarray:
    """One request's dominant-subspace basis Z as a pure function of
    the two sketch keys and the operand: the two rowwise dense-family
    sketches through the positional serve streams
    (:func:`libskylark_tpu.sketch.dense.serve_apply` — the exact bits
    the transforms' own ``apply`` produces), then QR / cross-product
    SVD / truncate, identical to
    :func:`approximate_dominant_subspace_basis` with a linear kernel.
    Zero-padded rows of ``A`` sketch to exact zero rows, QR carries
    them as zero rows of U, and Z's padded rows are exact zeros the
    executor slices off."""
    from libskylark_tpu.sketch.dense import serve_apply

    X = serve_apply(kd_s, scale_s, A, dist=dist, s_dim=int(s),
                    rowwise=True)
    Y = serve_apply(kd_t, scale_t, A, dist=dist, s_dim=int(t),
                    rowwise=True)
    U, _ = jnp.linalg.qr(X)
    M, _, _ = jnp.linalg.svd(U.T @ Y, full_matrices=False)
    return U @ M[:, : int(k)]


def lowrank_serve(transform_s, transform_t, A, k: int):
    """Eager twin of the ``lowrank`` serve endpoint: the identical
    computation from the two caller-held dense transforms (e.g.
    ``Linear(d).create_rft(s, ctx)`` JLTs — the
    :func:`approximate_dominant_subspace_basis` construction), at the
    serve layer's pow2 row class (the qos tests' bit-equality
    reference). Returns the (n, k) basis as a host array."""
    import numpy as np

    from libskylark_tpu.engine import bucket as bucketing
    from libskylark_tpu.engine.serve import (_lowrank_key_data,
                                             _lowrank_statics)

    _statics, info = _lowrank_statics(transform_s, transform_t, A, k,
                                      bucketing.PAD_FLOOR)
    A = info["A"]
    Ap = np.zeros(info["padded"], dtype=A.dtype)
    Ap[: A.shape[0], :] = A
    kd_s, sc_s = _lowrank_key_data(transform_s, A.dtype)
    kd_t, sc_t = _lowrank_key_data(transform_t, A.dtype)
    Z = lowrank_serve_apply(
        jnp.asarray(kd_s), jnp.asarray(sc_s), jnp.asarray(kd_t),
        jnp.asarray(sc_t), jnp.asarray(Ap), dist=info["dist"],
        s=transform_s.sketch_dim, t=transform_t.sketch_dim, k=int(k))
    return np.asarray(Z)[: A.shape[0], :]
