"""Dominant-subspace approximation via two-level sketching.

TPU-native analog of ref: python-skylark/skylark/nla/lowrank.py:7-48
(``approximate_domsubspace_basis``) — the sketch-based construction of a
basis Z whose span (1+ε)-approximates the k-dominant subspace of A (or of
φ(A) for a kernel feature map): sketch twice (sizes s and t), QR the first
sketch, SVD the cross product, truncate.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from libskylark_tpu.base.context import Context


def approximate_dominant_subspace_basis(
    A,
    k: int,
    s: int,
    t: int,
    context: Context,
    kernel=None,
    tag: str = "regular",
) -> Tuple[jnp.ndarray, object, jnp.ndarray, jnp.ndarray]:
    """Returns (Z, S, R, V) with Z = QR(S(A)).Q @ V; S is the (kept) feature
    transform so test points map through the same sketch
    (ref: lowrank.py:7-48). ``s = Ω(k/ε)``, ``t = Ω(k/ε²)`` give the
    (1+ε)‖A_k − A‖_F guarantee."""
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.ml.kernels import Linear

    A = jnp.asarray(A) if not hasattr(A, "todense") else A
    d = A.shape[1]
    if kernel is None:
        kernel = Linear(d)
    S = kernel.create_rft(s, context, tag)
    X = S.apply(A, sk.ROWWISE)
    T = kernel.create_rft(t, context, tag)
    Y = T.apply(A, sk.ROWWISE)
    U, R = jnp.linalg.qr(X)
    M, _, _ = jnp.linalg.svd(U.T @ Y, full_matrices=False)
    V = M[:, :k]
    Z = U @ V
    return Z, S, R, V
