"""Randomized EVD of AᵀA: sketch-preconditioned LOBPCG and power iteration.

TPU-native analog of ref: python-skylark/skylark/nla/randlobpcg.py:68-185.
``lobpcg_rand_evd`` sketches A down to s rows, QRs the sketch, and uses
R as a preconditioner for LOBPCG on the operator AᵀA — the sketch runs on
device through the framework transforms; the LOBPCG recurrence itself runs
in scipy on host exactly as the reference does (it is a small k-dimensional
iteration over matvecs, not a TPU-shaped workload).
``power_iterations_rand_evd`` is fully on-device.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context


def lobpcg_rand_evd(
    A,
    k: int,
    context: Context,
    s: Optional[int] = None,
    sketch: str = "cwt",
) -> Tuple[np.ndarray, np.ndarray]:
    """Top-k eigenpairs of AᵀA for tall A (ref: randlobpcg.py:68-110).

    Returns (lambdas, Vt) with Vt rows the right singular vectors of A.
    """
    import scipy.linalg as sla
    from scipy.sparse.linalg import LinearOperator, lobpcg

    from libskylark_tpu import sketch as sk

    A = jnp.asarray(A)
    m, n = A.shape
    if not (m > n and n >= k):
        raise errors.InvalidParametersError(
            f"expects tall A with n >= k; got {A.shape}, k={k}")
    s = 4 * n if s is None else int(s)
    if s >= m:
        raise errors.InvalidParametersError(f"sketch size {s} >= rows {m}")
    if s < n:
        # the preconditioner solves against R from qr(SA): R is square
        # only when the sketch keeps at least n rows (otherwise
        # solve_triangular fails with an opaque shape error deep inside)
        raise errors.InvalidParametersError(
            f"sketch size {s} < cols {n}; need s >= n for the "
            "(R'R)^-1 preconditioner")

    sketches = {"cwt": sk.CWT, "jlt": sk.JLT, "fjlt": sk.FJLT}
    if sketch not in sketches:
        raise errors.InvalidParametersError(
            f"sketch must be one of {sorted(sketches)}, got {sketch!r}")
    T = sketches[sketch](m, s, context)
    B = np.asarray(T.apply(A, sk.COLUMNWISE))
    _, Sigma, Vt = np.linalg.svd(B, full_matrices=False)
    _, R = np.linalg.qr(B)

    Ah = np.asarray(A)

    def amul(x):
        return Ah.T @ (Ah @ x)

    def precond(y):
        # (RᵀR)⁻¹ y via two triangular solves (ref: randlobpcg.py:47-64)
        z = sla.solve_triangular(R.T, y, lower=True)
        return sla.solve_triangular(R, z, lower=False)

    Aop = LinearOperator((n, n), matvec=amul, matmat=amul)
    Mop = LinearOperator((n, n), matvec=precond, matmat=precond)
    X = Vt[:k, :].T.copy()
    lambdas, V = lobpcg(Aop, X, M=Mop, largest=True)
    order = np.argsort(-lambdas)
    return lambdas[order], V[:, order].T


def power_iterations_rand_evd(
    A,
    k: int,
    context: Context,
    power_iters: int = 2,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k eigenpairs of AᵀA via sketched power iteration
    (ref: randlobpcg.py:113-155). Fully on-device; returns (lambdas, Vt)."""
    from libskylark_tpu import sketch as sk

    A = jnp.asarray(A)
    m, n = A.shape
    if not (m > n and n >= k):
        raise errors.InvalidParametersError(
            f"expects tall A with n >= k; got {A.shape}, k={k}")
    T = sk.JLT(n, k, context)
    Y = T.apply(A, sk.ROWWISE)          # A·Sᵀ (m, k)
    for _ in range(power_iters):
        Y = A @ (A.T @ Y)
    Q, _ = jnp.linalg.qr(Y)
    B = Q.T @ A
    _, Sigma, Vt = jnp.linalg.svd(B, full_matrices=False)
    return Sigma**2, Vt
