"""Chebyshev spectral helpers for time-dependent PPR.

TPU-native analog of ref: nla/spectral.hpp:17-96. Built host-side in float64
numpy (these are small dense setup matrices used by
ml/graph time-dependent PPR, not hot-path compute).
"""

from __future__ import annotations

import numpy as np


def chebyshev_points(N: int, a: float = -1.0, b: float = 1.0) -> np.ndarray:
    """N Chebyshev points of the second kind mapped to [a, b]
    (ref: nla/spectral.hpp:17-30; the reference's affine map is only correct
    for its default interval — here the map is x = a + (cos+1)·(b−a)/2 so the
    points actually land in [a, b], with the midpoint snapped exactly to the
    interval center, generalizing the reference's exact-zero fix)."""
    n = N - 1
    j = np.arange(n + 1)
    s = (b - a) / 2.0
    x = a + (np.cos(j * np.pi / n) + 1.0) * s
    if n % 2 == 0:
        x[n // 2] = a + s
    return x


def chebyshev_diff_matrix(
    N: int, a: float = -1.0, b: float = 1.0
) -> tuple[np.ndarray, np.ndarray]:
    """Differentiation matrix D on N Chebyshev points: p' = D·p for the
    interpolating polynomial (ref: nla/spectral.hpp:54-96). Returns (D, X)
    with X the points rescaled to [a, b]."""
    x = chebyshev_points(N)  # on [-1, 1]
    n = N - 1
    D = np.empty((n + 1, n + 1))
    for j in range(n + 1):
        for i in range(n + 1):
            d = i - j
            v = 2.0 / (b - a)
            if i == 0 and j == 0:
                v *= (2.0 * n * n + 1.0) / 6.0
            elif i == n and j == n:
                v *= -(2.0 * n * n + 1.0) / 6.0
            else:
                if i in (0, n):
                    v *= 2.0
                if j in (0, n):
                    v /= 2.0
                if d == 0:
                    v *= -x[j] / (2.0 * (1.0 - x[j] * x[j]))
                elif d % 2 == 0:
                    v *= 1.0 / (x[i] - x[j])
                else:
                    v *= -1.0 / (x[i] - x[j])
            D[i, j] = v

    if a != -1.0 or b != 1.0:
        x = a + (x + 1.0) * (b - a) / 2.0
    return D, x
