"""Randomized SVD: PowerIteration, ApproximateSVD, ApproximateSymmetricSVD.

TPU-native analog of ref: nla/svd.hpp:24-447 (Halko-Martinsson-Tropp):
sketch → power iteration with QR re-orthogonalization → small factorization →
rank truncation. The reference's four orientation combos and m≥n / m<n
branches collapse: everything is jnp, XLA handles layout, and the wide case
is the tall case on Aᵀ.

Dense operands run as ONE compiled program: sketch, power iteration
(``lax.fori_loop``) and the CholeskyQR2 Rayleigh-Ritz fuse into a single
executable served by :mod:`libskylark_tpu.engine` — compile once per
(shape, dtype, plan, params) class, then every subsequent solve is one
device dispatch. Two paths intentionally stay op-by-op: the phase-
profiling variant (``SKYLARK_TPU_PROFILE=1``), which must sync between
phases to attribute device time, and sparse/distributed-sparse operands,
whose containers are not jit inputs.

On a sharded A the sketch apply and the A·(Aᵀ·Q) products carry the
collectives while the (m × k') panel stays replicated — the TPU form of
the reference's [MC,MR] × [STAR,STAR] pattern.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from libskylark_tpu.base import errors
from libskylark_tpu.base.context import Context
from libskylark_tpu.base.params import Params
from libskylark_tpu.base.precision import with_solver_precision
from libskylark_tpu import engine


@dataclasses.dataclass
class ApproximateSVDParams(Params):
    """ref: nla/svd.hpp:24-52 (defaults oversampling_ratio=2, additive=0,
    num_iterations=0, skip_qr=False; JSON-loadable).

    ``ortho`` selects the panel orthogonalization: "cqr2" (CholeskyQR2,
    nla/tsqr.py — the mesh-native default: local gemm + one psum +
    triangular solve, all MXU work; the diagonal lift plus second pass
    keep it accurate far past the textbook cond ≲ 1/√ε bound for the
    truncated spectra randomized SVD meets) or "qr" (Householder — the
    reference's El::qr algebra, replicated LAPACK/XLA work on a mesh).

    ``rr`` selects the Rayleigh-Ritz reduction: "cqr2" (default —
    tall-QR-reduce Bᵀ = (Aᵀ·Q) with CholeskyQR2, then SVD only the
    (k'×k') triangular factor; every O(n·k'²) flop is a shardable gemm)
    or "svd" (the reference's direct SVD of the k'×n panel,
    nla/svd.hpp:286-290 — on a mesh XLA replicates that LAPACK/QR-
    iteration work on every device, measured 5× slower at 8192²/k'=128,
    and on TPU the wide-matrix SVD lowering is iterative and slow)."""

    oversampling_ratio: float = 2.0
    oversampling_additive: int = 0
    num_iterations: int = 0
    skip_qr: bool = False
    ortho: str = "cqr2"
    rr: str = "cqr2"


def _orthonormalize(Q: jnp.ndarray, method: str) -> jnp.ndarray:
    if method == "cqr2":
        from libskylark_tpu.nla.tsqr import cholesky_qr2

        return cholesky_qr2(Q)[0]
    if method != "qr":
        raise errors.InvalidParametersError(
            f"ortho must be 'qr' or 'cqr2', got {method!r}"
        )
    return jnp.linalg.qr(Q)[0]


def _validate_params(params: ApproximateSVDParams) -> None:
    """Eager parameter validation — the fused pipelines must reject bad
    params before tracing, with the same errors the eager path raises."""
    if params.ortho not in ("qr", "cqr2"):
        raise errors.InvalidParametersError(
            f"ortho must be 'qr' or 'cqr2', got {params.ortho!r}")
    if params.rr not in ("cqr2", "svd"):
        raise errors.InvalidParametersError(
            f"rr must be 'cqr2' or 'svd', got {params.rr!r}")


def _as_linear_ops(A):
    """(mv, rmv, shape): X ↦ A·X and X ↦ Aᵀ·X over any operand kind —
    dense array, local :class:`SparseMatrix`, or mesh-distributed
    :class:`DistSparseMatrix` (the analog of the reference's
    matrix-type-templated NLA, e.g. the sparse branch of
    nla/skylark_svd.cpp:129-215, which never densifies)."""
    from libskylark_tpu.base.dist_sparse import DistSparseMatrix
    from libskylark_tpu.base.sparse import SparseMatrix, spmm, spmm_t

    if isinstance(A, SparseMatrix):
        return (lambda X: spmm(A, X)), (lambda X: spmm_t(A, X)), A.shape
    if isinstance(A, DistSparseMatrix):
        return A.spmm, A.spmm_t, A.shape
    A = jnp.asarray(A)
    # rmv as (Xᵀ·A)ᵀ, not Aᵀ·X. These call sites serve the UNFUSED paths
    # (the SKYLARK_TPU_PROFILE phase-profiling variant and the sparse
    # containers), which dispatch op-by-op: an eager Aᵀ materializes a
    # transposed copy of the WHOLE operand per call (268 MB at 8192²
    # f32, with a resharding shuffle when A is mesh-sharded) where the
    # result transpose is a k'-panel. The fused pipelines write the
    # natural Aᵀ·Q — under jit XLA folds either form into the same gemm.
    return (lambda X: A @ X), (lambda X: (X.T @ A).T), A.shape


def _transposed(A):
    from libskylark_tpu.base.dist_sparse import DistSparseMatrix
    from libskylark_tpu.base.sparse import SparseMatrix

    if isinstance(A, (SparseMatrix, DistSparseMatrix)):
        return A.T
    return jnp.asarray(A).T


@with_solver_precision
def power_iteration(
    A,
    Q: jnp.ndarray,
    num_iterations: int,
    orthogonalize: bool = True,
    adjoint: bool = False,
    ortho: str = "qr",
) -> jnp.ndarray:
    """(A·Aᵀ)^q · Q (or (Aᵀ·A)^q · Q when ``adjoint``) with
    re-orthogonalization between products unless disabled
    (ref: nla/svd.hpp:76-153 — the four orientation combos). ``A`` may be
    dense, sparse, or distributed sparse; ``ortho`` as in
    :class:`ApproximateSVDParams`."""
    mv, rmv, _ = _as_linear_ops(A)
    for _ in range(num_iterations):
        if adjoint:
            Q = rmv(mv(Q))
        else:
            Q = mv(rmv(Q))
        if orthogonalize:
            Q = _orthonormalize(Q, ortho)
    return Q


# ---------------------------------------------------------------------------
# fused dense pipelines (one executable per solve; libskylark_tpu/engine)
# ---------------------------------------------------------------------------


def _jlt_panel(key, n: int, kp: int, dtype) -> jnp.ndarray:
    """The (k' × n) JLT operator, bit-identical to ``JLT.s_panel(0, n)``
    for the same allocation key — the stream format, distribution, and
    scale convention all come from the ONE definition in sketch/dense.py,
    so the fused pipeline sketches with exactly the bits the unfused
    ``JLT.apply`` path would generate."""
    from libskylark_tpu.sketch.dense import JLT, virtual_panel

    return virtual_panel(key, JLT.dist, kp, 0, n, JLT.scale_for(kp), dtype)


def _svd_pipeline(A, key, *, k: int, kp: int, num_iterations: int,
                  skip_qr: bool, ortho: str, rr: str):
    """The whole tall-dense randomized SVD as one traceable program:
    sketch → fori_loop power iteration → Rayleigh-Ritz
    (ref: nla/svd.hpp:227-324 collapsed into a single trace)."""
    n = A.shape[1]
    S = _jlt_panel(key, n, kp, A.dtype)
    Q = A @ S.T                                     # range sketch (m, kp)
    if not skip_qr:
        Q = _orthonormalize(Q, ortho)

    def body(_, Q):
        Q = A @ (A.T @ Q)
        if not skip_qr:
            Q = _orthonormalize(Q, ortho)
        return Q

    Q = lax.fori_loop(0, num_iterations, body, Q)
    if skip_qr:
        # one final orthogonalization is always required before projection
        Q = _orthonormalize(Q, ortho)

    Bt = A.T @ Q                                    # (n, kp); B = Btᵀ
    if rr == "svd":
        Ub, S_, Vt = jnp.linalg.svd(Bt.T, full_matrices=False)
        return Q @ Ub[:, :k], S_[:k], Vt[:k, :].T
    # rr == "cqr2": Bᵀ = Qb·Rb (all-gemm tall QR) ⇒ B = Rbᵀ·Qbᵀ; SVD only
    # the k'×k' factor: Rbᵀ = Ur·S·Vrᵀ ⇒ B = Ur·S·(Qb·Vr)ᵀ. The expensive
    # n-dimension work is gemms that shard along n.
    from libskylark_tpu.nla.tsqr import cholesky_qr2

    Qb, Rb = cholesky_qr2(Bt)
    Ur, S_, Vrt = jnp.linalg.svd(Rb.T, full_matrices=False)
    return Q @ Ur[:, :k], S_[:k], Qb @ Vrt.T[:, :k]


def _symmetric_svd_pipeline(A, key, *, k: int, kp: int,
                            num_iterations: int, skip_qr: bool,
                            ortho: str):
    """Symmetric variant as one program: Gaussian sketch → fori_loop
    power iteration → Rayleigh-Ritz via eigh (ref: nla/svd.hpp:326-396)."""
    n = A.shape[0]
    S = _jlt_panel(key, n, kp, A.dtype)
    Q = A @ S.T                                     # (n, kp) range sketch
    Q = _orthonormalize(Q, ortho)

    def body(_, Q):
        Q = A @ Q
        if not skip_qr:
            Q = _orthonormalize(Q, ortho)
        return Q

    Q = lax.fori_loop(0, num_iterations, body, Q)
    if skip_qr:
        Q = _orthonormalize(Q, ortho)

    # Rayleigh-Ritz: eigendecomposition of QᵀAQ (ref: nla/svd.hpp:175-225)
    G = Q.T @ (A @ Q)
    G = 0.5 * (G + G.T)
    w, Z = jnp.linalg.eigh(G)
    # take the k largest-magnitude eigenpairs, descending
    order = jnp.argsort(-jnp.abs(w))[:k]
    return Q @ Z[:, order], w[order]


# donate="auto": the operand is consumed only when the user opted in
# (SKYLARK_ENGINE_DONATE=1) — public solvers must not invalidate caller
# arrays by default (docs/performance.rst, donation caveats).
_STATIC_SVD = ("k", "kp", "num_iterations", "skip_qr", "ortho", "rr")
_svd_compiled = engine.compiled(
    _svd_pipeline, static_argnames=_STATIC_SVD, donate_argnums=(0,),
    donate="auto", name="approximate_svd")
_symmetric_svd_compiled = engine.compiled(
    _symmetric_svd_pipeline, static_argnames=_STATIC_SVD[:-1],
    donate_argnums=(0,), donate="auto", name="approximate_symmetric_svd")


def _profiling_enabled() -> bool:
    from libskylark_tpu.utility.timer import timers_enabled

    return timers_enabled()


def _is_dense(A) -> bool:
    return not hasattr(A, "coo") and not hasattr(A, "spmm")


def _oversampled(params: ApproximateSVDParams, k: int, limit: int) -> int:
    kp = min(int(params.oversampling_ratio * k)
             + int(params.oversampling_additive), limit)
    return max(kp, k)


@with_solver_precision
def approximate_svd(
    A: jnp.ndarray,
    rank: int,
    context: Context,
    params: Optional[ApproximateSVDParams] = None,
    dtype=None,
):
    """Rank-``rank`` approximate SVD: returns (U, S, V) with A ≈ U·diag(S)·Vᵀ
    (ref: nla/svd.hpp:227-324).

    Sketch size k' = ratio·k + additive; JLT range sketch; power iteration;
    small exact SVD; truncation. Wide matrices (m < n) are handled by
    factoring Aᵀ and swapping U/V (the reference's second branch).

    Dense operands run as a single compiled executable served by the
    engine cache (see module docstring); ``SKYLARK_TPU_PROFILE=1``
    selects the unfused per-phase variant instead. ``A`` may also be a
    local :class:`SparseMatrix` or a :class:`DistSparseMatrix` — the
    sparse kinds are never densified (the reference's sparse branch,
    nla/skylark_svd.cpp:129-215) and always run unfused."""
    params = params or ApproximateSVDParams()
    _validate_params(params)
    if _is_dense(A):
        A = jnp.asarray(A)
        if dtype is not None:
            A = A.astype(dtype)
    elif dtype is not None:
        raise errors.InvalidParametersError(
            "dtype override is only supported for dense operands; sparse "
            "operands compute at their device dtype"
        )
    m, n = A.shape
    k = int(rank)
    if k <= 0:
        raise errors.InvalidParametersError(f"rank must be positive, got {rank}")
    kp = _oversampled(params, k, min(m, n))

    if m < n:
        # the caller's dtype override must survive the recursion — A was
        # already cast above, and threading it keeps the (no-op) cast on
        # the transposed operand explicit
        V, S, U = approximate_svd(_transposed(A), rank, context, params,
                                  dtype=dtype)
        return U, S, V

    from libskylark_tpu import sketch as sk

    T = sk.JLT(n, kp, context)

    if _is_dense(A) and not _profiling_enabled():
        statics = dict(k=k, kp=kp, num_iterations=int(params.num_iterations),
                       skip_qr=bool(params.skip_qr), ortho=params.ortho,
                       rr=params.rr)
        if isinstance(A, jax.core.Tracer):
            # already inside an outer trace (a user jit): inline the same
            # pipeline — the outer jit owns compilation and caching
            return _svd_pipeline(A, T._alloc.key, **statics)
        return _svd_compiled(A, T._alloc.key, **statics)
    return _approximate_svd_unfused(A, T, k, params)


def _approximate_svd_unfused(A, T, k: int, params: ApproximateSVDParams):
    """The op-by-op variant: phase-profiled (SKYLARK_TPU_PROFILE=1) and
    the only path sparse operands take. Each phase syncs its outputs so
    device time attributes to the right phase — which is exactly why it
    cannot be the serving path: the reference profiles its solvers per
    phase (ref: ml/BlockADMM.hpp:357-365) and the north-star
    extrapolation (BASELINE.md) needs sketch / power-iteration /
    Rayleigh-Ritz wall-clock splits."""
    from libskylark_tpu import sketch as sk
    from libskylark_tpu.utility.timer import get_timer, timers_enabled

    mv, rmv, _ = _as_linear_ops(A)
    timer = get_timer("svd")
    _sync = jax.block_until_ready if timers_enabled() else (lambda x: x)

    # Range sketch: Y = A·Sᵀ via a rowwise JLT on the n-dimension
    # (ref: nla/svd.hpp:259-261).
    with timer.phase("SKETCH"):
        Q = _sync(T.apply(A, sk.ROWWISE))  # (m, kp)
    with timer.phase("POWER_ITERATION"):
        if not params.skip_qr:
            Q = _orthonormalize(Q, params.ortho)
        Q = power_iteration(A, Q, params.num_iterations,
                            orthogonalize=not params.skip_qr,
                            ortho=params.ortho)
        if params.skip_qr:
            # One final orthogonalization is always required before
            # projection.
            Q = _orthonormalize(Q, params.ortho)
        Q = _sync(Q)

    # Rayleigh-Ritz on the range: B = Qᵀ·A = (Aᵀ·Q)ᵀ, small
    # factorization, rotate back (ref: nla/svd.hpp:283-290). Profiled as
    # two phases: RR_PROJECT is the O(m·n·k') gemm over A — the same
    # cost class as SKETCH, irreducible — while RR_SMALL is the
    # factorization/rotation work the r4 verdict flagged at 43% of wall
    # (an eager whole-operand transpose + replicated wide SVD; now
    # sharded gemms + a k'×k' SVD).
    with timer.phase("RR_PROJECT"):
        Bt = _sync(rmv(Q))  # (n, kp) — tall; B = Btᵀ
    with timer.phase("RR_SMALL"):
        if params.rr == "svd":
            Ub, S, Vt = jnp.linalg.svd(Bt.T, full_matrices=False)
            U, S, V = _sync((Q @ Ub[:, :k], S[:k], Vt[:k, :].T))
        else:
            # Bᵀ = Qb·Rb (all-gemm tall QR) ⇒ B = Rbᵀ·Qbᵀ; SVD only the
            # k'×k' factor: Rbᵀ = Ur·S·Vrᵀ ⇒ B = Ur·S·(Qb·Vr)ᵀ. The
            # expensive n-dimension work is gemms that shard along n.
            from libskylark_tpu.nla.tsqr import cholesky_qr2

            Qb, Rb = cholesky_qr2(Bt)
            Ur, S, Vrt = jnp.linalg.svd(Rb.T, full_matrices=False)
            U, S, V = _sync((Q @ Ur[:, :k], S[:k], Qb @ Vrt.T[:, :k]))
    return U, S, V


@with_solver_precision
def approximate_symmetric_svd(
    A: jnp.ndarray,
    rank: int,
    context: Context,
    params: Optional[ApproximateSVDParams] = None,
):
    """Approximate eigendecomposition of symmetric A: returns (V, S) with
    A ≈ V·diag(S)·Vᵀ (ref: nla/svd.hpp:326-396 — Gaussian sketch +
    SymmetricPowerIteration + Rayleigh-Ritz via HermitianEig). ``A`` may
    be dense, sparse, or distributed sparse; dense operands run fused
    (one executable, engine-cached) with the power loop a
    ``lax.fori_loop``."""
    params = params or ApproximateSVDParams()
    _validate_params(params)
    if _is_dense(A):
        A = jnp.asarray(A)
    n, n2 = A.shape
    if n != n2:
        raise errors.InvalidParametersError("symmetric SVD expects a square matrix")
    if int(rank) <= 0:
        raise errors.InvalidParametersError(f"rank must be positive, got {rank}")
    k = int(rank)
    kp = _oversampled(params, k, n)

    from libskylark_tpu import sketch as sk

    T = sk.JLT(n, kp, context)

    if _is_dense(A) and not _profiling_enabled():
        statics = dict(k=k, kp=kp, num_iterations=int(params.num_iterations),
                       skip_qr=bool(params.skip_qr), ortho=params.ortho)
        if isinstance(A, jax.core.Tracer):
            return _symmetric_svd_pipeline(A, T._alloc.key, **statics)
        return _symmetric_svd_compiled(A, T._alloc.key, **statics)

    mv, _rmv, _ = _as_linear_ops(A)
    Q = T.apply(A, sk.ROWWISE)  # (n, kp) Gaussian range sketch
    Q = _orthonormalize(Q, params.ortho)
    for _ in range(params.num_iterations):
        Q = mv(Q)
        if not params.skip_qr:
            Q = _orthonormalize(Q, params.ortho)
    if params.skip_qr:
        Q = _orthonormalize(Q, params.ortho)

    # Rayleigh-Ritz: eigendecomposition of QᵀAQ (ref: nla/svd.hpp:175-225).
    G = Q.T @ mv(Q)
    G = 0.5 * (G + G.T)
    w, Z = jnp.linalg.eigh(G)
    # take the k largest-magnitude eigenpairs, descending
    order = jnp.argsort(-jnp.abs(w))[:k]
    return Q @ Z[:, order], w[order]
