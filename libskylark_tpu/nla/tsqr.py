"""Tall-skinny QR: CholeskyQR / CholeskyQR2 — the mesh-native
orthogonalization.

The reference orthogonalizes power-iteration panels with Elemental's
distributed Householder QR (`El::qr::ExplicitUnitary`,
ref: base/QR.hpp:12-32, nla/svd.hpp:113-119). Householder panels
serialize poorly on a TPU mesh; the TPU-native factorization for an
(m × k) panel with m ≫ k is CholeskyQR2 (Yamamoto et al. 2015):

    G = AᵀA          — one local gemm per shard + one psum over the mesh
    R = chol(G)
    Q = A·R⁻¹        — triangular solve, embarrassingly row-parallel

repeated twice (the second pass repairs the squared-condition loss of the
first; orthogonality error drops to O(ε) for cond(A) ≲ 1/√ε). Everything
is plain jnp, so a row-sharded A compiles to exactly the
local-gemm + all-reduce pattern of the reference's distributed QR —
but with the MXU doing all the flops and only one k×k collective.
"""

from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from libskylark_tpu.base.precision import with_solver_precision


@with_solver_precision
def cholesky_qr(A: jnp.ndarray):
    """One CholeskyQR pass: returns (Q, R) with A = Q·R, Q orthonormal to
    O(ε·cond²(A)). Use :func:`cholesky_qr2` unless A is known to be very
    well conditioned."""
    G = A.T @ A                                  # psum under sharding
    # tiny diagonal lift keeps chol defined when A is numerically
    # rank-deficient (the QR2 pass repairs the perturbation)
    eps = jnp.finfo(A.dtype).eps
    G = G + (eps * jnp.trace(G)) * jnp.eye(G.shape[0], dtype=A.dtype)
    R = jnp.linalg.cholesky(G, upper=True)
    # Q = A·R⁻¹ via an explicit k×k triangular inverse + gemm, NOT a
    # triangular solve over the tall operand: XLA's wide-rhs trisolve
    # lowers to a sequential substitution loop (slow on TPU, where the
    # gemm rides the MXU) and — measured on the 8-device mesh — loses
    # the operand's row sharding (its output came back fully replicated:
    # a hidden all-gather; the gemm propagates P('rows', None) through).
    # 2.8× faster at (8192, 128)×8 devices, numerics identical to the
    # solve at the conditioning CholeskyQR can repair anyway: the k×k
    # inverse's O(ε·cond(R)) error is subdominant to the pass's own
    # O(ε·cond²(A)) orthogonality error that pass 2 exists to fix.
    Rinv = jsl.solve_triangular(R, jnp.eye(R.shape[0], dtype=A.dtype))
    return A @ Rinv, R


@with_solver_precision
def cholesky_qr2(A: jnp.ndarray):
    """CholeskyQR2: two passes → Q orthonormal to O(ε) for
    cond(A) ≲ 1/√ε; R = R₂·R₁. The distributed-QR replacement for
    power-iteration re-orthogonalization on a mesh."""
    Q1, R1 = cholesky_qr(A)
    Q, R2 = cholesky_qr(Q1)
    return Q, R2 @ R1
