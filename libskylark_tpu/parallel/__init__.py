"""Parallelism layer: meshes, shardings, collective helpers (SURVEY.md §2.9)."""

from libskylark_tpu.parallel import multihost, shard_apply
from libskylark_tpu.parallel.mesh import (
    COLS,
    ROWS,
    col_sharded,
    distribute,
    grid2d,
    make_mesh,
    replicated,
    row_sharded,
    square_mesh,
    to_host,
    use_mesh,
    vec_sharded,
)

__all__ = [
    "multihost",
    "shard_apply",
    "COLS",
    "ROWS",
    "col_sharded",
    "distribute",
    "grid2d",
    "make_mesh",
    "replicated",
    "row_sharded",
    "square_mesh",
    "to_host",
    "use_mesh",
    "vec_sharded",
]
