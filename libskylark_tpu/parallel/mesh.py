"""Device-mesh and sharding helpers — the distribution vocabulary.

TPU-native replacement for Elemental's distribution template parameters
(SURVEY.md §2.9). The reference encodes data layout in types
([MC,MR], [VC,*], [*,VR], [*,*], [CIRC,CIRC]); here layout is a
``jax.sharding.NamedSharding`` over a ``Mesh``, and XLA inserts the
collectives that Elemental performed in redistribution assignments.

Correspondence (ref: sketch/sketch_transform.hpp:13-51 type universe):

=============  =======================================  =========================
Reference      Meaning                                  Here
=============  =======================================  =========================
[MC, MR]       2D block-cyclic over process grid        ``grid2d(mesh)`` — P(ROWS, COLS)
[VC, *]/[VR,*] 1D row distribution                      ``row_sharded(mesh)`` — P(axes, None)
[*, VC]/[*,VR] 1D column distribution                   ``col_sharded(mesh)`` — P(None, axes)
[*, *]         replicated on all ranks                  ``replicated(mesh)`` — P()
[CIRC, CIRC]   stored on root rank only                 host numpy / ``to_host``
=============  =======================================  =========================

Communicator extraction (ref: utility/get_communicator.hpp:25-51) has no
analog: mesh axes *are* the communicators.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS = "rows"
COLS = "cols"


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create a device mesh. Default: 1D over all devices, axis ``rows``.

    ``shape=(r, c)`` gives the 2D grid analog of Elemental's process grid
    (ref: El::Grid); XLA maps the first axis to the slower-varying ICI
    dimension via ``mesh_utils.create_device_mesh``.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),)
    if axis_names is None:
        axis_names = (ROWS, COLS)[: len(shape)]
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh shape {tuple(shape)} does not cover {len(devices)} devices"
        )
    dev_array = mesh_utils.create_device_mesh(tuple(shape), devices=devices)
    return Mesh(dev_array, tuple(axis_names))


def square_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Largest (r, c) grid with r*c == n_devices and r<=c, r maximal — the
    analog of Elemental's default near-square grid."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    r = int(np.floor(np.sqrt(n)))
    while n % r:
        r -= 1
    return make_mesh((r, n // r), (ROWS, COLS), devices)


def _all_axes(mesh: Mesh):
    axes = tuple(mesh.axis_names)
    return axes if len(axes) > 1 else axes[0]


def row_sharded(mesh: Mesh) -> NamedSharding:
    """1D row distribution over *all* mesh axes ([VC,*] analog)."""
    return NamedSharding(mesh, P(_all_axes(mesh), None))


def col_sharded(mesh: Mesh) -> NamedSharding:
    """1D column distribution over *all* mesh axes ([*,VR] analog)."""
    return NamedSharding(mesh, P(None, _all_axes(mesh)))


def grid2d(mesh: Mesh) -> NamedSharding:
    """2D distribution: rows over first axis, cols over second ([MC,MR] analog)."""
    if len(mesh.axis_names) < 2:
        return row_sharded(mesh)
    return NamedSharding(mesh, P(mesh.axis_names[0], mesh.axis_names[1]))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated ([*,*] analog)."""
    return NamedSharding(mesh, P())


def vec_sharded(mesh: Mesh) -> NamedSharding:
    """1D-sharded vector over all mesh axes."""
    return NamedSharding(mesh, P(_all_axes(mesh)))


def distribute(x, sharding: NamedSharding) -> jax.Array:
    """Place an array with the given sharding (the redistribution primitive —
    Elemental's ``B = A`` distribution-conversion assignment)."""
    return jax.device_put(x, sharding)


def to_host(x) -> np.ndarray:
    """Gather to host ([CIRC,CIRC] root-gather analog)."""
    return np.asarray(jax.device_get(x))


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager exposing the mesh for `jax.lax` collective lowering."""
    with mesh:
        yield mesh
