"""Multi-host bootstrap and host-level collectives.

The reference's process model is MPI: ``MPI_Init`` in every CLI main
(ref: ml/skylark_ml.cpp:17-20), Boost.MPI communicators threaded through
every layer (ref: utility/get_communicator.hpp). The TPU-native process
model is single-controller-per-host JAX: one call to
``jax.distributed.initialize`` turns N hosts into one logical device pool;
meshes built from ``jax.devices()`` then span hosts, and the same sharded
code paths run unchanged with XLA routing collectives over ICI within a
slice and DCN across slices (SURVEY.md §2.9).
"""

from __future__ import annotations

import inspect
import socket
import time
from typing import Optional

import jax

from libskylark_tpu.base import errors


def _probe_coordinator(address: str, timeout: float) -> None:
    """Bounded TCP reachability probe of the coordinator, retried until
    ``timeout`` (the coordinator may start moments after its workers).

    This runs BEFORE ``jax.distributed.initialize`` because the C++
    distributed client does not raise on an unreachable coordinator —
    its RegisterTask deadline trips a ``LOG(FATAL)`` that aborts the
    whole process, which no Python ``except`` can intercept (observed
    on this jax build). A plain socket connect is interceptable, so an
    unreachable coordinator becomes a catchable
    :class:`~libskylark_tpu.base.errors.CommunicationError` instead of
    a SIGABRT (or, without any timeout, an indefinite hang)."""
    host, _, port = address.rpartition(":")
    try:
        port_no = int(port)
    except ValueError:
        err = errors.CommunicationError(
            f"malformed coordinator address {address!r} "
            "(expected host:port)")
        raise err
    deadline = time.monotonic() + timeout
    last: Optional[BaseException] = None
    while True:
        step = max(min(deadline - time.monotonic(), 1.0), 0.05)
        try:
            with socket.create_connection((host or "127.0.0.1", port_no),
                                          timeout=step):
                return
        except OSError as e:
            last = e
        if time.monotonic() >= deadline:
            err = errors.CommunicationError(
                f"coordinator {address!r} unreachable after "
                f"{timeout}s: {last}")
            err.append_trace(f"coordinator={address!r} "
                             f"connect_timeout={timeout}")
            raise err from last
        time.sleep(min(0.1, max(deadline - time.monotonic(), 0)))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    connect_timeout: Optional[float] = None,
) -> None:
    """Join the multi-host pool (MPI_Init analog; idempotent).

    With no arguments, uses the cluster-environment auto-detection
    (TPU pods set the coordinator through the metadata environment).
    Call before any jax computation, once per host process.

    ``connect_timeout`` (seconds) bounds the coordinator handshake —
    without it jax's default wait is minutes (or a hard C++ abort once
    the internal deadline trips), and an unreachable coordinator
    (wrong address, dead pod slice) looks like a hang. With it, worker
    processes with an *explicit* nonzero ``process_id`` TCP-probe the
    coordinator first and raise
    :class:`~libskylark_tpu.base.errors.CommunicationError` (the
    taxonomy's MPI-exception analog) with the coordinator address in
    the trace, never a raw ``RuntimeError``. Process 0 — which hosts
    the coordinator service itself — and auto-detected processes
    (``process_id=None``: this process might *be* the coordinator, so
    a probe would deadlock the pod) skip the probe and get the bounded
    ``initialization_timeout``; that bound still ends in jax's C++
    ``LOG(FATAL)`` rather than a Python exception on this jax build,
    so pass an explicit ``process_id`` where a catchable failure
    matters.
    """
    kw = {}
    if connect_timeout is not None:
        if coordinator_address and process_id not in (None, 0):
            _probe_coordinator(coordinator_address, connect_timeout)
        # jax >= 0.4.15 takes initialization_timeout; degrade gracefully
        # (the default wait) on builds that predate it rather than dying
        # on an unexpected-kwarg TypeError
        params = inspect.signature(jax.distributed.initialize).parameters
        if "initialization_timeout" in params:
            kw["initialization_timeout"] = int(max(connect_timeout, 1))
    try:
        jax.distributed.initialize(
            coordinator_address, num_processes, process_id, **kw)
    except RuntimeError as e:  # already initialized — MPI_Init semantics
        msg = str(e).lower()
        if "already" in msg or "only be called once" in msg:
            return
        err = errors.CommunicationError(
            f"distributed initialization failed: {e}")
        err.append_trace(
            f"coordinator={coordinator_address!r} "
            f"num_processes={num_processes} process_id={process_id} "
            f"connect_timeout={connect_timeout}")
        raise err from e


def process_count() -> int:
    """Number of host processes (MPI size analog)."""
    return jax.process_count()


def process_index() -> int:
    """This host's id (MPI rank analog); 0 is the reference's 'root'."""
    return jax.process_index()


def is_root() -> bool:
    """ref: the ubiquitous ``rank == 0`` guard (e.g. ml/io.hpp readers)."""
    return jax.process_index() == 0
