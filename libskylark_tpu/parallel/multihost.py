"""Multi-host bootstrap and host-level collectives.

The reference's process model is MPI: ``MPI_Init`` in every CLI main
(ref: ml/skylark_ml.cpp:17-20), Boost.MPI communicators threaded through
every layer (ref: utility/get_communicator.hpp). The TPU-native process
model is single-controller-per-host JAX: one call to
``jax.distributed.initialize`` turns N hosts into one logical device pool;
meshes built from ``jax.devices()`` then span hosts, and the same sharded
code paths run unchanged with XLA routing collectives over ICI within a
slice and DCN across slices (SURVEY.md §2.9).
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host pool (MPI_Init analog; idempotent).

    With no arguments, uses the cluster-environment auto-detection
    (TPU pods set the coordinator through the metadata environment).
    Call before any jax computation, once per host process.
    """
    try:
        jax.distributed.initialize(
            coordinator_address, num_processes, process_id)
    except RuntimeError as e:  # already initialized — MPI_Init semantics
        msg = str(e).lower()
        if "already" not in msg and "only be called once" not in msg:
            raise


def process_count() -> int:
    """Number of host processes (MPI size analog)."""
    return jax.process_count()


def process_index() -> int:
    """This host's id (MPI rank analog); 0 is the reference's 'root'."""
    return jax.process_index()


def is_root() -> bool:
    """ref: the ubiquitous ``rank == 0`` guard (e.g. ml/io.hpp readers)."""
    return jax.process_index() == 0
