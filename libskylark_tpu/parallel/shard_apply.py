"""Sequence-parallel sketch application: explicit shard_map panel pipeline.

The reference's structural analog of long-context parallelism is scaling
the "long" dimension of a matrix past one node's memory: panel-blocked
apply with a lazily materialized operator
(ref: sketch/dense_transform_Elemental_mc_mr.hpp:87-207 panel loop,
sketch/dense_transform_data.hpp:79-152 realize_matrix_view; SURVEY.md §5
"long-context"). This module is that design made TPU-native and
*manually scheduled*: the long axis N is sharded across a mesh axis, each
device walks only its own column blocks of the virtual operator S —
generated on-device from (seed, counter), never at full size — and one
``psum`` combines the partial contractions. Memory per device:
A-shard + one (S_dim × BLOCK_COLS) panel.

This is the shard_map counterpart of the automatic path (plain
``T.apply`` on a sharded array, where XLA chooses the schedule); use it
when the panel pipeline must be explicit — ultra-long N where even the
XLA-fused apply would materialize an (S_dim × N/p) operator shard.

Works for any DenseTransform-backed sketch (JLT, CT, and the dense core
of the feature maps). The returned computation is not pre-jitted — wrap
in ``jax.jit`` at the call site like any other apply.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from libskylark_tpu.base import errors
from libskylark_tpu.parallel.mesh import ROWS
from libskylark_tpu.sketch.dense import BLOCK_COLS, DenseTransform


def _pipeline(T, A, mesh: Mesh, axis: str, seq_axis: int) -> jnp.ndarray:
    """Shared schedule: per-device fori_loop over the device's operator
    column blocks, contracting against the matching slice of the local
    A-shard along ``seq_axis``, then one psum (the reference's local-gemm
    + all_reduce pattern, ref: base/Gemm.hpp:84-103)."""
    if not isinstance(T, DenseTransform):
        raise errors.UnsupportedError(
            "sequence-parallel apply needs a DenseTransform-backed sketch; "
            f"got {type(T).__name__}"
        )
    A = jnp.asarray(A)
    N = T.input_dim
    if A.shape[seq_axis] != N:
        raise errors.SketchError(
            f"sequence axis has {A.shape[seq_axis]} entries, transform "
            f"expects {N} (A is {A.shape})"
        )
    p = mesh.shape[axis]
    if N % (p * BLOCK_COLS):
        raise errors.InvalidParametersError(
            f"N={N} must be divisible by devices×BLOCK_COLS "
            f"({p}×{BLOCK_COLS})"
        )
    blocks_per_shard = N // p // BLOCK_COLS
    s_dim = T.sketch_dim
    columnwise = seq_axis == 0

    def local(A_loc):
        d = lax.axis_index(axis)
        first = d * blocks_per_shard

        def body(b, acc):
            Sb = T.s_block(first + b, A_loc.dtype)       # (s_dim, BC)
            seg = lax.dynamic_slice_in_dim(
                A_loc, b * BLOCK_COLS, BLOCK_COLS, axis=seq_axis)
            return acc + (Sb @ seg if columnwise else seg @ Sb.T)

        out_shape = ((s_dim, A_loc.shape[1]) if columnwise
                     else (A_loc.shape[0], s_dim))
        # the carry must be marked device-varying to match the body output
        zero = jnp.zeros(out_shape, A_loc.dtype)
        if hasattr(lax, "pcast"):
            acc0 = lax.pcast(zero, axis, to="varying")
        else:  # older jax
            acc0 = lax.pvary(zero, axis)
        return lax.psum(lax.fori_loop(0, blocks_per_shard, body, acc0),
                        axis)

    in_spec = P(axis, None) if columnwise else P(None, axis)
    fn = shard_map(local, mesh=mesh, in_specs=in_spec,
                   out_specs=P(None, None))
    return fn(A)


def columnwise(T, A, mesh: Mesh, axis: str = ROWS) -> jnp.ndarray:
    """S·A for A (N, m) sharded on its first (sequence) axis; returns the
    (S_dim, m) result replicated."""
    return _pipeline(T, A, mesh, axis, seq_axis=0)


def rowwise(T, A, mesh: Mesh, axis: str = ROWS) -> jnp.ndarray:
    """A·Sᵀ for A (m, N) sharded on its second (sequence) axis; returns
    the (m, S_dim) result replicated."""
    return _pipeline(T, A, mesh, axis, seq_axis=1)
