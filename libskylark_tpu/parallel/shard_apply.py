"""Sequence-parallel sketch application: explicit shard_map panel pipeline.

The reference's structural analog of long-context parallelism is scaling
the "long" dimension of a matrix past one node's memory: panel-blocked
apply with a lazily materialized operator
(ref: sketch/dense_transform_Elemental_mc_mr.hpp:87-207 panel loop,
sketch/dense_transform_data.hpp:79-152 realize_matrix_view; SURVEY.md §5
"long-context"). This module is that design made TPU-native and
*manually scheduled*: the long axis N is sharded across a mesh axis, each
device walks only its own column blocks of the virtual operator S —
generated on-device from (seed, counter), never at full size — and one
``psum`` combines the partial contractions. Memory per device:
A-shard + one (S_dim × BLOCK_COLS) panel.

This is the shard_map counterpart of the automatic path (plain
``T.apply`` on a sharded array, where XLA chooses the schedule); use it
when the panel pipeline must be explicit — ultra-long N where even the
XLA-fused apply would materialize an (S_dim × N/p) operator shard.

Works for any DenseTransform-backed sketch (JLT, CT, and the dense core
of the feature maps). The returned computation is not pre-jitted — wrap
in ``jax.jit`` at the call site like any other apply.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from libskylark_tpu.base import errors
from libskylark_tpu.base.compat import pvary, shard_map
from libskylark_tpu.parallel.mesh import ROWS
from libskylark_tpu.sketch.dense import BLOCK_COLS, DenseTransform


def _pipeline(T, A, mesh: Mesh, axis: str, seq_axis: int,
              use_pallas: bool | None = None,
              interpret: bool = False) -> jnp.ndarray:
    """Shared schedule: per-device contraction of the device's operator
    column blocks against the local A-shard along ``seq_axis``, then one
    psum (the reference's local-gemm + all_reduce pattern,
    ref: base/Gemm.hpp:84-103).

    Per-device contraction runs through the fused Pallas kernel when the
    backend/distribution qualify (``pallas_dense.fused_partial`` — each
    device receives its slice of the global block-key table via the
    sharded in_spec), else a fori_loop of XLA matmuls over on-the-fly
    panels. Ragged N (not a devices×BLOCK_COLS multiple) is zero-padded
    on the sequence axis — exact for these contractions (the reference's
    np∈{5,7} ragged-layout discipline, ref: tests/unit/CMakeLists.txt:31-33).
    """
    from libskylark_tpu.sketch import params as sketch_params
    from libskylark_tpu.sketch import pallas_dense as pd

    if not isinstance(T, DenseTransform):
        raise errors.UnsupportedError(
            "sequence-parallel apply needs a DenseTransform-backed sketch; "
            f"got {type(T).__name__}"
        )
    A = jnp.asarray(A)
    N = T.input_dim
    if A.shape[seq_axis] != N:
        raise errors.SketchError(
            f"sequence axis has {A.shape[seq_axis]} entries, transform "
            f"expects {N} (A is {A.shape})"
        )
    p = mesh.shape[axis]
    step = p * BLOCK_COLS
    pad_N = -(-N // step) * step
    if pad_N != N:
        pads = [(0, 0), (0, 0)]
        pads[seq_axis] = (0, pad_N - N)
        A = jnp.pad(A, pads)
    blocks_per_shard = pad_N // p // BLOCK_COLS
    s_dim = T.sketch_dim
    columnwise = seq_axis == 0
    if use_pallas is None:
        use_pallas = sketch_params.get_use_pallas()
    # Only take the kernel branch when it can actually run — otherwise
    # the key table is dead weight and the fallback loses vma checking.
    use_pallas = (use_pallas and pd._HAVE_PALLAS
                  and (interpret or pd.available())
                  and pd.supported(T.dist, A.dtype))

    # Global block-key table, sharded so each device gets its own slice
    # (same bits as T.s_block — see pallas_dense._block_keys).
    keys_all = pd._block_keys(T._alloc.key, pad_N) if use_pallas else None

    def local(A_loc, keys_loc):
        d = lax.axis_index(axis)
        first = d * blocks_per_shard

        part = None
        if keys_loc is not None:
            part = pd.fused_partial(
                keys_loc, T.dist, A_loc, s_dim, seq_axis=seq_axis,
                interpret=interpret,
            )
            if part is not None:
                part = jnp.asarray(T.scale, A_loc.dtype) * part

        if part is None:
            def body(b, acc):
                Sb = T.s_block(first + b, A_loc.dtype)       # (s_dim, BC)
                seg = lax.dynamic_slice_in_dim(
                    A_loc, b * BLOCK_COLS, BLOCK_COLS, axis=seq_axis)
                return acc + (Sb @ seg if columnwise else seg @ Sb.T)

            out_shape = ((s_dim, A_loc.shape[1]) if columnwise
                         else (A_loc.shape[0], s_dim))
            # the carry must be marked device-varying to match the body
            # (identity on jax lines without the vma system — compat)
            acc0 = pvary(jnp.zeros(out_shape, A_loc.dtype), axis)
            part = lax.fori_loop(0, blocks_per_shard, body, acc0)
        return lax.psum(part, axis)

    in_spec = P(axis, None) if columnwise else P(None, axis)
    if keys_all is not None:
        # check_vma off: pallas_call's out_shape carries no varying-axis
        # annotation, which the vma checker (rightly) rejects; the psum
        # above establishes the replicated output explicitly.
        fn = shard_map(local, mesh=mesh, in_specs=(in_spec, P(axis, None)),
                       out_specs=P(None, None), check_vma=False)
        return fn(A, keys_all)
    fn = shard_map(lambda A_loc: local(A_loc, None), mesh=mesh,
                   in_specs=in_spec, out_specs=P(None, None))
    return fn(A)


def columnwise(T, A, mesh: Mesh, axis: str = ROWS,
               use_pallas: bool | None = None,
               interpret: bool = False) -> jnp.ndarray:
    """S·A for A (N, m) sharded on its first (sequence) axis; returns the
    (S_dim, m) result replicated."""
    return _pipeline(T, A, mesh, axis, seq_axis=0,
                     use_pallas=use_pallas, interpret=interpret)


def rowwise(T, A, mesh: Mesh, axis: str = ROWS,
            use_pallas: bool | None = None,
            interpret: bool = False) -> jnp.ndarray:
    """A·Sᵀ for A (m, N) sharded on its second (sequence) axis; returns
    the (m, S_dim) result replicated."""
    return _pipeline(T, A, mesh, axis, seq_axis=1,
                     use_pallas=use_pallas, interpret=interpret)
