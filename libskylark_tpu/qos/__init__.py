"""Multi-tenant QoS: SLO-aware scheduling, adaptive batching, and
admission control for the serve tier (docs/qos).

Three pieces, wired through :class:`~libskylark_tpu.engine.serve
.MicrobatchExecutor` and the fleet :class:`~libskylark_tpu.fleet
.Router`:

- :mod:`~libskylark_tpu.qos.tenants` — the tenant model: priority
  classes (``interactive`` / ``standard`` / ``best_effort``) with
  weights, shed fractions and p99 SLOs; named tenants with
  deterministic token-bucket rate limits
  (:class:`~libskylark_tpu.base.errors.TenantQuotaError` at
  admission); the process-global :func:`get_registry`.
- :mod:`~libskylark_tpu.qos.scheduler` — weighted-fair deficit
  scheduling (DRR) across per-class queues, replacing the executor's
  single FIFO drain order; class-ordered shedding (best_effort before
  standard before interactive, sessions below interactive).
- :mod:`~libskylark_tpu.qos.controller` — the adaptive batching
  controller retuning per-bucket ``linger``/``max_batch`` targets
  from the r10 latency/padding histograms against the class SLOs,
  moving only along already-warm pow2 capacity classes so adaptation
  causes **zero recompiles**; frozen by ``SKYLARK_QOS_ADAPT=0``.

Usage::

    from libskylark_tpu import qos

    qos.get_registry().register("search-ui", qos.INTERACTIVE)
    qos.get_registry().register("bulk-etl", qos.BEST_EFFORT,
                                rate=200.0)
    fut = ex.submit_sketch(T, A, tenant="search-ui")
    router.submit_solve(A, b, transform=T, tenant="bulk-etl")
"""

from libskylark_tpu.qos.controller import AdaptiveController
from libskylark_tpu.qos.scheduler import DeficitScheduler, drain_order
from libskylark_tpu.qos.tenants import (BEST_EFFORT, CLASSES,
                                        DEFAULT_WEIGHTS, INTERACTIVE,
                                        STANDARD, ClassPolicy, Tenant,
                                        TenantRegistry, TokenBucket,
                                        class_policy, coerce_class,
                                        default_class, get_registry,
                                        shed_fraction, slo_seconds)

__all__ = [
    "AdaptiveController", "BEST_EFFORT", "CLASSES", "ClassPolicy",
    "DEFAULT_WEIGHTS", "DeficitScheduler", "INTERACTIVE", "STANDARD",
    "Tenant", "TenantRegistry", "TokenBucket", "class_policy",
    "coerce_class", "default_class", "drain_order", "get_registry",
    "shed_fraction", "slo_seconds",
]
