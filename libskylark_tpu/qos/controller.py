"""Adaptive batching: per-bucket linger/batch targets tuned from the
live latency and padding-waste histograms against the class SLOs.

The static ``linger_us``/``max_batch`` executor config is one global
compromise: a linger long enough to fill best-effort cohorts taxes
every interactive request's p99, and a linger short enough for the
interactive SLO fragments bulk traffic into half-empty (padded)
flushes. The r10 telemetry already measures both failure modes —
p99 request latency and the padding-waste ratio — per executor; this
controller closes the loop per **bucket**:

- every tick (``SKYLARK_QOS_ADAPT_INTERVAL``), each bucket with fresh
  completions is scored against the strictest p99 SLO among the
  priority classes whose traffic it carried
  (:func:`~libskylark_tpu.qos.tenants.slo_seconds`);
- **over SLO** -> the bucket's linger target halves (a bounded step,
  floor 0 = flush immediately) and its batch target steps one rung
  DOWN the warm capacity ladder;
- **under half the SLO with high padding waste** -> linger grows 1.5x
  (capped at 8x the static config) and the batch target steps one
  rung UP the warm ladder — latency headroom is traded back for
  denser cohorts;
- two consecutive ticks must agree (hysteresis) before either change
  applies, and every change is one bounded step — the controller
  walks, it never jumps.

**Zero recompiles by construction**: batch targets move only along
the bucket's *already-warm* pow2 capacity classes (the capacities it
has actually flushed at, whose executables are therefore resident),
and the linger target does not enter any executable key at all — so
adaptation can never trigger a compile. The CI qos gate asserts this
empirically (engine compile counters flat while targets move).

``SKYLARK_QOS_ADAPT=0`` freezes every controller (ticks become no-ops
that only count themselves) — the operator's escape hatch, and the
A/B switch ``bench.py --qos`` uses.
"""

from __future__ import annotations

import threading
import warnings
from typing import Dict, Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.qos import tenants as _tenants
from libskylark_tpu.telemetry import metrics as _metrics

# controller gauges (docs/qos): the live targets, labeled by replica
# and endpoint so a dashboard can watch adaptation converge. Created
# HERE once (the metric-names one-creation-site contract).
_LINGER_TARGET = _metrics.gauge(
    "qos.linger_target",
    "Adaptive per-bucket linger target (seconds), by replica and "
    "endpoint")
_BATCH_TARGET = _metrics.gauge(
    "qos.batch_target",
    "Adaptive per-bucket cohort-size target (requests), by replica "
    "and endpoint")

#: Linger ceiling as a multiple of the executor's static config.
LINGER_CEILING_FACTOR = 8.0

#: Padding-waste ratio above which latency headroom is traded for
#: denser batching.
WASTE_THRESHOLD = 0.3

#: Consecutive same-direction ticks required before a change applies.
HYSTERESIS_TICKS = 2

#: Fresh completions a bucket needs between ticks to be scored.
MIN_SAMPLES = 4


class AdaptiveController:
    """One executor's adaptive batching loop (module doc). Owned and
    started by :class:`~libskylark_tpu.engine.serve
    .MicrobatchExecutor` when built with ``adaptive=True``; stopped
    from the executor's shutdown."""

    def __init__(self, executor, interval_s: Optional[float] = None,
                 start: bool = True):
        self._ex = executor
        self.interval_s = float(
            interval_s if interval_s is not None
            else _env.QOS_ADAPT_INTERVAL.get())
        self._cond = threading.Condition(
            _locks.make_lock("qos.controller"))
        self._stats_lock = _locks.make_lock("qos.controller_stats")
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # per-bucket controller memory: consecutive trend direction,
        # completions already scored, last applied targets
        self._trend: Dict[tuple, int] = {}
        self._seen_n: Dict[tuple, int] = {}
        self._counts = {"ticks": 0, "frozen_ticks": 0,
                        "linger_down": 0, "linger_up": 0,
                        "batch_down": 0, "batch_up": 0}
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop,
            name=f"skylark-qos-controller-{self._ex.name}", daemon=True)
        self._thread.start()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                self._cond.wait(timeout=self.interval_s)
                if self._stop:
                    return
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — controller lives
                warnings.warn(f"qos controller tick failed: {e}",
                              RuntimeWarning, stacklevel=1)

    # -- the control decision ------------------------------------------

    def tick(self) -> int:
        """Score every active bucket once; returns how many target
        changes were applied (tests drive this synchronously). A
        no-op (beyond counting) when ``SKYLARK_QOS_ADAPT=0`` — the
        freeze switch."""
        with self._stats_lock:
            self._counts["ticks"] += 1
        if not _env.QOS_ADAPT.get():
            with self._stats_lock:
                self._counts["frozen_ticks"] += 1
            return 0
        changes = 0
        obs = self._ex.qos_bucket_obs()
        for statics, o in obs.items():
            changes += self._score_bucket(statics, o)
        return changes

    def _score_bucket(self, statics: tuple, o: dict) -> int:
        n = int(o.get("n", 0))
        if n - self._seen_n.get(statics, 0) < MIN_SAMPLES:
            return 0
        self._seen_n[statics] = n
        p99 = o.get("p99")
        if p99 is None:
            return 0
        slo = min((_tenants.slo_seconds(c)
                   for c in (o.get("classes") or ("standard",))),
                  default=_tenants.slo_seconds("standard"))
        waste = o.get("padding_waste") or 0.0
        if p99 > slo:
            direction = -1            # too slow: batch less, flush sooner
        elif p99 < 0.5 * slo and waste > WASTE_THRESHOLD:
            direction = +1            # headroom + waste: batch denser
        else:
            direction = 0
        prev = self._trend.get(statics, 0)
        trend = (prev + direction
                 if direction and (prev == 0
                                   or (prev > 0) == (direction > 0))
                 else direction)
        self._trend[statics] = trend
        if direction == 0 or abs(trend) < HYSTERESIS_TICKS:
            return 0
        self._trend[statics] = 0       # acted: restart the hysteresis
        return self._apply(statics, o, direction)

    def _apply(self, statics: tuple, o: dict, direction: int) -> int:
        ex = self._ex
        linger, cap = ex.bucket_targets(statics)
        warm = sorted(int(c) for c in (o.get("caps") or ()))
        changed = 0
        if direction < 0:
            new_linger = 0.0 if linger < 1e-4 else linger * 0.5
            lower = [c for c in warm if c < cap]
            new_cap = lower[-1] if lower else cap
            key_l, key_b = "linger_down", "batch_down"
        else:
            new_linger = min(max(linger * 1.5, 1e-4),
                             ex.linger * LINGER_CEILING_FACTOR)
            higher = [c for c in warm
                      if cap < c <= ex.max_batch]
            new_cap = higher[0] if higher else cap
            key_l, key_b = "linger_up", "batch_up"
        if new_linger != linger:
            changed += 1
            with self._stats_lock:
                self._counts[key_l] += 1
        if new_cap != cap:
            changed += 1
            with self._stats_lock:
                self._counts[key_b] += 1
        if changed:
            ex.set_bucket_targets(statics, linger_s=new_linger,
                                  batch_cap=new_cap)
            # drop the evidence that triggered the step: the next
            # decision must score POST-change traffic, or the same
            # burst keeps driving same-direction steps for a whole
            # window length after latency recovered
            ex.qos_reset_bucket_obs(statics)
            endpoint = str(statics[0]) if statics else "?"
            _LINGER_TARGET.set(new_linger, replica=ex.name,
                               endpoint=endpoint)
            _BATCH_TARGET.set(float(new_cap), replica=ex.name,
                              endpoint=endpoint)
        return changed

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            c = dict(self._counts)
        c["adjustments"] = (c["linger_down"] + c["linger_up"]
                            + c["batch_down"] + c["batch_up"])
        c["frozen"] = not _env.QOS_ADAPT.get()
        c["interval_s"] = self.interval_s
        return c


__all__ = ["AdaptiveController", "HYSTERESIS_TICKS",
           "LINGER_CEILING_FACTOR", "MIN_SAMPLES", "WASTE_THRESHOLD"]
