"""Weighted-fair deficit scheduling across the QoS priority classes.

The pre-QoS executor drained its buckets in dict order — one global
FIFO in effect, so a best-effort storm and an interactive request
competed head-to-head for every flush slot. This module is the
replacement decision procedure: classic **deficit round robin** (DRR,
Shreedhar & Varghese) over one virtual queue per priority class.

Each class holds a *deficit* (credit measured in requests). A
scheduling round visits the classes in fixed priority order; a class
with ready work is credited ``quantum x weight`` once per round and
may dispatch cohorts while its deficit covers their request count.
The properties the test battery pins:

- **weighted fairness**: under sustained all-class backlog, served
  requests approach the 8:4:1 class weights
  (:data:`~libskylark_tpu.qos.tenants.DEFAULT_WEIGHTS`);
- **starvation freedom**: every class's weight is >= 1, so a class
  with backlog is credited every round and drains at least one
  cohort per round once its deficit accumulates — best_effort is
  *deprioritized*, never parked;
- **work conservation**: a round with exactly one backlogged class
  dispatches from it immediately (deficits never idle the executor);
- **determinism**: the decision is a pure function of the visible
  backlog and the carried deficits — no clocks, no randomness — so
  chaos replays schedule identically.

The scheduler is deliberately executor-agnostic (it sees class names
and request counts, not buckets) so the property battery can drive it
synthetically; :class:`~libskylark_tpu.engine.serve
.MicrobatchExecutor` owns the mapping from buckets to classes.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

from libskylark_tpu.qos import tenants as _tenants


class DeficitScheduler:
    """DRR decision state over the priority classes (module doc).

    Single-threaded by contract: the executor consults it only from
    the flusher thread (under the executor lock), so the deficits
    need no lock of their own.
    """

    def __init__(self, weights: Optional[Dict[str, int]] = None,
                 quantum: int = 1):
        self.weights = dict(_tenants.DEFAULT_WEIGHTS)
        if weights:
            for cls, w in weights.items():
                self.weights[_tenants.coerce_class(cls)] = max(int(w), 1)
        self.quantum = max(int(quantum), 1)
        self._deficit: Dict[str, float] = {c: 0.0 for c in
                                           _tenants.CLASSES}
        self.served: Dict[str, int] = {c: 0 for c in _tenants.CLASSES}
        # requests satisfied WITHOUT a dispatch (result-cache hits and
        # single-flight followers, docs/caching): they never consume a
        # flush slot, so they must not spend deficit — but the
        # fairness ledger has to show them or a hot cached class would
        # look starved next to its actual goodput
        self.bypassed: Dict[str, int] = {c: 0 for c in _tenants.CLASSES}

    # -- the decision procedure ---------------------------------------

    def next_class(self, backlog: Dict[str, int],
                   cost: Callable[[str], int]) -> Optional[str]:
        """Pick the class to dispatch from next. ``backlog`` maps class
        -> ready request count (classes with zero ready work are
        skipped and their deficit cleared — an idle class must not
        bank credit and then burst past its weight); ``cost(cls)`` is
        the request count of the cohort that WOULD be dispatched.
        Returns ``None`` when nothing is ready."""
        ready = [c for c in _tenants.CLASSES if backlog.get(c, 0) > 0]
        if not ready:
            for c in _tenants.CLASSES:
                self._deficit[c] = 0.0
            return None
        for c in _tenants.CLASSES:
            if backlog.get(c, 0) <= 0:
                # no banked credit for idle classes (DRR's anti-burst
                # rule): a class that sat empty must not return and
                # burst past its weight on saved deficit
                self._deficit[c] = 0.0
        if len(ready) == 1:
            # work conservation: a lone backlogged class never waits
            # on credit arithmetic
            return ready[0]
        # spend-then-credit rounds: serve the first class (priority
        # order) whose deficit covers its head cohort; when none can
        # afford theirs, credit every ready class one quantum x weight
        # and retry. Terminates: deficits grow at least 1/iteration
        # toward a bounded cohort cost.
        bound = int(max(cost(c) for c in ready)) + 2
        for _ in range(bound):
            for c in ready:
                if self._deficit[c] >= cost(c):
                    return c
            for c in ready:
                self._deficit[c] += self.quantum * self.weights[c]
        return max(ready, key=lambda c: self._deficit[c])

    def charge(self, cls: str, n: int) -> None:
        """Account one dispatched cohort of ``n`` requests."""
        cls = _tenants.coerce_class(cls)
        self._deficit[cls] = max(0.0, self._deficit[cls] - int(n))
        self.served[cls] = self.served.get(cls, 0) + int(n)

    def note_bypass(self, cls: str, n: int = 1) -> None:
        """Account ``n`` requests of ``cls`` satisfied without a
        dispatch (a result-cache hit or a coalesced single-flight
        follower): counted in the fairness ledger, charged to no
        deficit — a bypassed request consumed no flush slot, so
        spending credit for it would under-serve the class's actual
        queue (docs/caching)."""
        cls = _tenants.coerce_class(cls)
        self.bypassed[cls] = self.bypassed.get(cls, 0) + int(n)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        return {
            "weights": dict(self.weights),
            "deficit": {c: round(self._deficit[c], 3)
                        for c in _tenants.CLASSES},
            "served": dict(self.served),
            "bypassed": dict(self.bypassed),
        }


def drain_order(classes: Sequence[str]) -> list:
    """Shed order: least-protected first (the reverse of
    :data:`~libskylark_tpu.qos.tenants.CLASSES`). This is the
    *statement* of the ordering contract — the executor implements it
    through per-class admission bounds
    (``MicrobatchExecutor._class_shed_bound`` and the pressure
    fractions), not by consulting this function; tests pin the two
    against each other. Useful for tooling that ranks classes."""
    order = [c for c in reversed(_tenants.CLASSES) if c in classes]
    return order + [c for c in classes if c not in order]


__all__ = ["DeficitScheduler", "drain_order"]
