"""Tenant model of the multi-tenant QoS subsystem (docs/qos).

Serving millions of users means *interactive* and *batch* callers
share the same executors. This module gives traffic an identity the
scheduler can act on:

- a **priority class** — ``interactive`` / ``standard`` /
  ``best_effort`` (:data:`CLASSES`, authority in
  ``base/env.QOS_CLASSES``) — carrying a weighted-fair scheduling
  weight, a DEGRADED-shed fraction, a queue-pressure admission bound
  and a p99 latency SLO (:class:`ClassPolicy`);
- a **tenant** — a named principal mapped to one class, optionally
  rate-limited by a deterministic token bucket
  (:class:`TokenBucket`); an over-quota request is refused at
  admission with :class:`~libskylark_tpu.base.errors
  .TenantQuotaError` instead of occupying queue space;
- a **registry** (:class:`TenantRegistry`) resolving ``tenant=``
  submit arguments to ``(tenant, class)`` and charging the token
  bucket. Unknown tenants (and tenant-less requests) land in
  ``SKYLARK_QOS_DEFAULT_CLASS`` unlimited — QoS is opt-in per
  principal, never a prerequisite for serving.

Resolution happens once, at the front door: a
:class:`~libskylark_tpu.fleet.Router` resolves + admits in the parent
process and forwards the *resolved class* (``qos_class=``) to the
chosen replica, so process replicas — whose registry is a different
process's — schedule on the class without re-charging the quota.
A directly-submitted executor resolves against the process-global
registry (:func:`get_registry`).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional, Tuple

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors as _errors
from libskylark_tpu.base import locks as _locks

#: Priority classes, most- to least-protected (shed order is the
#: reverse). The tuple object is ``base/env.QOS_CLASSES`` — the env
#: parser and this module cannot disagree.
CLASSES: Tuple[str, ...] = _env.QOS_CLASSES

INTERACTIVE, STANDARD, BEST_EFFORT = CLASSES

#: Weighted-fair scheduling weights (deficit quanta per round). The
#: ratios — not the absolute values — are the contract: under
#: sustained full backlog the classes drain ~8:4:1.
DEFAULT_WEIGHTS: Dict[str, int] = {
    INTERACTIVE: 8, STANDARD: 4, BEST_EFFORT: 1,
}

#: Queue-pressure admission bound per class, as a fraction of
#: ``max_queue`` — applied even when the executor is healthy.
#: best_effort stops admitting at half the queue so a best-effort
#: storm can never fill the bound against higher classes; interactive
#: and standard keep the full bound (and the backpressure wait).
PRESSURE_FRACTIONS: Dict[str, float] = {
    INTERACTIVE: 1.0, STANDARD: 1.0, BEST_EFFORT: 0.5,
}


def default_class() -> str:
    """``SKYLARK_QOS_DEFAULT_CLASS`` (typo degrades to standard)."""
    return _env.QOS_DEFAULT_CLASS.get()


def shed_fraction(cls: str) -> float:
    """The class's DEGRADED-shed fraction of ``max_queue`` (env-
    tunable; interactive > standard > best_effort by default, which
    IS the shed ordering: the smaller the fraction, the earlier the
    class sheds)."""
    if cls == INTERACTIVE:
        return float(_env.QOS_SHED_INTERACTIVE.get())
    if cls == BEST_EFFORT:
        return float(_env.QOS_SHED_BEST_EFFORT.get())
    return float(_env.QOS_SHED_STANDARD.get())


def cache_quota_fraction(cls: str) -> float:
    """The class's share of the result-cache byte budget
    (``SKYLARK_CACHE_QUOTA_*``; docs/caching, "Tenant admission").
    Quotas are hard partitions — insertion into one class evicts only
    that class's own entries — so the fractions ARE the isolation
    contract: a best_effort storm can fill at most its own share and
    never displaces an interactive working set. Values clamp to
    [0, 1]; a non-positive fraction disables caching for the class."""
    if cls == INTERACTIVE:
        f = _env.CACHE_QUOTA_INTERACTIVE.get()
    elif cls == BEST_EFFORT:
        f = _env.CACHE_QUOTA_BEST_EFFORT.get()
    else:
        f = _env.CACHE_QUOTA_STANDARD.get()
    return min(max(float(f), 0.0), 1.0)


def slo_seconds(cls: str) -> float:
    """The class's p99 latency SLO in seconds (env-tunable)."""
    if cls == INTERACTIVE:
        ms = _env.QOS_SLO_INTERACTIVE_MS.get()
    elif cls == BEST_EFFORT:
        ms = _env.QOS_SLO_BEST_EFFORT_MS.get()
    else:
        ms = _env.QOS_SLO_STANDARD_MS.get()
    return max(float(ms), 0.0) / 1000.0


def coerce_class(cls: Optional[str]) -> str:
    """A valid class name (``None``/unknown degrade to the default
    class — the repo's typo-degrades convention, so a misspelled
    class never drops a request)."""
    if cls is None:
        return default_class()
    cls = str(cls).strip().lower()
    return cls if cls in CLASSES else default_class()


@dataclasses.dataclass(frozen=True)
class ClassPolicy:
    """One priority class's scheduling contract (docs/qos)."""

    name: str
    weight: int
    shed_fraction: float      # of max_queue, under DEGRADED
    pressure_fraction: float  # of max_queue, always
    slo_s: float              # p99 latency target


def class_policy(cls: str) -> ClassPolicy:
    """The live (env-resolved) policy of one class."""
    cls = coerce_class(cls)
    return ClassPolicy(
        name=cls,
        weight=DEFAULT_WEIGHTS[cls],
        shed_fraction=shed_fraction(cls),
        pressure_fraction=PRESSURE_FRACTIONS[cls],
        slo_s=slo_seconds(cls),
    )


class TokenBucket:
    """Deterministic token bucket: ``rate`` tokens/second refill up to
    ``burst`` capacity; each admission costs one token. All state
    transitions are pure functions of the observation times handed to
    :meth:`try_acquire` (tests drive a manual clock; production passes
    ``time.monotonic()``), so the same arrival schedule always admits
    the same subset — the determinism the property battery pins."""

    __slots__ = ("rate", "burst", "_tokens", "_stamp", "_lock")

    def __init__(self, rate: float, burst: Optional[float] = None):
        if rate <= 0:
            raise _errors.InvalidParametersError(
                f"token-bucket rate must be positive, got {rate}")
        self.rate = float(rate)
        self.burst = (float(burst) if burst is not None
                      else 2.0 * self.rate)
        if self.burst < 1.0:
            self.burst = 1.0
        self._tokens = self.burst      # starts full
        self._stamp: Optional[float] = None
        self._lock = _locks.make_lock("qos.bucket")

    def try_acquire(self, now: Optional[float] = None
                    ) -> Tuple[bool, float]:
        """``(admitted, retry_after_s)``: spend one token if available;
        otherwise the deterministic seconds until one refills."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._stamp is not None and now > self._stamp:
                self._tokens = min(
                    self.burst,
                    self._tokens + (now - self._stamp) * self.rate)
            if self._stamp is None or now > self._stamp:
                self._stamp = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True, 0.0
            return False, (1.0 - self._tokens) / self.rate

    def available(self) -> float:
        with self._lock:
            return self._tokens


@dataclasses.dataclass
class Tenant:
    """One registered principal: name, class, optional rate limit."""

    name: str
    priority_class: str = STANDARD
    bucket: Optional[TokenBucket] = None


class TenantRegistry:
    """Thread-safe name -> :class:`Tenant` map with admission.

    ::

        reg = qos.get_registry()
        reg.register("search-ui", "interactive")
        reg.register("bulk-etl", "best_effort", rate=100.0)
        tenant, cls = reg.resolve("search-ui")
        reg.admit("bulk-etl")        # raises TenantQuotaError over quota
    """

    def __init__(self):
        self._lock = _locks.make_lock("qos.registry")
        self._tenants: Dict[str, Tenant] = {}

    def register(self, name: str, priority_class: str = STANDARD, *,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None) -> Tenant:
        """Register (or re-register) a tenant. ``rate`` is requests/
        second (``None`` consults ``SKYLARK_QOS_RATE_DEFAULT``; both
        unset = unlimited); ``burst`` is the bucket capacity
        (``None`` consults ``SKYLARK_QOS_BURST_DEFAULT``, else 2x
        rate). Re-registering replaces the tenant — including a fresh
        token bucket. An *explicit* ``rate=0`` is an error
        (:class:`~libskylark_tpu.base.errors.InvalidParametersError`
        from the bucket — a zero rate is neither a limit nor
        unlimited; refuse rather than guess); a non-positive env
        DEFAULT degrades to unlimited (the typo convention)."""
        cls = coerce_class(priority_class)
        if rate is None:
            rate = _env.QOS_RATE_DEFAULT.get()
            if rate is not None and rate <= 0:
                rate = None          # env zero/typo = no default limit
        if burst is None:
            burst = _env.QOS_BURST_DEFAULT.get()
        bucket = TokenBucket(rate, burst) if rate is not None else None
        t = Tenant(name=str(name), priority_class=cls, bucket=bucket)
        with self._lock:
            self._tenants[t.name] = t
        return t

    def unregister(self, name: str) -> None:
        with self._lock:
            self._tenants.pop(str(name), None)

    def get(self, name: str) -> Optional[Tenant]:
        with self._lock:
            return self._tenants.get(str(name))

    def resolve(self, tenant: Optional[str]) -> Tuple[str, str]:
        """``(tenant_name, class)`` for a submit's ``tenant=``:
        registered tenants carry their class, unknown/anonymous ones
        land in the default class."""
        if tenant is None:
            return "", default_class()
        t = self.get(tenant)
        if t is None:
            return str(tenant), default_class()
        return t.name, t.priority_class

    def admit(self, tenant: Optional[str],
              now: Optional[float] = None) -> Tuple[str, str]:
        """Resolve AND charge the tenant's token bucket. Raises
        :class:`~libskylark_tpu.base.errors.TenantQuotaError` when the
        bucket is empty (the request must be refused, not queued)."""
        name, cls = self.resolve(tenant)
        t = self.get(name) if name else None
        if t is not None and t.bucket is not None:
            ok, retry = t.bucket.try_acquire(now)
            if not ok:
                raise _errors.TenantQuotaError(
                    f"tenant {name!r} over admission quota "
                    f"({t.bucket.rate:g} req/s); retry in "
                    f"{retry:.3f}s", tenant=name, retry_after_s=retry)
        return name, cls

    def accounting_name(self, tenant: Optional[str]) -> str:
        """The label under which a request's tenant is ACCOUNTED:
        the tenant's name when registered, else ``""`` (the anonymous
        bucket). Metric label sets and per-tenant stats key on this,
        never on the raw caller string — otherwise a client passing a
        unique ``tenant=`` per request (a user id, a request id)
        would grow the label dictionaries without bound."""
        if not tenant:
            return ""
        return tenant if self.get(tenant) is not None else ""

    def names(self) -> list:
        with self._lock:
            return sorted(self._tenants)

    def stats(self) -> dict:
        with self._lock:
            tenants = list(self._tenants.values())
        return {
            "tenants": {
                t.name: {
                    "class": t.priority_class,
                    "rate": t.bucket.rate if t.bucket else None,
                    "tokens": (round(t.bucket.available(), 3)
                               if t.bucket else None),
                }
                for t in sorted(tenants, key=lambda t: t.name)
            },
        }


# process-global registry: what MicrobatchExecutor / Router consult
# when not handed an explicit one (tests build their own)
_REGISTRY = TenantRegistry()


def get_registry() -> TenantRegistry:
    """The process-global tenant registry."""
    return _REGISTRY


__all__ = [
    "BEST_EFFORT", "CLASSES", "ClassPolicy", "DEFAULT_WEIGHTS",
    "INTERACTIVE", "PRESSURE_FRACTIONS", "STANDARD", "Tenant",
    "TenantRegistry", "TokenBucket", "cache_quota_fraction",
    "class_policy", "coerce_class", "default_class", "get_registry",
    "shed_fraction", "slo_seconds",
]
