"""Resilience subsystem: retry policies, deterministic fault
injection, and preemption-safe teardown.

The reference library has no failure handling (SURVEY.md §5's "failure
detection / checkpoint-resume" row is empty); this package makes the
rebuild's failure paths first-class and — the part chaos testing lives
or dies on — *deterministically testable*:

- :mod:`~libskylark_tpu.resilience.policy` — composable
  :class:`RetryPolicy` (exponential backoff + decorrelated jitter,
  per-attempt timeouts, error-class predicates over the
  :mod:`base.errors` taxonomy) and :class:`Deadline` budgets that
  thread through call stacks.
- :mod:`~libskylark_tpu.resilience.faults` — a seeded fault-injection
  registry behind named sites in the serve flush worker, the engine
  compile path, the WebHDFS/chunked readers and checkpoint saves;
  activated by ``SKYLARK_FAULT_PLAN`` or ``with fault_plan(...)``,
  replaying bit-identically for a fixed seed.
- :mod:`~libskylark_tpu.resilience.preemption` —
  :func:`install_preemption_handler` turns SIGTERM into serve drain
  plus a final synchronous checkpoint for registered host-loop
  solvers.

Consumers: the microbatch executor's poison-isolation bisection and
health states (:mod:`libskylark_tpu.engine.serve`), the WebHDFS
transport's reconnect-and-resume (:mod:`libskylark_tpu.io.webhdfs`),
the HDF5 batch reader, ``TrainCheckpointer.save_sync``, and
``BlockADMMSolver.train``'s preemption poll. See ``docs/resilience``.
"""

from libskylark_tpu.resilience import faults, health
from libskylark_tpu.resilience.faults import (FaultPlan, fault_plan,
                                              fired)
from libskylark_tpu.resilience.policy import (TRANSIENT_ERRORS, Deadline,
                                              DeadlineExceededError,
                                              RetryPolicy)
from libskylark_tpu.resilience.preemption import (
    drain_serving, install_preemption_handler, on_preemption,
    preemption_requested, register_checkpoint, reset_preemption,
    uninstall_preemption_handler, wait_for_preemption_teardown)

__all__ = [
    "Deadline", "DeadlineExceededError", "FaultPlan", "RetryPolicy",
    "TRANSIENT_ERRORS", "drain_serving", "fault_plan", "faults", "fired",
    "health",
    "install_preemption_handler", "on_preemption", "preemption_requested",
    "register_checkpoint", "reset_preemption",
    "uninstall_preemption_handler", "wait_for_preemption_teardown",
]
