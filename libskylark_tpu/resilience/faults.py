"""Deterministic, seeded fault injection behind named sites.

Chaos testing is only useful if a failure found once can be replayed
bit-identically. This registry gives the repo's failure paths that
property: production code plants cheap **named injection sites**
(``faults.check("serve.flush", ...)`` — a no-op unless a plan is
active) and a **fault plan** decides, deterministically, which hits of
which sites raise which error class.

Sites planted today:

====================  ====================================================
``serve.flush``       the microbatch flush worker, once per cohort
                      execution attempt (:mod:`libskylark_tpu.engine
                      .serve` — the poison-isolation bisection retries
                      re-enter the site)
``fleet.route``       the fleet router's per-candidate dispatch
                      (:mod:`libskylark_tpu.fleet.router` — a fired
                      fault fails ONE route attempt; the router
                      fails over to the next replica in preference
                      order, which is what the chaos battery's
                      failover leg replays deterministically)
``engine.compile``    the executable-cache cold-compile path
                      (:mod:`libskylark_tpu.engine.compiled`)
``io.webhdfs.open``   the WebHDFS OPEN request (per connection attempt)
``io.webhdfs.read``   the WebHDFS chunk-read loop (per chunk)
``io.chunked.read``   the HDF5 batch-slice reads
``io.chunked.batch``  the libsvm batch parser, once per yielded batch
``checkpoint.save``   :meth:`TrainCheckpointer.save` / ``save_sync``,
                      and the session-state :func:`utility.checkpoint
                      .save_sync` snapshots
``session.append``    the stateful-session append path, once per
                      accepted batch, BEFORE the journal write
                      (:mod:`libskylark_tpu.sessions.registry`) — a
                      fired fault (or ``crash``) kills the append
                      pre-durability, so the client's retry lands
                      exactly once
``dist.shard``        shard-task execution entry
                      (:mod:`libskylark_tpu.dist.plan`) — fires in
                      the process EXECUTING the task, so a ``crash``
                      spec riding a victim replica's env is the
                      deterministic kill -9 mid-storm; an error spec
                      fails one attempt and the coordinator
                      reassigns to the next ring preference
``dist.ingest``       the shard ingest loop, once per source batch —
                      a transient error here exercises the
                      resume-at-consumed-offset path
``dist.merge``        partial-sketch merge entry
                      (:func:`libskylark_tpu.dist.plan.merge_partials`)
``qos.admit``         the QoS admission point, once per submit after
                      tenant resolution (:mod:`libskylark_tpu.engine
                      .serve` — a fired fault refuses one admission
                      without touching the queue, so chaos plans can
                      prove class-ordered shedding stays intact under
                      admission failures)
``train.slice``       training-slice execution entry, once per slice
                      attempt, BEFORE the journaled append
                      (:mod:`libskylark_tpu.train.jobs`) — a ``crash``
                      spec kills the replica with the slice NOT yet
                      durable, so a peer's resume replays exactly the
                      acked prefix and continues bit-equal (the train
                      chaos gate's kill point); an error spec fails
                      one slice and the job's retry budget re-runs it
``net.accept``        the TCP front door's accept path, once per
                      accepted connection (:mod:`libskylark_tpu.net
                      .server` — a fired fault closes the fresh
                      socket before any frame is read; the client's
                      reconnect budget absorbs it)
``net.read``          one frame read on a server connection — a fired
                      fault tears the connection down mid-stream;
                      inflight futures detach and the client's
                      byte-identical re-send coalesces onto the
                      original flight (docs/networking)
``net.write``         one frame write on a server connection — same
                      teardown semantics from the response side
====================  ====================================================

A plan is a JSON document (or the equivalent dict)::

    {"seed": 7,
     "faults": [
       {"site": "serve.flush", "error": "SketchError", "tag": "poison"},
       {"site": "io.webhdfs.read", "error": "IOError_", "on_hit": 3},
       {"site": "serve.flush", "error": "IOError_", "every": 64},
       {"site": "engine.compile", "error": "AllocationError",
        "prob": 0.01, "times": 2}
     ]}

Spec fields (all optional except ``site``): ``error`` (a class name
from :mod:`libskylark_tpu.base.errors`, or a builtin exception name;
default ``IOError_``), ``message``, and the firing rule —

``on_hit``  fire exactly on the Nth matching hit (1-indexed);
``every``   fire on every Nth matching hit;
``prob``    fire with probability p from a per-spec RNG seeded by
            ``(plan seed, site, spec index)`` — same seed, same hit
            sequence ⇒ same decisions, bit-identical replay;
``after``   skip the first N matching hits;
``times``   fire at most N times (default unlimited);
``tag``     fire only when the check's ``tags`` contain this tag —
            the hook that pins a fault to a *request* (a test submits
            under ``with faults.tag("poison"):`` and only cohorts
            containing that request fail, which is exactly what the
            serve bisection needs to converge on the poison).

A spec may carry ``stall_s`` *instead of* ``error``: a fired stall
sleeps that many seconds at the site and then lets the hit proceed —
the straggler injector (a slow replica is a failure mode no exception
models) the fleet hedging chaos leg replays. Stalls appear in
``fired()`` with error name ``"stall"``.

A spec may instead carry ``"crash": true``: a fired crash hard-kills
the process with ``os._exit(137)`` — no exception, no cleanup, no
atexit, the same observable as a ``kill -9``. Mutually exclusive with
``error`` and ``stall_s``. This is how the chaos battery kills a
replica mid-session *deterministically* (the spec rides the victim
child's ``SKYLARK_FAULT_PLAN`` via the pool's ``replica_env`` seat)
instead of shelling out to ``kill``; meaningful only for process
targets — fired in the serving parent it takes the whole host down,
which is what a crash does. Crashes appear in ``fired()`` with error
name ``"crash"`` (visible only to a survivor sharing the plan — the
firing process is gone).

Activation: ``with fault_plan(plan): ...`` (tests), or the
``SKYLARK_FAULT_PLAN`` environment variable holding the JSON itself or
a path to it (chaos CI). A context plan shadows the env plan. Every
fired fault is recorded — ``fired()`` returns the
``(site, hit, error_name)`` sequence, which the chaos gate compares
across runs to prove determinism.
"""

from __future__ import annotations

import builtins
import contextlib
import json
import os
import random
import threading
import time
from typing import Iterable, Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.telemetry import metrics as _metrics

_VALID_KEYS = {"site", "error", "message", "on_hit", "every", "prob",
               "after", "times", "tag", "stall_s", "crash"}

# Unified-registry adapter (docs/observability): fired injections are
# chaos-run events — always counted (a fire raises an exception; the
# counter bump is noise) so the benchmarks snapshot carries them.
_FIRED = _metrics.counter(
    "resilience.faults_fired",
    "Injected faults that fired, by site and error class")


def _resolve_error(name: str) -> type:
    cls = getattr(errors, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls
    cls = getattr(builtins, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls
    raise errors.InvalidParametersError(
        f"fault plan names unknown error class {name!r} (expected a "
        f"libskylark_tpu.base.errors class or a builtin exception)")


class FaultSpec:
    """One compiled plan entry; owns its hit counter and RNG stream."""

    __slots__ = ("site", "error_name", "error_cls", "message", "on_hit",
                 "every", "prob", "after", "times", "tag", "stall_s",
                 "crash", "hits", "fires", "_rng")

    def __init__(self, doc: dict, seed: int, index: int):
        unknown = set(doc) - _VALID_KEYS
        if unknown:
            raise errors.InvalidParametersError(
                f"fault spec has unknown field(s) {sorted(unknown)}")
        if "site" not in doc:
            raise errors.InvalidParametersError(
                f"fault spec missing 'site': {doc!r}")
        modes = [k for k in ("error", "stall_s", "crash") if k in doc]
        if len(modes) > 1:
            raise errors.InvalidParametersError(
                "a fault spec is an error, a stall, OR a crash — "
                f"{modes} together make no sense: {doc!r}")
        self.site = str(doc["site"])
        # a stall spec delays the hit instead of raising: the straggler
        # injector the fleet hedging leg replays (a slow replica is a
        # failure mode no error class models)
        self.stall_s = (float(doc["stall_s"]) if "stall_s" in doc
                        else None)
        if self.stall_s is not None and self.stall_s < 0:
            raise errors.InvalidParametersError(
                f"fault spec stall_s must be >= 0, got {self.stall_s}")
        # a crash spec hard-kills the process at the site (module doc):
        # the deterministic kill -9 for process-replica chaos targets
        self.crash = bool(doc.get("crash", False))
        if self.stall_s is not None:
            self.error_name = "stall"
        elif self.crash:
            self.error_name = "crash"
        else:
            self.error_name = str(doc.get("error", "IOError_"))
        self.error_cls = (None if self.stall_s is not None or self.crash
                          else _resolve_error(self.error_name))
        self.message = doc.get("message")
        self.on_hit = int(doc["on_hit"]) if "on_hit" in doc else None
        self.every = int(doc["every"]) if "every" in doc else None
        self.prob = float(doc["prob"]) if "prob" in doc else None
        self.after = int(doc.get("after", 0))
        self.times = int(doc["times"]) if "times" in doc else None
        self.tag = doc.get("tag")
        self.hits = 0
        self.fires = 0
        # per-spec stream: decisions depend only on (plan seed, site,
        # spec position, matching-hit index) — replay is bit-identical
        self._rng = random.Random(f"{seed}:{self.site}:{index}")

    def decide(self, tags: frozenset) -> bool:
        """Whether this check fires the spec. Caller holds the plan
        lock; counters and the RNG advance only on *matching* hits so
        tag-filtered specs replay independently of other traffic."""
        if self.tag is not None and self.tag not in tags:
            return False
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.times is not None and self.fires >= self.times:
            return False
        if self.on_hit is not None and self.hits != self.on_hit:
            return False
        if self.every is not None and self.hits % self.every != 0:
            return False
        if self.prob is not None and self._rng.random() >= self.prob:
            return False
        self.fires += 1
        return True


class FaultPlan:
    """A compiled, activatable plan: specs + the fired-fault log."""

    def __init__(self, doc: dict):
        if not isinstance(doc, dict):
            raise errors.InvalidParametersError(
                f"fault plan must be a JSON object, got {type(doc).__name__}")
        self.seed = int(doc.get("seed", 0))
        self.specs = [FaultSpec(d, self.seed, i)
                      for i, d in enumerate(doc.get("faults", []))]
        self._sites = {s.site for s in self.specs}
        self._lock = _locks.make_lock("resilience.fault_plan")
        self.fired: list[tuple] = []      # (site, matching-hit, error name)

    @classmethod
    def parse(cls, text_or_path: str) -> "FaultPlan":
        """JSON text, or a path to a JSON file (the env-var forms)."""
        text = text_or_path.strip()
        if not text.startswith("{") and os.path.exists(text_or_path):
            with open(text_or_path) as fh:
                text = fh.read()
        try:
            return cls(json.loads(text))
        except json.JSONDecodeError as e:
            raise errors.InvalidParametersError(
                f"SKYLARK_FAULT_PLAN is neither valid JSON nor a "
                f"readable path: {e}") from e

    def check(self, site: str, tags: frozenset, detail: str) -> None:
        if site not in self._sites:
            return
        hit_spec = None
        with self._lock:
            for spec in self.specs:
                if spec.site != site:
                    continue
                if spec.decide(tags):
                    self.fired.append((site, spec.hits, spec.error_name))
                    _FIRED.inc_always(site=site, error=spec.error_name)
                    hit_spec, hit_n = spec, spec.hits
                    break
        if hit_spec is None:
            return
        if hit_spec.crash:
            # the deterministic kill -9: no exception, no cleanup, no
            # atexit — exactly what a preempted-without-grace replica
            # looks like from the outside. 137 = 128 + SIGKILL, the
            # code a supervisor would report for the real thing.
            os._exit(137)
            return  # pragma: no cover — only a test-stubbed _exit returns
        if hit_spec.stall_s is not None:
            # stall OUTSIDE the plan lock: a sleeping site must not
            # serialize every other site's checks behind it
            time.sleep(hit_spec.stall_s)
            return
        err = hit_spec.error_cls(
            hit_spec.message
            or f"injected fault at {site} (hit {hit_n})")
        if isinstance(err, errors.SkylarkError):
            err.append_trace(
                f"fault-injected: site={site} hit={hit_n}"
                + (f" detail={detail}" if detail else ""))
        raise err

    def reset(self) -> None:
        """Zero every counter, RNG stream, and the fired log — the next
        run under this plan replays from the beginning."""
        with self._lock:
            self.fired.clear()
            for i, spec in enumerate(self.specs):
                spec.hits = spec.fires = 0
                spec._rng = random.Random(f"{self.seed}:{spec.site}:{i}")


# ---------------------------------------------------------------------------
# activation: context-manager stack shadowing the env plan
# ---------------------------------------------------------------------------

_STACK: list[FaultPlan] = []
_STACK_LOCK = _locks.make_lock("resilience.fault_stack")
_ENV_RAW: Optional[str] = None
_ENV_PLAN: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    """The plan checks consult: the innermost context plan, else the
    ``SKYLARK_FAULT_PLAN`` env plan (parsed once per distinct value),
    else ``None`` (every site a no-op)."""
    if _STACK:
        return _STACK[-1]
    env = _env.FAULT_PLAN.raw()
    if not env:
        return None
    global _ENV_RAW, _ENV_PLAN
    if env != _ENV_RAW:
        # parse-and-cache under the lock: two threads racing the first
        # check must end up counting hits on ONE plan instance, or the
        # bit-identical-replay guarantee (and on_hit accounting) breaks
        with _STACK_LOCK:
            if env != _ENV_RAW:
                _ENV_PLAN = FaultPlan.parse(env)
                _ENV_RAW = env
    return _ENV_PLAN


@contextlib.contextmanager
def fault_plan(plan):
    """Activate ``plan`` (a dict, JSON string, or :class:`FaultPlan`)
    for the dynamic extent of the block. Nests; the innermost wins."""
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    elif isinstance(plan, dict):
        plan = FaultPlan(plan)
    elif not isinstance(plan, FaultPlan):
        raise errors.InvalidParametersError(
            f"fault_plan takes a dict / JSON string / FaultPlan, got "
            f"{type(plan).__name__}")
    with _STACK_LOCK:
        _STACK.append(plan)
    try:
        yield plan
    finally:
        with _STACK_LOCK:
            _STACK.remove(plan)


def check(site: str, tags: Iterable[str] = (), detail: str = "") -> None:
    """The injection-site entry point. Near-zero cost when no plan is
    active (one attr read + one env lookup); under a plan, consults the
    site's specs and raises the chosen error class when one fires."""
    plan = active_plan()
    if plan is None:
        return
    plan.check(site, frozenset(tags) | current_tags(), detail)


def fired() -> list[tuple]:
    """The active plan's fired-fault log ``[(site, hit, error), ...]``
    — the determinism witness the chaos gate compares across runs."""
    plan = active_plan()
    return list(plan.fired) if plan is not None else []


def reset() -> None:
    """Reset the active plan's counters/log (chaos replay runs)."""
    plan = active_plan()
    if plan is not None:
        plan.reset()


# ---------------------------------------------------------------------------
# request tagging: pin a fault to a request, not a call count
# ---------------------------------------------------------------------------

_TAGS = threading.local()


def current_tags() -> frozenset:
    """The calling thread's active fault tags (see :func:`tag`)."""
    return getattr(_TAGS, "tags", frozenset())


@contextlib.contextmanager
def tag(*names: str):
    """Tag everything submitted/executed in this block. The serve layer
    captures the submitting thread's tags onto each request and replays
    their union at every flush attempt — a spec with ``"tag":
    "poison"`` then fires exactly when the tagged request is in the
    executing cohort, which is what lets bisection converge on it."""
    prev = current_tags()
    _TAGS.tags = prev | frozenset(names)
    try:
        yield
    finally:
        _TAGS.tags = prev


def _telemetry_block() -> dict:
    """Snapshot adapter: the active plan's determinism-witness state
    (the process-lifetime fire counts live in the
    ``resilience.faults_fired`` counter)."""
    plan = active_plan()
    return {"active_plan": plan is not None,
            "fired_this_plan": len(plan.fired) if plan is not None else 0}


_metrics.register_collector("resilience.faults", _telemetry_block)


__all__ = [
    "FaultPlan", "FaultSpec", "active_plan", "check", "current_tags",
    "fault_plan", "fired", "reset", "tag",
]
