"""Health-state change hub: executors publish, routers subscribe.

The r9 health states (``SERVING`` / ``DEGRADED`` / ``DRAINING`` /
``STOPPED``) were, until the fleet round, pull-only: anyone who cared
polled ``MicrobatchExecutor.state``. A router spreading traffic over N
replicas cannot poll — by the time a poll sees DRAINING, requests have
already been queued behind a drain. This hub makes the states *push*:
the serve layer publishes every transition the moment it happens
(:meth:`~libskylark_tpu.engine.serve.MicrobatchExecutor` calls
:func:`publish` from the flush worker on DEGRADED flips, from
``drain()`` on DRAINING, from ``shutdown()`` on STOPPED), and the
fleet router (:mod:`libskylark_tpu.fleet.router`) subscribes to drop a
draining replica from its ring before the next route decision.

The hub is deliberately dumb: a process-global list of callbacks, no
filtering, no history. ``source`` is whatever object transitioned — a
:class:`~libskylark_tpu.engine.serve.MicrobatchExecutor` for in-process
replicas, a :class:`~libskylark_tpu.fleet.replica.ProcessReplica` for
process-backed ones — and subscribers resolve it to their own identity
space (the router asks its pool). Callback failures are warned, never
raised: a broken subscriber must not stop the drain that is publishing
to it. Transitions are also counted on the always-on
``resilience.health_transitions`` telemetry counter so chaos/bench
records carry the state history for free.
"""

from __future__ import annotations

import warnings
from typing import Callable

from libskylark_tpu.base import locks as _locks
from libskylark_tpu.telemetry import metrics as _metrics

_LOCK = _locks.make_lock("resilience.health")
_SUBSCRIBERS: "list[Callable[[object, str, str], None]]" = []
_SEQ = 0        # monotonic transition sequence (see transition_seq)

# always-on (the transition itself — a drain, a DEGRADED flip — dwarfs
# the counter bump), so benchmarks records carry the state history
_TRANSITIONS = _metrics.counter(
    "resilience.health_transitions",
    "Executor health-state transitions, by old and new state")


def subscribe(fn: Callable[[object, str, str], None]
              ) -> Callable[[], None]:
    """Register ``fn(source, old_state, new_state)`` to run on every
    published health transition in the process. Returns the
    unregister callable. The callback runs on whatever thread
    published (a flush worker, a drain caller, a SIGTERM teardown
    thread) — it must be cheap and must not call back into the
    publishing executor's submit/drain paths."""
    with _LOCK:
        _SUBSCRIBERS.append(fn)

    def unsubscribe() -> None:
        with _LOCK:
            try:
                _SUBSCRIBERS.remove(fn)
            except ValueError:
                pass

    return unsubscribe


def transition_seq() -> int:
    """Monotonic count of transitions published in this process — the
    hub-level **session-affinity epoch** anchor: any view derived from
    hub events (a router's ring membership, its session assignments)
    stamped with this value is provably stale once the value moves.
    The fleet router stamps each membership-epoch bump with this value
    (``Router.stats()["session_epoch_hub_seq"]``), so tests and
    forensics can order cross-object views against the hub's
    timeline."""
    with _LOCK:
        return _SEQ


def publish(source: object, old: str, new: str) -> None:
    """Fan one transition out to every subscriber (the serve layer's
    hook; see :meth:`MicrobatchExecutor._maybe_publish_state`).
    Subscriber failures are contained — publishing happens on drain
    and teardown paths that must complete regardless."""
    global _SEQ
    _TRANSITIONS.inc_always(old=old, new=new)
    with _LOCK:
        _SEQ += 1
        subs = list(_SUBSCRIBERS)
    for fn in subs:
        try:
            fn(source, old, new)
        except Exception as e:  # noqa: BLE001 — never rob the drain
            warnings.warn(
                f"health-state subscriber {fn!r} failed on "
                f"{old}->{new}: {e}", RuntimeWarning, stacklevel=2)


__all__ = ["publish", "subscribe", "transition_seq"]
