"""Retry/backoff policies and deadline budgets.

The reference library has no failure handling at all (its aux-subsystem
survey row "failure detection / checkpoint-resume" is empty — SURVEY.md
§5): a transient HDFS hiccup or a flaky coordinator kills the whole
run. This module is the policy half of the resilience subsystem — the
mechanism half (deterministic fault injection) lives in
:mod:`libskylark_tpu.resilience.faults`.

Two primitives:

:class:`Deadline`
    A monotonic wall-clock budget that threads *through* call stacks: a
    caller creates ``Deadline.after(30)`` once and every layer below
    derives its per-attempt timeouts from ``remaining()`` instead of
    stacking independent (and therefore additive) timeouts.

:class:`RetryPolicy`
    Composable retry with exponential backoff and decorrelated jitter
    (the AWS-architecture-blog discipline: each delay is drawn from
    ``uniform(base, prev * multiplier)``, capped — uncorrelated retry
    storms instead of thundering herds), per-attempt timeouts, a total
    deadline budget, and an error-class predicate over the
    :mod:`libskylark_tpu.base.errors` taxonomy. A ``seed`` makes the
    jitter sequence deterministic, so chaos tests replay bit-identically
    (:mod:`libskylark_tpu.resilience.faults`).

Neither primitive imports jax — policies are wired into host-side
control flow (I/O transports, the serve flush worker, checkpoint
saves), never into traced code.
"""

from __future__ import annotations

import dataclasses
import math
import random
import time
from typing import Callable, Iterator, Optional, Sequence, Union

from libskylark_tpu.base import errors
from libskylark_tpu.telemetry import metrics as _metrics
from libskylark_tpu.telemetry import trace as _trace

# Unified-registry adapter (docs/observability): every retry attempt
# under any policy bumps this counter — always, not gated on the
# telemetry switch, because a retry already paid for a failure + a
# backoff sleep and the benchmarks snapshot wants resilience counters
# even in disabled-mode runs.
_RETRIES = _metrics.counter(
    "resilience.retries",
    "Retry attempts under RetryPolicy, by error class")


class DeadlineExceededError(errors.SkylarkError, TimeoutError):
    """A total deadline budget was exhausted before the work completed."""


class Deadline:
    """A monotonic point in time a unit of work must finish by.

    ``Deadline.after(30)`` starts a 30-second budget; ``remaining()``
    is what's left (``inf`` for the unbounded deadline), ``expired``
    whether it ran out, and ``check()`` raises
    :class:`DeadlineExceededError` so deep call sites can bail without
    plumbing a boolean back up. A ``Deadline`` is intended to be
    created once at the top of a request and passed *down* — every
    layer below derives attempt timeouts from one shared budget.
    """

    __slots__ = ("_t",)

    def __init__(self, seconds: Optional[float] = None):
        self._t = None if seconds is None else time.monotonic() + float(seconds)

    @classmethod
    def after(cls, seconds: Optional[float]) -> "Deadline":
        return cls(seconds)

    @classmethod
    def coerce(cls, obj: Union[None, int, float, "Deadline"]
               ) -> Optional["Deadline"]:
        """``None`` → ``None``; a number → ``Deadline.after(number)``;
        a ``Deadline`` passes through (the submit-API convenience)."""
        if obj is None or isinstance(obj, Deadline):
            return obj
        return cls(float(obj))

    def remaining(self) -> float:
        if self._t is None:
            return math.inf
        return self._t - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "") -> None:
        if self.expired:
            raise DeadlineExceededError(
                f"deadline exceeded{': ' + what if what else ''}")

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        r = self.remaining()
        return f"Deadline(remaining={'inf' if r == math.inf else round(r, 3)})"


#: Error classes a policy retries by default: the taxonomy's transport
#: and resource failures plus their stdlib counterparts. Logic errors
#: (InvalidParametersError, UnsupportedError, ...) never retry — they
#: would fail identically forever.
TRANSIENT_ERRORS = (
    errors.IOError_,
    errors.CommunicationError,
    errors.AllocationError,
    ConnectionError,
    TimeoutError,
    OSError,
)


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff with decorrelated jitter over an error-class
    predicate.

    ``retry_on`` is either a tuple of exception classes or a predicate
    ``exc -> bool``. ``seed`` pins the jitter stream (deterministic
    replay); ``sleep`` is injectable so tests run without waiting.
    ``attempt_timeout``/``timeout_arg`` wire per-attempt timeouts into
    callables that accept one (e.g. ``urlopen(timeout=...)``): each
    attempt gets ``min(attempt_timeout, deadline.remaining())``.
    """

    max_attempts: int = 4
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 3.0
    jitter: str = "decorrelated"          # "decorrelated" | "full" | "none"
    retry_on: Union[Sequence[type], Callable] = TRANSIENT_ERRORS
    seed: Optional[int] = None
    attempt_timeout: Optional[float] = None
    timeout_arg: Optional[str] = None
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise errors.InvalidParametersError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.jitter not in ("decorrelated", "full", "none"):
            raise errors.InvalidParametersError(
                f"jitter must be decorrelated|full|none, got {self.jitter!r}")

    # -- predicate --

    def retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, DeadlineExceededError):
            # budget exhaustion means STOP: it inherits TimeoutError
            # (an OSError) so every transient predicate would otherwise
            # match it and retry precisely when the deadline said not to
            return False
        if callable(self.retry_on):
            return bool(self.retry_on(exc))
        return isinstance(exc, tuple(self.retry_on))

    # -- backoff schedule --

    def delays(self) -> Iterator[float]:
        """The (possibly seeded, hence replayable) backoff sequence."""
        rng = random.Random(self.seed)
        prev = self.base_delay
        k = 0
        while True:
            if self.jitter == "none":
                d = min(self.max_delay, self.base_delay * self.multiplier ** k)
            elif self.jitter == "full":
                cap = min(self.max_delay,
                          self.base_delay * self.multiplier ** k)
                d = rng.uniform(0.0, cap)
            else:  # decorrelated
                d = min(self.max_delay,
                        rng.uniform(self.base_delay, prev * self.multiplier))
                prev = d
            k += 1
            yield d

    # -- execution --

    def call(self, fn: Callable, *args,
             deadline: Union[None, float, Deadline] = None,
             on_retry: Optional[Callable] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under the policy.

        Retryable failures back off and re-attempt up to
        ``max_attempts`` within the ``deadline`` budget; the final
        failure re-raises with the attempt count appended to its trace
        (when it's a :class:`~libskylark_tpu.base.errors.SkylarkError`).
        ``on_retry(attempt, exc, delay)`` observes each retry (logging,
        counters). Non-retryable errors propagate immediately.
        """
        deadline = Deadline.coerce(deadline)
        delays = self.delays()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            if deadline is not None and deadline.expired:
                raise DeadlineExceededError(
                    f"retry budget exhausted after {attempt - 1} "
                    f"attempt(s)") from last
            kw = kwargs
            if self.timeout_arg:
                t = self.attempt_timeout
                if deadline is not None:
                    rem = max(deadline.remaining(), 0.001)
                    t = rem if t is None else min(t, rem)
                if t is not None:
                    kw = dict(kwargs)
                    kw[self.timeout_arg] = t
            try:
                return fn(*args, **kw)
            except BaseException as e:  # noqa: BLE001 — predicate decides
                if not self.retryable(e) or attempt == self.max_attempts:
                    if isinstance(e, errors.SkylarkError):
                        e.append_trace(
                            f"RetryPolicy: attempt {attempt}/"
                            f"{self.max_attempts}")
                    raise
                last = e
                d = next(delays)
                if deadline is not None:
                    d = min(d, max(deadline.remaining(), 0.0))
                _RETRIES.inc_always(error=type(e).__name__)
                # the retry-attempt event lands on whatever span is
                # executing (a webhdfs open inside an io span, a save
                # inside a checkpoint span) and carries that span's id
                # explicitly, so a JSONL consumer can correlate retries
                # without re-walking the tree
                cur = _trace.current_span()
                if cur is not None:
                    cur.add_event("resilience.retry", {
                        "attempt": attempt,
                        "error": type(e).__name__,
                        "delay_s": round(d, 4),
                        "span_id": cur.span_id,
                    })
                if on_retry is not None:
                    on_retry(attempt, e, d)
                if d > 0:
                    self.sleep(d)
        raise AssertionError("unreachable")  # pragma: no cover

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form: ``@policy`` wraps ``fn`` in :meth:`call`."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return wrapped
