"""Preemption-safe shutdown: SIGTERM → serve drain + final checkpoint.

Preemptible TPU capacity is the ROADMAP's operating point, and the
scheduler's eviction protocol is a SIGTERM followed (tens of seconds
later) by SIGKILL. This module turns that SIGTERM into an orderly
teardown instead of a mid-flight loss:

1. every live :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor`
   is **drained** — intake stops (new submits are load-shed with
   :class:`~libskylark_tpu.engine.serve.ServeOverloadedError`), every
   queued cohort flushes, every in-flight future resolves — and the
   drain itself **checkpoints every live stateful session** (journal
   fsync + accumulator snapshot under ``SKYLARK_SESSION_DIR``), so a
   peer replica resumes the streams a preempted replica was holding
   open (docs/sessions, "Graceful handoff");
2. every **registered checkpoint hook** runs a final *synchronous*
   :meth:`~libskylark_tpu.utility.checkpoint.TrainCheckpointer
   .save_sync` — durable on disk before the teardown completes
   (:func:`wait_for_preemption_teardown` joins it), so the follow-up
   SIGKILL loses nothing;
3. the **preemption flag** stays set: host-side training loops poll
   :func:`preemption_requested` and cut their own final checkpoint at
   the next iteration boundary (``BlockADMMSolver.train`` does).

The handler deliberately does **not** exit the process — whether to
``sys.exit`` after draining is the host's decision (a serving binary
may want to linger for connection draining; a training job usually
just falls off the end of its loop). A previously-installed Python
handler for the same signal is chained after ours.

Usage (see ``examples/preemptible_training.py`` for the live demo)::

    from libskylark_tpu import resilience

    resilience.install_preemption_handler()
    unregister = resilience.register_checkpoint(
        ckpt, lambda: (step, state, {"reason": "preempted"}))
    ...
    # on SIGTERM: executors drain, ckpt.save_sync runs, flag sets
"""

from __future__ import annotations

import signal
import threading
import warnings
from typing import Callable, Optional, Sequence

from libskylark_tpu.base import locks as _locks

_LOCK = _locks.make_lock("resilience.preemption")
_EVENT = threading.Event()
_PREV: dict[int, object] = {}          # signum -> previous handler
_HOOKS: list[Callable[[], None]] = []
_DRAIN_TIMEOUT = 30.0
_DRAIN_SERVING = True
_HANDLING = threading.Event()          # re-entrancy guard
_TEARDOWN: Optional[threading.Thread] = None


def preemption_requested() -> bool:
    """Whether a preemption signal has been received (sticky until
    :func:`reset_preemption`). Host training loops poll this at
    iteration boundaries."""
    return _EVENT.is_set()


def reset_preemption() -> None:
    """Clear the preemption flag (tests; a host that survived a
    spurious SIGTERM)."""
    _EVENT.clear()


def on_preemption(callback: Callable[[], None]) -> Callable[[], None]:
    """Register an arbitrary hook to run during preemption handling
    (after serve drain, in registration order). Returns an unregister
    callable. Hook failures are warned, never raised — one broken hook
    must not rob the others of their drain window."""
    with _LOCK:
        _HOOKS.append(callback)

    def unregister() -> None:
        with _LOCK:
            try:
                _HOOKS.remove(callback)
            except ValueError:
                pass

    return unregister


def register_checkpoint(checkpointer, state_fn: Callable[[], tuple]
                        ) -> Callable[[], None]:
    """Register a final-save hook for a host-loop solver:
    ``state_fn()`` returns ``(step, state, metadata)`` and the hook
    runs ``checkpointer.save_sync(step, state, metadata)`` — blocking
    until the write is durable. Returns the unregister callable."""

    def hook() -> None:
        step, state, metadata = state_fn()
        meta = dict(metadata or {})
        meta.setdefault("preempted", True)
        checkpointer.save_sync(int(step), state, meta)

    return on_preemption(hook)


def drain_serving(timeout: Optional[float] = None) -> int:
    """Drain every live microbatch executor in the process; returns how
    many were drained. Safe with zero executors (the import is lazy so
    a pure-solver process never touches the serve layer)."""
    try:
        from libskylark_tpu.engine import serve as _serve
    except Exception:  # pragma: no cover - engine always importable
        return 0
    n = 0
    for ex in list(_serve._EXECUTORS):
        try:
            ex.drain(timeout=timeout if timeout is not None
                     else _DRAIN_TIMEOUT)
            n += 1
        except Exception as e:  # noqa: BLE001 — drain the rest regardless
            warnings.warn(f"preemption drain of {ex!r} failed: {e}",
                          RuntimeWarning, stacklevel=2)
    return n


def _run_handler() -> None:
    if _HANDLING.is_set():      # second SIGTERM while already handling
        return
    _HANDLING.set()
    try:
        if _DRAIN_SERVING:
            drain_serving()
        with _LOCK:
            hooks = list(_HOOKS)
        for hook in hooks:
            try:
                hook()
            except Exception as e:  # noqa: BLE001
                warnings.warn(f"preemption hook {hook!r} failed: {e}",
                              RuntimeWarning, stacklevel=2)
    finally:
        _HANDLING.clear()


def _handle(signum, frame) -> None:
    global _TEARDOWN
    _EVENT.set()
    # The teardown must NOT run on the interrupted thread: CPython
    # delivers signals between bytecodes of whatever frame the main
    # thread is in — which may be inside the serve layer holding the
    # very (non-reentrant) executor lock drain() needs. A synchronous
    # drain here would deadlock until SIGKILL, losing exactly the data
    # the handler exists to save. The dedicated thread blocks only
    # until the main thread releases that lock (microseconds after
    # this handler returns); hosts and tests join via
    # :func:`wait_for_preemption_teardown`.
    #
    # Deliberately LOCK-FREE: taking _LOCK here would recreate the
    # held-lock deadlock one level up (the signal may interrupt a frame
    # inside on_preemption/register_checkpoint holding _LOCK). Safe
    # without it: Python signal handlers run only on the main thread
    # and are never re-entered mid-handler, so this is the sole writer
    # of _TEARDOWN.
    if _TEARDOWN is None or not _TEARDOWN.is_alive():
        t = threading.Thread(
            target=_run_handler,
            name="skylark-preemption-teardown", daemon=True)
        _TEARDOWN = t
        t.start()
    prev = _PREV.get(signum)
    if callable(prev):
        prev(signum, frame)


def wait_for_preemption_teardown(timeout: Optional[float] = None) -> bool:
    """Block until the preemption teardown (drain + checkpoint hooks)
    finishes; returns whether it did within ``timeout``. True
    trivially when no preemption has been handled. A preempted host's
    main loop typically calls this before exiting so the final save is
    durable before the process goes away."""
    t = _TEARDOWN          # lock-free read: assignment is atomic (GIL)
    if t is None:
        return True
    t.join(timeout)
    return not t.is_alive()


def install_preemption_handler(
    signals: Sequence[int] = (signal.SIGTERM,),
    drain_timeout: float = 30.0,
    drain_serving_executors: bool = True,
) -> None:
    """Install the preemption handler on ``signals`` (default SIGTERM —
    the TPU/GCE eviction protocol; add ``signal.SIGINT`` for notebook
    runs). Idempotent per signal; only callable from the main thread
    (a CPython ``signal.signal`` constraint). A previously-installed
    Python handler is chained after ours."""
    global _DRAIN_TIMEOUT, _DRAIN_SERVING
    _DRAIN_TIMEOUT = float(drain_timeout)
    _DRAIN_SERVING = bool(drain_serving_executors)
    with _LOCK:
        for signum in signals:
            if signum in _PREV:
                continue
            prev = signal.signal(signum, _handle)
            _PREV[signum] = prev


def uninstall_preemption_handler() -> None:
    """Restore the previous handlers and clear the flag (tests)."""
    with _LOCK:
        for signum, prev in list(_PREV.items()):
            try:
                signal.signal(
                    signum,
                    prev if prev is not None else signal.SIG_DFL)
            except (ValueError, TypeError):  # pragma: no cover
                pass
            del _PREV[signum]
    _EVENT.clear()


__all__ = [
    "drain_serving", "install_preemption_handler", "on_preemption",
    "preemption_requested", "register_checkpoint", "reset_preemption",
    "uninstall_preemption_handler", "wait_for_preemption_teardown",
]
