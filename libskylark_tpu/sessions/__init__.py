"""Survivable stateful serve sessions (docs/sessions).

The serve layer's endpoints are stateless one-shots; this subsystem
adds bucket-lived *sessions* that hold a maintained sketch open across
requests — row-batch appenders for CountSketch/JLT/SRHT, incremental
randomized SVD, and online KRR — with the resilience wiring that keeps
a session alive when its replica is drained (checkpoint + peer resume)
or killed outright (journal replay, idempotent sequence numbers).

Layering:

- :mod:`~libskylark_tpu.sessions.state` — the per-kind maintained
  sketch and its fold/finalize math (linearity is the whole trick);
- :mod:`~libskylark_tpu.sessions.journal` — the append-only durability
  log under ``SKYLARK_SESSION_DIR``;
- :mod:`~libskylark_tpu.sessions.registry` — open/append/finalize,
  TTL eviction, checkpointing, resume-with-replay;
- the serve layer (:class:`~libskylark_tpu.engine.serve
  .MicrobatchExecutor` session endpoints) and the fleet router
  (session affinity + handoff) wire it into traffic.
"""

from libskylark_tpu.sessions.journal import SessionJournal
from libskylark_tpu.sessions.registry import (SessionRegistry,
                                              default_session_dir,
                                              sessions_stats)
from libskylark_tpu.sessions.state import KINDS, SessionSpec, SessionState

__all__ = [
    "KINDS",
    "SessionJournal",
    "SessionRegistry",
    "SessionSpec",
    "SessionState",
    "default_session_dir",
    "sessions_stats",
]
