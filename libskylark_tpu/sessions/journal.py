"""Append-only session journal: every accepted append, durable before
its future resolves.

One file per session under the registry directory. Record layout::

    [u32 payload length][u32 crc32 of payload][payload bytes]

after an 8-byte file magic (``SKYJRNL2``). The payload is a small
JSON header (``{"seq", "keys"}``) followed by one raw ``.npy`` body
per batch key — **nothing in a record is executable**. The journal
lives under a shared, sometimes world-visible directory, so a planted
file must never be able to run code in the serving process; ``.npy``
bodies are read with ``allow_pickle=False`` and reproduce the exact
bytes of the batch the client sent, so replaying a record re-folds
exactly that batch.

Durability discipline (docs/sessions, "Journal format"):

- every append is one unbuffered ``write(2)`` straight to the OS page
  cache before returning — a ``kill -9``'d replica loses nothing
  already accepted (the OS holds the bytes; only a whole-machine crash
  can drop them);
- every ``SKYLARK_SESSION_FSYNC_EVERY``-th append (default 8) also
  **fsyncs**, bounding what a machine crash can lose; drain/checkpoint
  paths call :meth:`sync` to force the bound to zero.

Torn tails are expected, not fatal: a crash mid-write leaves a partial
final record. :func:`scan` validates length + CRC record by record and
stops at the first damage; :meth:`SessionJournal.open_for_append`
truncates the file back to the intact prefix, so a resumed session
replays exactly the accepted appends and the retried tail append lands
cleanly after them (idempotent sequence numbers make the overlap a
no-op either way).

A torn record must never be left MID-file either: a failed or short
append write (ENOSPC, a transient I/O error) rolls the file back to
the pre-write offset before the error surfaces, so the intact prefix
always covers every acknowledged record. If even the rollback fails,
the journal is **poisoned** — further appends refuse with the original
cause — because appending past damage would make ``scan`` silently
drop every later (acknowledged!) record at replay time.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Iterator, Optional, Tuple

import numpy as np

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors

MAGIC = b"SKYJRNL2"
_HDR = struct.Struct("<II")
_PHDR = struct.Struct("<I")


def _encode_record(seq: int, batch: dict) -> bytes:
    """JSON header + raw ``.npy`` array bodies (module doc: the
    payload carries data only, never executable state)."""
    keys = sorted(batch)
    head = json.dumps({"seq": int(seq), "keys": keys}).encode("utf-8")
    buf = io.BytesIO()
    buf.write(_PHDR.pack(len(head)))
    buf.write(head)
    for k in keys:
        np.lib.format.write_array(buf, np.asarray(batch[k]),
                                  allow_pickle=False)
    return buf.getvalue()


def _decode_record(payload: bytes) -> Tuple[int, dict]:
    buf = io.BytesIO(payload)
    (hlen,) = _PHDR.unpack(buf.read(_PHDR.size))
    head = json.loads(buf.read(hlen).decode("utf-8"))
    batch = {str(k): np.lib.format.read_array(buf, allow_pickle=False)
             for k in head["keys"]}
    return int(head["seq"]), batch


def scan(path: str) -> Tuple[list, int]:
    """``([(seq, batch_dict), ...], good_offset)`` — every intact
    record in order, plus the byte offset of the intact prefix (the
    truncation point for recovery). A missing file scans as empty; a
    bad magic raises (that is not a torn tail, it is not a journal)."""
    if not os.path.exists(path):
        return [], len(MAGIC)
    records: list = []
    with open(path, "rb") as fh:
        magic = fh.read(len(MAGIC))
        if magic != MAGIC:
            if magic == b"SKYJRNL1":
                raise errors.IOError_(
                    f"{path} is a version-1 session journal (pickle "
                    "payloads) — this build reads only "
                    f"{MAGIC.decode()}; v1 never shipped, delete the "
                    "artifacts and re-open the session")
            raise errors.IOError_(
                f"{path} is not a session journal (bad magic)")
        good = fh.tell()
        while True:
            hdr = fh.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            length, crc = _HDR.unpack(hdr)
            payload = fh.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break                      # torn tail: stop at damage
            try:
                seq, batch = _decode_record(payload)
            except Exception:              # noqa: BLE001 — torn payload
                break
            records.append((seq, batch))
            good = fh.tell()
    return records, good


class SessionJournal:
    """Writer half: append-only with batched fsync (module doc).
    The file is opened unbuffered, so every record is a single
    ``write(2)`` and nothing ever sits in a userspace buffer that an
    :meth:`abandon` (the fenced-owner path) could accidentally flush
    into a file this process no longer owns."""

    def __init__(self, path: str, fsync_every: Optional[int] = None):
        self.path = path
        self._fsync_every = max(int(
            fsync_every if fsync_every is not None
            else _env.SESSION_FSYNC_EVERY.get()), 1)
        self._since_sync = 0
        self._fh = None
        self._failed: Optional[str] = None

    @classmethod
    def create(cls, path: str,
               fsync_every: Optional[int] = None) -> "SessionJournal":
        j = cls(path, fsync_every)
        fh = open(path, "xb", buffering=0)
        fh.write(MAGIC)
        os.fsync(fh.fileno())
        j._fh = fh
        return j

    @classmethod
    def open_for_append(cls, path: str,
                        fsync_every: Optional[int] = None,
                        ) -> Tuple["SessionJournal", list]:
        """Recovery open: scan the intact prefix, truncate any torn
        tail, position for append. Returns ``(journal, records)``."""
        records, good = scan(path)
        j = cls(path, fsync_every)
        if not os.path.exists(path):
            return cls.create(path, fsync_every), records
        fh = open(path, "r+b", buffering=0)
        fh.truncate(good)
        fh.seek(good)
        j._fh = fh
        return j, records

    def append(self, seq: int, batch: dict) -> None:
        """Make one append durable (see the module durability
        discipline). The caller folds only after this returns."""
        if self._failed is not None:
            raise errors.IOError_(
                f"session journal {self.path} refused the append: a "
                f"previous write failed unrecoverably "
                f"({self._failed}); the intact prefix still covers "
                "every acknowledged record — resume elsewhere")
        payload = _encode_record(seq, batch)
        rec = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        start = self._fh.tell()
        short = False
        try:
            n = self._fh.write(rec)
            short = n is not None and n != len(rec)
        except OSError as e:
            self._rollback(start, e)
            raise
        if short:
            e = errors.IOError_(
                f"short write appending to {self.path} "
                "(disk full?)")
            self._rollback(start, e)
            raise e
        self._since_sync += 1
        if self._since_sync >= self._fsync_every:
            try:
                os.fsync(self._fh.fileno())
            except OSError as e:
                # post-failure fsync semantics are undefined (the
                # kernel may drop the dirty pages and clear the
                # error): the machine-crash durability bound cannot
                # be promised any more, so poison the journal
                self._failed = f"fsync failed: {e}"
                raise
            self._since_sync = 0

    def _rollback(self, offset: int, cause: BaseException) -> None:
        """Truncate a torn record back off the tail so the file ends
        at the intact prefix; poison the journal if that fails too."""
        try:
            self._fh.truncate(offset)
            self._fh.seek(offset)
        except OSError:
            self._failed = f"rollback after failed write failed: {cause}"

    def sync(self) -> None:
        """Force the fsync bound to zero (drain/checkpoint paths)."""
        if self._fh is not None and not self._fh.closed:
            try:
                os.fsync(self._fh.fileno())
            except OSError as e:
                self._failed = f"fsync failed: {e}"
                raise
            self._since_sync = 0

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            try:
                self.sync()
            finally:
                self._fh.close()

    def abandon(self) -> None:
        """Close WITHOUT syncing — the fenced-owner path: another
        replica owns this file now and this process must not touch
        another byte of it (appends are unbuffered, so nothing is
        lost)."""
        if self._fh is not None and not self._fh.closed:
            self._fh.close()


def replay(path: str) -> Iterator[Tuple[int, dict]]:
    """Read-only iteration over the intact records (peers inspecting a
    journal they do not own)."""
    records, _ = scan(path)
    return iter(records)


__all__ = ["SessionJournal", "replay", "scan"]
