"""Append-only session journal: every accepted append, durable before
its future resolves.

One file per session under the registry directory. Record layout::

    [u32 payload length][u32 crc32 of payload][payload bytes]

after an 8-byte file magic (``SKYJRNL1``). The payload is a pickled
``(seq, {"X": ndarray, "Y": ndarray | None})`` tuple — exact bytes, so
replaying a record re-folds exactly the batch the client sent.

Durability discipline (docs/sessions, "Journal format"):

- every append **flushes** to the OS page cache before returning — a
  ``kill -9``'d replica loses nothing already accepted (the OS holds
  the bytes; only a whole-machine crash can drop them);
- every ``SKYLARK_SESSION_FSYNC_EVERY``-th append (default 8) also
  **fsyncs**, bounding what a machine crash can lose; drain/checkpoint
  paths call :meth:`sync` to force the bound to zero.

Torn tails are expected, not fatal: a crash mid-write leaves a partial
final record. :func:`scan` validates length + CRC record by record and
stops at the first damage; :meth:`SessionJournal.open_for_append`
truncates the file back to the intact prefix, so a resumed session
replays exactly the accepted appends and the retried tail append lands
cleanly after them (idempotent sequence numbers make the overlap a
no-op either way).
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Iterator, Optional, Tuple

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors

MAGIC = b"SKYJRNL1"
_HDR = struct.Struct("<II")


def scan(path: str) -> Tuple[list, int]:
    """``([(seq, batch_dict), ...], good_offset)`` — every intact
    record in order, plus the byte offset of the intact prefix (the
    truncation point for recovery). A missing file scans as empty; a
    bad magic raises (that is not a torn tail, it is not a journal)."""
    if not os.path.exists(path):
        return [], len(MAGIC)
    records: list = []
    with open(path, "rb") as fh:
        magic = fh.read(len(MAGIC))
        if magic != MAGIC:
            raise errors.IOError_(
                f"{path} is not a session journal (bad magic)")
        good = fh.tell()
        while True:
            hdr = fh.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            length, crc = _HDR.unpack(hdr)
            payload = fh.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break                      # torn tail: stop at damage
            try:
                seq, batch = pickle.loads(payload)
            except Exception:              # noqa: BLE001 — torn pickle
                break
            records.append((int(seq), batch))
            good = fh.tell()
    return records, good


class SessionJournal:
    """Writer half: append-only with batched fsync (module doc)."""

    def __init__(self, path: str, fsync_every: Optional[int] = None):
        self.path = path
        self._fsync_every = max(int(
            fsync_every if fsync_every is not None
            else _env.SESSION_FSYNC_EVERY.get()), 1)
        self._since_sync = 0
        self._fh = None

    @classmethod
    def create(cls, path: str,
               fsync_every: Optional[int] = None) -> "SessionJournal":
        j = cls(path, fsync_every)
        fh = open(path, "xb")
        fh.write(MAGIC)
        fh.flush()
        os.fsync(fh.fileno())
        j._fh = fh
        return j

    @classmethod
    def open_for_append(cls, path: str,
                        fsync_every: Optional[int] = None,
                        ) -> Tuple["SessionJournal", list]:
        """Recovery open: scan the intact prefix, truncate any torn
        tail, position for append. Returns ``(journal, records)``."""
        records, good = scan(path)
        j = cls(path, fsync_every)
        if not os.path.exists(path):
            return cls.create(path, fsync_every), records
        fh = open(path, "r+b")
        fh.truncate(good)
        fh.seek(good)
        j._fh = fh
        return j, records

    def append(self, seq: int, batch: dict) -> None:
        """Make one append durable (see the module durability
        discipline). The caller folds only after this returns."""
        payload = pickle.dumps((int(seq), batch), protocol=4)
        self._fh.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        self._fh.write(payload)
        self._fh.flush()
        self._since_sync += 1
        if self._since_sync >= self._fsync_every:
            os.fsync(self._fh.fileno())
            self._since_sync = 0

    def sync(self) -> None:
        """Force the fsync bound to zero (drain/checkpoint paths)."""
        if self._fh is not None and not self._fh.closed:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._since_sync = 0

    def close(self) -> None:
        if self._fh is not None and not self._fh.closed:
            self.sync()
            self._fh.close()


def replay(path: str) -> Iterator[Tuple[int, dict]]:
    """Read-only iteration over the intact records (peers inspecting a
    journal they do not own)."""
    records, _ = scan(path)
    return iter(records)


__all__ = ["SessionJournal", "replay", "scan"]
