"""Session registry: open/append/finalize over journaled, resumable
sketch state.

The registry owns every live session of one executor (the serve layer
holds one per :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor`)
and the on-disk artifacts that make a session survivable — all under
the shared ``SKYLARK_SESSION_DIR`` root:

``<sid>.meta.json``
    the :class:`~libskylark_tpu.sessions.state.SessionSpec`, written
    atomically at open — everything a peer needs to rebuild the
    transform streams;
``<sid>.journal``
    the append-only journal (:mod:`libskylark_tpu.sessions.journal`):
    every accepted append is durable here *before* its future
    resolves;
``<sid>.ckpt.npz`` / ``.json``
    the newest checkpoint (:func:`libskylark_tpu.utility.checkpoint
    .save_sync`) — accumulator bytes at a recorded ``(seq, rows)``,
    written by the drain path and bounding replay cost.

Resilience tiers (docs/sessions):

1. **graceful** — a DRAINING replica's drain hook calls
   :meth:`checkpoint_all`; a peer's first touch of the session id
   resumes from the checkpoint (journal tail empty past it) and the
   stream continues bit-equal;
2. **crash** — a ``kill -9``'d replica wrote no checkpoint, but the
   journal holds every accepted append: the peer replays checkpoint +
   journal tail, truncating any torn final record, and idempotent
   sequence numbers make the client's retried append a no-op if it was
   already durable;
3. **degradation** — per-session TTL eviction raises
   :class:`~libskylark_tpu.base.errors.SessionEvictedError` (terminal:
   artifacts removed, the id tombstoned), and the serve layer sheds
   session appends before interactive traffic under DEGRADED health.

The ``session.append`` fault site fires before the journal write, so a
chaos plan (including the ``crash`` spec) kills an append *before* it
becomes durable — the client's retry then lands exactly once.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time
import uuid
import weakref
from typing import Optional

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.resilience import faults
from libskylark_tpu.sessions.journal import SessionJournal
from libskylark_tpu.sessions.state import SessionSpec, SessionState
from libskylark_tpu.telemetry import metrics as _metrics

_OPENED = _metrics.counter(
    "sessions.opened", "Stateful serve sessions opened, by kind")
_APPENDS = _metrics.counter(
    "sessions.appends", "Session append batches accepted (journaled "
    "and folded)")
_FINALIZED = _metrics.counter(
    "sessions.finalized", "Sessions finalized, by kind")
_EVICTED = _metrics.counter(
    "sessions.evicted", "Sessions evicted, by reason")
_RESUMED = _metrics.counter(
    "sessions.resumed", "Sessions resumed from disk (drain handoff or "
    "crash replay), by source")
_REPLAYED = _metrics.counter(
    "sessions.replayed_records", "Journal records re-folded during "
    "session resume")
_CKPTS = _metrics.counter(
    "sessions.checkpoints", "Synchronous session checkpoints written")
_LIVE = _metrics.gauge(
    "sessions.live", "Live sessions per registry")


def default_session_dir() -> str:
    """The durability root: ``SKYLARK_SESSION_DIR`` when set, else a
    host-stable directory under the system temp dir (single-host
    handoff works out of the box; point the variable at shared storage
    for cross-host resume)."""
    configured = _env.SESSION_DIR.get()
    if configured:
        return str(configured)
    try:
        uid = os.getuid()
    except AttributeError:  # pragma: no cover - non-posix
        uid = 0
    return os.path.join(tempfile.gettempdir(), f"skylark_sessions_{uid}")


class _Entry:
    """One live session: state + journal + its own fold lock."""

    __slots__ = ("state", "journal", "lock", "last_touch", "ttl",
                 "dead")

    def __init__(self, state: SessionState, journal: SessionJournal):
        self.state = state
        self.journal = journal
        self.lock = _locks.make_lock("sessions.session")
        self.last_touch = time.monotonic()
        ttl = state.spec.ttl_s
        self.ttl = float(ttl if ttl is not None
                         else _env.SESSION_TTL.get())
        self.dead: Optional[str] = None


class SessionRegistry:
    """Open/append/finalize with TTL eviction, checkpointing and
    resume-with-replay (module doc). Thread-safe; per-session folds
    serialize on the session's own lock, the registry lock only guards
    the id maps."""

    def __init__(self, directory: Optional[str] = None,
                 name: str = "sessions"):
        self.name = str(name)
        self.directory = os.path.abspath(directory
                                         or default_session_dir())
        os.makedirs(self.directory, exist_ok=True)
        self._lock = _locks.make_lock("sessions.registry")
        self._live: "dict[str, _Entry]" = {}
        self._tombstones: "dict[str, str]" = {}
        self._counts = {"opened": 0, "appends": 0, "duplicates": 0,
                        "finalized": 0, "evicted": 0, "resumed": 0,
                        "replayed_records": 0, "checkpoints": 0}
        _REGISTRIES.add(self)

    # -- paths ----------------------------------------------------------

    def _meta_path(self, sid: str) -> str:
        return os.path.join(self.directory, f"{sid}.meta.json")

    def _journal_path(self, sid: str) -> str:
        return os.path.join(self.directory, f"{sid}.journal")

    def _ckpt_path(self, sid: str) -> str:
        return os.path.join(self.directory, f"{sid}.ckpt")

    # -- open -----------------------------------------------------------

    def open(self, spec: SessionSpec,
             session_id: Optional[str] = None) -> str:
        """Create a fresh session; returns its id. An id colliding with
        a live session, a tombstone, or on-disk artifacts refuses —
        open never silently adopts existing state (that is
        :meth:`resume`'s explicit job, and it happens on first touch of
        an unknown-but-on-disk id)."""
        spec = spec.validate()
        sid = str(session_id) if session_id else uuid.uuid4().hex[:16]
        # explicit whitelist (ids become filenames under the shared
        # durability root): letters, digits, dash, underscore only
        if not re.fullmatch(r"[A-Za-z0-9_-]{1,64}", sid):
            raise errors.InvalidParametersError(
                f"session id {sid!r} must match [A-Za-z0-9_-]{{1,64}}")
        with self._lock:
            if sid in self._live or sid in self._tombstones:
                raise errors.InvalidParametersError(
                    f"session {sid!r} already exists")
            if os.path.exists(self._meta_path(sid)):
                raise errors.InvalidParametersError(
                    f"session {sid!r} has on-disk state; resume it by "
                    "appending, or pick a fresh id")
            state = SessionState(spec)
            tmp = self._meta_path(sid) + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"spec": spec.to_dict(), "v": 1}, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self._meta_path(sid))
            journal = SessionJournal.create(self._journal_path(sid))
            self._live[sid] = _Entry(state, journal)
            self._counts["opened"] += 1
            live = len(self._live)
        _OPENED.inc(kind=spec.kind)
        _LIVE.set(live, registry=self.name)
        return sid

    # -- resolution + resume --------------------------------------------

    def _resolve(self, sid: str) -> _Entry:
        with self._lock:
            e = self._live.get(sid)
            if e is not None:
                return e
            reason = self._tombstones.get(sid)
            if reason is not None:
                raise errors.SessionEvictedError(
                    f"session {sid!r} is gone ({reason})")
            return self._resume_locked(sid)

    def _resume_locked(self, sid: str) -> _Entry:
        """Rebuild a session from its disk artifacts (caller holds the
        registry lock — two threads racing the first touch must resume
        it once). Checkpoint (if any) restores the accumulator bytes at
        its recorded ``(seq, rows)``; the journal's intact tail replays
        on top, records at or below the checkpoint seq skipped
        (idempotent). The journal reopens truncated past any torn
        record, ready for the stream to continue."""
        from libskylark_tpu.utility import checkpoint as _ckpt

        meta_path = self._meta_path(sid)
        if not os.path.exists(meta_path):
            raise errors.SessionEvictedError(
                f"session {sid!r} is unknown here and has no journal/"
                f"checkpoint under {self.directory} — evicted, "
                "finalized, or never opened")
        with open(meta_path) as fh:
            meta = json.load(fh)
        state = SessionState(SessionSpec.from_dict(meta["spec"]))
        source = "journal"
        loaded = _ckpt.load_sync(self._ckpt_path(sid))
        if loaded is not None:
            arrays, cmeta = loaded
            state.load(arrays, cmeta["rows"], cmeta["seq"])
            source = "checkpoint"
        journal, records = SessionJournal.open_for_append(
            self._journal_path(sid))
        replayed = 0
        for seq, batch in records:
            if seq <= state.seq:
                continue                   # already in the checkpoint
            X, Y = state.coerce_batch(batch["X"], batch.get("Y"))
            state.fold(X, Y)
            state.seq = seq
            replayed += 1
        entry = _Entry(state, journal)
        self._live[sid] = entry
        self._counts["resumed"] += 1
        self._counts["replayed_records"] += replayed
        live = len(self._live)
        _RESUMED.inc(source=source)
        if replayed:
            _REPLAYED.inc(replayed)
        _LIVE.set(live, registry=self.name)
        return entry

    # -- ttl / eviction -------------------------------------------------

    def _check_ttl(self, sid: str, entry: _Entry) -> None:
        """Caller holds ``entry.lock``. Raises after evicting."""
        if entry.dead is not None:
            raise errors.SessionEvictedError(
                f"session {sid!r} is gone ({entry.dead})")
        if time.monotonic() - entry.last_touch > entry.ttl:
            self._evict(sid, entry, "ttl")
            raise errors.SessionEvictedError(
                f"session {sid!r} exceeded its idle TTL "
                f"({entry.ttl}s) and was evicted")

    def _evict(self, sid: str, entry: _Entry, reason: str) -> None:
        """Terminal removal (caller holds ``entry.lock``): close the
        journal, delete every artifact, tombstone the id."""
        entry.dead = reason
        try:
            entry.journal.close()
        except OSError:
            pass
        self._remove_artifacts(sid)
        with self._lock:
            self._live.pop(sid, None)
            self._tombstones[sid] = reason
            self._counts["evicted" if reason != "finalized"
                         else "finalized"] += 1
            live = len(self._live)
        if reason != "finalized":
            _EVICTED.inc(reason=reason)
        _LIVE.set(live, registry=self.name)

    def _remove_artifacts(self, sid: str) -> None:
        for p in (self._journal_path(sid), self._meta_path(sid),
                  self._ckpt_path(sid) + ".npz",
                  self._ckpt_path(sid) + ".json"):
            try:
                os.unlink(p)
            except OSError:
                pass

    def sweep(self) -> int:
        """Evict every TTL-expired session; returns how many."""
        with self._lock:
            snapshot = list(self._live.items())
        n = 0
        for sid, entry in snapshot:
            with entry.lock:
                try:
                    self._check_ttl(sid, entry)
                except errors.SessionEvictedError:
                    n += 1
        return n

    def evict(self, sid: str, reason: str = "operator") -> None:
        """Administrative eviction (terminal, like a TTL expiry)."""
        entry = self._resolve(sid)
        with entry.lock:
            if entry.dead is None:
                self._evict(sid, entry, reason)

    # -- append ---------------------------------------------------------

    def append(self, sid: str, X, Y=None, seq: Optional[int] = None,
               tags: frozenset = frozenset()) -> tuple:
        """Accept one row batch: validate, journal (durable), fold.
        Returns ``(seq, rows)`` — the applied sequence number and the
        stream position after the fold. A ``seq`` at or below the
        session's cursor is a duplicate replay and returns the current
        position as a no-op (crash-retry idempotency); a gap refuses.
        The ``session.append`` fault site fires *before* the journal
        write (module doc)."""
        entry = self._resolve(sid)
        with entry.lock:
            self._check_ttl(sid, entry)
            state = entry.state
            target = state.seq + 1 if seq is None else int(seq)
            if target <= state.seq:
                entry.last_touch = time.monotonic()
                with self._lock:
                    self._counts["duplicates"] += 1
                return state.seq, state.rows
            if target != state.seq + 1:
                raise errors.InvalidParametersError(
                    f"append sequence gap: session {sid!r} is at "
                    f"{state.seq}, got {target}")
            Xc, Yc = state.coerce_batch(X, Y)
            faults.check("session.append", tags=tags,
                         detail=f"{sid}#{target}")
            batch = {"X": Xc}
            if Yc is not None:
                batch["Y"] = Yc
            entry.journal.append(target, batch)
            state.fold(Xc, Yc)
            state.seq = target
            entry.last_touch = time.monotonic()
            out = (state.seq, state.rows)
        with self._lock:
            self._counts["appends"] += 1
        _APPENDS.inc()
        return out

    # -- finalize -------------------------------------------------------

    def finalize(self, sid: str) -> dict:
        """Compute the session's terminal result, then remove it (and
        its artifacts) — the id is tombstoned so a late append raises
        :class:`SessionEvictedError` instead of resurrecting state."""
        entry = self._resolve(sid)
        with entry.lock:
            self._check_ttl(sid, entry)
            result = entry.state.finalize()
            kind = entry.state.spec.kind
            self._evict(sid, entry, "finalized")
        _FINALIZED.inc(kind=kind)
        return result

    # -- checkpointing (the drain hook's verb) --------------------------

    def checkpoint(self, sid: str) -> None:
        """Synchronously checkpoint one session: journal fsync'd, the
        accumulator bytes durable under the session's checkpoint path
        (:func:`libskylark_tpu.utility.checkpoint.save_sync`)."""
        from libskylark_tpu.utility import checkpoint as _ckpt

        entry = self._resolve(sid)
        with entry.lock:
            if entry.dead is not None:
                return
            entry.journal.sync()
            _ckpt.save_sync(
                self._ckpt_path(sid), entry.state.arrays(),
                {"seq": entry.state.seq, "rows": entry.state.rows,
                 "spec": entry.state.spec.to_dict()})
        with self._lock:
            self._counts["checkpoints"] += 1
        _CKPTS.inc()

    def checkpoint_all(self) -> int:
        """Checkpoint every live session (the DRAINING replica's r9
        drain hook — :meth:`MicrobatchExecutor.drain` calls this before
        stopping, so a peer resumes from state, not from a full journal
        replay). Returns how many were written; per-session failures
        are contained (the drain must keep going)."""
        import warnings

        with self._lock:
            sids = list(self._live)
        n = 0
        for sid in sids:
            try:
                self.checkpoint(sid)
                n += 1
            except Exception as e:  # noqa: BLE001 — drain the rest
                warnings.warn(
                    f"session {sid!r} checkpoint failed: {e}",
                    RuntimeWarning, stacklevel=2)
        return n

    # -- introspection / lifecycle --------------------------------------

    def session_ids(self) -> list:
        with self._lock:
            return sorted(self._live)

    def rows(self, sid: str) -> tuple:
        """``(seq, rows)`` of a live (or resumable) session."""
        entry = self._resolve(sid)
        with entry.lock:
            return entry.state.seq, entry.state.rows

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["live"] = len(self._live)
        return out

    def close(self) -> None:
        """Sync every journal and drop the in-memory maps WITHOUT
        deleting artifacts — the shutdown path; a peer (or a restart)
        resumes from disk."""
        with self._lock:
            snapshot = list(self._live.items())
            self._live.clear()
        for _sid, entry in snapshot:
            try:
                entry.journal.close()
            except OSError:
                pass
        _LIVE.set(0, registry=self.name)


_REGISTRIES: "weakref.WeakSet[SessionRegistry]" = weakref.WeakSet()


def sessions_stats() -> dict:
    """Aggregate session counters over every live registry (the
    ``sessions`` telemetry collector block)."""
    agg = {"registries": 0, "live": 0}
    keys = ("opened", "appends", "duplicates", "finalized", "evicted",
            "resumed", "replayed_records", "checkpoints")
    for k in keys:
        agg[k] = 0
    for reg in list(_REGISTRIES):
        s = reg.stats()
        agg["registries"] += 1
        agg["live"] += s["live"]
        for k in keys:
            agg[k] += s[k]
    return agg


_metrics.register_collector("sessions", sessions_stats)


__all__ = ["SessionRegistry", "default_session_dir", "sessions_stats"]
