"""Session registry: open/append/finalize over journaled, resumable
sketch state.

The registry owns every live session of one executor (the serve layer
holds one per :class:`~libskylark_tpu.engine.serve.MicrobatchExecutor`)
and the on-disk artifacts that make a session survivable — all under
the shared ``SKYLARK_SESSION_DIR`` root:

``<sid>.meta.json``
    the :class:`~libskylark_tpu.sessions.state.SessionSpec`, written
    atomically at open — everything a peer needs to rebuild the
    transform streams;
``<sid>.journal``
    the append-only journal (:mod:`libskylark_tpu.sessions.journal`):
    every accepted append is durable here *before* its future
    resolves;
``<sid>.ckpt.npz`` / ``.json``
    the newest checkpoint (:func:`libskylark_tpu.utility.checkpoint
    .save_sync`) — accumulator bytes at a recorded ``(seq, rows)``,
    written by the drain path and bounding replay cost;
``<sid>.lease``
    the ownership fence: ``{"gen", "owner"}``, bumped atomically by
    whichever registry opens or resumes the session.

Because the artifacts are trusted state (a resume rebuilds whatever
they say), the implicit default root is created ``0o700`` and refused
outright when it is a symlink or owned by another uid — it lives at a
predictable path under the world-writable system temp dir, where any
local user could otherwise pre-create it and plant forged sessions.
An explicitly configured root (``SKYLARK_SESSION_DIR`` or the
``directory=`` argument) is the operator's deliberate choice — e.g.
group-shared network storage — and is not second-guessed.

**Ownership fencing.** Exactly one registry may hold a session live.
Opening or resuming a session bumps the generation in ``<sid>.lease``;
every verb re-reads the lease under the session's lock before acting
(appends re-validate again after the journal write, before the ack;
eviction re-validates adjacent to the unlink), and a registry whose
recorded generation no longer matches has been **fenced** — some peer
resumed the session out from under it (a drain race, a
partitioned-then-healed owner). A fenced owner drops its in-memory
entry, abandons its journal handle, and leaves every on-disk artifact
strictly alone (they belong to the new owner now — in particular its
TTL sweep must not delete them); the verb that observes the fence
raises ``SessionEvictedError``, and a *later* touch resumes from disk
again — the handoff-back path when the ring returns the session here.
The router keeps verbs pinned to one live owner in the first place
(:mod:`libskylark_tpu.fleet.router`, session affinity); the lease is
the storage-layer backstop for the races that remain. It fences at
*touch* granularity: no fenced owner ever acks, checkpoints over, or
deletes the new owner's state, though a single already-in-flight
journal write can still land before its (refused) ack — advisory
cross-process file locks cannot exclude it without also breaking
crash-orphaned-file adoption.

Resilience tiers (docs/sessions):

1. **graceful** — a DRAINING replica's drain hook calls
   :meth:`checkpoint_all`; a peer's first touch of the session id
   resumes from the checkpoint (journal tail empty past it) and the
   stream continues bit-equal;
2. **crash** — a ``kill -9``'d replica wrote no checkpoint, but the
   journal holds every accepted append: the peer replays checkpoint +
   journal tail, truncating any torn final record, and idempotent
   sequence numbers make the client's retried append a no-op if it was
   already durable;
3. **degradation** — per-session TTL eviction raises
   :class:`~libskylark_tpu.base.errors.SessionEvictedError` (terminal:
   artifacts removed, the id tombstoned), and the serve layer sheds
   session appends before interactive traffic under DEGRADED health.

The ``session.append`` fault site fires before the journal write, so a
chaos plan (including the ``crash`` spec) kills an append *before* it
becomes durable — the client's retry then lands exactly once.
"""

from __future__ import annotations

import json
import os
import re
import stat as _stat
import tempfile
import time
import uuid
import weakref
from typing import Optional, Tuple

from libskylark_tpu.base import env as _env
from libskylark_tpu.base import errors
from libskylark_tpu.base import locks as _locks
from libskylark_tpu.resilience import faults
from libskylark_tpu.sessions.journal import SessionJournal
from libskylark_tpu.sessions.state import (
    SessionSpec,
    SessionState,
    make_state,
)
from libskylark_tpu.telemetry import metrics as _metrics

_OPENED = _metrics.counter(
    "sessions.opened", "Stateful serve sessions opened, by kind")
_APPENDS = _metrics.counter(
    "sessions.appends", "Session append batches accepted (journaled "
    "and folded)")
_FINALIZED = _metrics.counter(
    "sessions.finalized", "Sessions finalized, by kind")
_EVICTED = _metrics.counter(
    "sessions.evicted", "Sessions evicted, by reason")
_RESUMED = _metrics.counter(
    "sessions.resumed", "Sessions resumed from disk (drain handoff or "
    "crash replay), by source")
_REPLAYED = _metrics.counter(
    "sessions.replayed_records", "Journal records re-folded during "
    "session resume")
_CKPTS = _metrics.counter(
    "sessions.checkpoints", "Synchronous session checkpoints written")
_FENCED = _metrics.counter(
    "sessions.fenced", "Stale session owners fenced off after a peer "
    "resumed their session (lease generation mismatch)")
_LIVE = _metrics.gauge(
    "sessions.live", "Live sessions per registry")


def default_session_dir() -> str:
    """The durability root: ``SKYLARK_SESSION_DIR`` when set, else a
    host-stable directory under the system temp dir (single-host
    handoff works out of the box; point the variable at shared storage
    for cross-host resume)."""
    configured = _env.SESSION_DIR.get()
    if configured:
        return str(configured)
    try:
        uid = os.getuid()
    except AttributeError:  # pragma: no cover - non-posix
        uid = 0
    return os.path.join(tempfile.gettempdir(), f"skylark_sessions_{uid}")


def _ensure_private_dir(path: str, strict: bool) -> None:
    """Create the durability root ``0o700``; with ``strict`` (the
    implicit default under the world-writable temp dir — module doc)
    also refuse a root that is a symlink or owned by another uid,
    since either means some other local user controls what a resume
    will trust."""
    os.makedirs(path, mode=0o700, exist_ok=True)
    if not strict or os.name != "posix":
        return
    st = os.lstat(path)
    if _stat.S_ISLNK(st.st_mode):
        raise errors.IOError_(
            f"session dir {path} is a symlink — refusing: its target "
            "is under someone else's control; set SKYLARK_SESSION_DIR "
            "to a directory you own")
    if st.st_uid != os.getuid():
        raise errors.IOError_(
            f"session dir {path} is owned by uid {st.st_uid}, not "
            f"this process's uid {os.getuid()} — refusing: another "
            "user could plant or delete session state; set "
            "SKYLARK_SESSION_DIR to a directory you own")
    if st.st_mode & 0o077:
        os.chmod(path, 0o700)


class _Entry:
    """One live session: state + journal + its own fold lock. Starts
    as an unpopulated placeholder during a resume (``state is None``,
    the lock held by the resumer for the whole replay) — every
    consumer acquires ``lock`` before touching ``state``, so racers on
    the first touch simply block until the resume lands (or observe
    ``dead`` if it failed)."""

    __slots__ = ("state", "journal", "lock", "last_touch", "ttl",
                 "dead", "lease_gen", "pins")

    def __init__(self, state: Optional[SessionState] = None,
                 journal: Optional[SessionJournal] = None):
        self.state = state
        self.journal = journal
        self.lock = _locks.make_lock("sessions.session")
        self.last_touch = time.monotonic()
        self.ttl = float("inf")
        self.dead: Optional[str] = None
        self.lease_gen = 0
        self.pins = 0
        if state is not None:
            self.reset_ttl()

    def reset_ttl(self) -> None:
        ttl = self.state.spec.ttl_s
        self.ttl = float(ttl if ttl is not None
                         else _env.SESSION_TTL.get())


class SessionRegistry:
    """Open/append/finalize with TTL eviction, checkpointing, lease
    fencing and resume-with-replay (module doc). Thread-safe;
    per-session folds serialize on the session's own lock, the
    registry lock only guards the id maps — a resume replays under
    the session's lock, never the registry's."""

    def __init__(self, directory: Optional[str] = None,
                 name: str = "sessions"):
        self.name = str(name)
        # the implicit default root sits at a predictable path under
        # the world-writable temp dir: hold it to the strict private
        # checks; an explicit root is the operator's choice
        implicit = directory is None and not _env.SESSION_DIR.get()
        self.directory = os.path.abspath(directory
                                         or default_session_dir())
        _ensure_private_dir(self.directory, strict=implicit)
        self._lock = _locks.make_lock("sessions.registry")
        # this registry's identity on the lease files it holds
        self._token = f"{os.getpid()}.{uuid.uuid4().hex[:12]}"
        self._live: "dict[str, _Entry]" = {}
        self._tombstones: "dict[str, tuple]" = {}  # sid -> (reason,
        #                                             monotonic stamp)
        self._counts = {"opened": 0, "appends": 0, "duplicates": 0,
                        "finalized": 0, "evicted": 0, "resumed": 0,
                        "replayed_records": 0, "checkpoints": 0,
                        "fenced": 0}
        _REGISTRIES.add(self)

    # -- paths ----------------------------------------------------------

    def _meta_path(self, sid: str) -> str:
        return os.path.join(self.directory, f"{sid}.meta.json")

    def _journal_path(self, sid: str) -> str:
        return os.path.join(self.directory, f"{sid}.journal")

    def _ckpt_path(self, sid: str) -> str:
        return os.path.join(self.directory, f"{sid}.ckpt")

    def _lease_path(self, sid: str) -> str:
        return os.path.join(self.directory, f"{sid}.lease")

    # -- tombstones -----------------------------------------------------

    def _tombstone_locked(self, sid: str, reason: str) -> None:
        """Caller holds ``self._lock``. Tombstones are a courtesy
        error-message cache — once the artifacts are gone, an unknown
        id yields the same :class:`SessionEvictedError` from the
        resume path — so they are pruned by age past a size cap
        rather than retained forever (a long-lived serving process
        must not leak one dict entry per session it ever finalized).
        Memory stays bounded by eviction rate x the grace period."""
        now = time.monotonic()
        self._tombstones[sid] = (reason, now)
        if len(self._tombstones) > _TOMBSTONE_CAP:
            grace = float(_env.SESSION_TTL.get())
            for k in [k for k, (_r, t) in self._tombstones.items()
                      if now - t > grace]:
                del self._tombstones[k]

    def _tombstone_reason(self, sid: str) -> Optional[str]:
        """Caller holds ``self._lock``."""
        hit = self._tombstones.get(sid)
        return hit[0] if hit is not None else None

    # -- lease fencing (module doc) -------------------------------------

    def _read_lease(self, sid: str) -> Tuple[int, str]:
        """A MISSING or unparsable lease reads as generation 0 (the
        lease is genuinely gone or replaced — writes are atomic, so
        garbage means someone removed it). Any other I/O error
        propagates: a transient EIO on network storage must surface
        as a retryable failure, never be misread as "a peer fenced
        us" (which would terminally drop a healthy session)."""
        try:
            with open(self._lease_path(sid)) as fh:
                d = json.load(fh)
            return int(d["gen"]), str(d.get("owner", ""))
        except (FileNotFoundError, ValueError, KeyError, TypeError):
            return 0, ""

    def _acquire_lease(self, sid: str) -> int:
        """Bump the session's lease generation to this registry,
        fencing whoever held it before (their next touch observes the
        mismatch). Atomic via rename; fsync'd so the fence survives
        the machine crashes the journal protects against."""
        gen = self._read_lease(sid)[0] + 1
        tmp = self._lease_path(sid) + f".{self._token}.tmp"
        with open(tmp, "w") as fh:
            json.dump({"gen": gen, "owner": self._token}, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._lease_path(sid))
        return gen

    def _fenced_locked(self, sid: str, entry: _Entry) -> Optional[str]:
        """Caller holds ``entry.lock``. Returns the fence reason if
        this registry lost the session's lease (a peer resumed it),
        after dropping the entry WITHOUT touching the on-disk
        artifacts — they belong to the new owner now."""
        if entry.lease_gen == 0:
            return None                    # unpopulated placeholder
        gen, owner = self._read_lease(sid)
        if gen == entry.lease_gen and owner == self._token:
            return None
        reason = (f"fenced: resumed by another replica (lease "
                  f"generation {gen}, held {entry.lease_gen})")
        entry.dead = reason
        try:
            entry.journal.abandon()
        except OSError:
            pass
        with self._lock:
            # dropped, NOT tombstoned: the artifacts on disk are
            # valid (they belong to the new owner), so when the ring
            # later hands the session BACK here — the new owner
            # drains or crashes in its turn — the first touch must
            # resume it, not refuse on a stale tombstone. Only the
            # verb that observes the fence errors; the next touch
            # re-resolves through the resume path.
            self._live.pop(sid, None)
            self._counts["fenced"] += 1
            live = len(self._live)
        _FENCED.inc()
        _LIVE.set(live, registry=self.name)
        return reason

    # -- open -----------------------------------------------------------

    def open(self, spec: SessionSpec,
             session_id: Optional[str] = None) -> str:
        """Create a fresh session; returns its id. An id colliding with
        a live session, a tombstone, or on-disk artifacts refuses —
        open never silently adopts existing state (that is the resume
        path's explicit job, and it happens on first touch of an
        unknown-but-on-disk id). Like resume, the file I/O (meta +
        lease fsyncs, journal create) and the accumulator build run
        under a placeholder entry's own lock, never the registry lock
        — opening one session must not stall every other session's
        verbs."""
        spec = spec.validate()
        sid = str(session_id) if session_id else uuid.uuid4().hex[:16]
        # explicit whitelist (ids become filenames under the shared
        # durability root): letters, digits, dash, underscore only
        if not re.fullmatch(r"[A-Za-z0-9_-]{1,64}", sid):
            raise errors.InvalidParametersError(
                f"session id {sid!r} must match [A-Za-z0-9_-]{{1,64}}")
        entry = _Entry()
        entry.lock.acquire()
        try:
            with self._lock:
                if sid in self._live or sid in self._tombstones:
                    raise errors.InvalidParametersError(
                        f"session {sid!r} already exists")
                if os.path.exists(self._meta_path(sid)):
                    raise errors.InvalidParametersError(
                        f"session {sid!r} has on-disk state; resume "
                        "it by appending, or pick a fresh id")
                self._live[sid] = entry
            journal = None
            try:
                # the journal's "xb" create is the atomic RESERVATION
                # of the id across registries sharing the dir: exactly
                # one racing open can win it (the meta-exists precheck
                # above is advisory fast-refusal), so the loser's
                # cleanup can never delete artifacts a winning peer
                # already owns
                try:
                    journal = SessionJournal.create(
                        self._journal_path(sid))
                except FileExistsError:
                    raise errors.InvalidParametersError(
                        f"session {sid!r} has on-disk state; resume "
                        "it by appending, or pick a fresh id"
                    ) from None
                state = make_state(spec, self.directory, sid)
                tmp = self._meta_path(sid) + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump({"spec": spec.to_dict(), "v": 1}, fh)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, self._meta_path(sid))
                lease_gen = self._acquire_lease(sid)
            except BaseException as e:
                entry.dead = f"open failed: {e}"
                with self._lock:
                    self._live.pop(sid, None)
                if journal is not None:
                    # we hold the reservation: the partial artifacts
                    # are ours to delete
                    try:
                        journal.abandon()
                    except OSError:
                        pass
                    self._remove_artifacts(sid)
                raise
            entry.state = state
            entry.journal = journal
            entry.lease_gen = lease_gen
            entry.reset_ttl()
            entry.last_touch = time.monotonic()
            with self._lock:
                self._counts["opened"] += 1
                live = len(self._live)
        finally:
            entry.lock.release()
        _OPENED.inc(kind=spec.kind)
        _LIVE.set(live, registry=self.name)
        return sid

    # -- resolution + resume --------------------------------------------

    def _resolve(self, sid: str) -> _Entry:
        with self._lock:
            e = self._live.get(sid)
            if e is not None:
                return e
            reason = self._tombstone_reason(sid)
            if reason is not None:
                raise errors.SessionEvictedError(
                    f"session {sid!r} is gone ({reason})")
        return self._resume(sid)

    def _resume(self, sid: str) -> _Entry:
        """First touch of an unknown-but-on-disk id: publish a
        placeholder entry with its lock already held, then replay the
        disk artifacts under THAT lock — never the registry lock, so
        one session's resume (checkpoint load + journal-tail re-fold)
        cannot block every other session's verbs. Racing resolvers get
        the placeholder and simply block on its lock like any other
        verb. Lock order is session → registry, same as every verb.
        A failed resume drops the placeholder without a tombstone — a
        later touch retries from disk."""
        entry = _Entry()
        entry.lock.acquire()
        try:
            with self._lock:
                raced = self._live.get(sid)
                if raced is not None:
                    return raced           # someone else is resuming
                reason = self._tombstone_reason(sid)
                if reason is not None:
                    raise errors.SessionEvictedError(
                        f"session {sid!r} is gone ({reason})")
                self._live[sid] = entry
            try:
                replayed, source = self._resume_into(sid, entry)
            except BaseException as e:
                entry.dead = f"resume failed: {e}"
                with self._lock:
                    self._live.pop(sid, None)
                raise
            with self._lock:
                self._counts["resumed"] += 1
                self._counts["replayed_records"] += replayed
                live = len(self._live)
            _RESUMED.inc(source=source)
            if replayed:
                _REPLAYED.inc(replayed)
            _LIVE.set(live, registry=self.name)
            return entry
        finally:
            entry.lock.release()

    def _resume_into(self, sid: str, entry: _Entry) -> Tuple[int, str]:
        """Rebuild a session from its disk artifacts into ``entry``
        (caller holds ``entry.lock``). Checkpoint (if any) restores
        the accumulator bytes at its recorded ``(seq, rows)``; the
        journal's intact tail replays on top, records at or below the
        checkpoint seq skipped (idempotent). The journal reopens
        truncated past any torn record, ready for the stream to
        continue."""
        from libskylark_tpu.utility import checkpoint as _ckpt

        meta_path = self._meta_path(sid)
        if not os.path.exists(meta_path):
            raise errors.SessionEvictedError(
                f"session {sid!r} is unknown here and has no journal/"
                f"checkpoint under {self.directory} — evicted, "
                "finalized, or never opened")
        with open(meta_path) as fh:
            meta = json.load(fh)
        state = make_state(SessionSpec.from_dict(meta["spec"]),
                           self.directory, sid)
        # fence the previous owner FIRST: once the generation is
        # bumped, its next touch drops its entry, so it can neither
        # append to the journal we are about to replay nor TTL-evict
        # the artifacts out from under us
        lease_gen = self._acquire_lease(sid)
        source = "journal"
        loaded = _ckpt.load_sync(self._ckpt_path(sid))
        if loaded is not None:
            arrays, cmeta = loaded
            state.load(arrays, cmeta["rows"], cmeta["seq"])
            source = "checkpoint"
        journal, records = SessionJournal.open_for_append(
            self._journal_path(sid))
        replayed = 0
        for seq, batch in records:
            if seq <= state.seq:
                continue                   # already in the checkpoint
            X, Y = state.coerce_batch(batch["X"], batch.get("Y"))
            state.fold(X, Y)
            state.seq = seq
            replayed += 1
        entry.state = state
        entry.journal = journal
        entry.lease_gen = lease_gen
        entry.reset_ttl()
        entry.last_touch = time.monotonic()
        return replayed, source

    # -- ttl / eviction -------------------------------------------------

    def _check_ttl(self, sid: str, entry: _Entry) -> None:
        """Caller holds ``entry.lock``. Raises after evicting (TTL) or
        after dropping a fenced entry (lease lost — artifacts left for
        the new owner)."""
        if entry.dead is not None:
            raise errors.SessionEvictedError(
                f"session {sid!r} is gone ({entry.dead})")
        fenced = self._fenced_locked(sid, entry)
        if fenced is not None:
            raise errors.SessionEvictedError(
                f"session {sid!r} is gone ({fenced})")
        if (entry.pins == 0
                and time.monotonic() - entry.last_touch > entry.ttl):
            # pinned sessions (an in-flight or scheduled train slice —
            # :meth:`pin`) never TTL-evict: a long slice that crosses
            # the TTL must not race its own checkpoint into eviction.
            # Fence and dead checks above still apply to pinned
            # entries — a pin is not a lease.
            self._evict(sid, entry, "ttl")
            raise errors.SessionEvictedError(
                f"session {sid!r} exceeded its idle TTL "
                f"({entry.ttl}s) and was evicted")

    def _evict(self, sid: str, entry: _Entry, reason: str) -> None:
        """Terminal removal (caller holds ``entry.lock`` and has
        verified the lease — see :meth:`_check_ttl`): delete every
        artifact while the journal handle is still open (so a racing
        resume cannot slip in between), close it, tombstone the id."""
        # delete gate: re-validate the lease ADJACENT to the
        # irreversible unlink (symmetric to append's ack gate) — a
        # peer's resume that landed since the caller's fence check
        # owns the artifacts now, and this owner must drop fenced
        # instead of deleting them
        fenced = self._fenced_locked(sid, entry)
        if fenced is not None:
            raise errors.SessionEvictedError(
                f"session {sid!r} is gone ({fenced})")
        entry.dead = reason
        self._remove_artifacts(sid)
        try:
            entry.journal.close()
        except OSError:
            pass
        if os.path.exists(self._journal_path(sid)):
            # non-posix: unlinking the open journal above may have
            # failed (Windows PermissionError, swallowed); retry now
            # that the handle is closed so the id cannot wedge
            try:
                os.unlink(self._journal_path(sid))
            except OSError:
                pass
        with self._lock:
            self._live.pop(sid, None)
            self._tombstone_locked(sid, reason)
            self._counts["evicted" if reason != "finalized"
                         else "finalized"] += 1
            live = len(self._live)
        if reason != "finalized":
            _EVICTED.inc(reason=reason)
        _LIVE.set(live, registry=self.name)

    def _remove_artifacts(self, sid: str) -> None:
        for p in (self._journal_path(sid), self._meta_path(sid),
                  self._ckpt_path(sid) + ".npz",
                  self._ckpt_path(sid) + ".json",
                  # train operand sidecar (train/state.py) — written
                  # before open, removed with the rest of the session
                  os.path.join(self.directory, f"{sid}.operands.npz"),
                  os.path.join(self.directory, f"{sid}.operands.json"),
                  self._lease_path(sid)):
            try:
                os.unlink(p)
            except OSError:
                pass

    def sweep(self) -> int:
        """Evict every TTL-expired session; returns how many (fenced
        entries count — they are dropped either way, just without
        touching the new owner's artifacts)."""
        with self._lock:
            snapshot = list(self._live.items())
        n = 0
        for sid, entry in snapshot:
            with entry.lock:
                try:
                    self._check_ttl(sid, entry)
                except errors.SessionEvictedError:
                    n += 1
        return n

    def evict(self, sid: str, reason: str = "operator") -> None:
        """Administrative eviction (terminal, like a TTL expiry)."""
        entry = self._resolve(sid)
        with entry.lock:
            if (entry.dead is None
                    and self._fenced_locked(sid, entry) is None):
                self._evict(sid, entry, reason)

    # -- append ---------------------------------------------------------

    def append(self, sid: str, X, Y=None, seq: Optional[int] = None,
               tags: frozenset = frozenset()) -> tuple:
        """Accept one row batch: validate, journal (durable), fold.
        Returns ``(seq, rows)`` — the applied sequence number and the
        stream position after the fold. A ``seq`` at or below the
        session's cursor is a duplicate replay and returns the current
        position as a no-op (crash-retry idempotency); a gap refuses.
        The ``session.append`` fault site fires *before* the journal
        write (module doc)."""
        entry = self._resolve(sid)
        with entry.lock:
            self._check_ttl(sid, entry)
            state = entry.state
            target = state.seq + 1 if seq is None else int(seq)
            if target <= state.seq:
                entry.last_touch = time.monotonic()
                with self._lock:
                    self._counts["duplicates"] += 1
                return state.seq, state.rows
            if target != state.seq + 1:
                raise errors.InvalidParametersError(
                    f"append sequence gap: session {sid!r} is at "
                    f"{state.seq}, got {target}")
            Xc, Yc = state.coerce_batch(X, Y)
            faults.check("session.append", tags=tags,
                         detail=f"{sid}#{target}")
            batch = {"X": Xc}
            if Yc is not None:
                batch["Y"] = Yc
            entry.journal.append(target, batch)
            # ack gate: re-validate the lease AFTER the write landed.
            # If a peer resumed (fenced us) between the entry check
            # and the write, the record may sit past the point the
            # peer's replay scanned — it must never be acknowledged
            # as durable (the client's retry lands on the new owner).
            fenced = self._fenced_locked(sid, entry)
            if fenced is not None:
                raise errors.SessionEvictedError(
                    f"session {sid!r} is gone ({fenced})")
            state.fold(Xc, Yc)
            state.seq = target
            entry.last_touch = time.monotonic()
            out = (state.seq, state.rows)
        with self._lock:
            self._counts["appends"] += 1
        _APPENDS.inc()
        return out

    # -- finalize -------------------------------------------------------

    def finalize(self, sid: str) -> dict:
        """Compute the session's terminal result, then remove it (and
        its artifacts) — the id is tombstoned so a late append raises
        :class:`SessionEvictedError` instead of resurrecting state."""
        entry = self._resolve(sid)
        with entry.lock:
            self._check_ttl(sid, entry)
            result = entry.state.finalize()
            kind = entry.state.spec.kind
            self._evict(sid, entry, "finalized")
        _FINALIZED.inc(kind=kind)
        return result

    # -- checkpointing (the drain hook's verb) --------------------------

    def checkpoint(self, sid: str) -> None:
        """Synchronously checkpoint one session: journal fsync'd, the
        accumulator bytes durable under the session's checkpoint path
        (:func:`libskylark_tpu.utility.checkpoint.save_sync`). A dead
        or fenced entry is skipped — a stale owner must not overwrite
        the new owner's checkpoint."""
        from libskylark_tpu.utility import checkpoint as _ckpt

        entry = self._resolve(sid)
        with entry.lock:
            if (entry.dead is not None
                    or self._fenced_locked(sid, entry) is not None):
                return
            entry.journal.sync()
            _ckpt.save_sync(
                self._ckpt_path(sid), entry.state.arrays(),
                {"seq": entry.state.seq, "rows": entry.state.rows,
                 "spec": entry.state.spec.to_dict()})
            # a checkpoint is activity: a train job checkpointing on
            # schedule must not drift toward its idle TTL while making
            # durable progress (satellite of the eviction/checkpoint
            # race — tests/test_train.py pins this)
            entry.last_touch = time.monotonic()
        with self._lock:
            self._counts["checkpoints"] += 1
        _CKPTS.inc()

    def checkpoint_all(self) -> int:
        """Checkpoint every live session (the DRAINING replica's r9
        drain hook — :meth:`MicrobatchExecutor.drain` calls this before
        stopping, so a peer resumes from state, not from a full journal
        replay). Returns how many were written; per-session failures
        are contained (the drain must keep going)."""
        import warnings

        with self._lock:
            sids = list(self._live)
        n = 0
        for sid in sids:
            try:
                self.checkpoint(sid)
                n += 1
            except Exception as e:  # noqa: BLE001 — drain the rest
                warnings.warn(
                    f"session {sid!r} checkpoint failed: {e}",
                    RuntimeWarning, stacklevel=2)
        return n

    # -- pinning (train jobs; satellite of the eviction/checkpoint race)

    def pin(self, sid: str) -> None:
        """Hold the session out of TTL eviction while work on it is
        scheduled or in flight (the train manager pins for the whole
        job: slices refresh ``last_touch`` on each ack, but a single
        slice longer than the TTL — or a deep scheduler backlog —
        must not let the sweep race the next slice's checkpoint).
        Pins nest; they do not survive the registry (an entry rebuilt
        by resume starts unpinned — the resuming owner re-pins)."""
        entry = self._resolve(sid)
        with entry.lock:
            self._check_ttl(sid, entry)
            entry.pins += 1
            entry.last_touch = time.monotonic()

    def unpin(self, sid: str) -> None:
        """Release one pin. Only live entries are touched — an
        unpin after eviction/fencing is a no-op, never a resume."""
        with self._lock:
            entry = self._live.get(sid)
        if entry is None:
            return
        with entry.lock:
            if entry.pins > 0:
                entry.pins -= 1
            entry.last_touch = time.monotonic()

    # -- introspection / lifecycle --------------------------------------

    def describe(self, sid: str) -> dict:
        """Snapshot of a live (or resumable) session: spec, cursor,
        and — for states that expose :meth:`info` (train sessions) —
        the solver's progress facts. Does not refresh ``last_touch``
        (status polling is not activity, same as :meth:`rows`)."""
        entry = self._resolve(sid)
        with entry.lock:
            self._check_ttl(sid, entry)
            state = entry.state
            out = {"spec": state.spec.to_dict(), "seq": state.seq,
                   "rows": state.rows, "pins": entry.pins}
            info = getattr(state, "info", None)
            if callable(info):
                out["info"] = info()
            return out

    def session_ids(self) -> list:
        with self._lock:
            return sorted(self._live)

    def rows(self, sid: str) -> tuple:
        """``(seq, rows)`` of a live (or resumable) session. Validates
        like every verb (fence + TTL) — a fenced stale owner must not
        keep reporting its pre-handoff cursor as live — but does not
        refresh ``last_touch`` (polling is not activity)."""
        entry = self._resolve(sid)
        with entry.lock:
            self._check_ttl(sid, entry)
            return entry.state.seq, entry.state.rows

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["live"] = len(self._live)
        return out

    def close(self) -> None:
        """Sync every journal and drop the in-memory maps WITHOUT
        deleting artifacts — the shutdown path; a peer (or a restart)
        resumes from disk."""
        with self._lock:
            snapshot = list(self._live.items())
            self._live.clear()
        for _sid, entry in snapshot:
            try:
                if entry.journal is not None:
                    entry.journal.close()
            except OSError:
                pass
        _LIVE.set(0, registry=self.name)


_TOMBSTONE_CAP = 1024

_REGISTRIES: "weakref.WeakSet[SessionRegistry]" = weakref.WeakSet()


def sessions_stats() -> dict:
    """Aggregate session counters over every live registry (the
    ``sessions`` telemetry collector block)."""
    agg = {"registries": 0, "live": 0}
    keys = ("opened", "appends", "duplicates", "finalized", "evicted",
            "resumed", "replayed_records", "checkpoints", "fenced")
    for k in keys:
        agg[k] = 0
    for reg in list(_REGISTRIES):
        s = reg.stats()
        agg["registries"] += 1
        agg["live"] += s["live"]
        for k in keys:
            agg[k] += s[k]
    return agg


_metrics.register_collector("sessions", sessions_stats)


__all__ = ["SessionRegistry", "default_session_dir", "sessions_stats"]
