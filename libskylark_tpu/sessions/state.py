"""Per-kind session state: the maintained sketch a session folds
row batches into.

A stateful serve session exploits the one mathematical fact the whole
subsystem stands on: sketching transforms are **linear maps**
(PAPER.md, "sketching transforms"), so the sketch of a row stream is
the sum of per-batch partial sketches — the same mergeability
FlashSketch exploits across sparse shards applies across time. That
makes the session state *small* (the s×d maintained sketch, never the
data), which is what makes it cheap to checkpoint on drain, journal
per append, and replay after a crash (:mod:`libskylark_tpu.sessions.
registry`).

Kinds and their maintained state:

===========  =============================================================
``cwt``      CountSketch appender: positional bucket/sign streams scatter
             each batch into the carried (s, d) accumulator — **bit-equal**
             to the one-shot ``CWT.apply`` on the concatenated rows (the
             :mod:`io.streaming` layout-independence invariant promoted
             into the serve layer; updates land in row order, exactly the
             one-shot scatter's order).
``jlt``      Dense JLT appender: the virtual operator's column panel for
             the batch's row positions (``DenseTransform.s_panel`` — the
             same positional stream the one-shot apply materializes)
             times the batch, accumulated in batch order. Bit-equal to a
             replayed/uninterrupted session at the same batch boundaries;
             allclose to the one-shot apply (XLA's single matmul
             re-associates the f32 row sum).
``srht``     SRHT appender (WHT-based FJLT): operator columns in closed
             form — ``S[k, j] = D[j] · (−1)^popcount(idx_k & j) / sqrt(s)``
             (Sylvester Hadamard entries at the transform's own sampled
             rows and Rademacher diagonal) — same guarantee tier as
             ``jlt``. Requires ``n`` a power of two.
``isvd``     Incremental randomized SVD: maintains the ``jlt`` row sketch;
             ``finalize`` returns the top-k singular values and right
             singular vectors of the maintained (s, d) sketch — the
             streaming one-pass randomized SVD of the row stream.
``krr``      Online KRR via random features: per batch, the GaussianRFT
             feature map Z of the rows updates the carried normal
             equations ``G += ZᵀZ``, ``b += ZᵀY``; ``finalize`` solves
             ``(G + λI) w = b``. Row-wise feature maps are positional-
             independent, so folding is exact per batch.
===========  =============================================================

Replay invariant (all kinds): ``fold`` is a deterministic eager
function of ``(state bytes, batch bytes)``, and checkpoints store the
accumulator bytes exactly — so a session resumed from checkpoint +
journal tail finalizes **bit-equal** to the uninterrupted session.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from libskylark_tpu.base import errors

KINDS = ("cwt", "jlt", "srht", "isvd", "krr", "train")


@dataclasses.dataclass(frozen=True)
class SessionSpec:
    """The (pickleable, JSON-able) identity of one session: everything
    a peer replica needs to rebuild the transform streams and resume.
    ``n`` is the declared row extent (the positional streams' length —
    appends past it refuse); ``s_dim`` the sketch/feature dimension;
    ``d`` the row width; ``seed`` the transform Context seed."""

    kind: str
    n: int
    s_dim: int
    d: int
    seed: int = 0
    dtype: str = "float32"
    targets: int = 0          # Y columns carried (0: X only)
    k: int = 0                # isvd: ranks returned at finalize
    lam: float = 1e-3         # krr: ridge
    sigma: float = 1.0        # krr: RFT bandwidth
    ttl_s: Optional[float] = None
    extra: Optional[dict] = None  # train: TrainJobSpec.to_dict()

    def validate(self) -> "SessionSpec":
        if self.kind not in KINDS:
            raise errors.InvalidParametersError(
                f"unknown session kind {self.kind!r}; expected one of "
                f"{KINDS}")
        if self.n < 1 or self.s_dim < 1 or self.d < 1:
            raise errors.InvalidParametersError(
                f"session dims must be positive, got n={self.n} "
                f"s_dim={self.s_dim} d={self.d}")
        if self.kind == "srht" and self.n & (self.n - 1):
            raise errors.InvalidParametersError(
                f"srht sessions need n a power of two (WHT length), "
                f"got {self.n}")
        if self.kind == "krr" and self.targets < 1:
            raise errors.InvalidParametersError(
                "krr sessions carry targets: open with targets >= 1")
        if self.kind == "isvd" and not 0 <= self.k <= min(self.s_dim,
                                                         self.d):
            raise errors.InvalidParametersError(
                f"isvd k must be in [0, min(s_dim, d)], got {self.k}")
        if self.kind == "train":
            if not isinstance(self.extra, dict) or "solver" not in \
                    self.extra:
                raise errors.InvalidParametersError(
                    "train sessions carry their TrainJobSpec in "
                    "spec.extra (a dict with at least 'solver')")
        return self

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SessionSpec":
        return cls(**{f.name: d[f.name]
                      for f in dataclasses.fields(cls)
                      if f.name in d}).validate()


class SessionState:
    """One live session's maintained sketch + positional cursor.

    ``rows`` is the stream position (how many rows are folded in),
    ``seq`` the last applied append sequence number (the idempotency
    cursor the journal replays against). The accumulators are jnp
    arrays; :meth:`arrays`/:meth:`load` move them to/from host bytes
    for checkpointing without rounding."""

    def __init__(self, spec: SessionSpec):
        import jax.numpy as jnp

        from libskylark_tpu.base.context import Context

        self.spec = spec.validate()
        if spec.kind == "train":
            raise errors.InvalidParametersError(
                "train sessions are built by sessions.state.make_state"
                " (they need the registry directory for operands)")
        self.rows = 0
        self.seq = 0
        dt = np.dtype(spec.dtype)
        ctx = Context(seed=int(spec.seed))
        self._h = self._v = None
        self._jlt = None
        self._srht = None
        self._rft = None
        if spec.kind == "cwt":
            from libskylark_tpu.sketch.hash import CWT

            t = CWT(spec.n, spec.s_dim, ctx)
            self._h = np.asarray(t.bucket_indices())
            self._v = np.asarray(t.values(jnp.dtype(dt)))
        elif spec.kind in ("jlt", "isvd"):
            from libskylark_tpu.sketch.dense import JLT

            self._jlt = JLT(spec.n, spec.s_dim, ctx)
        elif spec.kind == "srht":
            from libskylark_tpu.sketch.fjlt import FJLT

            # the transform itself: operator_panel is the positional
            # column-panel stream (closed-form Sylvester-Hadamard —
            # moved to sketch/fjlt.py where the dist shard tasks share
            # it). The full diagonal is generated ONCE here — a
            # session folds thousands of small appends, so per-append
            # stream regeneration would be pure waste (shard tasks,
            # whose n may dwarf one task, slice per panel instead)
            t = FJLT(spec.n, spec.s_dim, ctx, fut="wht")
            self._srht = (t, np.asarray(t.diagonal(jnp.dtype(dt))))
        else:  # krr
            from libskylark_tpu.sketch.rft import GaussianRFT

            self._rft = GaussianRFT(spec.d, spec.s_dim, ctx,
                                    sigma=float(spec.sigma))
        # eager accumulator init: a zero-append session checkpoints and
        # resumes like any other
        if spec.kind == "krr":
            self.acc = {
                "G": jnp.zeros((spec.s_dim, spec.s_dim), dt),
                "b": jnp.zeros((spec.s_dim, spec.targets), dt),
            }
        else:
            self.acc = {"SX": jnp.zeros((spec.s_dim, spec.d), dt)}
            if spec.targets:
                self.acc["SY"] = jnp.zeros((spec.s_dim, spec.targets),
                                           dt)

    # -- batch intake ---------------------------------------------------

    def coerce_batch(self, X, Y=None):
        """Validate + canonicalize one append batch against the spec
        (host arrays, spec dtype, row bound). Runs BEFORE the journal
        write so a record that cannot fold is never made durable."""
        s = self.spec
        X = np.asarray(X, dtype=np.dtype(s.dtype))
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != s.d:
            raise errors.InvalidParametersError(
                f"append batch must be (m, {s.d}), got {X.shape}")
        if self.rows + X.shape[0] > s.n:
            raise errors.InvalidParametersError(
                f"append past the declared stream extent: "
                f"{self.rows} + {X.shape[0]} > n={s.n}")
        if s.targets:
            if Y is None:
                raise errors.InvalidParametersError(
                    f"session carries {s.targets} target column(s); "
                    "append needs Y")
            Y = np.asarray(Y, dtype=np.dtype(s.dtype))
            if Y.ndim == 1:
                Y = Y[:, None]
            if Y.shape != (X.shape[0], s.targets):
                raise errors.InvalidParametersError(
                    f"Y batch must be ({X.shape[0]}, {s.targets}), "
                    f"got {Y.shape}")
        else:
            Y = None
        return X, Y

    def fold(self, X: np.ndarray, Y: Optional[np.ndarray]) -> None:
        """Fold one coerced batch into the maintained sketch at the
        current row position. Deterministic eager ops on the carried
        accumulator — the replay invariant (module doc).

        The cwt/jlt/srht fold math here has a twin in
        ``dist/plan._Folder.fold`` (shard tasks fold the same way at
        shard offsets, but materialize O(shard) stream slices instead
        of this class's cached O(n) streams — different memory/reuse
        trade, same bits). A change to either fold must land in both;
        the cross-subsystem ``transform.apply`` oracles in
        tests/test_sessions.py and tests/test_dist.py pin them to the
        same bit pattern."""
        import jax.numpy as jnp

        s = self.spec
        lo, hi = self.rows, self.rows + X.shape[0]
        Xj = jnp.asarray(X)
        if s.kind == "cwt":
            # scatter into the CARRIED accumulator in row order — the
            # exact accumulation order of the one-shot CWT scatter
            # (io/streaming.py proves the bit-equality)
            h = jnp.asarray(self._h[lo:hi])
            v = jnp.asarray(self._v[lo:hi])
            self.acc["SX"] = self.acc["SX"].at[h].add(v[:, None] * Xj)
            if Y is not None:
                self.acc["SY"] = self.acc["SY"].at[h].add(
                    v[:, None] * jnp.asarray(Y))
        elif s.kind in ("jlt", "isvd"):
            panel = self._jlt.s_panel(lo, hi, Xj.dtype)
            self.acc["SX"] = self.acc["SX"] + panel @ Xj
            if Y is not None:
                self.acc["SY"] = self.acc["SY"] + panel @ jnp.asarray(Y)
        elif s.kind == "srht":
            # panel-free FWHT fold (the dist/plan twin — see the
            # _Folder docstring's both-or-neither rule): the cached
            # full diagonal amortizes the Rademacher stream across
            # thousands of small appends, as it did for the panels.
            t, diag = self._srht
            self.acc["SX"] = self.acc["SX"] + t.fold_rows(
                Xj, lo, hi, np.dtype(s.dtype), diagonal=diag)
            if Y is not None:
                self.acc["SY"] = self.acc["SY"] + t.fold_rows(
                    jnp.asarray(Y), lo, hi, np.dtype(s.dtype),
                    diagonal=diag)
        else:  # krr
            from libskylark_tpu.sketch import ROWWISE

            Z = self._rft.apply(Xj, ROWWISE)
            self.acc["G"] = self.acc["G"] + Z.T @ Z
            self.acc["b"] = self.acc["b"] + Z.T @ jnp.asarray(Y)
        self.rows = hi

    # -- checkpoint round trip ------------------------------------------

    def arrays(self) -> dict:
        """Host snapshot of the accumulators (exact bytes)."""
        return {k: np.asarray(v) for k, v in self.acc.items()}

    def load(self, arrays: dict, rows: int, seq: int) -> None:
        import jax.numpy as jnp

        for k in self.acc:
            if k not in arrays:
                raise errors.InvalidParametersError(
                    f"checkpoint missing accumulator {k!r}")
            if tuple(arrays[k].shape) != tuple(self.acc[k].shape):
                raise errors.InvalidParametersError(
                    f"checkpoint accumulator {k!r} has shape "
                    f"{arrays[k].shape}, expected {self.acc[k].shape}")
            self.acc[k] = jnp.asarray(arrays[k])
        self.rows = int(rows)
        self.seq = int(seq)

    # -- finalize -------------------------------------------------------

    def finalize(self) -> dict:
        """The session's terminal result as host arrays: the maintained
        sketch(es) for the appenders, the factorization/solution for
        the composite kinds."""
        import jax.numpy as jnp

        s = self.spec
        if s.kind == "krr":
            lam = jnp.asarray(s.lam, self.acc["G"].dtype)
            eye = jnp.eye(s.s_dim, dtype=self.acc["G"].dtype)
            w = jnp.linalg.solve(self.acc["G"] + lam * eye,
                                 self.acc["b"])
            return {"coef": np.asarray(w), "rows": self.rows}
        if s.kind == "isvd":
            _, sv, Vt = jnp.linalg.svd(self.acc["SX"],
                                       full_matrices=False)
            k = s.k or min(s.s_dim, s.d)
            return {"singular_values": np.asarray(sv[:k]),
                    "Vt": np.asarray(Vt[:k]), "rows": self.rows}
        out = {"SX": np.asarray(self.acc["SX"]), "rows": self.rows}
        if "SY" in self.acc:
            out["SY"] = np.asarray(self.acc["SY"])
        return out


def make_state(spec: SessionSpec, directory: Optional[str] = None,
               sid: Optional[str] = None):
    """State factory the registry goes through at open *and* resume.

    Sketch kinds build the plain :class:`SessionState`; ``train``
    sessions build :class:`libskylark_tpu.train.state.
    TrainSessionState`, which needs the registry ``directory`` and
    ``sid`` to locate the job's persisted operand file (the solver
    inputs are too large for the spec, so they ride a sidecar
    ``<sid>.operands.npz`` written before the session opens)."""
    spec = spec.validate()
    if spec.kind == "train":
        from libskylark_tpu.train.state import TrainSessionState

        return TrainSessionState(spec, directory=directory, sid=sid)
    return SessionState(spec)


__all__ = ["KINDS", "SessionSpec", "SessionState", "make_state"]
