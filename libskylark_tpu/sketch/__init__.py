"""Sketching transforms — the core layer (SURVEY.md §2.2).

Uniform protocol: ``T = JLT(N, S, context); SA = T.apply(A, COLUMNWISE)``,
serialization via ``T.to_json()`` / ``deserialize_sketch``.
"""

from libskylark_tpu.sketch.transform import (
    COLUMNWISE,
    ROWWISE,
    Dimension,
    SketchTransform,
    deserialize_sketch,
    register,
)
from libskylark_tpu.sketch import params
from libskylark_tpu.sketch.dense import CT, JLT, DenseTransform
from libskylark_tpu.sketch.hash import CWT, MMT, WZT, HashTransform
from libskylark_tpu.sketch.rft import (
    ExpSemigroupRLT,
    GaussianRFT,
    LaplacianRFT,
    MaternRFT,
    RFT,
)
from libskylark_tpu.sketch.ust import UST
from libskylark_tpu.sketch import fut
from libskylark_tpu.sketch.fjlt import FJLT, RFUT
from libskylark_tpu.sketch.frft import FastGaussianRFT, FastMaternRFT, FastRFT
from libskylark_tpu.sketch.ppt import PPT
from libskylark_tpu.sketch.qrft import (
    ExpSemigroupQRLT,
    GaussianQRFT,
    LaplacianQRFT,
    QRFT,
)

__all__ = [
    "fut",
    "FJLT",
    "RFUT",
    "FastRFT",
    "FastGaussianRFT",
    "FastMaternRFT",
    "PPT",
    "QRFT",
    "GaussianQRFT",
    "LaplacianQRFT",
    "ExpSemigroupQRLT",
    "COLUMNWISE",
    "ROWWISE",
    "Dimension",
    "SketchTransform",
    "deserialize_sketch",
    "register",
    "params",
    "JLT",
    "CT",
    "DenseTransform",
    "CWT",
    "MMT",
    "WZT",
    "HashTransform",
    "UST",
    "RFT",
    "GaussianRFT",
    "LaplacianRFT",
    "MaternRFT",
    "ExpSemigroupRLT",
]
