"""Lazy dense sketch transforms: JLT, CT.

TPU-native analog of the reference's dense_transform family
(ref: sketch/dense_transform.hpp, sketch/dense_transform_data.hpp:22-174,
sketch/JLT_data.hpp:17-78, sketch/CT_data.hpp:21-60).

The sketch matrix S (S_dim × N) is *virtual*: entries are a pure function of
(allocation key, column block), so any column panel can be materialized
on-demand on whichever device needs it — the reference's
``realize_matrix_view`` trick (ref: sketch/dense_transform_data.hpp:79-152)
that lets distributed apply proceed without ever storing S. Column blocks are
``BLOCK_COLS`` wide; the block width is part of the transform's definition
(changing it changes the entries).

Three apply regimes (the analog of the reference's 3-regime panel algorithm,
ref: sketch/dense_transform_Elemental_mc_mr.hpp:617-658, tuned by
sketch_params blocksize/factor):
- small N: materialize S once, single fused matmul (XLA fuses generation
  into the pipeline; MXU does the work).
- large N (``apply_blocked``): lax.scan over column panels of S / row panels
  of A, materializing one (S_dim × blocksize) panel per step — bounded memory,
  traced block ids.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from libskylark_tpu.base import randgen
from libskylark_tpu.sketch import params as sketch_params
from libskylark_tpu.sketch.transform import (OperatorCache,
                                             SketchTransform, register)

# Width of a virtual-S column block; part of the stream format.
BLOCK_COLS = 256


def virtual_panel(key, dist, s_dim: int, col_start: int, col_stop: int,
                  scale: float, dtype=jnp.float32) -> jnp.ndarray:
    """Columns [col_start, col_stop) of the scaled virtual (s_dim × N)
    operator in the dense-block stream format. THE one definition of
    the stream (BLOCK_COLS included): ``DenseTransform.s_panel`` and
    the engine-fused solver pipelines (nla/svd.py) both call this, so
    their operator bits cannot drift apart."""
    return scale * randgen.dense_panel(
        key, dist, s_dim, col_start, col_stop, BLOCK_COLS, dtype)


def serve_apply(key_data, scale, A, *, dist, s_dim: int,
                rowwise: bool) -> jnp.ndarray:
    """Pure, vmap-batchable dense sketch apply for the microbatch
    serving layer (:mod:`libskylark_tpu.engine.serve`): one request's
    S·A (or A·Sᵀ) as a function of the transform's raw key data, with
    every knob static. The operator bits come from :func:`virtual_panel`
    — the same positional stream ``DenseTransform`` applies — so a
    request whose operand is zero-padded past the transform's true N
    produces the exact bits of the unpadded apply: padded coordinates
    multiply zero rows/columns, and the stream's first N positions are
    invariant to the padded width.

    ``key_data`` is ``jax.random.key_data(transform.allocation.key)``
    ((2,) uint32), which the executor can stack host-side; ``scale`` is
    traced so transforms differing only by scale (CT's C) share one
    executable."""
    import jax.random as jr

    key = jr.wrap_key_data(jnp.asarray(key_data))
    n = A.shape[1] if rowwise else A.shape[0]
    S = virtual_panel(key, dist, s_dim, 0, n,
                      jnp.asarray(scale, A.dtype), A.dtype)
    return (A @ S.T) if rowwise else (S @ A)


def pallas_ambient_ok(A) -> bool:
    """True when the fused kernel may run on ``A`` in the ambient context:
    use_pallas is on AND the array is single-device. Sharded applies keep
    the XLA path (its partitioning XLA handles); on a tracer the sharding
    is unreadable, so traced applies qualify only when the backend has a
    single device and sharding is impossible (the multi-device kernel
    route is the explicit shard_map pipeline, parallel/shard_apply.py)."""
    if not sketch_params.get_use_pallas():
        return False
    import jax

    if isinstance(A, jax.core.Tracer):
        return len(jax.devices()) == 1
    if isinstance(A, jax.Array):
        try:
            return len(A.sharding.device_set) == 1
        except Exception:
            return False
    return False


def pallas_serves_eager(A, dist, s_dim: int,
                        seq_axis: int | None) -> bool:
    """True when an eager dense apply of ``A`` would route through the
    fused Mosaic kernel — whose contraction numerics (bf16x3 split,
    accumulation order) differ from a materialized XLA gemm. Used to
    veto auto-materialize on that path: the Nth eager apply must not
    silently change numerics vs the first (cross-call reproducibility).
    Mirrors the dispatch's FULL qualification via ``effective_plan``
    (distribution/dtype support, pallas importability, VMEM/tile
    budget): any apply the kernel would decline runs the plain XLA
    contraction and must keep auto-amortizing."""
    if not pallas_ambient_ok(A):
        return False
    from libskylark_tpu.sketch import pallas_dense

    if not pallas_dense.available():
        return False
    if getattr(A, "ndim", 0) != 2:
        # non-2D never reaches the kernel (dispatch is 2-D only): the
        # XLA path serves it, auto-materialize may amortize freely
        return False
    if seq_axis is None:
        # orientation unknown: veto only if EITHER orientation would
        # take the kernel (r4 advisor — a bare supported() check vetoed
        # applies whose over-budget s_dim the VMEM/tile qualification
        # would decline, permanently disabling auto-materialize on an
        # apply that actually runs the XLA path)
        return any(
            bool(pallas_dense.effective_plan(
                dist, A.shape, A.dtype, s_dim, ax).get("kernel"))
            for ax in (0, 1))
    return bool(pallas_dense.effective_plan(
        dist, A.shape, A.dtype, s_dim, seq_axis).get("kernel"))


def try_pallas_apply(key, dist, A, s_dim: int, scale: float, which: str):
    """Fused generation+matmul TPU kernel (sketch/pallas_dense.py) for any
    virtual operator in the dense-block stream format — the dense
    transforms and the RFT frequency matrices share this dispatch.
    Returns None when the backend/input don't qualify — or when a cached
    autotuner plan (libskylark_tpu/tune/) certifies the XLA path for
    this workload; the kernel-side resolution also fills m_tile /
    precision from the cache before the heuristic defaults."""
    if not pallas_ambient_ok(A):
        return None
    from libskylark_tpu.sketch import pallas_dense

    return getattr(pallas_dense, which)(key, dist, A, s_dim, scale)


class DenseTransform(OperatorCache, SketchTransform):
    """Base: S = scale × i.i.d. matrix from ``dist``
    (ref: sketch/random_dense_transform_data.hpp:15-76)."""

    sketch_type = "DenseTransform"
    dist: randgen.Distribution = randgen.Normal()

    @property
    def scale(self) -> float:
        raise NotImplementedError

    # -- virtual S materialization --

    def s_panel(self, col_start: int, col_stop: int, dtype=jnp.float32) -> jnp.ndarray:
        """Materialize S[:, col_start:col_stop] (static bounds)."""
        return virtual_panel(self._alloc.key, self.dist, self._S,
                             col_start, col_stop, self.scale, dtype)

    def s_block(self, block_id, dtype=jnp.float32) -> jnp.ndarray:
        """Materialize column block ``block_id`` (traced id ok; for scan loops)."""
        return self.scale * randgen.dense_block(
            self._alloc.key, self.dist, self._S, block_id, BLOCK_COLS, dtype
        )

    # -- materialize-and-reuse (OperatorCache; entries identical to the
    # virtual stream's by construction — same s_panel) --

    def _full_operator(self, dtype) -> jnp.ndarray:
        return self.s_panel(0, self._N, dtype)

    def _materialize_changes_numerics(self, A, seq_axis=None) -> bool:
        return pallas_serves_eager(A, self.dist, self._S, seq_axis)

    # -- apply --

    def _effective_blocksize(self, dtype) -> int:
        """The panel width to apply at: the global ``blocksize`` knob, or
        — when unset (0) but the full operator would exceed the
        auto-blocking threshold — an automatic panel width. The reference
        defaults to blocked apply (blocksize=1000,
        ref: sketch/sketch_params.hpp:15-19) precisely so S never
        materializes; unbounded materialization of an (S_dim × N)
        operator at huge N would OOM where the reference works."""
        blocksize = sketch_params.get_blocksize()
        if blocksize:
            return blocksize if self._N > blocksize else 0
        itemsize = jnp.dtype(dtype).itemsize
        if self._S * self._N * itemsize > sketch_params.get_auto_block_bytes():
            # raw width; _panel_schedule rounds to BLOCK_COLS multiples
            return max(
                BLOCK_COLS,
                sketch_params.get_auto_block_bytes()
                // max(self._S * itemsize, 1),
            )
        return 0

    def _apply_columnwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A, seq_axis=0)
        S = self._cached_op(A.dtype)
        if S is not None:
            return S @ A
        out = self._try_pallas(A, "columnwise_apply")
        if out is not None:
            return out
        blocksize = self._effective_blocksize(A.dtype)
        if blocksize:
            return self._apply_columnwise_blocked(A, blocksize)
        S = self.s_panel(0, self._N, A.dtype)
        return S @ A

    def _apply_rowwise(self, A: jnp.ndarray) -> jnp.ndarray:
        self._note_eager_apply(A, seq_axis=1)
        S = self._cached_op(A.dtype)
        if S is not None:
            return A @ S.T
        out = self._try_pallas(A, "rowwise_apply")
        if out is not None:
            return out
        blocksize = self._effective_blocksize(A.dtype)
        if blocksize:
            return self._apply_rowwise_blocked(A, blocksize)
        S = self.s_panel(0, self._N, A.dtype)
        return A @ S.T

    def _try_pallas(self, A, which: str):
        return try_pallas_apply(
            self._alloc.key, self.dist, A, self._S, self.scale, which
        )

    # -- sparse input (ref: sketch/dense_transform_Mixed.hpp:19) --

    def _apply_columnwise_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.base.sparse import spmm_t

        S = self._cached_op(A.device_dtype)
        if S is not None:
            return spmm_t(A, S.T).T      # S·A = (Aᵀ·Sᵀ)ᵀ
        blocksize = self._effective_blocksize(A.device_dtype)
        if blocksize:
            # S·A = (Aᵀ·Sᵀ)ᵀ; Aᵀ's columns are A's rows = the sketched dim,
            # so the panel loop runs over Aᵀ (host CSC transpose, O(nnz)).
            return self._sparse_panel_loop(A.transpose(), blocksize).T
        S = self.s_panel(0, self._N, A.device_dtype)
        return spmm_t(A, S.T).T          # S·A = (Aᵀ·Sᵀ)ᵀ

    def _apply_rowwise_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.base.sparse import spmm

        S = self._cached_op(A.device_dtype)
        if S is not None:
            return spmm(A, S.T)          # A·Sᵀ
        blocksize = self._effective_blocksize(A.device_dtype)
        if blocksize:
            return self._sparse_panel_loop(A, blocksize)
        S = self.s_panel(0, self._N, A.device_dtype)
        return spmm(A, S.T)              # A·Sᵀ

    def _sparse_panel_loop(self, A, blocksize: int) -> jnp.ndarray:
        """A·Sᵀ for sparse (m, N) A without ever materializing S beyond an
        (S_dim × blocksize) panel — the sparse analog of the blocked dense
        apply (honors the reference's blocksize memory bound,
        ref: sketch/sketch_params.hpp:15-19). Host loop over column panels
        (CSC column views are O(1)); per-panel nonzeros are zero-padded to
        one uniform size so XLA compiles at most two program shapes."""
        import numpy as np

        dt = A.device_dtype
        bs, n_full, rem = self._panel_schedule(blocksize)
        bounds = [(p * bs, (p + 1) * bs) for p in range(n_full)]
        if rem:
            bounds.append((n_full * bs, self._N))
        views = [A.column_view(p0, p1) for p0, p1 in bounds]
        pad = max((v.nnz for v in views), default=1) or 1
        acc = jnp.zeros((A.height, self._S), dt)
        for (p0, p1), V in zip(bounds, views):
            sp = V.to_scipy().tocoo()
            r = np.zeros(pad, np.int32)
            c = np.zeros(pad, np.int32)
            vals = np.zeros(pad, np.dtype(dt))
            r[: V.nnz] = sp.row
            c[: V.nnz] = sp.col
            vals[: V.nnz] = sp.data  # padding rows add v=0 at (0, 0)
            Sp = self.s_panel(p0, p1, dt)        # (S_dim, p1-p0)
            G = Sp.T[jnp.asarray(c)] * jnp.asarray(vals, dt)[:, None]
            acc = acc + jax.ops.segment_sum(
                G, jnp.asarray(r), num_segments=A.height
            )
        return acc

    # -- distributed sparse input (P4/P5): per-cell virtual panels + psum
    # (ref: sketch/dense_transform_Mixed.hpp:19) --

    def _apply_columnwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return dsa.dense_columnwise(self, A)

    def _apply_rowwise_dist_sparse(self, A) -> jnp.ndarray:
        from libskylark_tpu.sketch import dist_sparse_apply as dsa

        return dsa.dense_rowwise(self, A)

    # -- blocked (memory-bounded) apply: scan over column panels of S --

    def _panel_schedule(self, blocksize: int):
        """Round blocksize down to a BLOCK_COLS multiple; compute panel count."""
        bs = max(BLOCK_COLS, (blocksize // BLOCK_COLS) * BLOCK_COLS)
        n_full = self._N // bs
        rem = self._N - n_full * bs
        return bs, n_full, rem

    def _apply_columnwise_blocked(self, A: jnp.ndarray, blocksize: int) -> jnp.ndarray:
        """SA = Σ_p S[:, p] @ A[p, :], one virtual panel at a time."""
        bs, n_full, rem = self._panel_schedule(blocksize)
        blocks_per_panel = bs // BLOCK_COLS
        m = A.shape[1]
        acc0 = jnp.zeros((self._S, m), A.dtype)

        def body(acc, p):
            first = p * blocks_per_panel
            panel = jnp.concatenate(
                [self.s_block(first + b, A.dtype) for b in range(blocks_per_panel)],
                axis=1,
            )
            a_rows = lax.dynamic_slice_in_dim(A, p * bs, bs, axis=0)
            return acc + panel @ a_rows, None

        acc, _ = lax.scan(body, acc0, jnp.arange(n_full, dtype=jnp.int32))
        if rem:
            tail = self.s_panel(n_full * bs, self._N, A.dtype)
            acc = acc + tail @ A[n_full * bs :, :]
        return acc

    def _apply_rowwise_blocked(self, A: jnp.ndarray, blocksize: int) -> jnp.ndarray:
        """A·Sᵀ = Σ_p A[:, p] @ S[:, p]ᵀ, one virtual panel at a time."""
        bs, n_full, rem = self._panel_schedule(blocksize)
        blocks_per_panel = bs // BLOCK_COLS
        m = A.shape[0]
        acc0 = jnp.zeros((m, self._S), A.dtype)

        def body(acc, p):
            first = p * blocks_per_panel
            panel = jnp.concatenate(
                [self.s_block(first + b, A.dtype) for b in range(blocks_per_panel)],
                axis=1,
            )
            a_cols = lax.dynamic_slice_in_dim(A, p * bs, bs, axis=1)
            return acc + a_cols @ panel.T, None

        acc, _ = lax.scan(body, acc0, jnp.arange(n_full, dtype=jnp.int32))
        if rem:
            tail = self.s_panel(n_full * bs, self._N, A.dtype)
            acc = acc + A[:, n_full * bs :] @ tail.T
        return acc


@register
class JLT(DenseTransform):
    """Johnson-Lindenstrauss transform: S ~ N(0, 1/S_dim)
    (ref: sketch/JLT_data.hpp:27-38 — scale sqrt(1/S))."""

    sketch_type = "JLT"
    dist = randgen.Normal()

    @staticmethod
    def scale_for(s_dim: int) -> float:
        """The JLT scale convention, callable without an instance (the
        fused solver pipelines rebuild the operator from a bare key)."""
        return math.sqrt(1.0 / s_dim)

    @property
    def scale(self) -> float:
        return self.scale_for(self._S)


@register
class CT(DenseTransform):
    """Cauchy transform for l1 embedding: Cauchy entries scaled C/S
    (ref: sketch/CT_data.hpp:35-47)."""

    sketch_type = "CT"
    dist = randgen.Cauchy()

    def __init__(self, N, S, context, C: float = 1.0):
        self._C = float(C)
        super().__init__(N, S, context)

    @property
    def scale(self) -> float:
        return self._C / self._S

    def _extra_params(self) -> dict[str, Any]:
        return {"C": self._C}

    @classmethod
    def _from_parts(cls, N, S, alloc, d):
        return cls(N, S, alloc, C=float(d.get("C", 1.0)))
