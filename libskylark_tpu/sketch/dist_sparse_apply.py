"""Sketch application over mesh-distributed sparse matrices (P4/P5).

TPU-native analog of the reference's distributed-sparse sketch engines:
the CombBLAS hash-transform specializations
(ref: sketch/hash_transform_CombBLAS.hpp:16-632) and the mixed
sparse-input dense transform (ref: sketch/dense_transform_Mixed.hpp:19).

Pattern shared by all four applies: a ``shard_map`` in which each grid
cell contracts its *local* nonzeros — hash transforms via an O(nnz)
scatter-add into the bucket dimension, dense transforms via a segment-sum
against an on-device-generated panel of the virtual operator S (the
``realize_matrix_view`` trick, ref: sketch/dense_transform_data.hpp:79-152,
here with traced block ids so each device builds exactly its own panel) —
followed by one ``psum`` over the mesh axis that carries the sketched
dimension (the reference's local-accumulate + all_reduce,
ref: sketch/hash_transform_Elemental.hpp:427-607).

Outputs are dense, sharded on the kept axis; the sketched dimension is
replicated (the [★,★]-output convention of the reference's dist applies).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from libskylark_tpu.base import errors
from libskylark_tpu.base.compat import shard_map
from libskylark_tpu.base.dist_sparse import DistSparseMatrix


def _check_dim(T, D: DistSparseMatrix, columnwise: bool) -> None:
    n = D.height if columnwise else D.width
    if n != T.input_dim:
        raise errors.SketchError(
            f"{'columnwise' if columnwise else 'rowwise'} apply expects "
            f"{T.input_dim} on the sketched dimension, got {D.shape}"
        )


# ---------------------------------------------------------------------------
# hash transforms (CWT / MMT / WZT)
# ---------------------------------------------------------------------------


def hash_columnwise(T, D: DistSparseMatrix) -> jax.Array:
    """S·A for A (N, w) distributed sparse → (S_dim, w) sharded on
    ``col_axis`` (bucket dimension replicated)."""
    _check_dim(T, D, columnwise=True)
    h = T.bucket_indices()
    vs = T.values(D.dtype)
    s_dim, bs_r, bs_c = T.sketch_dim, D.bs_r, D.bs_c
    row_axis, col_axis = D.row_axis, D.col_axis

    def local(lr, lc, v, h, vs):
        lr, lc, v = lr[0, 0], lc[0, 0], v[0, 0]
        rb = lax.axis_index(row_axis) if row_axis else 0
        g = rb * bs_r + lr                     # global input coordinate
        part = jnp.zeros((s_dim, bs_c), v.dtype).at[h[g], lc].add(vs[g] * v)
        if row_axis:
            part = lax.psum(part, row_axis)
        return part[None]

    out = shard_map(
        local,
        mesh=D.mesh,
        in_specs=(D._triplet_spec(),) * 3 + (P(), P()),
        out_specs=P(col_axis, None, None),
    )(D.lr, D.lc, D.v, h, vs)
    return out.transpose(1, 0, 2).reshape(s_dim, D.pc * bs_c)[:, : D.width]


def hash_rowwise(T, D: DistSparseMatrix) -> jax.Array:
    """A·Sᵀ for A (m, N) distributed sparse → (m, S_dim) sharded on
    ``row_axis``."""
    _check_dim(T, D, columnwise=False)
    h = T.bucket_indices()
    vs = T.values(D.dtype)
    s_dim, bs_r, bs_c = T.sketch_dim, D.bs_r, D.bs_c
    row_axis, col_axis = D.row_axis, D.col_axis

    def local(lr, lc, v, h, vs):
        lr, lc, v = lr[0, 0], lc[0, 0], v[0, 0]
        cb = lax.axis_index(col_axis) if col_axis else 0
        g = cb * bs_c + lc
        part = jnp.zeros((bs_r, s_dim), v.dtype).at[lr, h[g]].add(vs[g] * v)
        if col_axis:
            part = lax.psum(part, col_axis)
        return part[None]

    out = shard_map(
        local,
        mesh=D.mesh,
        in_specs=(D._triplet_spec(),) * 3 + (P(), P()),
        out_specs=P(row_axis, None, None),
    )(D.lr, D.lc, D.v, h, vs)
    return out.reshape(D.pr * bs_r, s_dim)[: D.height]


def hash_apply_sparse(T, D: DistSparseMatrix, columnwise: bool = True
                      ) -> DistSparseMatrix:
    """Sparse→sparse distributed hash apply: the analog of the reference's
    SpParMat → SpParMat CombBLAS path (ref:
    sketch/hash_transform_CombBLAS.hpp:141-632 — sketching a distributed
    sparse matrix without densifying it).

    A hash sketch maps each nonzero 1:1 — columnwise, (r, c, v) →
    (h[r], c, vs[r]·v) — so the triplets are rewritten cell-locally with
    NO arithmetic collective; the cells along the sketched axis then merge
    into one bucket-extent block (a reshape across that mesh axis — data
    movement proportional to nnz), leaving a :class:`DistSparseMatrix`
    distributed on the kept axis only. Padding entries stay padding (v=0
    at local (0,0)). Duplicate bucket collisions remain separate COO
    entries — every consumer (spmm/todense/to_local) sums duplicates, the
    CSC ``set()`` convention of ref: base/sparse_matrix.hpp:136.
    """
    import jax as _jax
    from jax.sharding import NamedSharding

    _check_dim(T, D, columnwise=columnwise)
    h = T.bucket_indices()
    vs = T.values(D.dtype)
    bs_r, bs_c = D.bs_r, D.bs_c
    row_axis, col_axis = D.row_axis, D.col_axis
    mesh = D.mesh

    def local(lr, lc, v, h, vs):
        lr_, lc_, v_ = lr[0, 0], lc[0, 0], v[0, 0]
        keep = v_ != 0
        if columnwise:
            rb = lax.axis_index(row_axis) if row_axis else 0
            g = rb * bs_r + lr_
            new_lr = jnp.where(keep, h[g], 0)
            new_lc = lc_
        else:
            cb = lax.axis_index(col_axis) if col_axis else 0
            g = cb * bs_c + lc_
            new_lr = lr_
            new_lc = jnp.where(keep, h[g], 0)
        new_v = jnp.where(keep, vs[g] * v_, jnp.zeros((), v_.dtype))
        return (new_lr[None, None], new_lc[None, None], new_v[None, None])

    nlr, nlc, nv = shard_map(
        local, mesh=mesh,
        in_specs=(D._triplet_spec(),) * 3 + (P(), P()),
        out_specs=(D._triplet_spec(),) * 3,
    )(D.lr, D.lc, D.v, h, vs)

    pr, pc, pad = D.pr, D.pc, D.v.shape[-1]
    if columnwise:
        # merge the pr row-cells into the single bucket row block
        spec = NamedSharding(mesh, P(None, col_axis, None))
        merge = lambda a: _jax.device_put(
            a.transpose(1, 0, 2).reshape(1, pc, pr * pad), spec)
        out = DistSparseMatrix(
            mesh, None, col_axis, (T.sketch_dim, D.width),
            merge(nlr), merge(nlc), merge(nv),
        )
    else:
        spec = NamedSharding(mesh, P(row_axis, None, None))
        merge = lambda a: _jax.device_put(
            a.reshape(pr, 1, pc * pad), spec)
        out = DistSparseMatrix(
            mesh, row_axis, None, (D.height, T.sketch_dim),
            merge(nlr), merge(nlc), merge(nv),
        )
    # the merge multiplied the slot count by the merged axis extent while
    # real nnz stayed fixed; re-compact so chained sparse applies don't
    # compound mostly-zero slots (advisor r2 finding). Skipped when the
    # merged axis had extent 1 (no growth): compact()'s nnz readback is a
    # blocking device sync not worth paying on the no-op case.
    merged_extent = pr if columnwise else pc
    return out.compact() if merged_extent > 1 else out


# ---------------------------------------------------------------------------
# UST (row/column sampling) — per-cell one-hot selection + psum
# ---------------------------------------------------------------------------


def ust_columnwise(T, D: DistSparseMatrix) -> jax.Array:
    """S·A = A[idx, :] for A (N, w) distributed sparse → (S_dim, w)
    dense, sharded on ``col_axis``. Each cell scatters the nonzeros whose
    global row is sampled into the output slots (handles
    with-replacement duplicates: every slot t with idx[t] == r receives
    row r)."""
    _check_dim(T, D, columnwise=True)
    idx = T.sample_indices()                      # (S_dim,) global rows
    s_dim, bs_r, bs_c = T.sketch_dim, D.bs_r, D.bs_c
    row_axis, col_axis = D.row_axis, D.col_axis

    # out[t, c] = Σ_j sel[t, j] · v[j] · [lc[j] == c]
    def local(lr, lc, v, idx):
        lr_, lc_, v_ = lr[0, 0], lc[0, 0], v[0, 0]
        rb = lax.axis_index(row_axis) if row_axis else 0
        g = rb * bs_r + lr_
        sel = (idx[:, None] == g[None, :]).astype(v_.dtype)  # (s, pad)
        weighted = sel * v_[None, :]
        part = jax.ops.segment_sum(
            weighted.T, lc_, num_segments=bs_c
        ).T                                        # (s, bs_c)
        if row_axis:
            part = lax.psum(part, row_axis)
        return part[None]

    out = shard_map(
        local,
        mesh=D.mesh,
        in_specs=(D._triplet_spec(),) * 3 + (P(),),
        out_specs=P(col_axis, None, None),
    )(D.lr, D.lc, D.v, idx)
    return out.transpose(1, 0, 2).reshape(s_dim, D.pc * bs_c)[:, : D.width]


def ust_rowwise(T, D: DistSparseMatrix) -> jax.Array:
    """A·Sᵀ = A[:, idx] for A (m, N) distributed sparse → (m, S_dim)
    dense, sharded on ``row_axis``."""
    _check_dim(T, D, columnwise=False)
    idx = T.sample_indices()
    s_dim, bs_r, bs_c = T.sketch_dim, D.bs_r, D.bs_c
    row_axis, col_axis = D.row_axis, D.col_axis

    def local(lr, lc, v, idx):
        lr_, lc_, v_ = lr[0, 0], lc[0, 0], v[0, 0]
        cb = lax.axis_index(col_axis) if col_axis else 0
        g = cb * bs_c + lc_
        sel = (g[:, None] == idx[None, :]).astype(v_.dtype)  # (pad, s)
        weighted = sel * v_[:, None]
        part = jax.ops.segment_sum(
            weighted, lr_, num_segments=bs_r
        )                                          # (bs_r, s)
        if col_axis:
            part = lax.psum(part, col_axis)
        return part[None]

    out = shard_map(
        local,
        mesh=D.mesh,
        in_specs=(D._triplet_spec(),) * 3 + (P(),),
        out_specs=P(row_axis, None, None),
    )(D.lr, D.lc, D.v, idx)
    return out.reshape(D.pr * bs_r, s_dim)[: D.height]


# ---------------------------------------------------------------------------
# dense transforms (JLT / CT) — virtual-operator panels per cell
# ---------------------------------------------------------------------------


def _cell_panel(T, block_start, width: int, dtype):
    """S[:, block_start .. +width) with a *traced* start column.

    Generates the static number of BLOCK_COLS blocks covering any
    alignment (one vmapped generator call — a single traced kernel, not
    nb unrolled ones), then dynamic-slices — each device materializes
    only its own (S_dim × width(+BC)) window of the virtual operator."""
    from libskylark_tpu.sketch.dense import BLOCK_COLS

    nb = -(-width // BLOCK_COLS) + 1
    first = block_start // BLOCK_COLS
    off = block_start % BLOCK_COLS
    blocks = jax.vmap(
        lambda b: T.s_block(b, dtype)
    )(first + jnp.arange(nb, dtype=jnp.int32))        # (nb, s_dim, BC)
    panel = blocks.transpose(1, 0, 2).reshape(T.sketch_dim, nb * BLOCK_COLS)
    return lax.dynamic_slice(
        panel, (0, off), (T.sketch_dim, width)
    )


def dense_rowwise(T, D: DistSparseMatrix) -> jax.Array:
    """A·Sᵀ for A (m, N) distributed sparse → (m, S_dim) sharded on
    ``row_axis``; contraction over the col axis rides one psum."""
    _check_dim(T, D, columnwise=False)
    s_dim, bs_r, bs_c = T.sketch_dim, D.bs_r, D.bs_c
    row_axis, col_axis = D.row_axis, D.col_axis

    def local(lr, lc, v):
        lr, lc, v = lr[0, 0], lc[0, 0], v[0, 0]
        cb = lax.axis_index(col_axis) if col_axis else 0
        panelT = _cell_panel(T, cb * bs_c, bs_c, v.dtype).T   # (bs_c, s_dim)
        part = jax.ops.segment_sum(
            v[:, None] * panelT[lc], lr, num_segments=bs_r
        )
        if col_axis:
            part = lax.psum(part, col_axis)
        return part[None]

    out = shard_map(
        local,
        mesh=D.mesh,
        in_specs=(D._triplet_spec(),) * 3,
        out_specs=P(row_axis, None, None),
    )(D.lr, D.lc, D.v)
    return out.reshape(D.pr * bs_r, s_dim)[: D.height]


def dense_columnwise(T, D: DistSparseMatrix) -> jax.Array:
    """S·A for A (N, w) distributed sparse → (S_dim, w) sharded on
    ``col_axis``."""
    _check_dim(T, D, columnwise=True)
    s_dim, bs_r, bs_c = T.sketch_dim, D.bs_r, D.bs_c
    row_axis, col_axis = D.row_axis, D.col_axis

    def local(lr, lc, v):
        lr, lc, v = lr[0, 0], lc[0, 0], v[0, 0]
        rb = lax.axis_index(row_axis) if row_axis else 0
        panelT = _cell_panel(T, rb * bs_r, bs_r, v.dtype).T   # (bs_r, s_dim)
        part = jax.ops.segment_sum(
            v[:, None] * panelT[lr], lc, num_segments=bs_c
        )
        if row_axis:
            part = lax.psum(part, row_axis)
        return part.T[None]

    out = shard_map(
        local,
        mesh=D.mesh,
        in_specs=(D._triplet_spec(),) * 3,
        out_specs=P(col_axis, None, None),
    )(D.lr, D.lc, D.v)
    return out.transpose(1, 0, 2).reshape(s_dim, D.pc * bs_c)[:, : D.width]
